"""``python -m tpuic.serve`` — online inference driver.

Three request sources, all feeding the same InferenceEngine:

- **stdin JSONL** (default): one request per line,
  ``{"id": "r1", "path": "img.png"}`` (``id`` optional, defaults to the
  path).  Responses stream to --out (default stdout) as JSONL:
  ``{"id", "pred", "prob", "topk": [[name, prob], ...]}``.
- **directory watch** (``--watch DIR``): polls DIR for new image files
  and classifies each once; ``--once`` processes the current contents
  and exits (the tier-1-testable mode).
- **socket JSONL** (``--listen HOST:PORT``): the replica transport the
  router (``python -m tpuic.serve.router``, docs/serving.md "Replica
  routing and failover") drives.  Same request lines as stdin plus a
  ``{"b64", "shape", "dtype"}`` raw-array payload (tpuic/serve/wire.py)
  and a ``{"op": "ping"}`` liveness probe answered with queue depth;
  responses go back on the requesting connection, keyed by id.
  ``--ready-file`` atomically publishes the bound port + pid once the
  engine is warmed — the router's port-handoff channel.

Decode (PIL) of request N+1 overlaps the device call for batch N: the
driver only *submits* work and drains completed futures opportunistically
— the engine's batcher thread owns the device.

    python -m tpuic.serve --ckpt-dir dtmodel/cp --model auto < reqs.jsonl
    python -m tpuic.serve --ckpt-dir dtmodel/cp --watch incoming/ --once

A final stats line (queue wait, pad efficiency, bucket histogram,
latency percentiles, compile counts) goes to stderr on shutdown.

Graceful shutdown (docs/robustness.md): SIGTERM/SIGINT latch a
PreemptionGuard (the trainer's mechanism, runtime/preemption.py) instead
of killing the process mid-batch — the driver stops accepting requests,
drains everything in flight for up to ``--drain-timeout`` seconds
(stragglers get a per-request error line, never a silent drop), closes
the engine, and exits 0. A scheduler eviction loses zero accepted
requests that the device can finish inside the grace window.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time
from collections import deque
from concurrent.futures import Future as _FutFuture
from concurrent.futures import TimeoutError as _FutTimeout

import numpy as np

from tpuic.runtime import faults as _faults  # stdlib-only import
from tpuic.serve import wire  # stdlib-only import
from tpuic.serve.admission import AdmissionError  # stdlib-only import


def _load_image(path: str, size: int) -> np.ndarray:
    """Decode + resize EXACTLY like the training/predict pipeline
    (folder.py -> transforms.resize_nearest): the checkpoint's val
    accuracy was measured on nearest-resized pixels, and serving the
    same image through a different interpolation would silently shift
    predictions relative to `python -m tpuic.predict`."""
    from PIL import Image

    from tpuic.data.transforms import resize_nearest
    img = np.asarray(Image.open(path).convert("RGB"), np.uint8)
    return resize_nearest(img, size)


def _class_names(ckpt_dir: str, model: str, num_classes: int,
                 classes_file: str) -> dict:
    """index -> display name: --classes file (one name per line) wins,
    else the class_to_idx.json sidecar the Trainer writes, else indices."""
    names = {i: str(i) for i in range(num_classes)}
    if classes_file:
        with open(classes_file) as f:
            for i, line in enumerate(ln.strip() for ln in f):
                if line:
                    names[i] = line
        return names
    sidecar = os.path.join(ckpt_dir, model, "class_to_idx.json")
    try:
        with open(sidecar) as f:
            names.update({int(v): k for k, v in json.load(f).items()})
    except (OSError, ValueError):
        pass
    return names


def _result_record(rid, probs, order, names, k: int) -> dict:
    """One response record: ``{"id", "pred", "prob", "topk"}`` — the
    shape every transport (stdin, watch, socket) emits."""
    topk = [[names.get(int(order[0, j]), str(int(order[0, j]))),
             round(float(probs[0, order[0, j]]), 6)]
            for j in range(k)]
    return {"id": rid, "pred": topk[0][0], "prob": topk[0][1],
            "topk": topk}


def serve_socket(engine, *, listen: str, names, top_k: int, size: int,
                 guard, beat, drain_timeout: float = 30.0,
                 ready_file: str = "", prom_port=None,
                 log=lambda msg: print(msg, file=sys.stderr)) -> int:
    """The socket-JSONL replica transport (docs/serving.md, "Replica
    routing and failover").

    Accepts connections on ``listen`` (HOST:PORT, port 0 = kernel
    assigned) and speaks newline-delimited JSON per connection:

    - request lines as in stdin mode (``path`` or a ``b64`` raw-array
      payload, optional SLA fields honored under --admission), answered
      on the SAME connection with the usual result record or a typed
      error line (wire.py — identical shape to the stdin tier's);
      responses are keyed by id and may arrive out of submission order
      (a deadline shed resolves before its batchmates).
    - ``{"op": "ping", "id": ...}`` -> ``{"op": "pong", "id",
      "queue_depth", "inflight", "pid"}`` — the router's live probe.

    Single-threaded select loop (the stdin design, multiplexed): reads
    submit, completed futures flush opportunistically each tick, and
    the SIGTERM latch drains everything in flight for up to
    ``drain_timeout`` seconds with typed straggler lines — the PR-2
    preemption contract, per connection.

    ``ready_file`` is written (atomic, wire.py) once the socket is
    bound — and the engine is already warmed by then — so the router's
    spawn handshake never races warmup.

    Fault points (runtime/faults.py): ``replica_crash`` SIGKILLs this
    process at the Nth accepted request; ``replica_wedge`` stops
    servicing the socket there (pings included) so the heartbeat goes
    stale — the two replica-death shapes the router must survive.
    """
    import select
    import signal as _signal
    import socket as _socket

    host, port = wire.parse_hostport(listen)
    srv = _socket.create_server((host, port), backlog=64)
    srv.setblocking(False)
    bound = srv.getsockname()[1]
    if ready_file:
        # Model identity rides the handoff (docs/serving.md, "Model
        # lifecycle"): digest + dtype-ladder tags let the router refuse
        # a silently-heterogeneous fleet before routing one request.
        # The ready file records the BOOT identity; the live identity
        # (post-swap) is whatever the pong says.
        wire.write_ready_file(ready_file, port=int(bound), pid=os.getpid(),
                              prom_port=prom_port,
                              digest=engine.model_digest,
                              dtypes=list(engine.variant_tags()),
                              generation=engine.generation)
    log(f"[serve] socket-JSONL transport on {host}:{bound}"
        + (f" (ready file {ready_file})" if ready_file else ""))

    # socket -> {"buf": bytes, "out": bytearray, "out_ofs": int,
    #            "pending": deque}; "out" holds unsent response bytes
    # from index "out_ofs" on (cleared when fully drained, so its
    # truthiness means "has pending output" at every check site).
    conns: dict = {}
    served = 0
    accepted = 0  # request counter: the fault points' step axis
    # A peer that stops reading grows its out buffer without bound;
    # past this the connection is condemned (the router's failover
    # handles its in-flight) rather than ballooning the replica.
    max_out_buf = 8 << 20

    def close_conn(sock) -> None:
        st = conns.pop(sock, None)
        try:
            sock.close()
        except OSError:
            pass
        if st is None:
            return
        for _, fut in st["pending"]:
            # Client gone: nothing to deliver to. Swallow the outcome
            # so an abandoned future never logs "exception never
            # retrieved" noise.
            fut.add_done_callback(lambda f: f.cancelled() or f.exception())

    def pump_out(sock) -> None:
        """Drain as much of the connection's out buffer as the kernel
        will take WITHOUT blocking.  A stalled peer must never stall
        the select loop: one slow sendall here used to freeze pings to
        every OTHER connection (and the supervisor heartbeat) for up
        to its 5s timeout — longer than the router's 3s ping window —
        so healthy links accrued breaker failures for this peer's
        sins.

        The buffer is a bytearray consumed via an offset (compacted
        every 256KB) so a slow drain costs one memmove per compaction,
        not a full copy of the multi-MB remainder per partial send."""
        st = conns.get(sock)
        if st is None or not st["out"]:
            return
        try:
            n = sock.send(memoryview(st["out"])[st["out_ofs"]:])
        except (BlockingIOError, InterruptedError):
            return  # kernel buffer full: the writable set drains it
        except OSError:
            close_conn(sock)
            return
        st["out_ofs"] += n
        if st["out_ofs"] >= len(st["out"]):
            del st["out"][:]
            st["out_ofs"] = 0
        elif st["out_ofs"] > (1 << 18):
            del st["out"][:st["out_ofs"]]
            st["out_ofs"] = 0

    def send(sock, rec: dict) -> None:
        st = conns.get(sock)
        if st is None:
            return
        st["out"] += (json.dumps(rec) + "\n").encode()
        if len(st["out"]) - st["out_ofs"] > max_out_buf:
            close_conn(sock)  # peer stopped reading: conclusive
            return
        pump_out(sock)

    def handle_line(sock, st, raw: str) -> None:
        nonlocal accepted
        try:
            req = json.loads(raw)
            if not isinstance(req, dict):
                raise ValueError("not an object")
        except ValueError:
            send(sock, wire.error_record(
                None, f"bad request line: {raw[:80]}"))
            return
        if req.get("op") == "ping":
            send(sock, {"id": req.get("id"), "op": "pong",
                        "queue_depth": engine.queue_depth(),
                        "inflight": sum(len(s["pending"])
                                        for s in conns.values()),
                        # Model identity (docs/serving.md, "Model
                        # lifecycle"): the router's heterogeneous-fleet
                        # gate and the rollout driver's promotion check
                        # both read the LIVE digest from pongs — a
                        # hot-swap shows up within one ping interval.
                        "digest": engine.model_digest,
                        "generation": engine.generation,
                        "pid": os.getpid()})
            return
        if req.get("op") == "swap":
            # Control line, not traffic: gates + flips on a worker
            # thread (submit_swap) so pings keep flowing; the result
            # record (or typed swap_corrupt/swap_accuracy verdict)
            # rides the normal pending/flush machinery, keyed by id.
            rid = str(req.get("id", "swap"))
            st["pending"].append((rid, submit_swap(engine, req, log)))
            return
        accepted += 1
        if _faults.fire("replica_crash", accepted):
            os.kill(os.getpid(), _signal.SIGKILL)
        if _faults.fire("replica_wedge", accepted):
            w = _faults.param("replica_wedge")
            time.sleep(3600.0 if w is None else float(w))  # tpuic-ok: TPU101 fault param is a host float
        rid = str(req.get("id", req.get("path", accepted)))
        try:
            if req.get("b64") is not None:
                img = wire.decode_array(req)
            elif req.get("path") is not None:
                img = _load_image(str(req["path"]), size)
            else:
                raise ValueError("request needs 'path' or 'b64'")
        except Exception as e:  # noqa: BLE001
            send(sock, wire.error_record(rid, f"decode: {e}"))
            return
        sla = {}
        if engine.admission is not None:
            sla = {f: req[f] for f in ("priority", "deadline_ms", "tenant")
                   if req.get(f) is not None}
            sla.setdefault("timeout", 0)
        if req.get("serve_dtype") is not None:
            # Dtype-ladder rung selection (docs/performance.md,
            # "Quantized serving").  The request key is serve_dtype —
            # NOT "dtype", which the b64 array payload already uses for
            # the ARRAY's element type (wire.py).  An unconfigured rung
            # gets a typed error line via the ValueError arm below.
            sla["dtype"] = str(req["serve_dtype"])
        try:
            st["pending"].append((rid, engine.submit(img, **sla)))
        except (AdmissionError, ValueError, TypeError) as e:
            send(sock, wire.error_record(rid, e))

    def flush(sock, st) -> None:
        """Emit every completed future on this connection (any order —
        responses are keyed by id, and a shed must not wait behind the
        batch ahead of it)."""
        nonlocal served
        still = deque()
        while st["pending"]:
            rid, fut = st["pending"].popleft()
            if not fut.done():
                still.append((rid, fut))
                continue
            if fut.cancelled():
                send(sock, wire.error_record(rid, "cancelled"))
            elif fut.exception() is not None:
                send(sock, wire.error_record(rid, fut.exception()))
            else:
                res = fut.result()
                if isinstance(res, dict):
                    # Control-line outcome (a swap_result): already a
                    # wire record — not counted as served traffic.
                    send(sock, {**res, "id": rid})
                else:
                    probs, order = res
                    send(sock, _result_record(rid, probs, order, names,
                                              top_k))
                    served += 1
            if sock not in conns:
                # send() failed and close_conn ran: it swallowed what
                # was left on the ORPHANED state dict, but the entries
                # already moved to `still` need the same treatment —
                # re-attaching them would strand futures nobody flushes.
                for _, f in still:
                    f.add_done_callback(
                        lambda fu: fu.cancelled() or fu.exception())
                return
        st["pending"] = still

    try:
        while not guard.triggered:
            # Only pending futures need the fast poll tick: buffered
            # output is event-driven — its socket sits in the writable
            # set, and select wakes the instant the kernel can take
            # more, so a stalled peer costs zero spin.
            busy = any(s["pending"] for s in conns.values())
            try:
                ready, writable, _ = select.select(
                    [srv] + list(conns),
                    [s for s, st in conns.items() if st["out"]], [],
                    0.005 if busy else 0.1)
            except (OSError, ValueError):
                break
            for sock in writable:
                pump_out(sock)
            for sock in ready:
                if sock is srv:
                    try:
                        c, _ = srv.accept()
                        c.setblocking(False)  # sends buffer, never stall
                        conns[c] = {"buf": b"", "out": bytearray(),
                                    "out_ofs": 0, "pending": deque()}
                    except OSError:
                        pass
                    continue
                st = conns.get(sock)
                if st is None:
                    continue
                try:
                    chunk = sock.recv(1 << 16)
                except (BlockingIOError, InterruptedError):
                    continue  # spurious wakeup on a non-blocking sock
                except OSError:
                    chunk = b""
                if not chunk:
                    close_conn(sock)  # peer EOF
                    continue
                *lines, st["buf"] = (st["buf"] + chunk).split(b"\n")
                for raw in lines:
                    if sock not in conns:
                        # handle_line condemned the connection (send
                        # failure, out-buffer overflow): the rest of
                        # this pipelined chunk has nobody to answer to
                        # — submitting it would strand futures on the
                        # orphaned state dict past close_conn's sweep.
                        break
                    if raw.strip():
                        handle_line(sock, st, raw.decode("utf-8", "replace"))
            for sock in list(conns):
                if sock in conns:
                    flush(sock, conns[sock])
            beat()
        # SIGTERM drain (the PR-2 preemption contract): stop accepting,
        # flush in-flight for the grace window, typed straggler lines.
        n_pending = sum(len(s["pending"]) for s in conns.values())
        if guard.triggered and n_pending:
            log(f"[serve] SIGTERM: draining {n_pending} in-flight "
                f"socket request(s) (timeout {drain_timeout:.1f}s)")
            deadline = time.monotonic() + max(0.0, drain_timeout)
            while (any(s["pending"] for s in conns.values())
                   and time.monotonic() < deadline):
                for sock in list(conns):
                    if sock in conns:
                        flush(sock, conns[sock])
                        pump_out(sock)
                time.sleep(0.02)
            for sock in list(conns):
                st = conns.get(sock)
                if st is None:
                    continue
                flush(sock, st)
                for rid, fut in st["pending"]:
                    fut.cancel()
                    send(sock, wire.error_record(
                        rid, "drain timeout: engine shutting down "
                        "before this request finished"))
                st["pending"] = deque()
        # Flush buffered response bytes before the finally closes the
        # sockets — a typed straggler line still sitting in an out
        # buffer is a silent drop from the peer's point of view.
        flush_deadline = time.monotonic() + 2.0
        while (any(s["out"] for s in conns.values())
               and time.monotonic() < flush_deadline):
            try:
                _, writable, _ = select.select(
                    [], [s for s, st in conns.items() if st["out"]],
                    [], 0.05)
            except (OSError, ValueError):
                break
            for sock in writable:
                pump_out(sock)
    finally:
        for sock in list(conns):
            close_conn(sock)
        try:
            srv.close()
        except OSError:
            pass
        if ready_file:
            try:
                os.remove(ready_file)  # a dead replica must not look ready
            except OSError:
                pass
    return served


def _parse_dtypes(spec: str):
    """--serve-dtypes 'fp32,bf16,int8' -> validated ladder tags (fp32
    always present and always the default rung)."""
    from tpuic.quant import DTYPE_TAGS
    tags = [t.strip() for t in (spec or "fp32").split(",") if t.strip()]
    for t in tags:
        if t not in DTYPE_TAGS:
            raise SystemExit(f"serve: --serve-dtypes: unknown dtype {t!r} "
                             f"(supported: {', '.join(DTYPE_TAGS)})")
    if "fp32" not in tags:
        tags.insert(0, "fp32")
    return tuple(dict.fromkeys(tags))


def _ladder_variants(model, variables, tags, size, *, mean, std, log):
    """Build the quantized rungs + run the accuracy gate (docs/
    performance.md, "Quantized serving"): a rung whose top-1 agreement
    with fp32 on the pinned synthetic eval set falls below the
    committed epsilon is REFUSED at startup — a quantization bug must
    fail the server loudly, not silently serve degraded predictions."""
    import jax

    from tpuic import quant
    variants = quant.serve_variants(model, variables, tags,
                                    normalize=True, mean=mean, std=std)
    if len(tags) > 1:
        imgs = quant.eval_images(128, size)
        ref_fwd, ref_vars = variants["fp32"]
        ref = jax.jit(ref_fwd)
        floor = 1.0 - quant.DEFAULT_EPSILON
        for tag in tags:
            if tag == "fp32":
                continue
            fwd, qv = variants[tag]
            agree = quant.top1_agreement(ref, ref_vars, jax.jit(fwd), qv,
                                         imgs)
            if agree < floor:
                raise SystemExit(
                    f"serve: dtype ladder rung {tag!r} FAILED the "
                    f"accuracy gate: top-1 agreement with fp32 is "
                    f"{agree:.4f} < {floor:.4f} on the pinned eval set "
                    f"(epsilon {quant.DEFAULT_EPSILON}) — refusing to "
                    "serve a quantization that moves predictions")
            log(f"dtype ladder rung {tag}: top-1 agreement "
                f"{agree:.4f} >= {floor:.4f} (accuracy gate OK)")
    return variants


# One swap at a time per process: the gate + stage + flip sequence is
# itself atomic from the operator's view, and a second candidate racing
# the first would gate against a moving incumbent.  Created at import
# (lazy creation would itself race two first swaps into separate locks).
_SWAP_LOCK = threading.Lock()


def _swap_context(engine, *, model, model_name: str, num_classes: int,
                  resize: int, tags, mean, std, ckpt_dir: str,
                  track: str) -> None:
    """Attach everything a later ``{"op": "swap"}`` control line needs
    to rebuild and gate a candidate ladder for THIS engine (model
    architecture, ladder tags, normalize stats, default checkpoint
    location).  Engines built outside this CLI (tests, embedders)
    simply have no context and refuse swap lines with a typed error."""
    engine.tpuic_swap_ctx = {
        "model": model, "model_name": model_name,
        "num_classes": int(num_classes), "resize": int(resize),
        "tags": tuple(tags), "mean": mean, "std": std,
        "ckpt_dir": ckpt_dir, "track": track,
    }


def _gate_outputs(engine, tree, imgs, tag: str):
    """Candidate outputs for one rung: through the engine's live AOT
    executables when the candidate is aval-identical (zero compiles —
    the hot-swap case the soak pins), else a one-off jit of the rung's
    forward (the aval-mismatch case prewarms executables in
    swap_weights anyway, so the gate compile is not the anomaly)."""
    try:
        return engine.candidate_outputs(tree, imgs, variant=tag)
    except ValueError:
        import jax
        fwd = engine._variants[tag][0]
        arr = np.asarray(imgs, engine.input_dtype)
        return jax.jit(fwd)(jax.device_put(tree), arr)


def run_swap(engine, req: dict, log) -> dict:
    """Gate + stage + flip for one ``{"op": "swap", ...}`` control line
    (docs/serving.md, "Model lifecycle: hot-swap, canary, rollback").

    Candidate source: ``{"ckpt_dir", "track"}`` (defaults: the serving
    checkpoint location) loads through the STRICT verified path
    (checkpoint/loading.py ``load_candidate_variables`` — CRC/manifest
    mandatory, no ladder fallback, typed ``swap_corrupt`` refusal), or
    ``{"synthetic_seed": N}`` re-inits the architecture from a seed
    (the load-test / soak candidate, no artifact to verify).

    Pre-flip admission gates, in order:

    1. **Integrity** — the candidate's bytes match its commit manifest
       (``swap_corrupt`` refusal; checkpoint candidates only).
    2. **Pinned-eval accuracy** — the candidate's fp32 outputs are
       finite on the pinned synthetic eval set (tpuic/quant
       ``eval_images``), and every configured dtype-ladder rung built
       from the candidate agrees with the candidate's own fp32 top-1
       within the committed epsilon — the PR-13 startup gate re-run
       per swap (``swap_accuracy`` refusal).  Gate evaluation rides the
       live generation's executables (``engine.candidate_outputs``):
       zero new compiles for aval-identical candidates.
    3. The flip itself is ``engine.swap_weights`` — the whole ladder as
       one unit, zero-drain by construction.

    A refused candidate never touches traffic: the incumbent keeps
    serving, untouched, and the caller gets the typed verdict.
    Raises ``SwapRejected`` / ``ValueError``; returns the
    ``swap_result`` record on success."""
    from tpuic.serve.admission import SwapRejected
    ctx = getattr(engine, "tpuic_swap_ctx", None)
    if ctx is None:
        raise ValueError("swap unsupported: this engine was built "
                         "without a swap context")
    if not _SWAP_LOCK.acquire(blocking=False):
        raise RuntimeError("swap already in progress — one candidate "
                           "at a time")
    try:
        import jax
        import jax.numpy as jnp

        from tpuic import quant
        from tpuic.checkpoint.loading import load_candidate_variables
        from tpuic.config import (Config, DataConfig, ModelConfig,
                                  OptimConfig, RunConfig)
        resize, tags = ctx["resize"], ctx["tags"]
        default = tags[0]
        if req.get("synthetic_seed") is not None:
            seed = int(req["synthetic_seed"])
            variables = ctx["model"].init(
                jax.random.key(seed),
                jnp.zeros((1, resize, resize, 3), jnp.float32),
                train=False)
            source = f"synthetic:{seed}"
        else:
            ckpt_dir = str(req.get("ckpt_dir") or ctx["ckpt_dir"] or "")
            if not ckpt_dir:
                raise ValueError(
                    "swap line needs 'ckpt_dir' (or 'synthetic_seed')")
            track = str(req.get("track") or ctx["track"] or "best")
            cfg = Config(
                data=DataConfig(data_dir=".", resize_size=resize),
                model=ModelConfig(name=ctx["model_name"],
                                  num_classes=ctx["num_classes"]),
                optim=OptimConfig(
                    ema_decay=_sidecar_ema(ckpt_dir, ctx["model_name"])),
                run=RunConfig(ckpt_dir=ckpt_dir))
            _, variables, _ = load_candidate_variables(
                cfg, track=track, log=log)
            source = os.path.join(ckpt_dir, ctx["model_name"], track)
        # Rebuild the dtype ladder FROM the candidate (the ladder swaps
        # as one unit — engine.swap_weights enforces the tag set).
        trees = {default: variables}
        for tag in tags[1:]:
            if tag == "bf16":
                trees[tag] = quant.bf16_variables(variables)
            elif tag == "int8":
                trees[tag] = quant.quantize_variables(variables)
            else:
                raise ValueError(f"unknown ladder rung {tag!r}")
        # Pinned-eval accuracy gate (pre-flip, off the request path).
        imgs = quant.eval_images(128, resize)
        ref = _gate_outputs(engine, trees[default], imgs, default)
        ref_probs, ref_order = (np.asarray(ref[0]), np.asarray(ref[1]))
        if not np.isfinite(ref_probs).all():
            raise SwapRejected(
                f"swap candidate {source} produced non-finite outputs "
                "on the pinned eval set — refusing to flip garbage "
                "into traffic", cause="swap_accuracy")
        floor = 1.0 - quant.DEFAULT_EPSILON
        for tag in tags[1:]:
            out_t = _gate_outputs(engine, trees[tag], imgs, tag)
            t_probs, t_order = (np.asarray(out_t[0]), np.asarray(out_t[1]))
            agree = float(np.mean(ref_order[:, 0] == t_order[:, 0]))
            if not np.isfinite(t_probs).all() or agree < floor:
                raise SwapRejected(
                    f"swap candidate {source} rung {tag!r} FAILED the "
                    f"accuracy gate: top-1 agreement with the "
                    f"candidate's fp32 is {agree:.4f} < {floor:.4f} on "
                    f"the pinned eval set (epsilon "
                    f"{quant.DEFAULT_EPSILON})", cause="swap_accuracy")
        res = engine.swap_weights(
            trees[default],
            variants={t: trees[t] for t in tags[1:]})
        how = ("executables reused" if res["reused_executables"]
               else f"{res['prewarmed']} executables prewarmed")
        log(f"[serve] hot-swap OK: {source} -> generation "
            f"{res['generation']} digest {res['digest']} ({how}, "
            f"{res['duration_s'] * 1000:.0f} ms)")
        return {"op": "swap_result", "ok": True, "source": source, **res}
    finally:
        _SWAP_LOCK.release()


def submit_swap(engine, req: dict, log):
    """Run the swap gate + flip on a worker thread, returning a Future
    that resolves to the ``swap_result`` record (or the typed verdict).

    Both transports ride their existing completion machinery: the
    future joins the pending deque like any request, so the accept /
    select loop keeps serving traffic and answering pings while the
    candidate loads and gates — the whole point of a ZERO-downtime
    lifecycle.  (A checkpoint load inside the select loop would stall
    pings past the router's window and read as a wedge.)"""
    fut = _FutFuture()

    def _worker() -> None:
        try:
            fut.set_result(run_swap(engine, req, log))
        except BaseException as e:
            fut.set_exception(e)

    threading.Thread(target=_worker, daemon=True,
                     name="tpuic-swap").start()
    return fut


def _sidecar_ema(ckpt_dir: str, model_name: str) -> float:
    """ema_decay from a checkpoint dir's config.json sidecar (0.0 when
    absent/corrupt — the same lenient rule build_engine applies)."""
    try:
        with open(os.path.join(ckpt_dir, model_name, "config.json")) as f:
            return float(
                json.load(f).get("optim", {}).get("ema_decay", 0.0))
    except (OSError, ValueError, TypeError):
        return 0.0


def build_engine(args):
    """Checkpoint -> warmed InferenceEngine (shared predict loading rules)."""
    if args.compile_cache_dir:
        # Persistent XLA compilation cache: warmup's per-bucket AOT
        # compiles land on disk, so a server RESTART warms up from cache
        # instead of recompiling (same mechanism the test suite and
        # bench.py use).
        import jax
        cache = os.path.expanduser(args.compile_cache_dir)
        os.makedirs(cache, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

    from tpuic.checkpoint.loading import load_inference_variables
    from tpuic.config import (Config, DataConfig, ModelConfig, OptimConfig,
                              RunConfig)
    from tpuic.predict import resolve_model_auto
    from tpuic.serve import InferenceEngine

    if args.synthetic_init:
        # Seeded random init, no checkpoint: the load-testing / router-
        # soak replica mode.  Every replica built from the same seed
        # carries IDENTICAL weights, so a failover replay on a survivor
        # returns the same prediction the dead replica would have.
        import jax
        import jax.numpy as jnp

        from tpuic.models import create_model
        if args.model == "auto" or args.num_classes <= 0:
            raise SystemExit("serve: --synthetic-init needs an explicit "
                             "--model and --num-classes (there is no "
                             "checkpoint to resolve them from)")
        resize = args.resize if args.resize is not None else 299
        model = create_model(args.model, args.num_classes, dtype="float32")
        variables = model.init(
            jax.random.key(0),
            jnp.zeros((1, resize, resize, 3), jnp.float32), train=False)
        dc = DataConfig(data_dir=".", resize_size=resize)
        tags = _parse_dtypes(getattr(args, "serve_dtypes", "fp32"))
        variants = _ladder_variants(
            model, variables, tags, resize, mean=dc.mean, std=dc.std,
            log=lambda m: print("[serve]", m, file=sys.stderr))
        engine = InferenceEngine(
            forward_fn=variants["fp32"][0], variables=variants["fp32"][1],
            image_size=resize, input_dtype=np.uint8,
            buckets=tuple(int(b) for b in args.buckets.split(",")),
            max_wait_ms=args.max_wait_ms, queue_size=args.queue_size,
            variants={k: v for k, v in variants.items() if k != "fp32"})
        t = engine.warmup()
        n_exe = sum(len(v) if isinstance(v, dict) else 1
                    for v in t.values())
        print(f"[serve] synthetic init ({args.model}); warmup compiled "
              f"{n_exe} bucket executables: {t}", file=sys.stderr)
        _swap_context(engine, model=model, model_name=args.model,
                      num_classes=args.num_classes, resize=resize,
                      tags=tags, mean=dc.mean, std=dc.std,
                      ckpt_dir=args.ckpt_dir, track=args.track)
        return engine, resize, args.num_classes, args.model

    model_name, num_classes, resize = args.model, args.num_classes, args.resize
    ema_decay = 0.0
    if model_name == "auto":
        saved = resolve_model_auto(args.ckpt_dir)
        model_name = saved["name"]
        num_classes = num_classes or saved["num_classes"]
        ema_decay = saved["ema_decay"]
        if resize is None:
            resize = saved["resize_size"]
        print(f"[serve] auto-resolved model '{model_name}' "
              f"(num_classes={num_classes}, resize={resize})",
              file=sys.stderr)
    elif not args.init_from:
        # Explicit --model: still honor THIS model's config.json sidecar
        # for ema_decay (same rule as tpuic.predict) — an EMA-trained
        # checkpoint must serve its EMA weights (the ones 'best' was
        # selected on), not silently fall back to the raw params.
        sidecar = os.path.join(args.ckpt_dir, model_name, "config.json")
        try:
            with open(sidecar) as f:
                ema_decay = float(
                    json.load(f).get("optim", {}).get("ema_decay", 0.0))
        except (OSError, ValueError, TypeError):
            # Absent or corrupt sidecar (non-atomic trainer write) falls
            # back to raw params, same as _class_names' fallback.
            pass
    if resize is None:
        resize = 299
    if num_classes <= 0:
        raise SystemExit("serve: --num-classes required (or --model auto "
                         "with a config.json sidecar)")
    cfg = Config(
        data=DataConfig(data_dir=".", resize_size=resize),
        model=ModelConfig(name=model_name, num_classes=num_classes),
        optim=OptimConfig(ema_decay=ema_decay),
        run=RunConfig(ckpt_dir=args.ckpt_dir, init_from=args.init_from),
    )
    model, variables = load_inference_variables(
        cfg, track=args.track, log=lambda *a: print("[serve]", *a,
                                                    file=sys.stderr))
    buckets = tuple(int(b) for b in args.buckets.split(","))
    # Raw uint8 in, normalize fused into the compiled forward (4x less
    # H2D than shipping float32 — the device_prep lesson).  The dtype
    # ladder (--serve-dtypes) adds bf16/int8 weight rungs behind the
    # startup accuracy gate; request lines select one with "dtype".
    tags = _parse_dtypes(getattr(args, "serve_dtypes", "fp32"))
    variants = _ladder_variants(
        model, variables, tags, resize, mean=cfg.data.mean,
        std=cfg.data.std,
        log=lambda m: print("[serve]", m, file=sys.stderr))
    engine = InferenceEngine(
        forward_fn=variants["fp32"][0], variables=variants["fp32"][1],
        image_size=resize, input_dtype=np.uint8,
        buckets=buckets, max_wait_ms=args.max_wait_ms,
        queue_size=args.queue_size,
        variants={k: v for k, v in variants.items() if k != "fp32"})
    t = engine.warmup()
    n_exe = sum(len(v) if isinstance(v, dict) else 1 for v in t.values())
    print(f"[serve] warmup compiled {n_exe} bucket executables: {t}",
          file=sys.stderr)
    _swap_context(engine, model=model, model_name=model_name,
                  num_classes=num_classes, resize=resize, tags=tags,
                  mean=cfg.data.mean, std=cfg.data.std,
                  ckpt_dir=args.ckpt_dir, track=args.track)
    return engine, resize, num_classes, model_name


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Dynamic-batching inference server (stdin JSONL or "
                    "directory watch)")
    p.add_argument("--ckpt-dir", default="dtmodel/cp")
    p.add_argument("--model", default="auto")
    p.add_argument("--num-classes", type=int, default=0)
    p.add_argument("--resize", type=int, default=None)
    p.add_argument("--track", default="best", choices=("best", "latest"))
    p.add_argument("--init-from", default="",
                   help="torch checkpoint instead of a tpuic one")
    p.add_argument("--buckets", default="1,8,32,128",
                   help="padding-bucket ladder (comma list)")
    p.add_argument("--serve-dtypes", default="fp32",
                   help="dtype ladder (comma list of fp32,bf16,int8): "
                        "per-dtype AOT executables share the bucket "
                        "cache; bf16 halves and int8 quarters weight "
                        "HBM (absmax per-channel, tpuic/quant). Each "
                        "quantized rung must pass the startup top-1 "
                        "accuracy gate vs fp32 on the pinned eval set "
                        "or the server refuses to start. Request lines "
                        "pick a rung with \"serve_dtype\"; default is "
                        "fp32")
    p.add_argument("--max-wait-ms", type=float, default=5.0)
    p.add_argument("--queue-size", type=int, default=256)
    p.add_argument("--compile-cache-dir", default="~/.cache/tpuic/xla",
                   help="persistent XLA compile cache (restarts warm up "
                        "from disk); empty string disables")
    p.add_argument("--top-k", type=int, default=1)
    p.add_argument("--classes", default="",
                   help="optional file of class names, one per line")
    p.add_argument("--watch", default="",
                   help="watch this directory for images instead of stdin")
    p.add_argument("--poll-s", type=float, default=0.5)
    p.add_argument("--once", action="store_true",
                   help="with --watch: process current files, then exit")
    p.add_argument("--listen", default="",
                   help="serve socket JSONL on HOST:PORT instead of "
                        "stdin (port 0 = kernel-assigned; the replica "
                        "transport behind python -m tpuic.serve.router)")
    p.add_argument("--ready-file", default="",
                   help="with --listen: atomically write {port, pid, "
                        "prom_port} here once the engine is warmed and "
                        "the socket is bound — the router's port "
                        "handoff")
    p.add_argument("--synthetic-init", action="store_true",
                   help="seeded random init instead of a checkpoint "
                        "(load testing / router-soak replicas; requires "
                        "explicit --model and --num-classes)")
    p.add_argument("--out", default="", help="output JSONL (default stdout)")
    p.add_argument("--drain-timeout", type=float, default=30.0,
                   help="on SIGTERM/SIGINT, wait up to this many seconds "
                        "for in-flight requests before failing stragglers "
                        "with an error line and exiting")
    p.add_argument("--prom-port", type=int, default=0,
                   help="serve a Prometheus /metrics endpoint on this "
                        "port (queue wait, pad efficiency, latency "
                        "percentiles from the shared meter; 0 disables; "
                        "-1 binds a kernel-assigned free port — the "
                        "resolved port lands in --ready-file, how "
                        "router replicas expose their health signals "
                        "without port races)")
    p.add_argument("--prom-host", default="127.0.0.1",
                   help="interface for --prom-port (loopback by default "
                        "— the endpoint is unauthenticated; bind "
                        "0.0.0.0 only behind a firewall)")
    p.add_argument("--prom-dump", default="",
                   help="write the Prometheus text exposition to this "
                        "file on shutdown (and each poll tick under "
                        "--watch) — the textfile-collector transport")
    p.add_argument("--slo", default="",
                   help="latency SLOs, comma list of "
                        "'serve_latency:pQ<=Nms[@target]' specs "
                        "(telemetry/slo.py). Subscribing the tracker is "
                        "what switches per-request span events on; "
                        "attainment and error-budget burn land in the "
                        "Prometheus exposition and the final stats line")
    p.add_argument("--admission", action="store_true",
                   help="SLA-aware admission control (docs/serving.md): "
                        "request lines may carry priority/deadline_ms/"
                        "tenant; a full queue rejects with a typed, "
                        "cause-labeled error line instead of blocking "
                        "the accept loop, higher priority classes are "
                        "batched first (and evict lower ones from a "
                        "full queue), and expired deadlines shed at "
                        "pop time")
    p.add_argument("--quota", action="append", default=[],
                   metavar="TENANT=RPS",
                   help="per-tenant token-bucket quota in requests/sec "
                        "(repeatable, or one comma list); '*=RPS' sets "
                        "the shared free pool unconfigured tenants and "
                        "dry tenant buckets draw from. Implies "
                        "--admission")
    p.add_argument("--brownout-slo", default="",
                   help="name of one --slo objective (e.g. "
                        "serve_latency_p99) whose error-budget burn "
                        "rate drives brownout: past --brownout-tighten "
                        "the controller sheds one priority class per "
                        "SLO report, recovering hysteretically below "
                        "--brownout-recover. Implies --admission")
    p.add_argument("--brownout-tighten", type=float, default=2.0,
                   help="burn rate at/above which brownout tightens "
                        "one level")
    p.add_argument("--brownout-recover", type=float, default=1.0,
                   help="burn rate at/below which (after 3 consecutive "
                        "reports) brownout relaxes one level")
    args = p.parse_args(argv)
    if args.quota or args.brownout_slo:
        args.admission = True

    slo_tracker = None
    if args.slo:
        # Parse BEFORE the checkpoint load + AOT warmup — a typo'd
        # objective must fail the command line, not minutes in.
        from tpuic.telemetry.slo import SLOTracker, parse_objectives
        try:
            slo_tracker = SLOTracker(parse_objectives(
                args.slo, allowed=("serve_latency",)))
        except ValueError as e:
            raise SystemExit(f"serve: --slo: {e}")

    # Admission config parses up front too (same fail-fast rule): a
    # typo'd quota would read as "unlimited" exactly when you meant to
    # cap someone, and a brownout coupled to an objective --slo never
    # tracks would silently never tighten.
    admission_ctl = None
    if args.admission:
        from tpuic.serve.admission import (AdmissionController,
                                           BrownoutController, parse_quotas)
        try:
            quotas = parse_quotas(args.quota)
        except ValueError as e:
            raise SystemExit(f"serve: --quota: {e}")
        brownout = None
        if args.brownout_slo:
            known = ([o.name for o in slo_tracker.objectives]
                     if slo_tracker is not None else [])
            if args.brownout_slo not in known:
                raise SystemExit(
                    f"serve: --brownout-slo {args.brownout_slo!r} names "
                    f"no --slo objective (configured: "
                    f"{', '.join(known) or 'none'}) — brownout would "
                    "never see a burn rate")
            brownout = BrownoutController(
                args.brownout_slo, tighten_above=args.brownout_tighten,
                recover_below=args.brownout_recover)
        admission_ctl = AdmissionController(quotas, brownout=brownout)

    # Install the latch BEFORE the (potentially minutes-long) checkpoint
    # load + AOT warmup: an eviction during startup must also exit
    # cleanly, not dump a traceback from inside a compile.
    import signal

    from tpuic.runtime.preemption import PreemptionGuard
    guard = PreemptionGuard(signals=(signal.SIGTERM,)).install()

    if args.classes and not os.path.isfile(args.classes):
        # Validate BEFORE the checkpoint load + per-bucket AOT warmup —
        # a typo'd path must not cost minutes of startup first.
        raise SystemExit(f"serve: --classes file not found: {args.classes}")
    engine, size, num_classes, model_name = build_engine(args)
    names = _class_names(args.ckpt_dir, model_name, num_classes,
                         args.classes)

    # Prometheus exposition (telemetry/prom.py): counters come straight
    # from engine.stats — the shared LatencyMeter percentiles, pad
    # efficiency, bucket histogram, compile counts.
    from tpuic.telemetry.prom import (PromServer, serve_exposition,
                                      write_exposition)

    # Supervised liveness (runtime/supervisor.py, docs/robustness.md):
    # under `python -m tpuic.supervise` the parent sets the heartbeat
    # env; mirror engine activity (serve_batch events) into the file AND
    # tick it from the accept loop — an idle server with no requests is
    # alive, and the watchdog must see that, not a stale file. The
    # flight recorder (telemetry/flight.py) registers its SIGQUIT dump
    # FIRST so the faulthandler stack dump chains into it: the
    # supervisor's hang escalation then captures stacks + the event
    # timeline (serve_batch/admission/slo — memory samples are
    # scrape-side only here, see the sampler below) leading into the
    # wedge.
    from tpuic.runtime.supervisor import (HeartbeatWriter,
                                          install_stack_dump_handler)
    from tpuic.telemetry.flight import install_flight_recorder
    flight = install_flight_recorder()
    heartbeat = HeartbeatWriter.from_env()
    if heartbeat is not None or flight is not None:
        install_stack_dump_handler(chain=flight is not None)
    if heartbeat is not None:
        from tpuic.telemetry.events import bus as _bus
        _bus.subscribe(heartbeat)

    def _beat() -> None:
        if heartbeat is not None:
            heartbeat.beat()

    if slo_tracker is not None:
        # Attaching subscribes for 'serve_span' events, which is exactly
        # what turns the engine's per-request span publishing on
        # (engine._resolve checks bus.active("serve_span")).
        from tpuic.telemetry.events import bus as _slo_bus
        slo_tracker.attach(_slo_bus)

    if admission_ctl is not None:
        # Post-build attach (engine.admission is a public, settable
        # field): submit() now consults brownout + quotas up front.
        engine.admission = admission_ctl
        if admission_ctl.brownout is not None:
            # Brownout rides the same bus the SLO tracker publishes its
            # periodic reports on; its tighten/recover transitions come
            # back as 'admission' events (JSONL/TensorBoard sinks).
            from tpuic.telemetry.events import bus as _adm_bus
            admission_ctl.brownout.attach(_adm_bus)
        print(f"[serve] admission control on: "
              f"{json.dumps(admission_ctl.state())}", file=sys.stderr)

    # Device-memory accounting (telemetry/memory.py): sampled at scrape
    # time (each /metrics hit, each --prom-dump tick, and shutdown) —
    # the serve tier has no step boundary, and a scrape-time metadata
    # read is free of the request path entirely. Deliberately NOT
    # published to the bus: scrapes run in the PromServer thread at the
    # scraper's cadence, and the supervised-liveness heartbeat treats
    # any bus activity as proof of life — an external scraper must not
    # keep a wedged server looking alive to the watchdog.
    from tpuic.telemetry.memory import MemorySampler
    mem_sampler = MemorySampler(publish=lambda *a, **kw: None)

    def _prom_text() -> str:
        mem_sampler.sample()
        return serve_exposition(
            engine.stats.snapshot(),
            heartbeat_age_s=(heartbeat.age_s() if heartbeat is not None
                             else None),
            slo=(slo_tracker.report() if slo_tracker is not None
                 else None),
            admission=(admission_ctl.state() if admission_ctl is not None
                       else None),
            memory=mem_sampler.snapshot(),
            # Device-time attribution (telemetry/profile.py): the
            # largest bucket executable's roofline waterfall, scaled to
            # the span ledger's measured device phase — scrape-time
            # only, never on the request path.
            profile=engine.profile_waterfall())

    prom_server = None
    if args.prom_port:
        prom_server = PromServer(max(0, args.prom_port), _prom_text,
                                 host=args.prom_host)
        print(f"[serve] prometheus /metrics on "
              f"{args.prom_host}:{prom_server.port}", file=sys.stderr)
    # 'flood' injection point (runtime/faults.py): a synthetic
    # low-priority request storm from inside the process, at #PARAM
    # req/s — reproducible overload under the TPUIC_FAULTS grammar, so
    # the admission layer's shedding can be driven (and CI-soaked)
    # without an external load generator.  Storm futures retrieve their
    # own outcomes: sheds and rejections are the point, not log spam.
    import threading as _threading
    flood_stop = _threading.Event()
    if _faults.fire("flood"):
        flood_rate = _faults.param("flood")
        flood_rate = 50.0 if flood_rate is None else float(flood_rate)
        flood_img = np.zeros((1, size, size, 3), engine.input_dtype)

        def _flood() -> None:
            period = 1.0 / max(flood_rate, 1e-3)
            while not flood_stop.is_set() and not guard.triggered:
                try:
                    fut = engine.submit(flood_img, timeout=0,
                                        priority="low", tenant="_flood")
                    fut.add_done_callback(
                        lambda f: f.cancelled() or f.exception())
                except Exception:  # noqa: BLE001 — rejects ARE the test
                    pass
                flood_stop.wait(period)

        _threading.Thread(target=_flood, daemon=True,
                          name="tpuic-flood").start()
        print(f"[serve] fault 'flood' armed: synthetic low-priority "
              f"storm at {flood_rate:g} req/s", file=sys.stderr)

    k = max(1, min(args.top_k, num_classes))
    out = open(args.out, "w") if args.out else sys.stdout
    pending = deque()  # (id, Future) in submission order
    # Control futures (swap lines) drain OUT of order, in their own
    # lane: a checkpoint load + gate takes seconds, and the in-order
    # traffic drain must not head-of-line block every predict answered
    # behind it (responses are keyed by id — order is not part of the
    # control contract).
    control_pending = deque()
    served = 0

    def emit(rid, probs, order) -> None:
        nonlocal served
        out.write(json.dumps(_result_record(rid, probs, order,
                                            names, k)) + "\n")
        out.flush()
        served += 1

    def emit_outcome(rid, res) -> None:
        """One resolved future: a (probs, order) result emits the usual
        record; a dict is a control-line outcome (swap_result) and is
        written as-is — not counted as served traffic."""
        if isinstance(res, dict):
            out.write(json.dumps({**res, "id": rid}) + "\n")
            out.flush()
        else:
            emit(rid, res[0], res[1])

    def drain_control(block: bool = False, deadline: float = None
                      ) -> None:
        """Emit completed control-line outcomes, any order (responses
        are keyed by id — control order is not part of the contract,
        and a seconds-long swap must never head-of-line block traffic
        results).  ``block`` waits each out, bounded by ``deadline``;
        past it the straggler gets an explicit error line — the same
        never-a-silent-drop rule as drain()."""
        still = deque()
        while control_pending:
            rid, fut = control_pending.popleft()
            if not fut.done():
                if not block:
                    still.append((rid, fut))
                    continue
                # Same escalation discipline as drain(): the
                # no-deadline wait polls in short slices re-checking
                # the SIGTERM latch (PEP 475 would resume a bare
                # result() right through the signal — a wedged swap
                # worker would make the server unkillable), and the
                # latch converts the wait into a --drain-timeout
                # deadline.
                if deadline is None:
                    while not fut.done() and not guard.triggered:
                        try:
                            fut.result(timeout=0.5)
                        except (TimeoutError, _FutTimeout):
                            pass
                        except Exception:  # noqa: BLE001
                            break  # done with an exception: read below
                    if not fut.done() and guard.triggered:
                        deadline = (time.monotonic()
                                    + max(0.0, args.drain_timeout))
                try:
                    if deadline is not None and not fut.done():
                        fut.result(timeout=max(
                            0.0, deadline - time.monotonic()))
                except (TimeoutError, _FutTimeout):
                    fut.cancel()
                    out.write(wire.error_line(
                        rid, "drain timeout: swap unresolved at "
                        "shutdown"))
                    out.flush()
                    continue
                except Exception:  # noqa: BLE001 — read below
                    pass
            if fut.cancelled():
                out.write(wire.error_line(rid, "cancelled"))
                out.flush()
            elif fut.exception() is not None:
                out.write(wire.error_line(rid, fut.exception()))
                out.flush()
            else:
                emit_outcome(rid, fut.result())
        control_pending.extend(still)

    def drain(block: bool, deadline: float = None) -> None:
        """Emit completed responses; ``block`` waits for stragglers, up to
        ``deadline`` (time.monotonic()). Past the deadline, requests the
        device DID finish still emit their results (in submission order);
        only genuinely unresolved ones get an explicit error line — never
        a silent drop, never a discarded finished result.

        The no-deadline blocking wait polls in short slices re-checking
        the SIGTERM latch: a plain ``fut.result()`` is resumed after
        signals (PEP 475), so a SIGTERM arriving while draining a wedged
        request at EOF would otherwise never be observed — the latch
        escalates the wait to a ``--drain-timeout`` deadline instead."""
        drain_control()  # opportunistic; the blocking pass runs last
        while pending and (block or pending[0][1].done()):
            rid, fut = pending.popleft()
            try:
                if block and deadline is None:
                    while not fut.done() and not guard.triggered:
                        try:
                            fut.result(timeout=0.5)
                        except (TimeoutError, _FutTimeout):
                            pass
                    if not fut.done() and guard.triggered:
                        # Escalate: persists for the remaining stragglers
                        # (``deadline`` is function-local).
                        deadline = (time.monotonic()
                                    + max(0.0, args.drain_timeout))
                if deadline is None:
                    res = fut.result()
                else:
                    res = fut.result(
                        timeout=max(0.0, deadline - time.monotonic()))
            except (TimeoutError, _FutTimeout):
                pending.appendleft((rid, fut))
                expired = list(pending)
                pending.clear()
                for srid, sfut in expired:
                    if sfut.done() and not sfut.cancelled():
                        try:
                            sres = sfut.result()
                        except Exception as e:  # noqa: BLE001
                            out.write(wire.error_line(srid, e))
                        else:
                            emit_outcome(srid, sres)
                        continue
                    sfut.cancel()  # not-yet-dispatched may still cancel
                    out.write(wire.error_line(
                        srid, "drain timeout: engine shutting down "
                        "before this request finished"))
                out.flush()
                drain_control(block=True, deadline=deadline)
                return
            except Exception as e:  # noqa: BLE001 — per-request error line
                # wire.error_line types the verdict (a pop-time
                # DeadlineExceeded shed, an eviction): cause + class
                # labels match the rejected_total counter — the one
                # encoder all three serve tiers share (wire.py).
                out.write(wire.error_line(rid, e))
                out.flush()
                continue
            except BaseException:
                # KeyboardInterrupt/SystemExit mid-wait: this request is
                # already popped — put it back so the handler's follow-up
                # drain still owns it (never a silent drop).
                pending.appendleft((rid, fut))
                raise
            emit_outcome(rid, res)
        if block:
            # Traffic drained in order; control outcomes last, bounded
            # by the same deadline.
            drain_control(block=True, deadline=deadline)

    def submit(rid: str, path: str, **sla) -> bool:
        """Decode + enqueue; False = decode failed (error line emitted).

        ``sla``: per-request ``priority``/``deadline_ms``/``tenant``
        from the request line.  With --admission the enqueue is
        non-blocking: a typed rejection (queue full / quota / brownout)
        becomes an immediate error line naming its cause instead of the
        accept loop stalling behind a flood."""
        try:
            img = _load_image(path, size)
        except Exception as e:  # noqa: BLE001
            out.write(wire.error_line(rid, f"decode: {e}"))
            out.flush()
            return False
        try:
            if engine.admission is not None:
                sla.setdefault("timeout", 0)
            pending.append((rid, engine.submit(img, **sla)))
        except AdmissionError as e:
            out.write(wire.error_line(rid, e))
            out.flush()
            return True  # the request was handled: verdict delivered
        except (ValueError, TypeError) as e:
            # Bad SLA fields (unknown priority, non-numeric deadline)
            # are the request's problem, not the server's.
            out.write(wire.error_line(rid, e))
            out.flush()
            return True
        drain(block=False)  # opportunistic: decode overlaps device work
        return True

    try:
        if args.listen:
            served = serve_socket(
                engine, listen=args.listen, names=names, top_k=k,
                size=size, guard=guard, beat=_beat,
                drain_timeout=args.drain_timeout,
                ready_file=args.ready_file,
                prom_port=(prom_server.port if prom_server is not None
                           else None),
                log=lambda msg: print(msg, file=sys.stderr))
        elif args.watch:
            exts = (".png", ".jpg", ".jpeg", ".bmp", ".gif", ".webp")
            seen: set = set()
            attempts: dict = {}
            while not guard.triggered:
                fresh = sorted(
                    f for f in os.listdir(args.watch)
                    if f.lower().endswith(exts) and f not in seen)
                for f in fresh:
                    if guard.triggered:
                        break  # stop ACCEPTING; in-flight drains below
                    if submit(f, os.path.join(args.watch, f)):
                        seen.add(f)
                        attempts.pop(f, None)
                    else:
                        # A file mid-copy decodes as truncated; retry on
                        # later ticks, give up (and stop re-erroring)
                        # after 3 — in --once mode immediately, there is
                        # no later tick.
                        attempts[f] = attempts.get(f, 0) + 1
                        if args.once or attempts[f] >= 3:
                            seen.add(f)
                drain(block=False)
                _beat()
                if args.prom_dump:
                    # Per-tick refresh: a textfile collector scraping the
                    # dump sees live counters, not only the final state.
                    # Guarded: monitoring must never take down serving
                    # (disk-full on the textfile path is not our outage).
                    try:
                        write_exposition(args.prom_dump, _prom_text())
                    except OSError as e:
                        print(f"[serve] prom dump failed: {e}",
                              file=sys.stderr)
                if args.once and not fresh and not pending:
                    break
                if args.once:
                    drain(block=True)
                    break
                time.sleep(args.poll_s)
        else:
            def handle(line: str) -> None:
                line = line.strip()
                if not line:
                    return
                try:
                    req = json.loads(line)
                    if not isinstance(req, dict):
                        raise TypeError("not an object")
                    if req.get("op") == "swap":
                        # Control line (docs/serving.md, "Model
                        # lifecycle"): gate + flip off-thread; the
                        # swap_result (or typed verdict) drains on the
                        # CONTROL lane, out of order — a seconds-long
                        # checkpoint load must not head-of-line block
                        # the in-order traffic drain behind it.
                        control_pending.append(
                            (str(req.get("id", "swap")),
                             submit_swap(engine, req,
                                         lambda m: print(
                                             m, file=sys.stderr))))
                        return
                    path = req["path"]
                except (ValueError, KeyError, TypeError):
                    out.write(wire.error_line(
                        None, f"bad request line: {line[:80]}"))
                    out.flush()
                    return
                # Optional SLA fields per request line — honored only
                # under --admission (docs/serving.md): without the
                # operator opt-in, a client self-assigning "high" could
                # evict other clients' queued requests on a server
                # whose policy is plain FIFO.
                sla = {}
                if engine.admission is not None:
                    sla = {k: req[k] for k in ("priority", "deadline_ms",
                                               "tenant") if req.get(k)
                           is not None}
                if req.get("serve_dtype") is not None:
                    # Ladder rung selection (serve_dtype, matching the
                    # socket transport; "dtype" is the wire array
                    # payload's element type); a typo'd rung gets a
                    # typed error line through submit()'s ValueError
                    # arm.
                    sla["dtype"] = str(req["serve_dtype"])
                submit(str(req.get("id", path)), path, **sla)

            # select()-gated RAW reads, not ``for line in sys.stdin``: a
            # signal handler only sets the latch and PEP 475 would resume
            # a blocked readline — an idle server would never observe
            # SIGTERM. With a select timeout the loop re-checks the latch
            # (and opportunistically drains) at least every 200 ms. Raw
            # os.read + explicit line splitting, because Python's stdin
            # buffering would hide burst-written lines from select (the
            # bytes sit in the TextIOWrapper, not at the fd) and stall
            # every request after the first. A non-fd stdin (tests feeding
            # a StringIO) can't select; it reads unguarded, the
            # pre-rewrite behavior.
            import select
            try:
                stdin_fd = sys.stdin.fileno()
            except (ValueError, OSError, AttributeError):
                stdin_fd = None
            if stdin_fd is None:
                for line in sys.stdin:
                    if guard.triggered:
                        break
                    handle(line)
            else:
                tail = b""
                while not guard.triggered:
                    try:
                        ready, _, _ = select.select([stdin_fd], [], [], 0.2)
                    except (OSError, ValueError):  # stdin closed under us
                        break
                    if not ready:
                        drain(block=False)
                        _beat()
                        continue
                    _beat()
                    chunk = os.read(stdin_fd, 1 << 16)  # ready: won't block
                    if not chunk:
                        break  # EOF
                    *lines, tail = (tail + chunk).split(b"\n")
                    for raw in lines:
                        handle(raw.decode("utf-8", "replace"))
                if tail.strip() and not guard.triggered:
                    handle(tail.decode("utf-8", "replace"))  # unterminated last line
        if guard.triggered:
            # Graceful preemption: everything already accepted drains for
            # up to --drain-timeout; stragglers get explicit error lines.
            print(f"[serve] SIGTERM: draining {len(pending)} in-flight "
                  f"request(s) (timeout {args.drain_timeout:.1f}s)",
                  file=sys.stderr)
            drain(block=True,
                  deadline=time.monotonic() + max(0.0, args.drain_timeout))
        else:
            drain(block=True)
    except KeyboardInterrupt:
        drain(block=True,
              deadline=time.monotonic() + max(0.0, args.drain_timeout))
    finally:
        guard.uninstall()
        flood_stop.set()
        engine.close(timeout=max(5.0, args.drain_timeout))
        if prom_server is not None:
            prom_server.close()
        if args.prom_dump:
            try:
                write_exposition(args.prom_dump, _prom_text())
                print(f"[serve] prometheus exposition -> {args.prom_dump}",
                      file=sys.stderr)
            except OSError as e:
                print(f"[serve] prom dump failed: {e}", file=sys.stderr)
        if slo_tracker is not None:
            print(f"[serve] slo: {slo_tracker.summary_line()}",
                  file=sys.stderr)
        if admission_ctl is not None:
            # Attribution companion to the [slo] line: the rejected_by
            # split says whether budget burn came from sheds (deadline /
            # brownout causes) or from slow service (no sheds, blown
            # attainment).  The FULL typed vocabulary is folded in —
            # zero-filled causes included — so a soak ledger attributes
            # every cause (replica_lost, the swap verdicts) from this
            # one line without grepping raw JSONL for causes that
            # happened not to fire.
            from tpuic.serve.admission import CAUSES
            snap = engine.stats.snapshot()
            rej = {c: snap["rejected_by"].get(c, {}) for c in CAUSES}
            rej.update({c: by for c, by in snap["rejected_by"].items()
                        if c not in rej})  # never drop an unknown cause
            print(f"[admission] state={json.dumps(admission_ctl.state())} "
                  f"rejected_by={json.dumps(rej)}",
                  file=sys.stderr)
        print(f"[serve] served {served} requests; stats: "
              f"{json.dumps(engine.stats.snapshot())}", file=sys.stderr)
        if out is not sys.stdout:
            out.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
