"""Serving counters: the numbers that tell you whether batching is working.

Everything the engine's micro-batcher decides leaves a trace here:

- **queue wait** (enqueue -> dispatch) and **total latency** (enqueue ->
  result on host), p50/p95/p99 over a sliding window
  (tpuic.metrics.LatencyMeter — the same primitive the training side's
  meters build on).
- **pad efficiency**: valid rows / device rows.  A stream of size-1
  requests against a 128 bucket reads 0.008 here — the signal to shrink
  the ladder or raise max_wait_ms.
- **batch-size histogram**: device calls per bucket.
- **compiles vs executable-cache hits**: the steady-state-recompiles=0
  contract is asserted against ``compiles`` (tests/test_serve.py) — after
  warmup every device call must be a cache hit.

All updates happen under one lock: the engine touches this from its
batcher thread while callers snapshot from theirs.
"""

from __future__ import annotations

import threading
import time
from typing import Dict

from tpuic.metrics import LatencyMeter

# Re-export shim: the percentile meter is owned by tpuic.metrics.meters
# (ONE implementation shared by serve stats, the telemetry StepTimer,
# and bench.py's per-step spread); ``from tpuic.serve.metrics import
# LatencyMeter`` keeps working for existing callers.  Percentiles are
# nearest-rank, pinned and documented at tpuic.metrics.meters.quantile.
__all__ = ["LatencyMeter", "ServeStats", "SPAN_PHASES"]

# The request span ledger's phase order (docs/observability.md, "Request
# tracing") — cumulative host timestamps through a request's life, so the
# phases sum to the end-to-end latency by construction:
#   queue    submit() -> batcher pops the request off the queue
#   batch    popped -> batch closed (waiting for batchmates / held-over)
#   staging  batch closed -> padded batch assembled (host gather/copy)
#   dispatch staged -> executable call returned (async enqueue)
#   device   dispatched -> device->host readback complete (includes the
#            double-buffer wait behind the previous in-flight batch)
#   scatter  readback -> this request's future resolved (slice + deliver)
SPAN_PHASES = ("queue", "batch", "staging", "dispatch", "device", "scatter")


class ServeStats:
    """Thread-safe counters for one InferenceEngine."""

    def __init__(self, window: int = 8192) -> None:
        self._lock = threading.Lock()
        self._window = window
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.queue_wait = LatencyMeter(self._window)
            self.latency = LatencyMeter(self._window)
            self.spans = {p: LatencyMeter(self._window)
                          for p in SPAN_PHASES}
            self.batch_hist: Dict[int, int] = {}
            self.requests = 0
            self.images = 0
            self.valid_rows = 0
            self.padded_rows = 0
            self.device_calls = 0
            self.compiles = 0
            self.compiles_by_bucket: Dict[int, int] = {}
            self.compile_s = 0.0
            self.cache_hits = 0
            self.rejected = 0
            # cause -> priority -> count (docs/serving.md, "Admission
            # control and overload"): queue_full (backpressure or a
            # priority eviction), deadline (pop-time shed), quota
            # (token bucket dry), brownout (SLO-coupled class shed).
            # ``rejected`` stays the total so pre-admission readers of
            # the snapshot / the bare attribute keep working.
            self.rejected_by: Dict[str, Dict[str, int]] = {}
            self._est = 0.0            # cached estimated_service_s
            self._est_t = float("-inf")
            self._t0 = time.monotonic()
        # Per-bucket compiled-executable cost analysis (engine._compile
        # records it where the runtime exposes cost_analysis):
        # {bucket: {"flops", "bytes", "intensity"}}.  Deliberately a
        # property of the executables, not the measurement window — it
        # is (re)assigned outside the reset-scoped block so reset()
        # between load phases keeps the roofline context.
        if not hasattr(self, "executable_cost"):
            self.executable_cost: Dict[int, dict] = {}
        # Model-lifecycle identity (docs/serving.md, "Model lifecycle"):
        # like executable_cost, a property of the ENGINE rather than the
        # measurement window — reset() between load phases must not
        # erase which weights are serving or how many swaps happened.
        if not hasattr(self, "swaps"):
            self.swaps = 0
            self.generation = 0
            self.model_digest: str = ""

    def note_identity(self, digest: str, generation: int = 0) -> None:
        """Record the BOOT weights' identity (engine construction) —
        no swap happened, the counters stay."""
        with self._lock:
            self.model_digest = str(digest)
            self.generation = int(generation)

    def record_swap(self, generation: int, digest: str) -> None:
        """One completed atomic hot-swap (engine.swap_weights)."""
        with self._lock:
            self.swaps += 1
            self.generation = int(generation)
            self.model_digest = str(digest)

    # -- engine-side updates -------------------------------------------
    def record_compile(self, bucket: int, seconds: float) -> None:
        with self._lock:
            self.compiles += 1
            self.compiles_by_bucket[bucket] = \
                self.compiles_by_bucket.get(bucket, 0) + 1
            self.compile_s += float(seconds)

    def record_cache_hit(self) -> None:
        with self._lock:
            self.cache_hits += 1

    def record_cost(self, bucket: int, flops: float, bytes_: float) -> None:
        """Compiled cost analysis of one bucket executable (flops/bytes
        per call) — the roofline context the exposition renders as
        ``executable_{flops,bytes,intensity}{bucket=...}``."""
        from tpuic.telemetry.goodput import roofline_intensity
        inten = roofline_intensity(flops, bytes_)
        with self._lock:
            self.executable_cost[int(bucket)] = {
                "flops": float(flops), "bytes": float(bytes_),
                "intensity": round(inten, 3) if inten is not None else None}

    def record_reject(self, cause: str = "queue_full",
                      priority: str = "normal") -> None:
        """One rejected/shed request, labeled by cause and priority
        class.  ``accepted + rejected == offered`` is the ledger the
        overload soak asserts exactly: every submit either resolves
        (``requests``) or lands here under exactly one cause."""
        with self._lock:
            self.rejected += 1
            by_prio = self.rejected_by.setdefault(cause, {})
            by_prio[priority] = by_prio.get(priority, 0) + 1

    def record_dispatch(self, bucket: int, valid: int,
                        queue_waits) -> None:
        with self._lock:
            self.device_calls += 1
            self.batch_hist[bucket] = self.batch_hist.get(bucket, 0) + 1
            self.valid_rows += valid
            self.padded_rows += bucket - valid
            for w in queue_waits:
                self.queue_wait.update(w)

    def record_done(self, n_requests: int, n_images: int,
                    latencies) -> None:
        with self._lock:
            self.requests += n_requests
            self.images += n_images
            for lat in latencies:
                self.latency.update(lat)

    def record_spans(self, spans) -> None:
        """One request's span ledger (seconds, SPAN_PHASES order)."""
        with self._lock:
            for phase, s in zip(SPAN_PHASES, spans):
                self.spans[phase].update(s)

    # -- reads ---------------------------------------------------------
    def estimated_service_s(self) -> float:
        """Rolling estimate of the service time a *popped* request still
        has ahead of it — the span ledger's p50s for every post-queue
        phase (batch formation, staging, dispatch, device, scatter).
        The deadline shedder uses it at pop time: a request whose
        deadline will expire inside this estimate cannot make it, so
        dispatching it would waste a batch slot on a dead answer.
        0.0 until the ledger has samples (a cold engine sheds only
        already-expired deadlines — it has no evidence to predict with).

        Cached for ``max_age_s`` (the nearest-rank quantile sorts its
        window): the batcher calls this once per pop, and a 50 ms-stale
        estimate is far inside the noise of the thing it estimates.
        """
        max_age_s = 0.05
        with self._lock:
            now = time.monotonic()
            if now - self._est_t < max_age_s:
                return self._est
            est = 0.0
            for phase in SPAN_PHASES:
                if phase == "queue":
                    continue  # already behind a popped request
                p50 = self.spans[phase].quantile_s(50)
                if p50 is not None:
                    est += p50
            self._est, self._est_t = est, now
            return est

    def pad_efficiency_rows(self) -> tuple:
        """(valid_rows, padded_rows) so far."""
        with self._lock:
            return self.valid_rows, self.padded_rows

    def snapshot(self) -> dict:
        """One JSON-able dict of everything above (plus derived rates)."""
        with self._lock:
            elapsed = max(1e-9, time.monotonic() - self._t0)
            rows = self.valid_rows + self.padded_rows
            return {
                "requests": self.requests,
                "images": self.images,
                "device_calls": self.device_calls,
                "throughput_images_per_sec": round(self.images / elapsed, 2),
                "queue_wait_ms": self.queue_wait.percentiles_ms(),
                "latency_ms": self.latency.percentiles_ms(),
                "span_ms": {p: m.percentiles_ms((50, 99))
                            for p, m in self.spans.items() if m.count},
                "batch_hist": {str(k): v for k, v in
                               sorted(self.batch_hist.items())},
                "pad_efficiency": round(self.valid_rows / rows, 4)
                                  if rows else None,
                "compiles": self.compiles,
                "compiles_by_bucket": {str(k): v for k, v in
                                       sorted(self.compiles_by_bucket
                                              .items())},
                "compile_s": round(self.compile_s, 3),
                "executable_cache_hits": self.cache_hits,
                "rejected": self.rejected,
                "rejected_by": {c: dict(sorted(p.items())) for c, p in
                                sorted(self.rejected_by.items())},
                "executable_cost": {str(k): dict(v) for k, v in
                                    sorted(self.executable_cost.items())},
                "swaps": self.swaps,
                "generation": self.generation,
                "model_digest": self.model_digest,
                "elapsed_s": round(elapsed, 3),
            }
