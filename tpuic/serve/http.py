"""Minimal HTTP front-end for the replica router (ROADMAP item 3's
front-end bullet, kept deliberately small).

Stdlib ``ThreadingHTTPServer`` over a :class:`~tpuic.serve.router.Router`
(docs/serving.md, "Replica routing and failover"):

- ``POST /predict`` — body is one JSON request line (the same shape the
  stdin/socket transports accept: ``{"path": ...}`` or a
  ``{"b64", "shape", "dtype"}`` payload, optional SLA fields).  A
  result returns 200 with the usual record; a **typed verdict** maps to
  an HTTP status a load balancer understands, with ``Retry-After``:

  ====================  ======  =============================
  cause                 status  meaning to the caller
  ====================  ======  =============================
  ``queue_full``        429     back off, the fleet is saturated
  ``quota``             429     your tenant is over its budget
  ``brownout``          503     shedding your class to protect the SLO
  ``deadline``          503     your deadline passed before service
  ``replica_lost``      503     safe to retry end-to-end (at-most-once
                                held: no response was emitted)
  ====================  ======  =============================

  The JSON body carries the same ``{"error", "cause", "priority"}``
  record the socket tier emits (tpuic/serve/wire.py — one vocabulary,
  three transports).  Untyped failures are 500.
- ``GET /healthz`` — 200 ``{"status": "ok", ...}`` while at least one
  replica is up, else 503 ``{"status": "down"}`` (a load balancer's
  eject signal).
- ``GET /metrics`` — the ``tpuic_router_*`` Prometheus exposition
  (telemetry/prom.py ``router_exposition``).

Stdlib-only (the router-process rule).  One OS thread per in-flight
HTTP request (ThreadingHTTPServer) — the router behind it is
non-blocking, so threads spend their life parked on a Future; the
admission tiers bound how many requests are genuinely in flight.
"""

from __future__ import annotations

import json
import threading
from concurrent.futures import TimeoutError as _FutTimeout
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from tpuic.serve import wire
from tpuic.serve.admission import AdmissionError

# Typed verdict -> HTTP status (module docstring table).  Unknown typed
# causes (vocabulary growth) conservatively map to 503: retryable-ish,
# and never a silent 200.
CAUSE_STATUS = {
    "queue_full": 429,
    "quota": 429,
    "brownout": 503,
    "deadline": 503,
    "replica_lost": 503,
}


class RouterHTTPServer:
    """HTTP front tier over a Router; ``port=0`` = kernel-assigned.

    ``result_timeout_s`` bounds how long one HTTP request waits for the
    fleet; past it the caller gets 503 + Retry-After (the request's
    future keeps its at-most-once accounting inside the router)."""

    def __init__(self, router, port: int = 0, host: str = "127.0.0.1",
                 result_timeout_s: float = 60.0,
                 retry_after_s: int = 1) -> None:
        self.router = router
        self.result_timeout_s = float(result_timeout_s)
        self.retry_after_s = int(retry_after_s)
        outer = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def _reply(self, status: int, payload: dict,
                       retry_after: Optional[int] = None) -> None:
                body = (json.dumps(payload) + "\n").encode()
                self.send_response(status)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                if retry_after is not None:
                    self.send_header("Retry-After", str(retry_after))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self) -> None:  # noqa: N802 — http.server API
                if self.path == "/healthz":
                    outer._healthz(self)
                elif self.path == "/metrics":
                    outer._metrics(self)
                else:
                    self._reply(404, {"error": "not found"})

            def do_POST(self) -> None:  # noqa: N802 — http.server API
                if self.path != "/predict":
                    self._reply(404, {"error": "not found"})
                    return
                outer._predict(self)

            def log_message(self, *a) -> None:
                pass  # stderr belongs to the router's own logs

        self._srv = ThreadingHTTPServer((host, int(port)), Handler)
        self._srv.daemon_threads = True
        self.port = self._srv.server_address[1]
        self.host = host
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True,
                                        name="tpuic-router-http")
        self._thread.start()

    # -- endpoints ------------------------------------------------------
    def _healthz(self, h) -> None:
        up = sum(r.state == "up" for r in self.router.replicas)
        payload = {
            "status": "ok" if up else "down",
            "replicas_up": up,
            "replicas": len(self.router.replicas),
            "fleet_digest": self.router.fleet_digest,
        }
        h._reply(200 if up else 503, payload,
                 retry_after=None if up else self.retry_after_s)

    def _metrics(self, h) -> None:
        from tpuic.telemetry.prom import router_exposition
        text = router_exposition(self.router.snapshot()).encode()
        h.send_response(200)
        h.send_header("Content-Type", "text/plain; version=0.0.4")
        h.send_header("Content-Length", str(len(text)))
        h.end_headers()
        h.wfile.write(text)

    def _predict(self, h) -> None:
        try:
            n = int(h.headers.get("Content-Length", 0))
            req = json.loads(h.rfile.read(n).decode("utf-8", "replace"))
            if not isinstance(req, dict):
                raise ValueError("not an object")
        except (ValueError, OSError) as e:
            h._reply(400, {"error": f"bad request body: {e}"})
            return
        rid = str(req.get("id", "http"))
        try:
            _, fut = self.router.submit_line(req)
            rec = fut.result(timeout=self.result_timeout_s)
        except AdmissionError as e:
            status = CAUSE_STATUS.get(e.cause, 503)
            h._reply(status, wire.error_record(rid, e),
                     retry_after=self.retry_after_s)
            return
        except (ValueError, TypeError) as e:
            # The request's problem, not the server's (unknown
            # priority, a control 'op' line on the data path, bad SLA
            # fields): 400, so a load balancer counting 5xx toward
            # replica health never ejects a healthy fleet over a
            # malformed client.
            h._reply(400, wire.error_record(rid, e))
            return
        except (TimeoutError, _FutTimeout):
            h._reply(503, wire.error_record(
                rid, f"no response within {self.result_timeout_s:g}s"),
                retry_after=self.retry_after_s)
            return
        except Exception as e:  # noqa: BLE001 — untyped = server error
            h._reply(500, wire.error_record(rid, e))
            return
        h._reply(200, {**rec, "id": rid})

    def close(self) -> None:
        try:
            self._srv.shutdown()
            self._srv.server_close()
        except OSError:
            pass
        self._thread.join(timeout=2.0)
