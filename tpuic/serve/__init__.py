"""tpuic.serve — dynamic-batching AOT inference engine.

Online serving counterpart of the training loop's saturate-the-chip
design: a bounded request queue + micro-batcher coalesces caller
requests into a handful of pre-compiled fixed-shape device calls
(padding buckets), with zero steady-state recompiles and per-request
latency/throughput accounting.

    from tpuic.serve import InferenceEngine
    eng = InferenceEngine(model, variables, image_size=224,
                          buckets=(1, 8, 32, 128))
    eng.warmup()                      # AOT: one compile per bucket
    fut = eng.submit(images_u8)       # [n,S,S,3] -> Future
    probs, order = fut.result()

``python -m tpuic.serve`` runs the stdin-JSONL / directory-watch driver
(tpuic/serve/__main__.py) — no network dependency.
"""

from tpuic.serve.admission import (PRIORITIES, AdmissionController,  # noqa: F401
                                   AdmissionError, AdmissionRejected,
                                   BrownoutController, DeadlineExceeded,
                                   TokenBucket, parse_quotas)
from tpuic.serve.engine import (DEFAULT_BUCKETS, InferenceEngine,  # noqa: F401
                                default_buckets, make_forward)
from tpuic.serve.metrics import ServeStats  # noqa: F401
