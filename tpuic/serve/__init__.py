"""tpuic.serve — dynamic-batching AOT inference engine + replica router.

Online serving counterpart of the training loop's saturate-the-chip
design: a bounded request queue + micro-batcher coalesces caller
requests into a handful of pre-compiled fixed-shape device calls
(padding buckets), with zero steady-state recompiles and per-request
latency/throughput accounting.

    from tpuic.serve import InferenceEngine
    eng = InferenceEngine(model, variables, image_size=224,
                          buckets=(1, 8, 32, 128))
    eng.warmup()                      # AOT: one compile per bucket
    fut = eng.submit(images_u8)       # [n,S,S,3] -> Future
    probs, order = fut.result()

``python -m tpuic.serve`` runs the stdin-JSONL / directory-watch /
socket-JSONL driver (tpuic/serve/__main__.py); ``python -m
tpuic.serve.router`` runs N such replicas behind a health-checked,
breaker-guarded front tier (tpuic/serve/router.py, docs/serving.md
"Replica routing and failover").

Re-exports resolve lazily (PEP 562, the tpuic/__init__.py idiom): the
router and the admission/wire modules are stdlib-only, and importing
this package from the router process must not pull the engine's
numpy/jax stack into a parent that has to outlive a backend wedge.
"""

from __future__ import annotations

_LAZY = {
    # admission (stdlib-only module)
    "PRIORITIES": ("tpuic.serve.admission", "PRIORITIES"),
    "AdmissionController": ("tpuic.serve.admission", "AdmissionController"),
    "AdmissionError": ("tpuic.serve.admission", "AdmissionError"),
    "AdmissionRejected": ("tpuic.serve.admission", "AdmissionRejected"),
    "BrownoutController": ("tpuic.serve.admission", "BrownoutController"),
    "DeadlineExceeded": ("tpuic.serve.admission", "DeadlineExceeded"),
    "ReplicaLost": ("tpuic.serve.admission", "ReplicaLost"),
    "TokenBucket": ("tpuic.serve.admission", "TokenBucket"),
    "parse_quotas": ("tpuic.serve.admission", "parse_quotas"),
    # engine (numpy + lazy jax)
    "DEFAULT_BUCKETS": ("tpuic.serve.engine", "DEFAULT_BUCKETS"),
    "InferenceEngine": ("tpuic.serve.engine", "InferenceEngine"),
    "default_buckets": ("tpuic.serve.engine", "default_buckets"),
    "make_forward": ("tpuic.serve.engine", "make_forward"),
    # metrics
    "ServeStats": ("tpuic.serve.metrics", "ServeStats"),
    # router (stdlib-only module)
    "Router": ("tpuic.serve.router", "Router"),
    "RouterStats": ("tpuic.serve.router", "RouterStats"),
    "CircuitBreaker": ("tpuic.serve.router", "CircuitBreaker"),
    "RetryBudget": ("tpuic.serve.router", "RetryBudget"),
    # model lifecycle (stdlib-only modules)
    "CanaryRollout": ("tpuic.serve.rollout", "CanaryRollout"),
    "RouterHTTPServer": ("tpuic.serve.http", "RouterHTTPServer"),
    "SwapRejected": ("tpuic.serve.admission", "SwapRejected"),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib
        module, attr = _LAZY[name]
        value = getattr(importlib.import_module(module), attr)
        globals()[name] = value  # cache: next access skips the import
        return value
    raise AttributeError(f"module 'tpuic.serve' has no attribute '{name}'")


def __dir__():
    return sorted(set(list(globals()) + list(_LAZY)))
