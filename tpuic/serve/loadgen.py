"""Shared load-generation harness for driving an InferenceEngine.

One paced submission driver and one counter-settling wait, used by BOTH
``bench_serve.py`` (closed-loop curves + the open-loop Poisson sweep)
and the perf-regression gate (``tpuic.telemetry.regress``) — a fix to
the pacing or settling logic lands in every consumer, so the gate and
the benchmark cannot silently measure different things.
"""

from __future__ import annotations

import time
from typing import Optional, Sequence, Tuple


def settle(stats, n: int, timeout_s: float = 2.0) -> dict:
    """Wait (bounded) for ``stats`` to have recorded ``n`` requests,
    then return the snapshot.

    Futures resolve BEFORE the batcher's ``record_done`` runs, so a
    caller that snapshots the instant its last result lands can be
    short the final batch's counters."""
    deadline = time.perf_counter() + timeout_s
    while (stats.snapshot()["requests"] < n
           and time.perf_counter() < deadline):
        time.sleep(0.01)
    return stats.snapshot()


def run_stream(engine, reqs: Sequence, *,
               offsets_s: Optional[Sequence[float]] = None,
               result_timeout_s: float = 600.0) -> Tuple[float, float, dict]:
    """Submit every request, wait for every result, settle the counters.

    ``offsets_s[i]`` is request *i*'s target submit time relative to the
    first submit — ``None`` offers the stream as fast as possible,
    ``[i / rate ...]`` is a closed-loop paced curve, cumulative
    exponential gaps make a Poisson open-loop arrival process.  The
    driver never waits on results until the whole stream is submitted
    (at deep saturation the engine's bounded queue blocks ``submit()``
    itself, which shows up honestly as achieved < offered).

    Returns ``(wall_s, arrival_s, snapshot)``: first submit -> last
    result, first submit -> last submit, and the settled stats.
    ``engine.stats`` is reset first, so ``snapshot["compiles"]`` is
    exactly the executables built during this run."""
    engine.stats.reset()
    futs = [None] * len(reqs)
    t0 = time.perf_counter()
    for i, r in enumerate(reqs):
        if offsets_s is not None:
            delay = t0 + offsets_s[i] - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
        futs[i] = engine.submit(r)
    arrival_s = time.perf_counter() - t0
    for f in futs:
        f.result(timeout=result_timeout_s)
    wall = time.perf_counter() - t0
    return wall, arrival_s, settle(engine.stats, len(reqs))
