"""Shared load-generation harness for driving a serve endpoint.

One paced submission driver and one counter-settling wait, used by
``bench_serve.py`` (closed-loop curves + the open-loop Poisson sweep),
the perf-regression gate (``tpuic.telemetry.regress``), the CI
overload soak (``scripts/overload_soak.py``), AND the router soak
(``scripts/router_soak.py``) — a fix to the pacing or settling logic
lands in every consumer, so the gate, the benchmarks, and the soaks
cannot silently measure different things.

**Endpoint-aware**: the drive targets anything implementing the
endpoint protocol — ``submit(item, **kw) -> Future`` plus a ``stats``
object with ``reset()``/``snapshot()`` whose snapshot keeps the exact
offered-traffic ledger.  An ``InferenceEngine`` and a
``tpuic.serve.router.Router`` both qualify, so the same harness drives
one engine in-process or a whole replica fleet over sockets.

Workload items may carry per-request SLA fields: a bare array submits
plainly; an ``(array, kwargs)`` pair forwards ``kwargs`` to
``engine.submit`` (``priority``/``deadline_ms``/``tenant``/``timeout``
— docs/serving.md, "Admission control and overload").  Typed admission
verdicts are part of the measurement, not an error: a submit-time
``AdmissionError`` (quota/brownout/queue-full with ``timeout=0``) or a
future resolving with one (a pop-time deadline shed) is counted and the
drive continues — the engine's ``rejected_by`` counters carry the
breakdown, and ``accepted + rejected == offered`` stays exact.
"""

from __future__ import annotations

import queue
import time
from typing import Callable, Optional, Sequence, Tuple

from tpuic.serve.admission import AdmissionError


def settle(stats, n: int, timeout_s: float = 2.0) -> dict:
    """Wait (bounded) for ``stats`` to have recorded ``n`` resolved
    requests, then return the snapshot.

    Futures resolve BEFORE the batcher's ``record_done`` runs, so a
    caller that snapshots the instant its last result lands can be
    short the final batch's counters."""
    deadline = time.perf_counter() + timeout_s
    while (stats.snapshot()["requests"] < n
           and time.perf_counter() < deadline):
        time.sleep(0.01)
    return stats.snapshot()


def probe_unbatched_rps(engine, reqs: Sequence,
                        probe_n: int = 16) -> Tuple[float, float,
                                                    float, float]:
    """Sequential single-request capacity probe: submit one, wait,
    repeat — the service rate with no batching to hide behind.

    A sequential ``predict()`` sits in batch formation for the full
    ``max_wait`` (empty queue, rows < max_batch) — a coalescing stall,
    not service — so the probe's own span ledger's queue + batch p50s
    are stripped from the raw per-request time.  This is THE rate
    anchor: bench_serve's open-loop sweep and the CI overload soak both
    call it, so the gate and the benchmark cannot anchor to different
    capacity numbers.  Resets ``engine.stats``.

    Returns ``(unbatched_rps, service_s, probe_raw_s, stall_s)``."""
    engine.stats.reset()
    n = max(1, min(probe_n, len(reqs)))
    t0 = time.perf_counter()
    for r in reqs[:n]:
        engine.predict(r)
    probe_raw_s = (time.perf_counter() - t0) / n
    span = engine.stats.snapshot()["span_ms"]
    stall_s = (span.get("queue", {}).get("p50", 0.0)
               + span.get("batch", {}).get("p50", 0.0)) / 1000.0
    service_s = max(probe_raw_s - stall_s, 1e-6)
    return 1.0 / service_s, service_s, probe_raw_s, stall_s


def probe_batched_rps(engine, reqs: Sequence, probe_n: int = 400) -> float:
    """Full-batching burst-capacity probe: offer a burst as fast as
    possible (run_stream, no pacing) and return requests/sec.

    The OTHER half of the dual anchor (docs/serving.md): micro-batching
    lets the engine sustain several times the unbatched rate, so an
    overload drive anchored only to ``probe_unbatched_rps`` can sit
    BELOW true capacity on a fast machine.  The CI overload soak and
    bench_serve both record it next to the unbatched probe, so a
    container-speed wobble in the committed knee is diagnosable from
    the artifact instead of silently absorbed.  Resets ``engine.stats``
    (via run_stream)."""
    n = max(1, min(int(probe_n), len(reqs)))
    t0 = time.perf_counter()
    run_stream(engine, reqs[:n])
    return n / max(time.perf_counter() - t0, 1e-9)


def run_stream(engine, reqs: Sequence, *,
               offsets_s: Optional[Sequence[float]] = None,
               result_timeout_s: float = 600.0,
               on_done: Optional[Callable] = None,
               on_retry: Optional[Callable] = None
               ) -> Tuple[float, float, dict]:
    """Submit every item, wait for every outcome, settle the counters.

    ``reqs[i]`` is an image array or an ``(array, submit_kwargs)`` pair.
    ``offsets_s[i]`` is item *i*'s target submit time relative to the
    first submit — ``None`` offers the stream as fast as possible,
    ``[i / rate ...]`` is a closed-loop paced curve, cumulative
    exponential gaps make a Poisson open-loop arrival process.  The
    driver never waits on results until the whole stream is submitted
    (at deep saturation the engine's bounded queue blocks ``submit()``
    itself, which shows up honestly as achieved < offered — unless the
    item carries ``timeout=0``, in which case the rejection — typed
    when an AdmissionController is attached, bare ``queue.Full``
    otherwise — is counted instead).

    ``on_done(i, ok, latency_s)``: optional per-item outcome hook,
    called the instant item *i* settles — from the batcher thread for
    resolved/shed futures (a completion stamp undistorted by this
    driver's own result-wait loop), inline for submit-time rejections
    (``ok=False, latency_s=None``).  The overload soak's per-class p99
    accounting rides this instead of duplicating the pacing loop.

    ``on_retry(i, retries)``: optional retry outcome hook, fired
    alongside ``on_done`` for items whose future carries a nonzero
    ``tpuic_retries`` stamp — the endpoint contract the router uses to
    report that item *i* was replayed ``retries`` times after a
    replica loss.  An engine endpoint never stamps it, so the hook is
    free there; the router soak's failover accounting rides this
    instead of growing its own pacing loop.

    Returns ``(wall_s, arrival_s, snapshot)``: first submit -> last
    outcome, first submit -> last submit, and the settled stats.
    ``engine.stats`` is reset first, so ``snapshot["compiles"]`` is
    exactly the executables built during this run and
    ``snapshot["requests"] + snapshot["rejected"] == len(reqs)`` is the
    exact offered-traffic ledger."""
    engine.stats.reset()
    futs = [None] * len(reqs)
    t0 = time.perf_counter()
    for i, item in enumerate(reqs):
        arr, kw = item if isinstance(item, tuple) else (item, None)
        if offsets_s is not None:
            delay = t0 + offsets_s[i] - time.perf_counter()
            if delay > 0:
                time.sleep(delay)
        ts = time.perf_counter()
        try:
            fut = engine.submit(arr, **(kw or {}))
        except (AdmissionError, queue.Full):
            # Submit-time verdict (typed quota/brownout/queue-full, or
            # the bare backpressure Full of a controller-less engine):
            # already recorded in stats.rejected_by by the engine; the
            # drive goes on — shed rate is a measurement, not a failure.
            if on_done is not None:
                on_done(i, False, None)
            continue
        futs[i] = fut
        if on_done is not None or on_retry is not None:
            def _settled(f, i=i, ts=ts):
                if on_retry is not None:
                    retries = getattr(f, "tpuic_retries", 0)
                    if retries:
                        on_retry(i, retries)
                if on_done is not None:
                    on_done(i, not f.cancelled() and f.exception() is None,
                            time.perf_counter() - ts)
            fut.add_done_callback(_settled)
    arrival_s = time.perf_counter() - t0
    resolved = 0
    for f in futs:
        if f is None:
            continue
        try:
            f.result(timeout=result_timeout_s)
            resolved += 1
        except AdmissionError:
            pass  # pop-time shed (DeadlineExceeded) / eviction: counted
    wall = time.perf_counter() - t0
    return wall, arrival_s, settle(engine.stats, resolved)
