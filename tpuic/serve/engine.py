"""Dynamic-batching AOT inference engine.

The training side learned (PERF_ANALYSIS §1) that accelerator throughput
comes from few, large, *fixed-shape* device calls; ``tpuic.predict``'s
per-caller ``jax.jit`` forward violates all three for online traffic —
every distinct request size is a fresh trace+compile and every request is
a separate device call.  This engine sits between callers and the model
and restores the invariant:

- **Micro-batcher**: a bounded request queue (backpressure: ``submit``
  blocks or raises ``queue.Full`` when the server is saturated) feeds one
  batcher thread that coalesces FIFO requests until ``max_batch`` rows
  are ready or ``max_wait_ms`` has passed since the batch opened —
  whichever comes first.
- **Padding buckets**: every device call is padded up to one of a small
  ladder of shapes (default 1/8/32/128), so the executable count is
  ``len(buckets)``, not ``len(distinct request sizes)``.  Padding rows
  are sliced off the results before futures resolve — they can never
  leak into a caller's view.
- **AOT executable cache**: ``warmup()`` lowers and compiles every
  (model, bucket) pair once via ``jax.jit(...).lower(...).compile()``
  and the batcher only ever calls those executables — zero steady-state
  recompiles, asserted by test and counted by ``stats.compiles``.  With
  a persistent ``jax_compilation_cache_dir`` configured (conftest/bench
  already do), warmup itself is a disk hit after the first process.
- **Double-buffered staging**: the batcher assembles + dispatches batch
  N+1 (host gather, pad, H2D, executable call — all async under JAX's
  dispatch model) *before* blocking on batch N's device->host readback,
  the same overlap idiom as data/device_prep's resident loader.
- **Counters**: tpuic.serve.metrics.ServeStats (queue wait, pad
  efficiency, bucket histogram, latency percentiles, compile/cache-hit
  counts) — ``engine.stats.snapshot()`` is one JSON-able dict.

The forward contract: ``forward(variables, images[B,S,S,C]) -> pytree``
whose leaves all carry the batch dim first.  The default forward is
predict's — softmax probs + class order.  Results resolve per request as
the same pytree sliced to the request's rows.

CPU/TPU-agnostic: nothing here is device-specific, so tier-1 covers the
whole engine on the 8-fake-CPU test topology.
"""

from __future__ import annotations

import itertools
import queue
import threading
import time
from collections import deque
from concurrent.futures import Future
from typing import Optional, Sequence

import numpy as np

from tpuic.runtime import faults as _faults
from tpuic.serve.admission import (DEFAULT_PRIORITY, PRIORITIES,
                                   AdmissionRejected, DeadlineExceeded,
                                   priority_index)
from tpuic.serve.metrics import SPAN_PHASES, ServeStats
from tpuic.telemetry.events import bus as _tm_bus
from tpuic.telemetry.events import publish as _tm_publish

DEFAULT_BUCKETS = (1, 8, 32, 128)


def default_buckets(max_batch: int) -> tuple:
    """Bucket ladder for a known caller batch size: ``max_batch`` and
    /4 steps down to 1 (e.g. 64 -> (1, 4, 16, 64)).  Keeps worst-case
    pad waste at 4x while holding the executable count at ~log4(B)."""
    b, out = max(1, int(max_batch)), []
    while b > 1:
        out.append(b)
        b = max(1, b // 4)
    out.append(1)
    return tuple(sorted(set(out)))


def make_forward(model, *, normalize: bool = False, mean=None, std=None):
    """predict's forward as an engine-compatible function.

    ``normalize=True`` folds uint8 -> (x/255 - mean)/std into the
    compiled program (serving raw images ships 4x fewer H2D bytes —
    the device_prep lesson applied to inference)."""
    import jax
    import jax.numpy as jnp

    from tpuic.data.transforms import IMAGENET_MEAN, IMAGENET_STD
    m = jnp.asarray(IMAGENET_MEAN if mean is None else mean, jnp.float32)
    s = jnp.asarray(IMAGENET_STD if std is None else std, jnp.float32)

    def forward(variables, images):
        x = images
        if normalize:
            x = (x.astype(jnp.float32) / 255.0 - m) / s
        logits = model.apply(variables, x, train=False)
        probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
        order = jnp.argsort(-probs, axis=-1)
        return probs, order

    return forward


class _Request:
    """One submitted request plus its trace: a monotonically-assigned
    trace id and the cumulative host-side timestamps the span ledger is
    computed from (docs/observability.md, "Request tracing").  Stamps are
    ``time.monotonic()`` reads — no device interaction, ever."""

    __slots__ = ("images", "n", "future", "trace", "priority", "pidx",
                 "tenant", "deadline", "t_enqueue", "t_gather", "variant")

    def __init__(self, images: np.ndarray, future: Future,
                 trace: int = 0, priority: str = DEFAULT_PRIORITY,
                 tenant: Optional[str] = None,
                 deadline_ms: Optional[float] = None,
                 variant: str = "fp32") -> None:
        self.images = images
        self.n = images.shape[0]
        self.future = future
        self.trace = trace
        self.priority = priority
        self.pidx = priority_index(priority)
        self.tenant = tenant
        self.variant = variant
        self.t_enqueue = time.monotonic()
        self.t_gather = self.t_enqueue  # stamped when the batcher pops it
        # Absolute monotonic deadline; None = the caller waits forever.
        self.deadline = (None if deadline_ms is None
                         else self.t_enqueue + float(deadline_ms) / 1000.0)


#: Default per-engine registry namespace suffix — unique per instance so
#: two engines never alias executables unless a caller explicitly claims
#: program identity via ``cache_tag``.
_ENGINE_SEQ = itertools.count()


def _tree_digest(variables) -> str:
    """Content digest of a variables tree: CRC32 folded over every
    leaf's path, shape, dtype, and bytes — 8 hex chars.  This is the
    model-identity tag the ready-file/ping protocol carries
    (docs/serving.md, "Model lifecycle"): two replicas with the same
    digest serve bitwise-identical weights, so the router can refuse a
    silently-heterogeneous fleet.  One host pass over the tree —
    swap/startup-time only, never the request path."""
    import zlib

    import jax
    crc = 0
    leaves = jax.tree_util.tree_flatten_with_path(variables)[0]
    for path, leaf in leaves:
        arr = np.asarray(leaf)  # tpuic-ok: TPU101 one-time identity hash at swap/startup, not a hot path
        head = f"{jax.tree_util.keystr(path)}|{arr.shape}|{arr.dtype}|"
        crc = zlib.crc32(head.encode(), crc)
        crc = zlib.crc32(np.ascontiguousarray(arr).tobytes(), crc)
    return f"{crc & 0xFFFFFFFF:08x}"


def _tree_avals(variables):
    """Hashable (path, shape, dtype) signature of a tree — the
    executable-compatibility key (tpuic.compiled.tree_avals; kept as a
    module name here because swap/candidate call sites and their tests
    predate the registry)."""
    from tpuic.compiled import tree_avals
    return tree_avals(variables)


class _Generation:
    """One immutable serving generation (docs/serving.md, "Model
    lifecycle: hot-swap, canary, rollback"): the variant map
    ``{tag: (forward, device-resident variables)}`` plus the registry
    keys (tpuic.compiled) its AOT executables live under.  The engine
    holds exactly one live reference (``engine._gen``); ``swap_weights``
    builds the next generation completely off-path — staged on device,
    executables reused or prewarmed in the registry — and then flips
    that single reference, so a device batch (which reads the reference
    once, at dispatch) is all-old or all-new, never mixed, and nothing
    ever drains.

    ``program_gen`` is the registry generation the keys carry: it is
    SHARED with the previous serving generation when the new trees are
    aval-identical and no forward was replaced (the executables take
    variables as call arguments — same shapes/dtypes means the same
    keys, zero recompiles), and bumped otherwise, so retiring the old
    program generation GCs exactly the superseded executables."""

    __slots__ = ("variants", "keys", "program_gen", "generation", "digest")

    def __init__(self, variants: dict, keys: dict, program_gen: int,
                 generation: int, digest: str) -> None:
        self.variants = variants
        self.keys = keys          # {(variant, bucket): ProgramKey}
        self.program_gen = program_gen
        self.generation = generation
        self.digest = digest


class _PriorityQueue:
    """Bounded multi-class FIFO (docs/serving.md, "Admission control and
    overload"): one lane per priority class, ``get`` pops the highest
    populated class first and FIFO within it, so under contention
    high-priority requests are batched first.  ``put`` on a full queue
    may **evict** the youngest request of the lowest populated class
    that is strictly below the arrival's — under overload the flood
    waits (or sheds), never the traffic with an SLO.  All-one-class
    traffic degrades to exactly the old bounded FIFO: nothing is ever
    evicted by its own class, and ``queue.Full``/``queue.Empty`` keep
    the stdlib semantics callers already handle."""

    def __init__(self, maxsize: int) -> None:
        self._maxsize = max(1, int(maxsize))
        self._lock = threading.Lock()
        self._not_empty = threading.Condition(self._lock)
        self._not_full = threading.Condition(self._lock)
        self._lanes = tuple(deque() for _ in PRIORITIES)
        self._size = 0

    def qsize(self) -> int:
        with self._lock:
            return self._size

    def empty(self) -> bool:
        return self.qsize() == 0

    def _evict_locked(self, pidx: int) -> Optional[_Request]:
        """Youngest request of the lowest class strictly below ``pidx``
        (None when every queued request is >= the arrival's class)."""
        for lane in reversed(self._lanes[pidx + 1:]):
            if lane:
                self._size -= 1
                return lane.pop()
        return None

    def put(self, req: _Request,
            timeout: Optional[float] = None) -> Optional[_Request]:
        """Enqueue ``req``; returns the evicted lower-priority request
        when admission came at someone else's expense (the caller owns
        failing its future — this class never touches futures).
        ``timeout=None`` blocks, ``0`` raises ``queue.Full`` at once,
        else waits that long — only when no eviction candidate exists."""
        with self._not_full:
            deadline = (None if timeout is None
                        else time.monotonic() + max(0.0, timeout))
            while self._size >= self._maxsize:
                victim = self._evict_locked(req.pidx)
                if victim is not None:
                    self._lanes[req.pidx].append(req)
                    self._size += 1
                    self._not_empty.notify()
                    return victim
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise queue.Full
                self._not_full.wait(remaining)
            self._lanes[req.pidx].append(req)
            self._size += 1
            self._not_empty.notify()
            return None

    def put_nowait(self, req: _Request) -> Optional[_Request]:
        return self.put(req, timeout=0)

    def get(self, timeout: Optional[float] = None) -> _Request:
        with self._not_empty:
            deadline = (None if timeout is None
                        else time.monotonic() + max(0.0, timeout))
            while self._size == 0:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    raise queue.Empty
                self._not_empty.wait(remaining)
            for lane in self._lanes:
                if lane:
                    self._size -= 1
                    self._not_full.notify()
                    return lane.popleft()
            raise queue.Empty  # unreachable: _size > 0 implies a lane

    def get_nowait(self) -> _Request:
        return self.get(timeout=0)


class InferenceEngine:
    """Queue + micro-batcher + bucketed AOT executables around one model.

    Parameters
    ----------
    model, variables : the Flax module and its inference variables
        ({'params': ..., 'batch_stats': ...}); ``forward_fn`` overrides
        the default ``make_forward(model)`` entirely (then ``model`` may
        be None).
    image_size, channels, input_dtype : the fixed per-row shape/dtype
        every request must carry — [n, S, S, C] of ``input_dtype``.
    buckets : padding ladder; the largest bucket is ``max_batch`` (the
        coalescing cut) and the largest request size accepted.
    max_wait_ms : how long an open batch waits for more requests before
        dispatching below max_batch.  0 dispatches immediately (predict's
        offline mode: requests are already big).
    queue_size : bound of the request queue — backpressure, not memory.
    autostart : start the batcher thread in the constructor.  Tests pass
        False to exercise queue semantics deterministically.
    """

    def __init__(self, model=None, variables=None, *, image_size: int,
                 channels: int = 3, input_dtype=np.float32,
                 buckets: Sequence[int] = DEFAULT_BUCKETS,
                 max_wait_ms: float = 5.0, queue_size: int = 256,
                 normalize: bool = False, mean=None, std=None,
                 forward_fn=None, stats: Optional[ServeStats] = None,
                 admission=None, variants: Optional[dict] = None,
                 default_variant: str = "fp32",
                 cache_tag: Optional[str] = None,
                 autostart: bool = True) -> None:
        import jax

        if not buckets:
            raise ValueError("need at least one padding bucket")
        self.buckets = tuple(sorted({int(b) for b in buckets}))
        if self.buckets[0] < 1:
            raise ValueError(f"buckets must be >= 1, got {self.buckets}")
        self.max_batch = self.buckets[-1]
        self.image_size = int(image_size)
        self.channels = int(channels)
        self.input_dtype = np.dtype(input_dtype)
        self.max_wait = max(0.0, float(max_wait_ms)) / 1000.0
        self._forward = (forward_fn if forward_fn is not None
                         else make_forward(model, normalize=normalize,
                                           mean=mean, std=std))
        # One up-front transfer (predict.py's lesson): host leaves would be
        # re-uploaded on every executable call.
        self._variables = jax.device_put(variables)
        # Dtype ladder (docs/performance.md, "Quantized serving"): the
        # default variant is (forward, variables) above under
        # ``default_variant``; ``variants`` adds named alternates — e.g.
        # tpuic.quant.serve_variants' bf16/int8 weight representations —
        # each with its OWN forward + variables but sharing the bucket
        # ladder, queue, and batcher.  Executables are keyed
        # (variant, bucket) into the one AOT cache, so the zero
        # steady-state-compile contract holds per rung.
        self.default_variant = str(default_variant)
        gen_variants = {self.default_variant: (self._forward,
                                               self._variables)}
        for tag, (fwd, vs) in (variants or {}).items():
            tag = str(tag)
            if tag == self.default_variant:
                continue  # the constructor pair IS the default rung
            gen_variants[tag] = (fwd, jax.device_put(vs))
        # Executable home: the process-wide compiled-program registry
        # (tpuic/compiled, docs/performance.md "Compiled-program
        # registry") — this engine owns no private executable cache.
        # ``cache_tag`` namespaces its keys: the default is unique per
        # engine instance (two engines with coincidentally identical
        # aval signatures but different forward closures — e.g.
        # normalize on vs off — must never alias executables); callers
        # that want cross-process manifest prewarm pass a tag that is
        # BOTH stable across restarts and a full program identity
        # (model + preprocessing config), asserting that identity.
        from tpuic.compiled import registry as _program_registry
        self._registry = _program_registry
        self._cache_tag = (str(cache_tag) if cache_tag
                           else f"serve:{next(_ENGINE_SEQ)}")
        # The live generation (docs/serving.md, "Model lifecycle"): ONE
        # reference the batcher reads once per dispatch; swap_weights
        # flips it between batches — atomic hot-swap, nothing drains.
        self._gen = _Generation(gen_variants,
                                self._program_keys(gen_variants, 0), 0, 0,
                                _tree_digest(variables))
        # The boot digest: the canary_degrade fault point keys off
        # "serving weights other than the ones this process booted
        # with" (runtime/faults.py) — rollback restores the boot digest
        # and stands the fault down.
        self._boot_digest = self._gen.digest
        self._swap_lock = threading.Lock()
        self._jax = jax
        self.stats = stats if stats is not None else ServeStats()
        self.stats.note_identity(self._gen.digest)
        # Request-scoped tracing: every submit gets the next trace id
        # (itertools.count is safe under the GIL for concurrent callers).
        self._traces = itertools.count(1)
        # Submit-time admission (tpuic/serve/admission.py): brownout
        # class shedding + per-tenant quotas.  None = admit everything
        # the bounded queue takes (the pre-admission behavior).  Public
        # and settable post-construction: the CLI driver attaches it
        # after build_engine.
        self.admission = admission
        self._queue = _PriorityQueue(max(1, int(queue_size)))
        self._held: Optional[_Request] = None
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        if autostart:
            self.start()

    # -- generation views ----------------------------------------------
    @property
    def _variants(self) -> dict:
        """The LIVE generation's variant map (one reference read)."""
        return self._gen.variants

    @property
    def _executables(self) -> dict:
        """Registry view of the live generation's compiled executables:
        ``{(variant, bucket): executable}`` for every key that has
        compiled.  A derived read — the registry (tpuic/compiled) owns
        the cache; this engine holds no private copy."""
        out = {}
        for vb, key in self._gen.keys.items():
            entry = self._registry.lookup(key)
            if entry is not None:
                out[vb] = entry.executable
        return out

    def _program_keys(self, variants: dict, program_gen: int) -> dict:
        """Precompute the registry key of every (variant, bucket) pair:
        the per-rung variables aval CRC pins the program signature (an
        aval-identical hot-swap recomputes IDENTICAL keys and therefore
        hits; any shape/dtype/structure change misses), the bucketed
        input spec is the shapes field, and ``program_gen`` scopes GC."""
        from tpuic.compiled import ProgramKey, avals_crc, tree_avals
        keys = {}
        for tag, (_fwd, tree) in variants.items():
            crc = avals_crc(tree_avals(tree))
            for b in self.buckets:
                keys[(tag, b)] = ProgramKey(
                    model=f"{self._cache_tag}/{tag}",
                    shapes=((b, self.image_size, self.image_size,
                             self.channels), str(self.input_dtype), crc),
                    mesh=(), dtype=tag, generation=program_gen)
        return keys

    @property
    def generation(self) -> int:
        """Weight generation counter: 0 at boot, +1 per hot-swap."""
        return self._gen.generation

    @property
    def model_digest(self) -> str:
        """Content digest of the live default-rung weights — the
        identity tag the ready-file/ping protocol carries."""
        return self._gen.digest

    def variant_tags(self) -> tuple:
        """Configured dtype-ladder tags, default rung first."""
        tags = list(self._gen.variants)
        tags.remove(self.default_variant)
        return (self.default_variant, *tags)

    # -- lifecycle -----------------------------------------------------
    def start(self) -> "InferenceEngine":
        if self._thread is None or not self._thread.is_alive():
            self._stop.clear()
            self._thread = threading.Thread(target=self._run, daemon=True,
                                            name="tpuic-serve-batcher")
            self._thread.start()
        return self

    def close(self, timeout: float = 30.0) -> None:
        """Drain queued requests, then stop the batcher thread."""
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=timeout)
            if self._thread.is_alive():
                # Batcher wedged past the timeout (e.g. a stuck device
                # call). It still owns the queue — do NOT fail queued
                # requests it may yet serve, and do NOT pretend it is
                # gone (a restart would race it on _held/_queue).
                return
            self._thread = None
        # A submit() racing close() can slip a request in after the
        # batcher's final drain check — fail it rather than hang the
        # caller's future forever (submit() runs the same sweep after
        # its put for the symmetric side of the race).
        self._fail_queued()

    def _fail_queued(self) -> None:
        """Fail every queued request — only once the batcher is gone."""
        while True:
            try:
                req = self._queue.get_nowait()
            except queue.Empty:
                return
            if not req.future.cancelled():
                req.future.set_exception(RuntimeError("engine closed"))

    def __enter__(self) -> "InferenceEngine":
        return self.start()

    def __exit__(self, *exc) -> None:
        self.close()

    # -- AOT warmup / executable cache ---------------------------------
    def bucket_for(self, n: int) -> int:
        """Smallest bucket >= n (the shape the device will actually see)."""
        for b in self.buckets:
            if b >= n:
                return b
        raise ValueError(f"request of {n} rows exceeds max bucket "
                         f"{self.max_batch}")

    def warmup(self) -> dict:
        """AOT-compile every (variant, bucket) executable.

        Returns ``{bucket: secs}`` for a single-variant engine (the
        historical shape) or ``{variant: {bucket: secs}}`` for a dtype
        ladder.  After this, a request stream of any size mix in
        1..max_batch on any configured variant performs ZERO further
        lowerings — the steady-state contract, per rung.  Per
        (model, variant, bucket) the HLO also lands in the persistent
        XLA compilation cache when one is configured, so the *next*
        process warms up from disk."""
        gen = self._gen
        per_variant = {}
        for tag in gen.variants:
            timings = {}
            for b in self.buckets:
                t0 = time.perf_counter()
                self._compile(gen, tag, b)
                timings[b] = round(time.perf_counter() - t0, 3)
            per_variant[tag] = timings
        if len(per_variant) == 1:
            return per_variant[self.default_variant]
        return per_variant

    def _compile(self, gen: _Generation, variant: str, bucket: int,
                 prewarm: bool = False):
        # The registry lock serializes racing compilers for the same
        # key (warmup in the caller thread, the batcher's lazy fallback,
        # a swap's off-path prewarm) — without it both would compile and
        # the compiles-flat contract would report phantom recompiles.
        key = gen.keys[(variant, bucket)]

        def build():
            forward, variables = gen.variants[variant]
            spec = self._jax.ShapeDtypeStruct(
                (bucket, self.image_size, self.image_size, self.channels),
                self.input_dtype)
            return self._jax.jit(forward).lower(variables, spec).compile()

        entry = self._registry.get_or_compile(key, build, prewarm=prewarm)
        if entry.hit_count == 0:
            # This call built it: fold the registry's recorded compile
            # time + cost analysis into the engine-lifetime serve stats
            # (roofline context for the span ledger's device phase —
            # docs/observability.md; cost is best-effort, a backend
            # without cost analysis serves identically).
            self.stats.record_compile(bucket, entry.compile_s)
            if entry.cost:
                self.stats.record_cost(
                    bucket, float(entry.cost.get("flops", 0.0)),
                    float(entry.cost.get("bytes accessed", 0.0)))
        return entry.executable

    def profile_waterfall(self):
        """Per-op-class device-time waterfall of the largest warmed
        bucket executable (telemetry/profile.py), with the measured
        span-ledger ``device`` phase as the per-call device time —
        ``device_time_ms{op_class}`` rows in the serve exposition.
        None until a bucket has compiled; best-effort (a backend
        without ``as_text``/cost analysis serves identically)."""
        if not self._executables:
            return None
        try:
            from tpuic.telemetry.goodput import (cost_analysis_dict,
                                                 hbm_bandwidth, peak_flops)
            from tpuic.telemetry.profile import (attribute_device_time,
                                                 hlo_waterfall)
            # Largest warmed bucket of the DEFAULT variant (fall back to
            # any variant when only an alternate rung has compiled).
            keys = [k for k in self._executables
                    if k[0] == self.default_variant] or \
                list(self._executables)
            key = max(keys, key=lambda k: k[1])
            bucket = key[1]
            cached = getattr(self, "_profile_model_wf", None)
            if (cached is None or cached.get("bucket") != bucket
                    or cached.get("gen") != self._gen.generation):
                exe = self._executables[key]
                try:
                    cost = cost_analysis_dict(exe)
                except Exception:
                    cost = {}
                dev = self._jax.devices()[0]
                cached = hlo_waterfall(
                    exe.as_text(),
                    total_flops=float(cost.get("flops", 0.0)),
                    peak=peak_flops(dev),
                    hbm_bytes_per_s=hbm_bandwidth(dev))
                cached["bucket"] = bucket
                cached["gen"] = self._gen.generation
                # HLO parse cached per (bucket, generation): scrapes
                # only re-scale it onto the measured device phase; a
                # hot-swap that prewarmed new executables invalidates
                # the parse (an aval-matched swap reuses them, so the
                # generation key is conservative but cheap).
                self._profile_model_wf = cached
            wf = cached
            meter = self.stats.spans.get("device")
            if meter is not None and meter.count:
                per_call_ms = 1000.0 * meter.total / meter.count
                wf = attribute_device_time(wf, [per_call_ms])
                wf["bucket"] = bucket
            return wf
        except Exception:
            return None

    def _executable_for(self, gen: _Generation, variant: str, bucket: int):
        # Lock-free registry read on the request path (the registry's
        # peek is one dict lookup — the same cost the old private
        # executables dict paid).
        exe = self._registry.peek(gen.keys[(variant, bucket)])
        if exe is None:
            # Lazy fallback so an un-warmed engine still works; counted,
            # so the compile-flat-after-warmup test catches any batcher
            # path that would hit this in steady state.
            return self._compile(gen, variant, bucket)
        self.stats.record_cache_hit()
        return exe

    def prewarm(self, manifest_path: str) -> int:
        """Manifest-driven cold-start prewarm (docs/performance.md):
        compile every (variant, bucket) executable the manifest lists
        for this engine's keys BEFORE first traffic — against the
        persistent XLA cache those compiles are disk reads.  Requires a
        stable ``cache_tag`` (the default per-instance tag never
        matches across processes).  Raises
        :class:`tpuic.compiled.ManifestError` on a corrupt manifest —
        refusal, never best-effort — and ``FileNotFoundError`` when no
        manifest exists yet.  Returns the number of programs compiled."""
        from tpuic.compiled import ProgramKey, load_manifest
        listed = {ProgramKey.from_dict(e["key"])
                  for e in load_manifest(manifest_path)}
        gen = self._gen
        n = 0
        for (variant, bucket), key in gen.keys.items():
            if key in listed and self._registry.lookup(key) is None:
                self._compile(gen, variant, bucket, prewarm=True)
                n += 1
        return n

    # -- atomic hot-swap (docs/serving.md, "Model lifecycle") -----------
    def swap_weights(self, variables=None, *, variants: Optional[dict]
                     = None) -> dict:
        """Atomically replace the serving weights — zero drain, zero
        dropped requests, by construction.

        ``variables`` is the new default-rung tree; ``variants`` maps
        each alternate dtype-ladder tag to its new tree (or to a
        ``(forward, tree)`` pair to replace the rung's forward too).
        The tag set must cover the configured ladder EXACTLY — the
        ladder swaps as one unit, because a swap that updated fp32 but
        left int8 serving the old checkpoint would be a silent
        split-brain behind one endpoint.

        Executable policy: the AOT executables take variables as call
        *arguments*, so when every new tree is aval-identical to its
        incumbent (same structure, shapes, dtypes) and no forward was
        replaced, the new generation REUSES the incumbent's executable
        cache — zero recompiles, compile-counter-asserted in
        tests/test_serve.py.  Otherwise every (variant, bucket)
        executable is prewarmed here, off the serving path, BEFORE the
        flip — the incumbent keeps serving through the whole compile.

        The flip itself is one reference assignment.  The batcher reads
        the generation once per device batch (``_dispatch``), so every
        in-flight and already-dispatched batch resolves against the old
        weights and every batch formed after the flip runs the new ones
        — no queued request is dropped, rejected, or re-run.

        Thread-safe (one swap at a time); callers gate candidates
        BEFORE calling this (the swap-time admission gates,
        serve/__main__.py) — by the time a tree reaches here it is
        traffic-worthy.  Returns a summary dict (generation, digest,
        reused_executables, prewarmed, duration_s) and publishes a
        ``swap`` event."""
        t0 = time.perf_counter()
        with self._swap_lock:
            cur = self._gen
            staged_in: dict = {}
            if variables is not None:
                staged_in[self.default_variant] = variables
            for tag, spec in (variants or {}).items():
                tag = str(tag)
                if tag in staged_in:
                    raise ValueError(f"duplicate swap rung {tag!r}")
                staged_in[tag] = spec
            if set(staged_in) != set(cur.variants):
                raise ValueError(
                    f"swap must replace the dtype ladder as one unit: "
                    f"configured rungs {sorted(cur.variants)}, swap "
                    f"covers {sorted(staged_in)}")
            replaced_forward = False
            staged = {}
            for tag, spec in staged_in.items():
                if (isinstance(spec, tuple) and len(spec) == 2
                        and callable(spec[0])):
                    replaced_forward = True
                    staged[tag] = (spec[0], spec[1])
                else:
                    staged[tag] = (cur.variants[tag][0], spec)
            digest = _tree_digest(staged[self.default_variant][1])
            # Stage on device BEFORE the flip: the first post-flip batch
            # must not pay (or fail) the H2D transfer on the hot path.
            # Aval compatibility is judged on the STAGED (device) trees
            # — device_put canonicalizes python-scalar leaves (the int8
            # marker dicts) exactly the way the lowered executables saw
            # them, so host-vs-device representation can't spoof a
            # mismatch.
            put = {tag: (fwd, self._jax.device_put(tree))
                   for tag, (fwd, tree) in staged.items()}
            reused = not replaced_forward and all(
                _tree_avals(tree) == _tree_avals(cur.variants[tag][1])
                for tag, (_, tree) in put.items())
            # Aval-identical + same forward => the recomputed registry
            # keys are IDENTICAL to the incumbent's (same aval CRCs,
            # same program generation) — every lookup hits, zero
            # recompiles.  Otherwise the program generation bumps: the
            # new keys all miss (prewarmed below) and the incumbent's
            # entries are retired after the flip.
            program_gen = cur.program_gen if reused else cur.program_gen + 1
            new_gen = _Generation(
                put, self._program_keys(put, program_gen), program_gen,
                cur.generation + 1, digest)
            prewarmed = 0
            if not reused:
                # Off-path prewarm: compiles land in the registry under
                # the NEW program generation while the incumbent keeps
                # serving; counted honestly in stats.compiles (they are
                # real compiles — just never on the request path, and
                # never after the flip).
                for tag in new_gen.variants:
                    for b in self.buckets:
                        self._compile(new_gen, tag, b)
                        prewarmed += 1
            self._gen = new_gen  # THE flip — one reference, atomic
            if not reused:
                # Generation-scoped GC: the superseded program
                # generation's executables can never serve again.  The
                # trailing "/" keeps the prefix exact ("serve:1" must
                # not retire "serve:10").
                self._registry.retire(self._cache_tag + "/",
                                      generation=cur.program_gen)
            # Stats + event INSIDE the swap lock: a later swap's
            # record_swap must not land before an earlier one's, or the
            # exposed generation/digest would disagree with what is
            # actually serving (swaps are rare control ops — ordering
            # beats the few extra microseconds of lock hold).
            duration_s = time.perf_counter() - t0
            self.stats.record_swap(new_gen.generation, digest)
            _tm_publish("swap", generation=new_gen.generation,
                        digest=digest, reused_executables=bool(reused),
                        prewarmed=prewarmed,
                        duration_ms=round(1000.0 * duration_s, 3))
        return {"generation": new_gen.generation, "digest": digest,
                "reused_executables": bool(reused),
                "prewarmed": prewarmed,
                "duration_s": round(duration_s, 4)}

    def candidate_outputs(self, variables, images, *,
                          variant: Optional[str] = None):
        """Gate-side evaluation of a swap CANDIDATE tree: run ``images``
        through the live generation's AOT executables with ``variables``
        in place of the serving weights (the executables take variables
        as call arguments), WITHOUT touching what traffic sees.

        This is how the swap-time accuracy gate scores a candidate with
        zero new compiles: an aval-identical candidate (the hot-swap
        case) rides the already-warmed (variant, bucket) executables.
        Raises ValueError when the candidate's avals differ from the
        live rung's — those candidates prewarm in ``swap_weights``
        anyway, and the caller gates them post-prewarm.  Returns the
        forward's pytree with rows matching ``images`` (host arrays)."""
        variant = (self.default_variant if variant is None
                   else str(variant))
        gen = self._gen
        if variant not in gen.variants:
            raise ValueError(f"unknown serve dtype {variant!r}; "
                             f"configured: {sorted(gen.variants)}")
        # Stage first: device_put canonicalizes python-scalar leaves
        # (the int8 marker dicts) before the aval comparison, matching
        # what the lowered executables actually saw.
        dev_vars = self._jax.device_put(variables)
        if _tree_avals(dev_vars) != _tree_avals(gen.variants[variant][1]):
            raise ValueError(
                f"candidate tree for rung {variant!r} is not "
                "aval-identical to the serving tree — gate it through "
                "swap_weights' prewarm path instead")
        arr = np.asarray(images, self.input_dtype)  # tpuic-ok: TPU101 gate-side eval, not the request path
        if arr.ndim == 3:
            arr = arr[None]
        chunks = []
        step = self.max_batch
        for lo in range(0, arr.shape[0], step):
            chunk = arr[lo:lo + step]
            n = chunk.shape[0]
            bucket = self.bucket_for(n)
            if n < bucket:
                pad = np.zeros((bucket, self.image_size, self.image_size,
                                self.channels), self.input_dtype)
                pad[:n] = chunk
                chunk = pad
            exe = self._executable_for(gen, variant, bucket)
            out = exe(dev_vars, self._jax.device_put(chunk))
            chunks.append(self._jax.tree.map(
                lambda a, n=n: np.asarray(a)[:n], out))  # tpuic-ok: TPU101 gate-side eval, not the request path
        if len(chunks) == 1:
            return chunks[0]
        return self._jax.tree.map(
            lambda *xs: np.concatenate(xs, axis=0), *chunks)

    # -- request side --------------------------------------------------
    def submit(self, images, *, timeout: Optional[float] = None,
               priority: str = DEFAULT_PRIORITY,
               deadline_ms: Optional[float] = None,
               tenant: Optional[str] = None,
               dtype: Optional[str] = None) -> Future:
        """Enqueue [n,S,S,C] (or one [S,S,C] row) for inference.

        Returns a Future resolving to the forward's pytree sliced to this
        request's n rows.  When the queue is full: ``timeout=None``
        blocks (backpressure), ``timeout=0`` raises ``queue.Full``
        immediately, other values wait that long first — unless a
        strictly lower-priority request is queued, in which case IT is
        evicted (its future gets a typed ``AdmissionRejected``) and this
        one is admitted.

        SLA fields (docs/serving.md, "Admission control and overload"):
        ``priority`` is one of :data:`tpuic.serve.admission.PRIORITIES`
        (higher classes are batched first under contention);
        ``deadline_ms`` is this request's latency budget — once it
        cannot be met the batcher sheds the request at pop time and the
        future raises :class:`DeadlineExceeded` instead of burning a
        batch slot; ``tenant`` names the quota bucket when an
        :class:`AdmissionController` is attached, which may reject
        up front with a typed, cause-labeled ``AdmissionRejected``
        (also a ``queue.Full``, so old backpressure handlers work).

        The engine BORROWS the array until the future resolves (no
        defensive copy — the exact-bucket-fit path ships it to the
        device as-is): callers reusing a staging buffer must copy first.
        A device-resident ``jax.Array`` of the right dtype is accepted
        and stays on device when it exactly fills a bucket — predict's
        packed-loader path scores whole batches with no host bounce."""
        if (isinstance(images, self._jax.Array)
                and images.dtype == self.input_dtype):
            arr = images
        else:
            arr = np.asarray(images, self.input_dtype)  # tpuic-ok: TPU101 request arrays are host-side by contract
        if arr.ndim == 3:
            arr = arr[None]
        expect = (self.image_size, self.image_size, self.channels)
        if arr.ndim != 4 or arr.shape[1:] != expect:
            raise ValueError(f"expected [n,{expect[0]},{expect[1]},"
                             f"{expect[2]}] images, got {arr.shape}")
        if arr.shape[0] == 0:
            raise ValueError("empty request")
        if arr.shape[0] > self.max_batch:
            raise ValueError(f"request of {arr.shape[0]} rows exceeds max "
                             f"bucket {self.max_batch}; chunk it caller-side")
        if self._stop.is_set():
            raise RuntimeError("engine is closed")
        # Validate the SLA fields BEFORE consulting admission: a
        # malformed deadline failing after admit() would have consumed a
        # quota token for a request that never enters the ledger.
        priority_index(priority)
        # Dtype-ladder routing: None rides the default rung; a named
        # rung must exist — serving fp32 under a typo'd 'int8' label
        # would silently void the accuracy-gate contract.
        variant = self.default_variant if dtype is None else str(dtype)
        if variant not in self._variants:
            raise ValueError(f"unknown serve dtype {variant!r}; "
                             f"configured: {sorted(self._variants)}")
        if deadline_ms is not None:
            deadline_ms = float(deadline_ms)  # tpuic-ok: TPU101 SLA fields are host scalars by contract
        if self.admission is not None:
            verdict = self.admission.admit(priority=priority,
                                           tenant=tenant)
            if not verdict:
                self.stats.record_reject(verdict.cause, priority)
                raise AdmissionRejected(
                    f"admission rejected ({verdict.cause}, "
                    f"priority={priority}, tenant={tenant})",
                    cause=verdict.cause, priority=priority, tenant=tenant)
        fut: Future = Future()
        req = _Request(arr, fut, trace=next(self._traces),
                       priority=priority, tenant=tenant,
                       deadline_ms=deadline_ms, variant=variant)
        # Caller-side correlation handle: a driver logging an error line
        # can name the same trace id the span ledger carries.
        fut.tpuic_trace = req.trace
        try:
            evicted = self._queue.put(req, timeout=timeout)
        except queue.Full:
            self.stats.record_reject("queue_full", priority)
            if self.admission is not None:
                raise AdmissionRejected(
                    f"queue full (priority={priority})",
                    cause="queue_full", priority=priority,
                    tenant=tenant) from None
            raise
        if evicted is not None:
            # Priority eviction: the displaced request gets the same
            # typed queue_full verdict a rejected submit would — from
            # ITS labels' point of view the queue was full of more
            # important work.
            self.stats.record_reject("queue_full", evicted.priority)
            if not evicted.future.cancelled():
                evicted.future.set_exception(AdmissionRejected(
                    f"evicted by a higher-priority arrival "
                    f"(priority={evicted.priority})", cause="queue_full",
                    priority=evicted.priority, tenant=evicted.tenant))
        # Re-check after the put: a close() that ran inside the window
        # between the _stop check above and the put has already drained
        # the queue, and nothing will ever read this request — fail it
        # (and any other strays) instead of hanging the caller.
        if self._stop.is_set() and (self._thread is None
                                    or not self._thread.is_alive()):
            self._fail_queued()
        return fut

    def predict(self, images, *, timeout: Optional[float] = None):
        """Blocking convenience: submit + wait for the result."""
        return self.submit(images).result(timeout)

    def queue_depth(self) -> int:
        """Requests queued but not yet popped by the batcher — the live
        load signal the socket transport's ping response reports, which
        the replica router folds into its least-loaded routing view
        (docs/serving.md, "Replica routing and failover")."""
        return self._queue.qsize()

    # -- batcher thread ------------------------------------------------
    def _maybe_shed(self, req: _Request) -> bool:
        """Pop-time deadline shed (docs/serving.md): True when ``req``'s
        deadline has already expired — or will, within the span ledger's
        rolling estimate of the service time still ahead of it
        (ServeStats.estimated_service_s) — in which case its future gets
        a typed :class:`DeadlineExceeded` and the batch slot goes to a
        request someone is still waiting for.  Batchmates are untouched:
        shedding happens strictly before batch membership (the PR-2
        isolation discipline).  Host-clock arithmetic only."""
        if req.deadline is None:
            return False
        if time.monotonic() + self.stats.estimated_service_s() \
                <= req.deadline:
            return False
        self.stats.record_reject("deadline", req.priority)
        if not req.future.cancelled():
            req.future.set_exception(DeadlineExceeded(
                f"deadline expired before service (trace {req.trace}, "
                f"priority={req.priority})", priority=req.priority,
                tenant=req.tenant))
        return True

    def _gather(self, idle_timeout: float):
        """One coalescing decision: requests (highest priority class
        first, FIFO within a class) until max_batch rows or max_wait_ms
        after the batch opened.  A request that would overflow max_batch
        is held for the next batch (requests are never split, so
        per-request results stay contiguous; the held request leads the
        next batch regardless of class — held work is never starved).
        Expired-deadline requests are shed here, at pop time."""
        first, self._held = self._held, None
        if first is not None and self._maybe_shed(first):
            first = None
        while first is None:
            try:
                first = self._queue.get(timeout=idle_timeout)
            except queue.Empty:
                return None
            # Queue span ends when the batcher takes ownership; a held
            # request keeps its ORIGINAL pop time — the wait while held
            # belongs to batch formation, not the queue.
            first.t_gather = time.monotonic()
            if self._maybe_shed(first):
                first = None
        reqs, rows = [first], first.n
        deadline = time.monotonic() + self.max_wait
        while rows < self.max_batch:
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                break
            try:
                nxt = self._queue.get(timeout=remaining)
            except queue.Empty:
                break
            nxt.t_gather = time.monotonic()
            if self._maybe_shed(nxt):
                continue
            if rows + nxt.n > self.max_batch:
                self._held = nxt
                break
            if nxt.variant != first.variant:
                # A device batch runs ONE (variant, bucket) executable,
                # so a dtype-ladder boundary closes the batch the same
                # way an overflow does: the mismatched request is held
                # and LEADS the next batch (held work is never starved).
                self._held = nxt
                break
            reqs.append(nxt)
            rows += nxt.n
        return reqs

    def _dispatch(self, reqs):
        """Pad to bucket, H2D, call the cached executable.  Returns the
        in-flight batch (None when every request failed staging); results
        are NOT read back here — JAX dispatch is async, so the device
        crunches this batch while the batcher assembles the next one
        (double buffering).

        Error isolation: a request whose array fails the staging copy
        (caller handed something np can't materialize) gets the exception
        on ITS future and is dropped from the batch — siblings coalesced
        into the same device batch still dispatch and resolve. One bad
        request must never strand its batchmates (docs/robustness.md)."""
        t_batch = time.monotonic()  # batch closed: formation span ends
        rows = sum(r.n for r in reqs)
        bucket = self.bucket_for(rows)
        if len(reqs) == 1 and reqs[0].n == bucket:
            # Exact fit (predict's dominant case: full batches sized to a
            # bucket) — no staging copy; a device-resident request also
            # skips the H2D (device_put below no-ops on device arrays).
            batch = reqs[0].images
        else:
            batch = np.zeros((bucket, self.image_size, self.image_size,
                              self.channels), self.input_dtype)
            off = 0
            ok = []
            for r in reqs:
                try:
                    # np coerces a jax.Array operand here (one D2H for the
                    # request's rows — only on the padded/coalesced path).
                    batch[off:off + r.n] = r.images
                except BaseException as e:
                    if not r.future.cancelled():
                        r.future.set_exception(e)
                    continue
                ok.append(r)
                off += r.n
            if not ok:
                return None
            if off < rows:
                # Some request dropped: the survivors may fit a smaller
                # bucket (rows packed contiguously from 0, so a prefix
                # view is the valid batch).
                reqs = ok
                bucket = self.bucket_for(off)
                batch = batch[:bucket]
                rows = off
        t_staged = time.monotonic()  # staging (pad/copy) span ends
        if _faults.fire("hang_device"):
            # 'hang_device' injection (runtime/faults.py): a stuck device
            # call, for close()/drain-timeout and perf-gate tests.
            hang_s = _faults.param("hang_device")
            # Explicit None check: '#0' must mean a 0 s stall (a
            # severity-sweep control run), not the 1 s default.
            time.sleep(
                1.0 if hang_s is None else float(hang_s))  # tpuic-ok: TPU101 fault param is a host float
        # ONE generation read per batch (docs/serving.md, "Model
        # lifecycle"): everything below — executable lookup AND the
        # variables passed to it — comes from this snapshot, so a
        # concurrent swap_weights flip lands between batches, never
        # inside one.  In-flight batches hold their own `out` reference
        # and resolve against the weights they dispatched with.
        gen = self._gen
        if gen.digest != self._boot_digest \
                and _faults.fire("canary_degrade"):
            # 'canary_degrade' (runtime/faults.py): a hot-swapped
            # candidate that serves slower on demand — fires only while
            # serving non-boot weights, so a fleet-wide arm degrades
            # exactly the canary and a rollback stands it down.
            d = _faults.param("canary_degrade")
            time.sleep(
                0.05 if d is None else float(d))  # tpuic-ok: TPU101 fault param is a host float
        self.stats.record_dispatch(bucket, rows,
                                   [t_staged - r.t_enqueue for r in reqs])
        variant = reqs[0].variant  # _gather guarantees a pure batch
        exe = self._executable_for(gen, variant, bucket)
        out = exe(gen.variants[variant][1], self._jax.device_put(batch))
        # Async dispatch: the call returns once work is ENQUEUED; the
        # stamp closes the dispatch span, device time accrues until the
        # readback in _resolve.
        return reqs, out, bucket, (t_batch, t_staged, time.monotonic())

    def _resolve(self, inflight) -> None:
        """Block on device->host readback, slice per request, resolve
        futures.  Rows >= the batch's valid count are padding and are
        never part of any slice.

        This is also where each request's span ledger closes
        (docs/observability.md, "Request tracing"): the cumulative
        timestamps stamped through submit -> gather -> dispatch plus the
        readback/scatter stamps here become one ``serve_span`` event per
        request whose phases sum to its end-to-end latency by
        construction.  Everything is host-clock arithmetic — zero device
        syncs and zero compiles added (checker-asserted in
        tests/test_serve.py)."""
        reqs, out, bucket, (t_batch, t_staged, t_dispatched) = inflight
        try:
            # Async-dispatch contract: device-side errors surface HERE,
            # not at dispatch — so this readback is also the error edge.
            host = self._jax.tree.map(np.asarray, out)
        except BaseException as e:
            for r in reqs:
                if not r.future.cancelled():
                    r.future.set_exception(e)
            return
        now = time.monotonic()  # device span ends: results are on host
        # Counters first: a caller woken by set_result may snapshot stats
        # immediately, and the batch it just completed must be in them.
        latencies = [now - r.t_enqueue for r in reqs]
        valid = sum(r.n for r in reqs)
        self.stats.record_done(len(reqs), valid, latencies)
        # Typed event per completed device batch (docs/observability.md):
        # the in-band record of what the micro-batcher decided, published
        # from the batcher thread (the bus is thread-safe; idle = free).
        _tm_publish("serve_batch", bucket=int(bucket), requests=len(reqs),
                    images=int(valid), variant=reqs[0].variant,
                    latency_ms=round(1000.0 * max(latencies), 3))
        # Span events are per REQUEST — only build the dicts when someone
        # is listening (the bus's active() check keeps an unobserved
        # engine free); the stats-side span meters always update (cheap
        # deque appends feeding snapshot()/prom percentiles).
        spans_live = _tm_bus.active("serve_span")
        off = 0
        for r in reqs:
            lo, hi = off, off + r.n
            off = hi
            if r.future.cancelled():
                continue
            # Per-request isolation: an exception while slicing/setting ONE
            # request's result (exotic result pytree, an already-resolved
            # future) lands on that future alone — sibling requests in the
            # same device batch still resolve and the batcher stays alive.
            try:
                r.future.set_result(
                    self._jax.tree.map(lambda a: a[lo:hi], host))
            except BaseException as e:
                try:
                    r.future.set_exception(e)
                except BaseException:
                    pass  # future already done — nothing left to deliver
            t_done = time.monotonic()  # scatter span ends: result delivered
            spans = (r.t_gather - r.t_enqueue,   # queue
                     t_batch - r.t_gather,       # batch formation
                     t_staged - t_batch,         # staging pad/copy
                     t_dispatched - t_staged,    # dispatch enqueue
                     now - t_dispatched,         # device (+readback)
                     t_done - now)               # result scatter
            self.stats.record_spans(spans)
            if spans_live:
                data = {"trace": r.trace, "bucket": int(bucket),
                        "rows": int(r.n), "batch_requests": len(reqs)}
                for phase, s in zip(SPAN_PHASES, spans):
                    data[f"{phase}_ms"] = round(1000.0 * s, 4)
                data["total_ms"] = round(1000.0 * (t_done - r.t_enqueue), 4)
                _tm_publish("serve_span", **data)

    def _run(self) -> None:
        inflight = None
        while True:
            if (self._stop.is_set() and self._held is None
                    and self._queue.empty()):
                break
            # With a batch in flight, poll briefly so its readback isn't
            # delayed when the queue goes idle; when nothing is pending a
            # longer block keeps the idle loop cheap.
            reqs = self._gather(0.002 if inflight is not None else 0.05)
            if reqs is not None:
                try:
                    nxt = self._dispatch(reqs)
                except BaseException as e:  # resolve, don't kill the loop
                    for r in reqs:
                        if not r.future.cancelled():
                            r.future.set_exception(e)
                    nxt = None
                if inflight is not None:
                    self._resolve(inflight)
                inflight = nxt
            elif inflight is not None:
                self._resolve(inflight)
                inflight = None
        if inflight is not None:
            self._resolve(inflight)
