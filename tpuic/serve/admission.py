"""SLA-aware admission control for the serve tier.

PR 6 measured where the engine falls over — the open-loop saturation
sweep records the latency knee in ``perf/bench_serve.json`` — but past
that knee the engine's only defense used to be a blind queue-full
rejection: every request equally likely to be dropped, accepted requests
seeing unbounded queue latency, and the SLO machinery watching the error
budget burn without being able to act.  This module converts the knee
from a measured number into an enforced contract (docs/serving.md,
"Admission control and overload"):

- **Priority classes** (:data:`PRIORITIES`): ``high``/``normal``/``low``.
  The engine's queue pops higher classes first (FIFO within a class),
  and a full queue *evicts* the youngest lowest-priority request to
  admit a strictly-higher-priority arrival — under overload the flood is
  what waits (or sheds), never the traffic you promised an SLO.
- **Typed verdicts**: every rejection is an :class:`AdmissionRejected`
  (or :class:`DeadlineExceeded` for pop-time sheds) carrying ``cause``
  (``queue_full|deadline|quota|brownout``), ``priority``, and ``tenant``
  — the same labels the split ``rejected_total`` counter and the
  ``tpuic_serve_rejected_total`` Prometheus rows use, so a caller's
  error handling and the operator's dashboard speak one vocabulary.
- **Deadline-aware shedding**: ``submit(deadline_ms=...)`` stamps an
  absolute deadline; at *pop* time the batcher sheds any request whose
  deadline has already expired (or will, within the span ledger's
  rolling estimate of remaining service time) instead of wasting a
  batch slot on an answer nobody is still waiting for.  The future
  resolves with :class:`DeadlineExceeded`; batchmates are unaffected
  (the PR-2 isolation discipline).
- **Per-tenant token-bucket quotas** with a shared free pool: each
  configured tenant refills at its own req/s; a dry tenant (and any
  unconfigured tenant) falls through to the ``*`` pool when one is
  configured.  No pool configured = unconfigured tenants are unlimited.
- **Brownout** (:class:`BrownoutController`): couples admission to the
  PR-6 SLO tracker.  When the named objective's error-budget burn rate
  crosses ``tighten_above``, the controller tightens one priority class
  per report (level 1 sheds ``low``, level 2 sheds ``normal`` too — the
  highest class is never shed); it recovers one level only after
  ``recover_after`` consecutive reports at or below ``recover_below``
  (hysteresis: a burn rate oscillating around the threshold must not
  flap admission).  Every transition publishes an ``admission`` event.

Everything here is host-side arithmetic on monotonic clocks and event
payloads — zero device syncs, zero compiles (checker-asserted in
tests/test_admission.py), the telemetry discipline.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Callable, Dict, Optional, Tuple

# Highest class first.  Index = strictness: brownout level L sheds the L
# lowest classes; the queue pops lower indices first.
PRIORITIES: Tuple[str, ...] = ("high", "normal", "low")
_PRIORITY_INDEX = {p: i for i, p in enumerate(PRIORITIES)}
DEFAULT_PRIORITY = "normal"

# The typed rejection vocabulary — exactly the causes the split
# rejected_total counter and the prom rows are labeled with.
# ``replica_lost`` is the routing tier's verdict (tpuic/serve/router.py):
# the replica serving a request died and the request could not be safely
# replayed (non-idempotent, retries exhausted, or the retry budget dry).
# ``swap_corrupt``/``swap_accuracy`` are the model-lifecycle tier's
# verdicts (docs/serving.md, "Model lifecycle"): a hot-swap CANDIDATE
# refused at the pre-flip gate — failed the checkpoint CRC/manifest
# integrity ladder, or failed the pinned-eval accuracy gate — so a bad
# artifact never reaches traffic.  They label the refused swap request,
# never serving traffic.
CAUSES: Tuple[str, ...] = ("queue_full", "deadline", "quota", "brownout",
                           "replica_lost", "swap_corrupt", "swap_accuracy")

# The --quota spec key for the shared free pool.
FREE_POOL = "*"


def priority_index(priority: str) -> int:
    """Validated index of ``priority`` in :data:`PRIORITIES`."""
    try:
        return _PRIORITY_INDEX[priority]
    except KeyError:
        raise ValueError(
            f"unknown priority {priority!r} "
            f"(known: {', '.join(PRIORITIES)})") from None


class AdmissionError(RuntimeError):
    """Base of every typed admission verdict: ``cause`` names why
    (one of :data:`CAUSES`), ``priority``/``tenant`` name who."""

    def __init__(self, message: str, *, cause: str,
                 priority: str = DEFAULT_PRIORITY,
                 tenant: Optional[str] = None) -> None:
        super().__init__(message)
        self.cause = cause
        self.priority = priority
        self.tenant = tenant


class AdmissionRejected(AdmissionError, queue.Full):
    """Submit-time rejection (queue_full / quota / brownout) — also a
    ``queue.Full`` so pre-admission callers that handled backpressure
    with ``except queue.Full`` keep working unchanged."""


class DeadlineExceeded(AdmissionError):
    """Pop-time shed: the request's deadline expired (or would, within
    the estimated remaining service time) before it reached a batch
    slot.  Set on the request's future by the batcher."""

    def __init__(self, message: str, *, priority: str = DEFAULT_PRIORITY,
                 tenant: Optional[str] = None) -> None:
        super().__init__(message, cause="deadline", priority=priority,
                         tenant=tenant)


class ReplicaLost(AdmissionError):
    """Routing-tier verdict (tpuic/serve/router.py): the replica holding
    this request died (or wedged past the watchdog) and the request was
    NOT replayed — it was non-idempotent, its retry attempts were
    exhausted, or the global retry budget was dry (a storm of failovers
    must not amplify into a retry storm).  At-most-once delivery holds:
    a ``replica_lost`` verdict means the caller may safely retry
    end-to-end, knowing the router never emitted a response for it."""

    def __init__(self, message: str, *, priority: str = DEFAULT_PRIORITY,
                 tenant: Optional[str] = None) -> None:
        super().__init__(message, cause="replica_lost", priority=priority,
                         tenant=tenant)


class SwapRejected(AdmissionError):
    """Swap-time gate verdict (docs/serving.md, "Model lifecycle"):
    a hot-swap candidate was refused BEFORE the weight flip — it never
    served a request.  ``cause`` is ``swap_corrupt`` (the candidate
    failed the checkpoint CRC/manifest integrity check: missing,
    manifest-less, or bytes that don't match their recorded checksums)
    or ``swap_accuracy`` (the candidate failed the pinned-eval gate:
    non-finite outputs, or a dtype-ladder rung disagreeing with the
    candidate's own fp32 past the committed epsilon).  The incumbent
    keeps serving untouched — refusal is always zero-downtime."""

    def __init__(self, message: str, *, cause: str = "swap_corrupt",
                 priority: str = DEFAULT_PRIORITY,
                 tenant: Optional[str] = None) -> None:
        if cause not in ("swap_corrupt", "swap_accuracy"):
            raise ValueError(f"SwapRejected cause must be swap_corrupt or "
                             f"swap_accuracy, got {cause!r}")
        super().__init__(message, cause=cause, priority=priority,
                         tenant=tenant)


class Decision:
    """One admission verdict: ``admit`` or the rejecting ``cause``."""

    __slots__ = ("admit", "cause")

    def __init__(self, admit: bool, cause: Optional[str] = None) -> None:
        self.admit = admit
        self.cause = cause

    def __bool__(self) -> bool:
        return self.admit


_ADMIT = Decision(True)


class TokenBucket:
    """Classic token bucket on the monotonic clock: refills at ``rate``
    tokens/sec up to ``burst`` (default: one second of rate, min 1), so
    a tenant can spike briefly but sustains exactly its quota.

    ``clock`` is injectable for deterministic refill-math tests.  Not
    internally locked — the AdmissionController serializes access."""

    def __init__(self, rate: float, burst: Optional[float] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        if rate <= 0:
            raise ValueError(f"token-bucket rate must be > 0, got {rate}")
        self.rate = float(rate)
        self.capacity = float(burst) if burst is not None \
            else max(1.0, self.rate)
        if self.capacity <= 0:
            raise ValueError("token-bucket burst must be > 0")
        self._clock = clock
        self.tokens = self.capacity  # start full: a fresh tenant may burst
        self._t = self._clock()

    def _refill(self) -> None:
        now = self._clock()
        self.tokens = min(self.capacity,
                          self.tokens + (now - self._t) * self.rate)
        self._t = now

    def try_take(self, n: float = 1.0) -> bool:
        """Take ``n`` tokens if available; False (taking nothing) when
        the bucket is dry — never goes negative, never blocks."""
        self._refill()
        if self.tokens >= n:
            self.tokens -= n
            return True
        return False


def parse_quotas(specs) -> Dict[str, float]:
    """``['tenantA=50', '*=200']`` (or one comma list) -> {tenant: rps}.

    ``*`` is the shared free pool.  Malformed specs raise ValueError up
    front — a typo'd quota that silently never applies would read as
    "unlimited" exactly when you meant to cap someone."""
    out: Dict[str, float] = {}
    if isinstance(specs, str):
        specs = specs.split(",")
    for raw in specs or ():
        for spec in str(raw).split(","):
            spec = spec.strip()
            if not spec:
                continue
            tenant, sep, rate = spec.partition("=")
            tenant = tenant.strip()
            try:
                rps = float(rate)
            except ValueError:
                rps = -1.0
            if not sep or not tenant or rps <= 0:
                raise ValueError(
                    f"bad quota spec {spec!r} (expected tenant=rps with "
                    f"rps > 0, '{FREE_POOL}' for the shared free pool)")
            if tenant in out:
                raise ValueError(f"duplicate quota for tenant {tenant!r}")
            out[tenant] = rps
    return out


class BrownoutController:
    """SLO-coupled progressive load shedding with hysteresis.

    Subscribes to the bus's ``slo`` events (telemetry/slo.py publishes
    one per objective every ``publish_every`` samples); reacts only to
    the named objective.  State machine over ``level`` in
    ``0..max_level`` (``max_level`` < len(PRIORITIES), so the highest
    class is never shed):

    - ``burn_rate >= tighten_above``  -> level += 1 (immediately, one
      class per report — progressive, not cliff-edge)
    - ``burn_rate <= recover_below`` for ``recover_after`` consecutive
      reports -> level -= 1 (the hysteresis band between the two
      thresholds holds the level steady)

    Every transition publishes an ``admission`` event (level, burn rate,
    direction) so the JSONL/TensorBoard record shows when and why the
    tier browned out.  Thread-safe: slo events arrive from whatever
    thread published the underlying latency sample, while ``sheds()``
    is read on the submit path."""

    def __init__(self, slo_name: str, *, tighten_above: float = 2.0,
                 recover_below: float = 1.0, recover_after: int = 3,
                 max_level: int = len(PRIORITIES) - 1,
                 publish=None) -> None:
        if not slo_name:
            raise ValueError("brownout needs the name of an SLO objective")
        if recover_below > tighten_above:
            raise ValueError(
                f"recover_below ({recover_below}) must not exceed "
                f"tighten_above ({tighten_above}) — the band between "
                "them is the hysteresis")
        self.slo_name = slo_name
        self.tighten_above = float(tighten_above)
        self.recover_below = float(recover_below)
        self.recover_after = max(1, int(recover_after))
        self.max_level = max(0, min(int(max_level), len(PRIORITIES) - 1))
        self._publish = publish
        self._lock = threading.Lock()
        self._level = 0
        self._good_streak = 0
        self.transitions = 0

    @property
    def level(self) -> int:
        return self._level

    def sheds(self, priority: str) -> bool:
        """Whether the current level sheds ``priority`` (level L sheds
        the L lowest classes)."""
        return priority_index(priority) >= len(PRIORITIES) - self._level

    def attach(self, bus) -> Callable[[], None]:
        """Subscribe to ``bus`` for ``slo`` events; transitions publish
        ``admission`` events back to the same bus.  Returns the
        unsubscribe callable."""
        if self._publish is None:
            self._publish = bus.publish
        return bus.subscribe(self.on_event, kinds=("slo",))

    def on_event(self, ev) -> None:
        """One SLO report for the coupled objective -> maybe transition."""
        if ev.data.get("name") != self.slo_name:
            return
        burn = ev.data.get("burn_rate")
        if burn is None:
            return
        self.observe(float(burn))

    def observe(self, burn_rate: float) -> None:
        """Feed one burn-rate sample through the state machine (the
        event-free entry point tests and pollers use)."""
        action = None
        with self._lock:
            if burn_rate >= self.tighten_above:
                self._good_streak = 0
                if self._level < self.max_level:
                    self._level += 1
                    action = "tighten"
            elif burn_rate <= self.recover_below:
                self._good_streak += 1
                if (self._good_streak >= self.recover_after
                        and self._level > 0):
                    self._level -= 1
                    self._good_streak = 0
                    action = "recover"
            else:
                # Inside the hysteresis band: hold the level, and a
                # recovery streak does not survive a band excursion.
                self._good_streak = 0
            level = self._level
        if action is not None:
            self.transitions += 1
            if self._publish is not None:
                self._publish("admission", action=action, level=level,
                              slo=self.slo_name,
                              burn_rate=round(burn_rate, 4),
                              sheds=[p for p in PRIORITIES
                                     if priority_index(p)
                                     >= len(PRIORITIES) - level])

    def state(self) -> dict:
        """JSON-able snapshot for the exit summary / prom exposition."""
        with self._lock:
            return {"slo": self.slo_name, "level": self._level,
                    "max_level": self.max_level,
                    "tighten_above": self.tighten_above,
                    "recover_below": self.recover_below,
                    "transitions": self.transitions}


class AdmissionController:
    """Submit-time admission: brownout class shedding, then per-tenant
    token-bucket quotas with the shared free pool.

    The controller sits *in front of* the engine's queue (the engine
    consults it before the put); queue-full itself stays the engine's
    verdict because only the queue knows.  ``admit()`` is one lock, two
    dict lookups and at most two bucket refills — cheap enough for the
    submit hot path, and it touches no device state ever."""

    def __init__(self, quotas: Optional[Dict[str, float]] = None,
                 brownout: Optional[BrownoutController] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        quotas = dict(quotas or {})
        self._lock = threading.Lock()
        self.brownout = brownout
        pool_rate = quotas.pop(FREE_POOL, None)
        self._pool = (TokenBucket(pool_rate, clock=clock)
                      if pool_rate is not None else None)
        self._buckets = {t: TokenBucket(r, clock=clock)
                         for t, r in quotas.items()}

    def admit(self, *, priority: str = DEFAULT_PRIORITY,
              tenant: Optional[str] = None) -> Decision:
        """Verdict for one arriving request.  Never blocks."""
        priority_index(priority)  # validate early, typed error
        if self.brownout is not None and self.brownout.sheds(priority):
            return Decision(False, "brownout")
        with self._lock:
            bucket = self._buckets.get(tenant) if tenant else None
            if bucket is not None:
                if bucket.try_take():
                    return _ADMIT
                # Dry tenant bucket: borrow from the shared pool when
                # one exists — a quota is a guarantee floor, not a cap,
                # as long as spare capacity is pooled.
                if self._pool is not None and self._pool.try_take():
                    return _ADMIT
                return Decision(False, "quota")
            if self._pool is not None:
                # Unconfigured tenant (or no tenant): the free pool is
                # the only thing between it and the queue.
                if self._pool.try_take():
                    return _ADMIT
                return Decision(False, "quota")
            return _ADMIT

    def state(self) -> dict:
        """JSON-able snapshot: per-tenant tokens + brownout state.
        Buckets refill lazily (inside ``try_take``), so reads refill
        first — a dry bucket with no traffic since must not scrape as
        permanently out of quota."""
        with self._lock:
            for b in self._buckets.values():
                b._refill()
            if self._pool is not None:
                self._pool._refill()
            tenants = {t: round(b.tokens, 2)
                       for t, b in self._buckets.items()}
            pool = round(self._pool.tokens, 2) if self._pool else None
        return {"tenant_tokens": tenants, "free_pool_tokens": pool,
                "brownout": (self.brownout.state()
                             if self.brownout is not None else None)}
