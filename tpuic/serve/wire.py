"""Serve-tier wire format: THE typed verdict-line encoder, shared.

Three tiers emit JSONL error lines for a request that will never get a
result — the ``python -m tpuic.serve`` accept path, its ``drain()``
straggler path, and the replica router (``tpuic/serve/router.py``).
Before this module each hand-built its ``{"id", "error", ...}`` dict;
now all of them call :func:`error_line`, so a typed
:class:`~tpuic.serve.admission.AdmissionError` renders the identical
``{"id", "error", "cause", "priority"}`` shape no matter which tier
issued the verdict, and a client's error handling parses one vocabulary
(docs/serving.md, "Admission control and overload").

Also here: the socket-JSONL transport's array payload codec
(``encode_array``/``decode_array`` — base64 of the raw row-major bytes
plus shape/dtype, so the stdlib-only router can forward tensors without
importing numpy) and the replica ready-file protocol
(``write_ready_file``/``read_ready_file`` — how a spawned replica tells
the router which port it bound).

Stdlib-only at module level by design: the router imports this (like
the supervisor parent, it must never initialize jax or even numpy);
``decode_array`` — only the engine side calls it — imports numpy
lazily.
"""

from __future__ import annotations

import base64
import json
import os
from typing import Optional, Tuple, Union


def error_record(rid: Optional[str], err: Union[str, BaseException],
                 **extra) -> dict:
    """The one typed verdict shape: ``{"id", "error"}`` plus, when
    ``err`` is an :class:`~tpuic.serve.admission.AdmissionError`, the
    ``cause``/``priority`` labels the rejected_total counters carry.
    ``rid=None`` omits the id (a request line too malformed to have
    one).  ``extra`` appends caller fields (e.g. a trace id)."""
    from tpuic.serve.admission import AdmissionError
    rec: dict = {}
    if rid is not None:
        rec["id"] = rid
    rec["error"] = str(err)
    if isinstance(err, AdmissionError):
        rec["cause"] = err.cause
        rec["priority"] = err.priority
    rec.update(extra)
    return rec


def error_line(rid: Optional[str], err: Union[str, BaseException],
               **extra) -> str:
    """:func:`error_record` as one newline-terminated JSONL line."""
    return json.dumps(error_record(rid, err, **extra)) + "\n"


def rebuild_error(record: dict) -> Exception:
    """Inverse of :func:`error_record` for the router's client side: a
    wire error record becomes the typed exception its future raises, so
    a caller sees the same exception type whether the verdict came from
    a local engine or crossed a socket.  Untyped records (decode
    failures, drain timeouts) become plain RuntimeError."""
    from tpuic.serve.admission import (AdmissionRejected, DeadlineExceeded,
                                       ReplicaLost, SwapRejected)
    msg = str(record.get("error", "unknown error"))
    cause = record.get("cause")
    if cause is None:
        return RuntimeError(msg)
    priority = record.get("priority", "normal")
    if cause == "deadline":
        return DeadlineExceeded(msg, priority=priority,
                                tenant=record.get("tenant"))
    if cause == "replica_lost":
        return ReplicaLost(msg, priority=priority,
                           tenant=record.get("tenant"))
    if cause in ("swap_corrupt", "swap_accuracy"):
        # Swap-gate refusal crossing the wire (the rollout driver's
        # control channel): same typed exception as an in-process gate.
        return SwapRejected(msg, cause=cause, priority=priority,
                            tenant=record.get("tenant"))
    return AdmissionRejected(msg, cause=cause, priority=priority,
                             tenant=record.get("tenant"))


# -- array payloads (socket-JSONL transport) ---------------------------------
def encode_array(arr) -> dict:
    """``{"b64", "shape", "dtype"}`` fields for a request line.  Duck
    typed (``.tobytes()``/``.shape``/``.dtype``) so the stdlib-only
    router can encode a caller's numpy array without importing numpy
    itself; the bytes are the C-contiguous row-major buffer."""
    return {"b64": base64.b64encode(arr.tobytes()).decode("ascii"),
            "shape": [int(s) for s in arr.shape],
            "dtype": str(getattr(arr.dtype, "name", arr.dtype))}


def decode_array(req: dict):
    """Engine-side inverse of :func:`encode_array` (imports numpy —
    never called by the router).  Raises ValueError on a malformed
    payload so the transport can answer with a typed error line instead
    of dying."""
    import numpy as np
    try:
        raw = base64.b64decode(req["b64"])
        shape = tuple(int(s) for s in req["shape"])
        dtype = np.dtype(req.get("dtype", "uint8"))
        return np.frombuffer(raw, dtype).reshape(shape).copy()
    except (KeyError, TypeError, ValueError) as e:
        raise ValueError(f"bad array payload: {e}") from None


# -- replica ready-file protocol ---------------------------------------------
def write_ready_file(path: str, **payload) -> None:
    """Atomic (tmp + rename, the heartbeat discipline) dump of the
    replica's bound address: ``{"port", "pid", ...}``.  The router polls
    for this file after spawning — it is the only port-handoff channel,
    so a torn read must be impossible."""
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        json.dump(payload, f)
    os.replace(tmp, path)


def read_ready_file(path: str) -> Optional[dict]:
    """Parse a ready file; None while absent/unreadable (still
    starting)."""
    try:
        with open(path) as f:
            out = json.load(f)
    except (OSError, ValueError):
        return None
    return out if isinstance(out, dict) else None


def parse_hostport(spec: str) -> Tuple[str, int]:
    """``'127.0.0.1:8000'`` -> (host, port); port 0 = kernel-assigned."""
    host, sep, port = spec.rpartition(":")
    if not sep or not host:
        raise ValueError(f"expected HOST:PORT, got {spec!r}")
    return host, int(port)
