"""Replica-fleet router: health-checked, breaker-guarded, retry-budgeted.

``python -m tpuic.serve`` is one engine process; a single crash, wedge,
or brownout used to take the whole service down with it.  This module is
the front tier that makes the serve story a *fleet* story
(docs/serving.md, "Replica routing and failover"): it spawns (or
attaches to) N engine replicas speaking the socket-JSONL transport
(``--listen`` in serve/__main__.py) and routes requests with layered
fault handling:

- **Health states** per replica: a live probe (the transport's
  ``{"op": "ping"}`` answered with queue depth), heartbeat-file age
  (the supervisor protocol — spawned replicas run with
  ``TPUIC_HEARTBEAT_FILE`` set via the shared ``_Child``), and the
  ``brownout_level`` / span-ledger service estimate scraped from each
  replica's existing Prometheus exposition.  States:
  ``starting → up → (wedged|down) → starting…`` and terminal
  ``failed``/``stopped``.
- **Least-loaded shed-aware routing**: requests go to the routable
  replica with the fewest in-flight requests, preferring replicas whose
  brownout level would not shed the request's priority class; a replica
  at/over its **spill limit** — ``ceil(knee_rps × estimated_service_s)``
  by Little's law, i.e. the concurrency at the committed latency knee
  (perf/bench_serve.json) — is spilled *past*, and when every replica
  is at the limit the router sheds with a typed ``queue_full`` verdict
  instead of queueing toward a timeout.
- **Global retry budget** (:class:`RetryBudget`): a ratio of successes,
  not a per-request count — each delivered response deposits
  ``ratio`` tokens (capped), each replay withdraws one, so a fleet-wide
  failure cannot amplify into a retry storm.  Replays back off
  exponentially (capped) and only **idempotent** requests replay at
  all.
- **Circuit breakers** per replica (:class:`CircuitBreaker`):
  closed → open on ``threshold`` consecutive transport failures (and
  tripped immediately on conclusive connection loss); after a cooldown
  a **half-open** probe routes exactly one request — success closes the
  breaker (the respawned replica rejoins), failure re-opens it.
- **In-flight failover**: when a replica dies (socket EOF, SIGKILL,
  watchdog escalation), its in-flight requests requeue to survivors —
  at-most-once enforced by router-assigned request-id dedupe (a late
  duplicate response is dropped; the client future resolves exactly
  once).  Unreplayable requests (non-idempotent, attempts exhausted,
  budget dry) resolve with a typed
  :class:`~tpuic.serve.admission.ReplicaLost` verdict — the
  ``replica_lost`` cause in the shared AdmissionError vocabulary.
- **Respawn rides the supervisor ladder**: spawned replicas are
  ``runtime/supervisor.py`` ``_Child`` processes (heartbeat file,
  per-attempt stack/flight dump artifacts, per-replica log files); a
  wedged replica is escalated SIGQUIT → SIGTERM → SIGKILL exactly like
  a wedged trainer, then respawned with backoff.
- **Graceful drain on SIGTERM** (the PR-2 preemption contract): stop
  accepting, wait out in-flight up to the drain timeout, typed
  straggler verdicts, then one TERM per replica with the flush window.

Telemetry: ``router_replica`` / ``router_breaker`` / ``router_retry`` /
``router_failover`` events (EVENT_KINDS, docs/observability.md) land in
the router ledger JSONL (and on a bus via the optional ``publish``
hook); counters render as ``tpuic_router_*`` Prometheus rows
(telemetry/prom.py ``router_exposition``).

Like the supervisor parent, this module is **stdlib-only** and must
stay that way: the router has to outlive any backend wedge its
replicas hit, so it never imports jax or numpy (request arrays are
forwarded as duck-typed ``.tobytes()`` base64 payloads — wire.py).
The CI gate is ``scripts/router_soak.py``: two replicas under a
Poisson storm, one SIGKILLed mid-storm, zero client timeouts, breaker
open → half-open → closed rejoin, exact ledger.
"""

from __future__ import annotations

import heapq
import itertools
import json
import math
import os
import random
import shlex
import socket
import subprocess
import sys
import threading
import time
from collections import deque
from concurrent.futures import Future
from concurrent.futures import TimeoutError as _FutTimeout
from typing import Callable, Dict, List, Optional, Set, Tuple

from tpuic.runtime.supervisor import _Child, read_heartbeat
from tpuic.serve import wire
from tpuic.serve.admission import (DEFAULT_PRIORITY, PRIORITIES,
                                   AdmissionRejected, DeadlineExceeded,
                                   ReplicaLost, priority_index)

# Replica health states (docs/serving.md, "Replica routing and
# failover").  Only "up" replicas with a permitting breaker are routed.
STARTING, UP, WEDGED, DOWN, FAILED, STOPPED = (
    "starting", "up", "wedged", "down", "failed", "stopped")


class RetryBudget:
    """Ratio-of-successes retry budget (the no-retry-storms rule).

    Each delivered response deposits ``ratio`` tokens (so sustained
    retries are bounded at ``ratio`` × the success rate); each replay
    withdraws one whole token.  ``cap`` bounds the burst — the bucket
    starts full so a cold-start failover (replica dies before any
    successes landed) can still replay its in-flight handful.  Not a
    per-request count: a single request may retry several times in a
    healthy fleet, and a thousand requests may not retry at all in a
    dying one.  Thread-safe."""

    def __init__(self, ratio: float = 0.1, cap: float = 32.0) -> None:
        if ratio < 0:
            raise ValueError(f"retry ratio must be >= 0, got {ratio}")
        self.ratio = float(ratio)
        self.cap = max(1.0, float(cap))
        self._lock = threading.Lock()
        self.tokens = self.cap
        self.spent = 0
        self.denied = 0

    def deposit(self) -> None:
        """One delivered response (result OR typed verdict — the
        transport worked) earns ``ratio`` tokens."""
        with self._lock:
            self.tokens = min(self.cap, self.tokens + self.ratio)

    def try_retry(self) -> bool:
        """Withdraw one token for a replay; False when the budget is
        dry (the caller sheds with ``replica_lost`` instead)."""
        with self._lock:
            if self.tokens >= 1.0:
                self.tokens -= 1.0
                self.spent += 1
                return True
            self.denied += 1
            return False

    def state(self) -> dict:
        with self._lock:
            return {"tokens": round(self.tokens, 2), "cap": self.cap,
                    "ratio": self.ratio, "spent": self.spent,
                    "denied": self.denied}


class CircuitBreaker:
    """Per-replica transport circuit breaker.

    closed → open after ``threshold`` *consecutive* transport failures
    (or immediately via :meth:`trip` on conclusive evidence — a dropped
    connection).  After ``cooldown_s`` the first :meth:`try_acquire`
    moves to half-open and grants exactly one probe slot; the probe's
    outcome (``record_success``/``record_failure``) closes or re-opens
    the breaker.  Engine-side *typed* rejections are transport
    successes — the breaker watches the pipe, not the verdicts.

    ``on_transition(old, new, reason)`` fires outside the lock on every
    state change (the router publishes it as a ``router_breaker``
    event).  ``clock`` is injectable for deterministic tests."""

    def __init__(self, threshold: int = 5, cooldown_s: float = 1.0,
                 on_transition: Optional[Callable] = None,
                 clock: Callable[[], float] = time.monotonic) -> None:
        self.threshold = max(1, int(threshold))
        self.cooldown_s = max(0.0, float(cooldown_s))
        self._on_transition = on_transition
        self._clock = clock
        self._lock = threading.Lock()
        self.state = "closed"
        self.consecutive_failures = 0
        self.transitions = 0
        self._opened_at = 0.0
        self._probe_out = False

    def _set(self, new: str, reason: str) -> Optional[Tuple[str, str, str]]:
        old, self.state = self.state, new
        self.transitions += 1
        return (old, new, reason)

    def _emit(self, change) -> None:
        if change is not None and self._on_transition is not None:
            self._on_transition(*change)

    def try_acquire(self) -> bool:
        """Whether a request may route to this replica now.  Closed:
        always.  Open: past the cooldown, transitions to half-open and
        grants the one probe slot.  Half-open: only while the probe
        slot is free."""
        change = None
        with self._lock:
            if self.state == "closed":
                ok = True
            elif self.state == "open":
                if self._clock() - self._opened_at >= self.cooldown_s:
                    change = self._set("half_open", "cooldown elapsed")
                    self._probe_out = True
                    ok = True
                else:
                    ok = False
            else:  # half_open
                ok = not self._probe_out
                if ok:
                    self._probe_out = True
        self._emit(change)
        return ok

    def record_success(self) -> None:
        change = None
        with self._lock:
            self.consecutive_failures = 0
            self._probe_out = False
            if self.state != "closed":
                change = self._set("closed", "probe succeeded")
        self._emit(change)

    def record_failure(self, reason: str = "transport failure") -> None:
        change = None
        with self._lock:
            self.consecutive_failures += 1
            self._probe_out = False
            if self.state == "half_open":
                change = self._set("open", f"probe failed: {reason}")
                self._opened_at = self._clock()
            elif (self.state == "closed"
                  and self.consecutive_failures >= self.threshold):
                change = self._set(
                    "open", f"{self.consecutive_failures} consecutive "
                    f"failures ({reason})")
                self._opened_at = self._clock()
        self._emit(change)

    def trip(self, reason: str) -> None:
        """Conclusive failure (connection lost): open immediately —
        counting to ``threshold`` against a dead socket only delays the
        verdict the EOF already delivered."""
        change = None
        with self._lock:
            self.consecutive_failures = max(self.consecutive_failures,
                                            self.threshold)
            self._probe_out = False
            if self.state != "open":
                change = self._set("open", reason)
                self._opened_at = self._clock()
        self._emit(change)

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self.state,
                    "consecutive_failures": self.consecutive_failures,
                    "transitions": self.transitions}


class RouterStats:
    """Thread-safe router counters mirroring the ServeStats ledger
    contract: every offered request either resolves (``requests``),
    lands in ``rejected_by`` under exactly one typed cause, or — never,
    outside of bugs — counts as an untyped ``errors``.  The soak
    asserts ``requests + rejected + errors == offered`` exactly.

    Stdlib-only by design (the router rule), so the latency window
    carries its own nearest-rank quantile — the same pinned formula as
    ``tpuic.metrics.meters.quantile`` (ceil(q/100·n), clamped), kept
    numerically identical so router percentiles and engine percentiles
    mean the same thing."""

    def __init__(self, window: int = 8192) -> None:
        self._lock = threading.Lock()
        self._window = window
        self.replica_state_fn: Optional[Callable[[], dict]] = None
        self.reset()

    def reset(self) -> None:
        with self._lock:
            self.offered = 0
            self.requests = 0          # resolved with a result
            self.rejected = 0          # typed verdicts, any cause
            self.rejected_by: Dict[str, Dict[str, int]] = {}
            self.errors = 0            # untyped failures (decode, bugs)
            self.retries = 0
            self.failovers = 0
            self.failover_requeued = 0
            self.failover_lost = 0
            self.duplicates = 0
            # Replica lines whose id the router never issued (torn
            # framing, protocol bugs) — NOT part of the offered-request
            # ledger, and deliberately not folded into `duplicates`: a
            # wire-corruption symptom must not masquerade as benign
            # at-most-once dedupe activity.
            self.wire_errors = 0
            self._lat = deque(maxlen=self._window)
            self._t0 = time.monotonic()

    # -- updates --------------------------------------------------------
    def record_offered(self) -> None:
        with self._lock:
            self.offered += 1

    def record_resolved(self, latency_s: float) -> None:
        with self._lock:
            self.requests += 1
            self._lat.append(float(latency_s))

    def record_reject(self, cause: str, priority: str) -> None:
        with self._lock:
            self.rejected += 1
            by = self.rejected_by.setdefault(cause, {})
            by[priority] = by.get(priority, 0) + 1

    def record_error(self) -> None:
        with self._lock:
            self.errors += 1

    def record_retry(self) -> None:
        with self._lock:
            self.retries += 1

    def record_failover(self, requeued: int, lost: int) -> None:
        with self._lock:
            self.failovers += 1
            self.failover_requeued += requeued
            self.failover_lost += lost

    def record_duplicate(self) -> None:
        with self._lock:
            self.duplicates += 1

    def record_wire_error(self) -> None:
        with self._lock:
            self.wire_errors += 1

    # -- reads ----------------------------------------------------------
    @staticmethod
    def _quantile(samples: List[float], q: float) -> float:
        # Nearest-rank, pinned identically to tpuic.metrics.meters.
        return samples[max(1, min(len(samples),
                                  math.ceil(q / 100.0 * len(samples)))) - 1]

    def snapshot(self) -> dict:
        with self._lock:
            lat = sorted(self._lat)
            out = {
                "offered": self.offered,
                "requests": self.requests,
                "rejected": self.rejected,
                "rejected_by": {c: dict(sorted(p.items())) for c, p in
                                sorted(self.rejected_by.items())},
                "errors": self.errors,
                "retries": self.retries,
                "failovers": self.failovers,
                "failover_requeued": self.failover_requeued,
                "failover_lost": self.failover_lost,
                "duplicates": self.duplicates,
                "wire_errors": self.wire_errors,
                "latency_ms": ({f"p{q}": round(
                    1000.0 * self._quantile(lat, q), 3)
                    for q in (50, 95, 99)} if lat else {}),
                "elapsed_s": round(time.monotonic() - self._t0, 3),
            }
        fn = self.replica_state_fn
        if fn is not None:
            try:
                out["replicas"] = fn()
            except Exception:  # snapshot must never take the router down
                out["replicas"] = {}
        return out


class _Request:
    """One client request as the router tracks it: the wire payload
    (sans id — the router assigns its own unique wire id per send for
    at-most-once dedupe), the client's id/future, and the replay
    ledger."""

    __slots__ = ("client_id", "payload", "future", "priority", "tenant",
                 "idempotent", "deadline", "attempts", "t_offered",
                 "wire_id", "retry_deadline")

    def __init__(self, client_id: str, payload: dict, *,
                 priority: str = DEFAULT_PRIORITY,
                 tenant: Optional[str] = None, idempotent: bool = True,
                 deadline_ms: Optional[float] = None) -> None:
        self.client_id = client_id
        self.payload = payload
        self.future: Future = Future()
        self.priority = priority
        self.tenant = tenant
        self.idempotent = bool(idempotent)
        self.t_offered = time.monotonic()
        self.deadline = (None if deadline_ms is None
                         else self.t_offered + float(deadline_ms) / 1000.0)
        self.attempts = 0
        self.wire_id = ""
        self.retry_deadline: Optional[float] = None  # set when first requeued


class _Replica:
    """Router-side view of one engine replica (spawned or attached)."""

    def __init__(self, idx: int, router: "Router", *,
                 cmd: Optional[List[str]] = None,
                 addr: Optional[Tuple[str, int]] = None,
                 prom_port: Optional[int] = None) -> None:
        self.idx = idx
        self.name = f"r{idx}"
        self.router = router
        self.cmd = cmd                  # None = attached, never respawned
        self.addr = addr                # (host, port); spawned: from ready file
        self.prom_port = prom_port
        self.state = STARTING
        self.child: Optional[_Child] = None
        self.spawns = 0
        self.consecutive_spawn_failures = 0
        self.sock: Optional[socket.socket] = None
        self.reader: Optional[threading.Thread] = None
        self._send_lock = threading.Lock()
        self.inflight: Dict[str, _Request] = {}  # guarded by router._lock
        # Control-channel futures (swap lines etc.) — guarded by
        # router._lock, NEVER failed over to a survivor: replaying a
        # swap on a different replica would flip the wrong process.
        self.control: Dict[str, Future] = {}
        self.routed = 0
        self.transport_failures = 0
        # Per-replica outcome ledger (docs/serving.md, "Model
        # lifecycle"): what THIS replica answered — the canary rollout
        # driver's health signal (resolved + typed verdicts are
        # health; untyped errors on a canary trigger rollback).
        self.resolved = 0
        self.rejected_typed = 0
        self.resp_errors = 0
        # Model identity (ready file at spawn, then live pongs).
        self.digest: Optional[str] = None
        self.generation: Optional[int] = None
        self.dtypes: Optional[Tuple[str, ...]] = None
        self._digest_flagged = False
        self.breaker = CircuitBreaker(
            threshold=router.breaker_threshold,
            cooldown_s=router.breaker_cooldown_s,
            on_transition=lambda old, new, reason: router._publish(
                "router_breaker", replica=self.name, old=old, new=new,
                reason=reason))
        # Health signals
        self.connected_at = 0.0
        self.last_pong = 0.0
        self.last_ping_sent = 0.0
        self.queue_depth: Optional[int] = None
        self.brownout_level = 0
        self.service_est_s: Optional[float] = None
        self.last_scrape = 0.0
        self.respawn_at = 0.0
        self.started_at = time.monotonic()
        self._last_timeout_fail = 0.0
        self.state_dir = os.path.join(router.state_dir, self.name)
        self.ready_file = os.path.join(self.state_dir, "ready.json")
        self.heartbeat_file = os.path.join(self.state_dir, "heartbeat.json")
        self.log_file = os.path.join(self.state_dir, "replica.log")
        self._log_fh = None
        os.makedirs(self.state_dir, exist_ok=True)

    # -- health ---------------------------------------------------------
    def live(self, now: float) -> bool:
        """Live probe verdict: a pong (or fresh connect) inside the
        ping timeout."""
        anchor = max(self.last_pong, self.connected_at)
        return anchor > 0 and now - anchor <= self.router.ping_timeout_s

    def heartbeat_age_s(self) -> Optional[float]:
        if self.cmd is None:
            return None
        hb = read_heartbeat(self.heartbeat_file)
        if hb is None or not isinstance(hb.get("t"), (int, float)):
            return None
        return max(0.0, time.time() - float(hb["t"]))

    def spill_limit(self) -> int:
        """The shed-aware knee: Little's law concurrency at the
        committed knee (knee_rps × the replica's scraped service-time
        estimate), floored at 2 so a cold replica is still routable.
        ``--spill-inflight`` overrides; no knee signal = a permissive
        default (the engine's own bounded queue backstops)."""
        r = self.router
        if r.spill_inflight:
            return r.spill_inflight
        if r.knee_rps and self.service_est_s:
            return max(2, math.ceil(r.knee_rps * self.service_est_s))
        return 64

    def sheds(self, priority: str) -> bool:
        """Whether this replica's scraped brownout level would shed
        ``priority`` (the admission tier's level-L-sheds-the-L-lowest
        rule) — used to deprioritize, never to hard-exclude: if every
        replica sheds, the replica's own typed verdict is the answer."""
        lvl = self.brownout_level
        return lvl > 0 and priority_index(priority) >= len(PRIORITIES) - lvl

    def health(self) -> dict:
        now = time.monotonic()
        return {
            "state": self.state,
            "breaker": self.breaker.snapshot(),
            "inflight": len(self.inflight),
            "routed": self.routed,
            "resolved": self.resolved,
            "rejected_typed": self.rejected_typed,
            "resp_errors": self.resp_errors,
            "digest": self.digest,
            "generation": self.generation,
            "dtypes": (list(self.dtypes) if self.dtypes else None),
            "digest_ok": not self._digest_flagged,
            "transport_failures": self.transport_failures,
            "live": self.live(now),
            "queue_depth": self.queue_depth,
            "brownout_level": self.brownout_level,
            "service_est_s": self.service_est_s,
            "spill_limit": self.spill_limit(),
            "heartbeat_age_s": self.heartbeat_age_s(),
            "spawns": self.spawns,
            "pid": (self.child.pid if self.child is not None else None),
            "addr": (list(self.addr) if self.addr else None),
            "prom_port": self.prom_port,
        }

    # -- transport ------------------------------------------------------
    def send_line(self, rec: dict) -> None:
        """One JSONL line to the replica; raises OSError on transport
        failure (caller owns the breaker/retry consequences)."""
        data = (json.dumps(rec) + "\n").encode()
        with self._send_lock:
            sock = self.sock
            if sock is None:
                raise OSError("not connected")
            sock.sendall(data)

    def close_socket(self) -> None:
        with self._send_lock:
            sock, self.sock = self.sock, None
        if sock is not None:
            try:
                sock.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                sock.close()
            except OSError:
                pass

    def close_log(self) -> None:
        if self._log_fh is not None:
            try:
                self._log_fh.close()
            except OSError:
                pass
            self._log_fh = None


class Router:
    """The replica-fleet front tier (module docstring; docs/serving.md,
    "Replica routing and failover").

    Construct with either ``replica_cmd`` (+ ``n_replicas``) to spawn
    and supervise engine processes — a list command template;
    ``{i}`` is substituted with the replica index and ``{ready}`` with
    the per-replica ready-file path (appended as ``--ready-file`` if
    the template omits it) — or ``attach`` (a list of ``(host, port)``
    or ``(host, port, prom_port)`` tuples) to route to replicas managed
    elsewhere (attached replicas are reconnected but never respawned).

    ``submit(images, ...)`` / ``submit_line(request_dict)`` return a
    Future resolving to the replica's response record (the
    ``{"id", "pred", "prob", "topk"}`` wire shape, id rewritten to the
    client's) or raising the typed verdict.  The ``stats`` attribute
    satisfies the loadgen endpoint protocol (``reset``/``snapshot``
    with an exact offered-traffic ledger), so ``loadgen.run_stream``
    drives a Router exactly like an engine.
    """

    def __init__(self, *, replica_cmd: Optional[List[str]] = None,
                 n_replicas: int = 2,
                 attach: Optional[List[Tuple]] = None,
                 state_dir: str = "router-state",
                 knee_rps: float = 0.0, spill_inflight: int = 0,
                 retry_ratio: float = 0.1, retry_cap: float = 32.0,
                 max_attempts: int = 3, retry_backoff_s: float = 0.05,
                 retry_backoff_cap_s: float = 1.0,
                 retry_window_s: float = 10.0,
                 breaker_threshold: int = 5,
                 breaker_cooldown_s: float = 1.0,
                 ping_interval_s: float = 0.25, ping_timeout_s: float = 3.0,
                 wedge_timeout_s: float = 15.0,
                 spawn_timeout_s: float = 300.0,
                 respawn_backoff_s: float = 0.5, max_respawns: int = 8,
                 grace_s: float = 10.0, drain_timeout_s: float = 30.0,
                 publish: Optional[Callable] = None,
                 ledger_path: str = "",
                 log: Optional[Callable[[str], None]] = None) -> None:
        if not replica_cmd and not attach:
            raise ValueError("Router needs replica_cmd (spawn) and/or "
                             "attach (existing replicas)")
        self.state_dir = os.path.abspath(state_dir)
        os.makedirs(self.state_dir, exist_ok=True)
        self.knee_rps = float(knee_rps)
        self.spill_inflight = int(spill_inflight)
        self.max_attempts = max(1, int(max_attempts))
        self.retry_backoff_s = max(0.0, float(retry_backoff_s))
        self.retry_backoff_cap_s = max(self.retry_backoff_s,
                                       float(retry_backoff_cap_s))
        self.retry_window_s = max(1.0, float(retry_window_s))
        self.breaker_threshold = int(breaker_threshold)
        self.breaker_cooldown_s = float(breaker_cooldown_s)
        self.ping_interval_s = float(ping_interval_s)
        self.ping_timeout_s = float(ping_timeout_s)
        self.wedge_timeout_s = float(wedge_timeout_s)
        self.spawn_timeout_s = float(spawn_timeout_s)
        self.respawn_backoff_s = float(respawn_backoff_s)
        self.max_respawns = int(max_respawns)
        self.grace_s = float(grace_s)
        self.drain_timeout_s = float(drain_timeout_s)
        self._publish_hook = publish
        self._log = log or (lambda msg: print(f"[router] {msg}",
                                              file=sys.stderr, flush=True))
        self.stats = RouterStats()
        self.stats.replica_state_fn = self.replica_health
        self.retry_budget = RetryBudget(ratio=retry_ratio, cap=retry_cap)
        self._lock = threading.Lock()
        # Model-identity gate (docs/serving.md, "Model lifecycle"): the
        # first digest a replica reports becomes the fleet's; replicas
        # reporting a digest outside the allowed set are refused
        # traffic — hot-swap must not silently open a heterogeneous
        # fleet.  The rollout driver widens the set (allow_digest)
        # for the canary's candidate and narrows it again on
        # promote/rollback (set_fleet_digest / disallow_digest).
        self.fleet_digest: Optional[str] = None
        self._allowed_digests: Set[str] = set()
        # Canary traffic split: (frozenset of replica names, fraction).
        # None = normal least-loaded routing over the whole fleet.
        self._split: Optional[Tuple[frozenset, float]] = None
        # Deterministic-seedable split draw (tests inject their own).
        self._split_rng = random.Random()
        # Optional per-outcome hook: fn(replica_name, kind, latency_s)
        # with kind in ("resolved", "rejected", "error") — the rollout
        # driver's canary-scoped SLO feed.  Called outside locks;
        # exceptions contained.
        self.outcome_hook: Optional[Callable] = None
        # Digest transitions noted under self._lock, published outside
        # it (events write files).  A LIST, not a single slot: several
        # replica reader threads can transition at once (e.g. a
        # rollback disallowing a digest two replicas still report) and
        # the ledger must record every one.
        self._digest_events: List[Tuple[str, str, str, Optional[str]]] = []
        self._ledger_lock = threading.Lock()
        self.ledger_path = ledger_path or os.path.join(
            self.state_dir, "router_ledger.jsonl")
        self._wire_ids = itertools.count(1)
        # Replay queue: a HEAP on due time, not a FIFO — entries carry
        # attempt-dependent backoffs (up to retry_backoff_cap_s), so a
        # long-backoff head must not head-of-line block already-due
        # replays behind it.  The seq tiebreaks equal due times
        # (_Request is not orderable) and keeps same-instant replays
        # FIFO.
        self._retryq: List[Tuple[float, int, _Request]] = []
        self._retry_seq = itertools.count()
        self._draining = False
        self._closed = False
        self._pump: Optional[threading.Thread] = None
        self._stop = threading.Event()
        self.replicas: List[_Replica] = []
        idx = 0
        for spec in (attach or ()):
            host, port = spec[0], int(spec[1])
            prom = int(spec[2]) if len(spec) > 2 and spec[2] else None
            rep = _Replica(idx, self, addr=(host, port), prom_port=prom)
            self.replicas.append(rep)
            idx += 1
        if replica_cmd:
            for _ in range(max(1, int(n_replicas))):
                rep = _Replica(idx, self, cmd=list(replica_cmd))
                self.replicas.append(rep)
                idx += 1

    # -- model identity / canary split ----------------------------------
    def allow_digest(self, digest: str) -> None:
        """Authorize a second model digest fleet-wide (the rollout
        driver calls this for the canary's candidate BEFORE shifting
        traffic to it)."""
        with self._lock:
            self._allowed_digests.add(str(digest))
        self._publish("router_replica", replica="*",
                      action="digest_allow", digest=str(digest))

    def disallow_digest(self, digest: str) -> None:
        """Withdraw a digest's authorization (rollback): replicas still
        reporting it are refused traffic until they swap back — even a
        failed swap-back cannot leak candidate predictions."""
        with self._lock:
            self._allowed_digests.discard(str(digest))
        self._publish("router_replica", replica="*",
                      action="digest_disallow", digest=str(digest))

    def set_fleet_digest(self, digest: str) -> None:
        """Promotion: the candidate digest becomes THE fleet digest and
        the only authorized one."""
        with self._lock:
            self.fleet_digest = str(digest)
            self._allowed_digests = {str(digest)}
        self._publish("router_replica", replica="*",
                      action="fleet_digest", digest=str(digest))

    def set_traffic_split(self, canaries, fraction: float) -> None:
        """Route ``fraction`` of pick decisions to the named canary
        replicas, the rest to everyone else (least-loaded within each
        group).  A group with no routable member falls back to the
        other — availability beats split fidelity mid-rollout."""
        names = frozenset(str(n) for n in canaries)
        frac = min(1.0, max(0.0, float(fraction)))
        with self._lock:
            self._split = (names, frac)
        self._publish("router_replica", replica="*", action="split",
                      canaries=sorted(names), fraction=frac)

    def clear_traffic_split(self) -> None:
        with self._lock:
            self._split = None
        self._publish("router_replica", replica="*", action="split_clear")

    def _note_digest_locked(self, rep: _Replica) -> None:
        """Adopt / flag a replica's reported digest (caller holds
        ``self._lock``).  First digest seen becomes the fleet's; a
        digest outside the allowed set flags the replica (refused by
        ``_pick``) until it matches again or the set widens."""
        d = rep.digest
        if d is None:
            return
        if self.fleet_digest is None:
            self.fleet_digest = d
            self._allowed_digests.add(d)
        flagged = d not in self._allowed_digests
        if flagged != rep._digest_flagged:
            rep._digest_flagged = flagged
            self._digest_events.append(
                (rep.name, "digest_mismatch" if flagged else "digest_ok",
                 d, self.fleet_digest))

    def _flush_digest_event(self) -> None:
        """Publish digest transitions recorded under the lock (events
        write files — never inside ``self._lock``)."""
        with self._lock:
            if not self._digest_events:
                return
            evs, self._digest_events = self._digest_events, []
        for name, action, digest, fleet in evs:
            self._publish("router_replica", replica=name, action=action,
                          digest=digest, fleet_digest=fleet)
            if action == "digest_mismatch":
                self._log(f"{name}: MODEL DIGEST MISMATCH ({digest} not "
                          f"in allowed set; fleet {fleet}) — refusing "
                          "to route to it")

    # -- control channel ------------------------------------------------
    def control_request(self, replica: str, payload: dict,
                        timeout_s: float = 120.0) -> dict:
        """One control line (e.g. ``{"op": "swap", ...}``) to the NAMED
        replica; blocks for its keyed response.

        Control requests are deliberately OUTSIDE the failover path:
        they are never replayed on a survivor (a swap replayed on a
        different replica would flip the wrong process), never counted
        in the offered-traffic ledger, and a replica death mid-request
        raises :class:`ReplicaLost`.  A wire error record raises its
        rebuilt typed exception — a gate's ``SwapRejected`` crosses the
        socket intact (tpuic/serve/wire.py)."""
        rep = None
        for r in self.replicas:
            if r.name == str(replica):
                rep = r
                break
        if rep is None:
            raise ValueError(f"no replica named {replica!r} "
                             f"(have: {[r.name for r in self.replicas]})")
        wire_id = f"c{next(self._wire_ids)}"
        fut: Future = Future()
        with self._lock:
            rep.control[wire_id] = fut
        try:
            rep.send_line({**payload, "id": wire_id})
        except OSError as e:
            with self._lock:
                rep.control.pop(wire_id, None)
            self._on_replica_down(rep, f"control send: {e}")
            raise ReplicaLost(f"control send to {rep.name} failed: {e}")
        try:
            return fut.result(timeout=timeout_s)
        except _FutTimeout:
            with self._lock:
                rep.control.pop(wire_id, None)
            raise TimeoutError(
                f"control request to {rep.name} timed out after "
                f"{timeout_s:g}s (op={payload.get('op')!r})") from None

    # -- telemetry ------------------------------------------------------
    def _publish(self, kind: str, **data) -> None:
        rec = {"event": kind, "t": round(time.time(), 3), **data}
        with self._ledger_lock:
            try:
                with open(self.ledger_path, "a") as f:
                    f.write(json.dumps(rec) + "\n")
            except OSError:
                pass  # a full disk must not take down routing
        if self._publish_hook is not None:
            try:
                self._publish_hook(kind, **data)
            except Exception:
                pass

    # -- lifecycle ------------------------------------------------------
    def start(self, timeout_s: Optional[float] = None) -> "Router":
        """Spawn/connect every replica and start the health pump.
        Blocks until every replica is up (or ``timeout_s``, default
        ``spawn_timeout_s``); raises RuntimeError if none made it —
        a router with zero replicas can only shed."""
        for rep in self.replicas:
            if rep.cmd is not None:
                self._spawn(rep)
            else:
                self._try_connect(rep)
        self._stop.clear()
        self._pump = threading.Thread(target=self._pump_loop, daemon=True,
                                      name="tpuic-router-pump")
        self._pump.start()
        deadline = time.monotonic() + (self.spawn_timeout_s
                                       if timeout_s is None else timeout_s)
        while time.monotonic() < deadline:
            states = [r.state for r in self.replicas]
            if all(s == UP for s in states):
                return self
            if all(s in (FAILED, STOPPED) for s in states):
                break
            time.sleep(0.05)
        up = sum(r.state == UP for r in self.replicas)
        if up == 0:
            self.close(drain=False)
            raise RuntimeError(
                f"no replica became ready within the spawn timeout "
                f"(states: {[r.state for r in self.replicas]}; see "
                f"per-replica logs under {self.state_dir})")
        self._log(f"started with {up}/{len(self.replicas)} replicas up")
        return self

    def _spawn(self, rep: _Replica) -> None:
        rep.spawns += 1
        try:
            os.remove(rep.ready_file)
        except OSError:
            pass
        cmd = []
        for a in rep.cmd:
            cmd.append(a.replace("{i}", str(rep.idx))
                       .replace("{ready}", rep.ready_file))
        if "--ready-file" not in " ".join(cmd):
            cmd += ["--ready-file", rep.ready_file]
        rep.child = _Child(
            cmd, heartbeat_file=rep.heartbeat_file,
            stack_dump=os.path.join(rep.state_dir,
                                    f"stackdump-{rep.spawns}.txt"),
            flight_dump=os.path.join(rep.state_dir,
                                     f"flightdump-{rep.spawns}.jsonl"),
            label=rep.name)
        rep.close_log()
        rep._log_fh = open(rep.log_file, "a")
        rep.child.spawn(dict(os.environ), stdout=rep._log_fh,
                        stderr=subprocess.STDOUT)
        rep.state = STARTING
        rep.started_at = time.monotonic()
        self._publish("router_replica", replica=rep.name, state=STARTING,
                      action="spawn", spawn=rep.spawns, pid=rep.child.pid)

    def _try_connect(self, rep: _Replica) -> bool:
        if rep.addr is None:
            return False
        try:
            sock = socket.create_connection(rep.addr, timeout=2.0)
        except OSError:
            return False
        sock.settimeout(2.0)  # send timeout; recv loop handles its own
        rep.sock = sock
        rep.connected_at = time.monotonic()
        rep.last_pong = rep.connected_at
        rep.state = UP
        rep.reader = threading.Thread(
            target=self._reader, args=(rep, sock), daemon=True,
            name=f"tpuic-router-read-{rep.name}")
        rep.reader.start()
        self._publish("router_replica", replica=rep.name, state=UP,
                      action="connect", addr=list(rep.addr))
        self._log(f"{rep.name}: connected to {rep.addr[0]}:{rep.addr[1]}"
                  + (f" (breaker {rep.breaker.state})"
                     if rep.breaker.state != "closed" else ""))
        return True

    # -- submit path ----------------------------------------------------
    def submit(self, images=None, *, line: Optional[dict] = None,
               timeout: Optional[float] = None,
               priority: str = DEFAULT_PRIORITY,
               deadline_ms: Optional[float] = None,
               tenant: Optional[str] = None,
               idempotent: bool = True,
               client_id: str = "") -> Future:
        """Route one request; returns a Future resolving to the
        replica's response record (or raising its typed verdict).

        ``images``: a duck-typed array (``.tobytes()``/``.shape``/
        ``.dtype``) shipped as a base64 payload — the router never
        imports numpy.  A dict is treated as a raw request line
        (``line``).  ``timeout`` bounds the wait for a routable replica
        (None blocks, 0 sheds immediately — the engine's backpressure
        contract).  ``idempotent=False`` marks the request
        non-replayable: if its replica dies mid-flight it resolves with
        :class:`ReplicaLost` instead of being requeued."""
        if isinstance(images, dict) and line is None:
            images, line = None, images
        payload: dict = dict(line or {})
        payload.pop("id", None)
        if payload.get("op") is not None:
            # Control lines (swap, ping) must NEVER ride the data path:
            # submit() failover-replays idempotent requests onto
            # survivors — a replayed swap would flip a replica nobody
            # named — and an unauthenticated front-end forwarding raw
            # lines here must not be a one-line weight flip.  The only
            # control channel is Router.control_request.
            raise ValueError(
                f"control line op={payload['op']!r} cannot ride the "
                "data path — use Router.control_request")
        if images is not None:
            payload.update(wire.encode_array(images))
        if priority != DEFAULT_PRIORITY or "priority" in payload:
            payload.setdefault("priority", priority)
        priority = payload.get("priority", priority)
        priority_index(priority)  # validate early, typed error
        if deadline_ms is not None:
            payload.setdefault("deadline_ms", float(deadline_ms))
        if tenant is not None:
            payload.setdefault("tenant", tenant)
        idempotent = bool(payload.pop("idempotent", idempotent))
        req = _Request(client_id, payload, priority=priority, tenant=tenant,
                       idempotent=idempotent,
                       deadline_ms=payload.get("deadline_ms"))
        self.stats.record_offered()
        if self._draining or self._closed:
            self._resolve_reject(req, AdmissionRejected(
                "router draining", cause="queue_full", priority=priority,
                tenant=tenant))
            return req.future
        self._dispatch(req, timeout=timeout)
        return req.future

    def submit_line(self, req_line: dict) -> Tuple[str, Future]:
        """CLI accept path: one parsed request line (path-based or b64)
        routed non-blocking.  Returns ``(client_id, future)``."""
        rid = str(req_line.get("id", req_line.get("path", "?")))
        fut = self.submit(line=dict(req_line), timeout=0, client_id=rid)
        return rid, fut

    def _resolve_reject(self, req: _Request, exc: Exception) -> None:
        from tpuic.serve.admission import AdmissionError
        if isinstance(exc, AdmissionError):
            self.stats.record_reject(exc.cause, req.priority)
        else:
            self.stats.record_error()
        if not req.future.done():
            req.future.set_exception(exc)

    def _try_once(self, req: _Request) -> Tuple[bool, Optional[str]]:
        """ONE non-blocking route attempt: pick + send, re-picking past
        transport failures until either the request is handled (sent,
        or typed-resolved) or no replica is routable right now.
        Returns ``(handled, why_not)``.  Never sleeps — safe on the
        health-pump thread."""
        if req.deadline is not None and time.monotonic() > req.deadline:
            self._resolve_reject(req, DeadlineExceeded(
                "deadline expired before a replica accepted it",
                priority=req.priority, tenant=req.tenant))
            return True, None
        while True:
            rep, why = self._pick(req.priority)
            if rep is None:
                return False, why
            if self._send(rep, req):
                return True, None
            # transport failure: breaker recorded, socket condemned —
            # the next pick sees it unroutable; loop is bounded by
            # replicas going unroutable.

    def _dispatch(self, req: _Request,
                  timeout: Optional[float] = 0.0) -> None:
        """Pick a replica and send; shed typed when none is routable
        within ``timeout``.  Runs on caller threads (submit)."""
        deadline = (None if timeout is None
                    else time.monotonic() + max(0.0, timeout))
        while True:
            handled, why = self._try_once(req)
            if handled:
                return
            if self._draining or self._closed:
                why = "router draining"
            elif deadline is not None and time.monotonic() >= deadline:
                pass  # shed below
            else:
                time.sleep(0.005)
                continue
            self._resolve_reject(req, AdmissionRejected(
                f"router shed: {why} (priority={req.priority})",
                cause="queue_full", priority=req.priority,
                tenant=req.tenant))
            return

    def _pick(self, priority: str
              ) -> Tuple[Optional[_Replica], Optional[str]]:
        """Least-loaded shed-aware selection (module docstring), behind
        the model-identity gate and the canary traffic split."""
        now = time.monotonic()
        with self._lock:
            cands = [r for r in self.replicas
                     if r.state == UP and r.live(now)]
            if not cands:
                return None, "no live replica"
            # Model-identity gate (docs/serving.md, "Model lifecycle"):
            # a replica reporting a digest outside the allowed set is
            # refused — a hot-swap mid-rollout must not silently serve
            # unauthorized weights.  Unknown digests (no signal yet,
            # pre-identity replicas) pass: the gate refuses proven
            # heterogeneity, it does not demand proof of homogeneity.
            gated = [r for r in cands if not r._digest_flagged]
            if not gated:
                return None, (f"all {len(cands)} live replicas refused: "
                              "model digest outside the allowed set")
            cands = gated
            split = self._split
            if split is not None:
                names, frac = split
                canary = [r for r in cands if r.name in names]
                rest = [r for r in cands if r.name not in names]
                if canary and rest:
                    at_limit = lambda grp: all(  # noqa: E731
                        len(r.inflight) >= r.spill_limit() for r in grp)
                    chosen = (canary if self._split_rng.random() < frac
                              else rest)
                    other = rest if chosen is canary else canary
                    # Availability over split fidelity: a group at its
                    # spill limit falls back to the other instead of
                    # shedding while capacity idles.
                    if at_limit(chosen) and not at_limit(other):
                        chosen = other
                    cands = chosen
            ranked = sorted(
                cands, key=lambda r: (r.sheds(priority),
                                      len(r.inflight) >= r.spill_limit(),
                                      len(r.inflight), r.routed))
            if all(len(r.inflight) >= r.spill_limit() for r in ranked):
                # Shed-aware: every replica is at/past its committed
                # knee — spilling anywhere buys queueing toward a
                # timeout, so the router sheds typed instead.
                return None, (f"all {len(ranked)} replicas at their "
                              "spill limit")
        for rep in ranked:
            if rep.breaker.try_acquire():
                return rep, None
        return None, "breaker open on every live replica"

    def _send(self, rep: _Replica, req: _Request) -> bool:
        req.attempts += 1
        wire_id = f"q{next(self._wire_ids)}"
        req.wire_id = wire_id
        with self._lock:
            rep.inflight[wire_id] = req
            rep.routed += 1
        try:
            rep.send_line({**req.payload, "id": wire_id})
        except OSError as e:
            # A failed sendall may have left a PARTIAL line on the
            # socket: the connection's framing is indeterminate and
            # every later request on it would be corrupted — conclusive
            # for this connection, exactly like a recv error.  Pop our
            # own wire id first (this request is handled HERE), then
            # run the down path directly — it trips the breaker, closes
            # the socket, and fails over whatever else is in flight.
            # Don't wait for the reader to notice the close: whether
            # the sender or the reader sees the failure first is a
            # race, and _on_replica_down is idempotent either way.
            with self._lock:
                owned = rep.inflight.pop(wire_id, None) is not None
            self._on_replica_down(rep, f"send: {e}")
            if not owned:
                # The reader's failover beat us to the pop and owns the
                # request now (replay or typed verdict) — a second
                # dispatch here would double-route it.
                return True
            if not req.idempotent:
                # The line may have partially left; a non-idempotent
                # request cannot risk double execution.
                self._resolve_reject(req, ReplicaLost(
                    f"send to {rep.name} failed and the request is "
                    f"not idempotent: {e}", priority=req.priority,
                    tenant=req.tenant))
                return True  # handled (verdict delivered)
            return False
        # A successful send is NOT a breaker success — only a delivered
        # response is (the reader records it); half-open probes stay
        # out until their outcome arrives.
        return True

    # -- replica reader -------------------------------------------------
    def _reader(self, rep: _Replica, sock: socket.socket) -> None:
        buf = b""
        reason = "connection closed by replica"
        while not self._stop.is_set():
            try:
                sock.settimeout(0.5)
                chunk = sock.recv(1 << 16)
            except socket.timeout:
                continue
            except OSError as e:
                reason = f"recv: {e}"
                chunk = b""
            if not chunk:
                break
            *lines, buf = (buf + chunk).split(b"\n")
            for raw in lines:
                if raw.strip():
                    self._on_line(rep, raw.decode("utf-8", "replace"))
        with rep._send_lock:
            # rep.sock is sock: EOF/error on the live socket — the
            # replica is gone.  rep.sock is None: somebody condemned
            # THIS connection (close_socket nulls the slot before
            # shutting the fd down) — the down path must still run,
            # and _on_replica_down's state check makes the second
            # call from a racing sender/ping a no-op.  Only a non-None
            # DIFFERENT socket means a reconnect already replaced this
            # connection; downing the replica then would kill the new
            # link.
            replaced = rep.sock is not None and rep.sock is not sock
        if not self._stop.is_set() and not replaced:
            self._on_replica_down(rep, reason)

    def _on_line(self, rep: _Replica, raw: str) -> None:
        try:
            rec = json.loads(raw)
            if not isinstance(rec, dict):
                raise ValueError
        except ValueError:
            return  # a torn line from a dying replica
        if rec.get("op") == "pong":
            rep.last_pong = time.monotonic()
            if rec.get("queue_depth") is not None:
                rep.queue_depth = int(rec["queue_depth"])
            if rec.get("generation") is not None:
                rep.generation = int(rec["generation"])
            if rec.get("digest") is not None:
                # Live model identity: a hot-swap shows up here within
                # one ping interval, and the identity gate reacts
                # before the next pick.
                with self._lock:
                    rep.digest = str(rec["digest"])
                    self._note_digest_locked(rep)
                self._flush_digest_event()
            return
        wire_id = rec.get("id")
        with self._lock:
            ctl = (rep.control.pop(wire_id, None)
                   if wire_id is not None else None)
            req = (rep.inflight.pop(wire_id, None)
                   if ctl is None else None)
        if ctl is not None:
            # Control-channel outcome (swap lines): typed errors rebuild
            # to the exception the in-process gate would have raised;
            # breaker credit applies (the transport worked), the
            # offered-traffic ledger is untouched.
            rep.breaker.record_success()
            if ctl.done():
                return
            if "error" in rec:
                ctl.set_exception(wire.rebuild_error(rec))
            else:
                ctl.set_result(dict(rec))
            return
        if req is None:
            if (isinstance(wire_id, str) and wire_id[:1] in ("q", "c")
                    and wire_id[1:].isdigit()):
                # An id this router issued (request or control), no
                # longer in flight: a late duplicate (e.g. the original
                # response raced a failover replay, or a control
                # response landed after its timeout).  At-most-once =
                # first wins.
                self.stats.record_duplicate()
            else:
                # An id we never issued (a replica's id-less
                # bad-request-line answer, torn framing): a protocol
                # symptom, counted apart from dedupe activity.
                self.stats.record_wire_error()
            return
        rep.breaker.record_success()
        self.retry_budget.deposit()
        if req.future.done():
            self.stats.record_duplicate()
            return
        if "error" in rec:
            exc = wire.rebuild_error(rec)
            from tpuic.serve.admission import AdmissionError
            if isinstance(exc, AdmissionError):
                self.stats.record_reject(exc.cause, req.priority)
                rep.rejected_typed += 1
                self._outcome(rep.name, "rejected", None)
            else:
                self.stats.record_error()
                rep.resp_errors += 1
                self._outcome(rep.name, "error", None)
            req.future.set_exception(exc)
            return
        out = dict(rec)
        out["id"] = req.client_id
        out["replica"] = rep.name
        latency_s = time.monotonic() - req.t_offered
        self.stats.record_resolved(latency_s)
        rep.resolved += 1
        if req.attempts > 1:
            # The outcome hook contract loadgen.run_stream consumes:
            # replayed requests stamp their retry count on the future.
            req.future.tpuic_retries = req.attempts - 1
        req.future.set_result(out)
        self._outcome(rep.name, "resolved", latency_s)

    def _outcome(self, replica: str, kind: str,
                 latency_s: Optional[float]) -> None:
        """Invoke the optional per-outcome hook (rollout driver's
        canary-scoped SLO feed) — contained, outside locks."""
        hook = self.outcome_hook
        if hook is None:
            return
        try:
            hook(replica, kind, latency_s)
        except Exception:  # a monitoring hook must never kill routing
            pass

    # -- failure handling -----------------------------------------------
    def _on_replica_down(self, rep: _Replica, reason: str) -> None:
        with self._lock:
            if rep.state in (DOWN, FAILED, STOPPED):
                return
            was_wedged = rep.state == WEDGED
            rep.state = DOWN
            orphans = list(rep.inflight.values())
            rep.inflight.clear()
            controls = list(rep.control.values())
            rep.control.clear()
            rep.respawn_at = (time.monotonic() + self.respawn_backoff_s
                              * (2.0 ** min(6, rep.consecutive_spawn_failures)))
        rep.close_socket()
        rep.transport_failures += 1
        rep.breaker.trip(f"connection lost: {reason}")
        for ctl in controls:
            # Control requests never fail over (a swap replayed on a
            # survivor would flip the wrong replica): the caller gets
            # the typed loss verdict and decides.
            if not ctl.done():
                ctl.set_exception(ReplicaLost(
                    f"replica {rep.name} lost mid-control-request "
                    f"({reason})"))
        requeued = lost = 0
        for req in orphans:
            if req.future.done():
                continue
            if not req.idempotent:
                self._resolve_reject(req, ReplicaLost(
                    f"replica {rep.name} lost mid-request and the "
                    "request is not idempotent", priority=req.priority,
                    tenant=req.tenant))
                lost += 1
            elif req.attempts >= self.max_attempts:
                self._resolve_reject(req, ReplicaLost(
                    f"replica {rep.name} lost mid-request; "
                    f"{req.attempts} attempts exhausted",
                    priority=req.priority, tenant=req.tenant))
                lost += 1
            elif not self.retry_budget.try_retry():
                self._resolve_reject(req, ReplicaLost(
                    f"replica {rep.name} lost mid-request; retry "
                    "budget exhausted (no retry storms)",
                    priority=req.priority, tenant=req.tenant))
                lost += 1
            else:
                self.stats.record_retry()
                delay = min(self.retry_backoff_cap_s,
                            self.retry_backoff_s
                            * (2.0 ** max(0, req.attempts - 1)))
                if req.retry_deadline is None:
                    req.retry_deadline = (time.monotonic()
                                          + self.retry_window_s)
                with self._lock:
                    heapq.heappush(self._retryq,
                                   (time.monotonic() + delay,
                                    next(self._retry_seq), req))
                self._publish("router_retry", replica=rep.name,
                              attempt=req.attempts + 1,
                              backoff_s=round(delay, 4),
                              budget=self.retry_budget.state()["tokens"])
                requeued += 1
        if requeued or lost:
            self.stats.record_failover(requeued, lost)
        self._publish("router_failover", replica=rep.name, reason=reason,
                      requeued=requeued, lost=lost, wedged=was_wedged)
        self._publish("router_replica", replica=rep.name, state=DOWN,
                      action="down", reason=reason)
        self._log(f"{rep.name}: DOWN ({reason}) — {requeued} in-flight "
                  f"requeued, {lost} replica_lost")

    def _declare_wedge(self, rep: _Replica, age: float) -> None:
        """Heartbeat stale past the watchdog: the _Child escalation
        ladder (SIGQUIT stacks + flight dump → TERM flush → KILL), then
        the normal down/respawn path.  Runs the blocking ladder on its
        own thread so pings/retries keep flowing."""
        with self._lock:
            if rep.state != UP:
                return
            rep.state = WEDGED
        self._publish("router_replica", replica=rep.name, state=WEDGED,
                      action="wedge", heartbeat_age_s=round(age, 1),
                      stack_dump=(rep.child.stack_dump
                                  if rep.child else None))
        self._log(f"{rep.name}: WEDGE — heartbeat stale {age:.1f}s; "
                  "escalating SIGQUIT→TERM→KILL")
        rep.close_socket()  # reader EOF -> immediate failover of in-flight

        def _ladder() -> None:
            try:
                if rep.child is not None and rep.child.alive():
                    rep.child.escalate(quit_wait_s=2.0,
                                       grace_s=self.grace_s)
            finally:
                # Backstop only: the reader's EOF normally ran the
                # down path long before the ladder finishes.  Gate on
                # still-WEDGED so a replica that was already downed
                # AND respawned (state STARTING by now) is not
                # condemned a second time.
                if rep.state == WEDGED:
                    self._on_replica_down(rep, "wedge escalation")

        threading.Thread(target=_ladder, daemon=True,
                         name=f"tpuic-router-escalate-{rep.name}").start()

    # -- the pump (health, retries, respawn) -----------------------------
    def _pump_loop(self) -> None:
        while not self._stop.is_set():
            now = time.monotonic()
            self._pump_retries(now)
            for rep in self.replicas:
                try:
                    self._pump_replica(rep, now)
                except Exception as e:  # health must never kill routing
                    self._log(f"{rep.name}: pump error: {e}")
            self._stop.wait(0.05)

    def _pump_retries(self, now: float) -> None:
        """Dispatch due replays WITHOUT blocking: the pump is also the
        fleet's health heartbeat, and a failover burst sleeping here
        would stop pings exactly when the survivors' liveness matters
        most.  A replay that finds nothing routable right now re-queues
        on a short tick until its retry window closes, then resolves
        typed replica_lost (the budget was already spent — honest
        accounting beats a second withdrawal)."""
        requeue = []
        while True:
            with self._lock:
                if not self._retryq or self._retryq[0][0] > now:
                    break
                _, _, req = heapq.heappop(self._retryq)
            handled, why = self._try_once(req)
            if handled:
                continue
            if (self._draining or self._closed
                    or (req.retry_deadline is not None
                        and now > req.retry_deadline)):
                self._resolve_reject(req, ReplicaLost(
                    f"failover replay found no routable replica "
                    f"({why})", priority=req.priority,
                    tenant=req.tenant))
            else:
                requeue.append((now + 0.05, req))
        if requeue:
            with self._lock:
                for due, req in requeue:
                    heapq.heappush(self._retryq,
                                   (due, next(self._retry_seq), req))

    def _pump_replica(self, rep: _Replica, now: float) -> None:
        if rep.state == UP:
            if now - rep.last_ping_sent >= self.ping_interval_s:
                rep.last_ping_sent = now
                try:
                    rep.send_line({"op": "ping", "id": f"hp{rep.idx}"})
                except OSError as e:
                    # A torn ping corrupts the framing for everything
                    # after it — conclusive: run the down/failover
                    # path directly (trips the breaker, closes the
                    # socket, requeues in-flight) instead of waiting
                    # for the reader to notice the close.
                    self._on_replica_down(rep, f"ping send: {e}")
                    return
            if (not rep.live(now)
                    and now - rep.connected_at > self.ping_timeout_s
                    and now - rep._last_timeout_fail > self.ping_timeout_s):
                # Pings go unanswered: one transport failure per timeout
                # window accrues toward the breaker (the live() gate
                # already unroutes the replica meanwhile).
                rep._last_timeout_fail = now
                rep.transport_failures += 1
                rep.breaker.record_failure("ping timeout")
            if rep.child is not None:
                age = rep.heartbeat_age_s()
                if age is not None and age > self.wedge_timeout_s:
                    self._declare_wedge(rep, age)
                    return
            if (rep.prom_port and now - rep.last_scrape >= 1.0):
                rep.last_scrape = now
                self._scrape(rep)
            return
        if rep.state == STARTING:
            self._pump_starting(rep, now)
            return
        if rep.state == DOWN:
            if rep.cmd is None:
                # Attached replica: reconnect (the breaker's half-open
                # probe governs rejoin) with backoff.
                if now >= rep.respawn_at:
                    rep.respawn_at = now + min(
                        5.0, self.respawn_backoff_s
                        * (2.0 ** min(6, rep.consecutive_spawn_failures)))
                    if self._try_connect(rep):
                        rep.consecutive_spawn_failures = 0
                    else:
                        rep.consecutive_spawn_failures += 1
                return
            if self._draining or self._closed:
                return
            if rep.spawns >= self.max_respawns + 1:
                rep.state = FAILED
                self._publish("router_replica", replica=rep.name,
                              state=FAILED, action="giveup",
                              spawns=rep.spawns)
                self._log(f"{rep.name}: FAILED — respawn budget "
                          f"exhausted ({rep.spawns} spawns)")
                return
            if now >= rep.respawn_at and (rep.child is None
                                          or not rep.child.alive()):
                if rep.child is not None and rep.child.proc is not None:
                    rep.child.proc.poll()  # reap: no zombie per respawn
                self._spawn(rep)

    def _pump_starting(self, rep: _Replica, now: float) -> None:
        if rep.cmd is None:
            # Attached replica: keep knocking on the configured address.
            if now >= rep.respawn_at:
                rep.respawn_at = now + 0.5
                self._try_connect(rep)
            return
        if rep.child is not None and rep.child.poll() is not None:
            rep.consecutive_spawn_failures += 1
            self._on_replica_down(
                rep, f"exited {rep.child.poll()} during startup")
            return
        if now - rep.started_at > self.spawn_timeout_s:
            rep.consecutive_spawn_failures += 1
            if rep.child is not None:
                rep.child.term()
                rep.child.wait_or_kill(self.grace_s)
            self._on_replica_down(rep, "startup timeout")
            return
        ready = wire.read_ready_file(rep.ready_file)
        if ready is None:
            return
        if (rep.child is not None and rep.child.pid is not None
                and ready.get("pid") not in (None, rep.child.pid)):
            return  # stale file from a previous life
        port = ready.get("port")
        if port is None:
            return
        rep.addr = ("127.0.0.1", int(port))
        if ready.get("prom_port"):
            rep.prom_port = int(ready["prom_port"])
        if ready.get("dtypes"):
            rep.dtypes = tuple(str(t) for t in ready["dtypes"])
        if ready.get("generation") is not None:
            rep.generation = int(ready["generation"])
        if ready.get("digest"):
            # Boot identity from the handoff (live identity rides the
            # pongs): the heterogeneous-fleet gate engages before the
            # first request is ever routed to this replica.
            with self._lock:
                rep.digest = str(ready["digest"])
                self._note_digest_locked(rep)
            self._flush_digest_event()
        if self._try_connect(rep):
            rep.consecutive_spawn_failures = 0

    def _scrape(self, rep: _Replica) -> None:
        """Best-effort scrape of the replica's own prom exposition:
        brownout level (the shed-aware routing signal) and the span
        ledger's post-queue p50s (the service estimate the spill limit
        consumes — the same sum as ServeStats.estimated_service_s)."""
        import urllib.request
        try:
            with urllib.request.urlopen(
                    f"http://127.0.0.1:{rep.prom_port}/metrics",
                    timeout=0.8) as resp:
                text = resp.read().decode("utf-8", "replace")
        except Exception:
            return  # monitoring outage != replica outage
        est = 0.0
        for ln in text.splitlines():
            if ln.startswith("#") or not ln.strip():
                continue
            try:
                key, val = ln.rsplit(None, 1)
                v = float(val)
            except ValueError:
                continue
            if key.startswith("tpuic_serve_brownout_level"):
                rep.brownout_level = int(v)
            elif key.startswith("tpuic_serve_span_ms{phase=\""):
                phase = key.split('phase="', 1)[1].split('"', 1)[0]
                if phase != "queue" and 'quantile="p50"' in key:
                    est += v / 1000.0
        if est > 0:
            rep.service_est_s = est

    # -- views ----------------------------------------------------------
    def replica_health(self) -> dict:
        return {rep.name: rep.health() for rep in self.replicas}

    def snapshot(self) -> dict:
        """Stats + retry budget + per-replica health + model-identity
        state, one JSON-able dict (the prom exposition's input)."""
        out = self.stats.snapshot()
        out["retry_budget"] = self.retry_budget.state()
        with self._lock:
            out["fleet_digest"] = self.fleet_digest
            out["allowed_digests"] = sorted(self._allowed_digests)
            split = self._split
        out["traffic_split"] = (
            {"canaries": sorted(split[0]), "fraction": split[1]}
            if split is not None else None)
        return out

    # -- drain / close ---------------------------------------------------
    def drain(self, timeout_s: Optional[float] = None) -> int:
        """Stop accepting (new submits shed typed), wait out in-flight
        and queued replays up to the timeout, then resolve stragglers
        with a typed ``replica_lost`` verdict (the fleet is going
        away).  Returns the straggler count.  The PR-2 preemption
        contract: everything accepted either resolves or gets an
        explicit typed verdict — never a silent drop."""
        self._draining = True
        deadline = time.monotonic() + (self.drain_timeout_s
                                       if timeout_s is None else timeout_s)
        while time.monotonic() < deadline:
            with self._lock:
                pending = (sum(len(r.inflight) for r in self.replicas)
                           + len(self._retryq))
            if pending == 0:
                return 0
            time.sleep(0.02)
        stragglers: List[_Request] = []
        with self._lock:
            for rep in self.replicas:
                stragglers.extend(rep.inflight.values())
                rep.inflight.clear()
            stragglers.extend(req for _, _, req in self._retryq)
            self._retryq.clear()
        n = 0
        for req in stragglers:
            if req.future.done():
                continue
            n += 1
            self._resolve_reject(req, ReplicaLost(
                "drain timeout: router shutting down before this "
                "request finished", priority=req.priority,
                tenant=req.tenant))
        if n:
            self._log(f"drain: {n} straggler(s) resolved replica_lost")
        return n

    def close(self, drain: bool = True) -> None:
        """Drain (optionally), then stop the fleet: one SIGTERM per
        replica (the engine's own graceful drain — _Child.term's
        one-TERM-per-pid guard), the grace window, SIGKILL leftovers,
        reap, close sockets and threads."""
        if self._closed:
            return
        if drain:
            self.drain()
        self._closed = True
        self._draining = True
        self._stop.set()
        for rep in self.replicas:
            if rep.child is not None and rep.child.alive():
                rep.child.term()
        for rep in self.replicas:
            if rep.child is not None and rep.child.proc is not None:
                try:
                    rep.child.wait_or_kill(self.grace_s)
                except Exception:
                    pass
            rep.state = STOPPED if rep.state != FAILED else FAILED
            rep.close_socket()
            rep.close_log()
        if self._pump is not None:
            self._pump.join(timeout=2.0)
            self._pump = None
        for rep in self.replicas:
            if rep.reader is not None:
                rep.reader.join(timeout=2.0)
        self._publish("router_replica", replica="*", state=STOPPED,
                      action="close")

    def __enter__(self) -> "Router":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


# -- CLI ---------------------------------------------------------------------
def pump_stdin(handle: Callable[[str], None], guard,
               beat: Optional[Callable[[], None]] = None) -> None:
    """Select-gated raw stdin pump (the serve driver's idiom: PEP 475
    would resume a blocked readline right through SIGTERM; raw os.read
    because TextIOWrapper buffering hides burst-written lines from
    select).  Shared by the router CLI and the rollout CLI — one
    implementation of the accept loop, not three.  ``handle`` gets each
    complete line; ``beat`` ticks the supervised-liveness heartbeat."""
    import select
    try:
        stdin_fd = sys.stdin.fileno()
    except (ValueError, OSError, AttributeError):
        stdin_fd = None
    if stdin_fd is None:
        for line in sys.stdin:
            if guard.triggered:
                return
            handle(line)
        return
    tail = b""
    while not guard.triggered:
        try:
            ready, _, _ = select.select([stdin_fd], [], [], 0.2)
        except (OSError, ValueError):
            return
        if beat is not None:
            beat()
        if not ready:
            continue
        chunk = os.read(stdin_fd, 1 << 16)
        if not chunk:
            break  # EOF
        *lines, tail = (tail + chunk).split(b"\n")
        for raw in lines:
            handle(raw.decode("utf-8", "replace"))
    if tail.strip() and not guard.triggered:
        handle(tail.decode("utf-8", "replace"))


def make_line_handler(router: Router, out, out_lock: threading.Lock
                      ) -> Callable[[str], None]:
    """Client-line handler for the stdin CLIs (router and rollout):
    parse one JSONL request line, route it, write the outcome (result
    record or typed error line) to ``out`` under ``out_lock``.  One
    implementation so the two CLIs cannot drift on the wire shape."""

    def emit_outcome(rid: str, fut) -> None:
        try:
            rec = fut.result()
            line = json.dumps({**rec, "id": rid}) + "\n"
        except Exception as e:  # noqa: BLE001 — typed via the one encoder
            line = wire.error_line(rid, e)
        with out_lock:
            out.write(line)
            out.flush()

    def handle(line: str) -> None:
        line = line.strip()
        if not line:
            return
        try:
            req = json.loads(line)
            if not isinstance(req, dict):
                raise ValueError
        except ValueError:
            with out_lock:
                out.write(wire.error_line(
                    None, f"bad request line: {line[:80]}"))
                out.flush()
            return
        try:
            rid, fut = router.submit_line(req)
        except (ValueError, TypeError) as e:
            with out_lock:
                out.write(wire.error_line(
                    str(req.get("id", "?")), e))
                out.flush()
            return
        fut.add_done_callback(lambda f, rid=rid: emit_outcome(rid, f))

    return handle


def main(argv=None) -> int:
    """``python -m tpuic.serve.router`` — stdin-JSONL in, fleet out.

    Same client protocol as ``python -m tpuic.serve`` stdin mode
    (``{"id", "path", ...}`` per line; responses/typed error lines to
    --out, keyed by id — responses may arrive out of submission
    order).  Lines may carry ``"idempotent": false`` to forbid
    failover replay for that request."""
    import argparse

    p = argparse.ArgumentParser(
        description="Replica-fleet router over socket-JSONL engine "
                    "replicas (docs/serving.md)")
    p.add_argument("--replicas", type=int, default=2,
                   help="replica count to spawn from --replica-cmd")
    p.add_argument("--replica-cmd", default="",
                   help="replica command template ({i} = index, {ready} "
                        "= ready-file path); must include --listen. "
                        "E.g.: 'python -m tpuic.serve --synthetic-init "
                        "--model resnet18-cifar --num-classes 10 "
                        "--listen 127.0.0.1:0 --prom-port 0'")
    p.add_argument("--attach", action="append", default=[],
                   metavar="HOST:PORT[:PROMPORT]",
                   help="attach to an already-running replica "
                        "(repeatable; reconnected but never respawned)")
    p.add_argument("--state-dir", default="router-state")
    p.add_argument("--knee-rps", type=float, default=0.0,
                   help="committed per-replica latency knee (req/s, "
                        "perf/bench_serve.json) — with the scraped "
                        "service estimate it sets the spill limit")
    p.add_argument("--spill-inflight", type=int, default=0,
                   help="explicit per-replica in-flight spill limit "
                        "(overrides the knee-derived one)")
    p.add_argument("--retry-ratio", type=float, default=0.1)
    p.add_argument("--retry-cap", type=float, default=32.0)
    p.add_argument("--max-attempts", type=int, default=3)
    p.add_argument("--breaker-threshold", type=int, default=5)
    p.add_argument("--breaker-cooldown-s", type=float, default=1.0)
    p.add_argument("--ping-interval-s", type=float, default=0.25)
    p.add_argument("--ping-timeout-s", type=float, default=3.0)
    p.add_argument("--wedge-timeout-s", type=float, default=15.0)
    p.add_argument("--spawn-timeout-s", type=float, default=300.0)
    p.add_argument("--drain-timeout", type=float, default=30.0)
    p.add_argument("--http-port", type=int, default=0,
                   help="HTTP front-end (tpuic/serve/http.py): POST "
                        "/predict with typed-verdict 429/503 mapping + "
                        "Retry-After, GET /healthz, GET /metrics. "
                        "0 disables; -1 binds a kernel-assigned port "
                        "(logged)")
    p.add_argument("--http-host", default="127.0.0.1",
                   help="interface for --http-port (loopback default — "
                        "unauthenticated; bind 0.0.0.0 only behind a "
                        "firewall/load balancer)")
    p.add_argument("--prom-port", type=int, default=0,
                   help="serve the router's own tpuic_router_* "
                        "/metrics exposition on this port (0 disables)")
    p.add_argument("--prom-host", default="127.0.0.1")
    p.add_argument("--prom-dump", default="",
                   help="write the router exposition here on shutdown")
    p.add_argument("--out", default="", help="output JSONL (default stdout)")
    args = p.parse_args(argv)

    attach = []
    for spec in args.attach:
        parts = spec.split(":")
        if len(parts) < 2:
            raise SystemExit(f"router: bad --attach {spec!r} "
                             "(expected HOST:PORT[:PROMPORT])")
        attach.append((parts[0], int(parts[1]),
                       int(parts[2]) if len(parts) > 2 else None))
    cmd = shlex.split(args.replica_cmd) if args.replica_cmd else None
    if not cmd and not attach:
        raise SystemExit("router: need --replica-cmd and/or --attach")

    import signal

    from tpuic.runtime.preemption import PreemptionGuard
    from tpuic.runtime.supervisor import (HeartbeatWriter,
                                          install_stack_dump_handler)
    from tpuic.telemetry.prom import PromServer, router_exposition, \
        write_exposition
    guard = PreemptionGuard(signals=(signal.SIGTERM,)).install()
    heartbeat = HeartbeatWriter.from_env()
    if heartbeat is not None:
        install_stack_dump_handler()

    router = Router(
        replica_cmd=cmd, n_replicas=args.replicas, attach=attach,
        state_dir=args.state_dir, knee_rps=args.knee_rps,
        spill_inflight=args.spill_inflight, retry_ratio=args.retry_ratio,
        retry_cap=args.retry_cap, max_attempts=args.max_attempts,
        breaker_threshold=args.breaker_threshold,
        breaker_cooldown_s=args.breaker_cooldown_s,
        ping_interval_s=args.ping_interval_s,
        ping_timeout_s=args.ping_timeout_s,
        wedge_timeout_s=args.wedge_timeout_s,
        spawn_timeout_s=args.spawn_timeout_s,
        drain_timeout_s=args.drain_timeout)
    router.start()

    prom_server = None
    if args.prom_port:
        prom_server = PromServer(
            args.prom_port, lambda: router_exposition(router.snapshot()),
            host=args.prom_host)
        print(f"[router] prometheus /metrics on "
              f"{args.prom_host}:{prom_server.port}", file=sys.stderr)
    http_server = None
    if args.http_port:
        from tpuic.serve.http import RouterHTTPServer
        http_server = RouterHTTPServer(router, port=max(0, args.http_port),
                                       host=args.http_host)
        print(f"[router] http front-end on "
              f"{args.http_host}:{http_server.port} "
              "(POST /predict, GET /healthz, GET /metrics)",
              file=sys.stderr)

    out = open(args.out, "w") if args.out else sys.stdout
    out_lock = threading.Lock()
    handle = make_line_handler(router, out, out_lock)

    try:
        pump_stdin(handle, guard,
                   beat=(heartbeat.beat if heartbeat is not None
                         else None))
    except KeyboardInterrupt:
        pass
    finally:
        guard.uninstall()
        stragglers = router.drain(args.drain_timeout)
        router.close(drain=False)
        if http_server is not None:
            http_server.close()
        if prom_server is not None:
            prom_server.close()
        if args.prom_dump:
            try:
                write_exposition(args.prom_dump,
                                 router_exposition(router.snapshot()))
            except OSError as e:
                print(f"[router] prom dump failed: {e}", file=sys.stderr)
        snap = router.snapshot()
        print(f"[router] done: {json.dumps(snap)}"
              + (f" ({stragglers} stragglers)" if stragglers else ""),
              file=sys.stderr)
        if out is not sys.stdout:
            out.close()
    return 0


if __name__ == "__main__":
    sys.exit(main())
