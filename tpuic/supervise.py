"""``python -m tpuic.supervise`` — run a trainer under the supervisor.

Everything after ``--`` is the child command, launched as-is with the
heartbeat/stack-dump environment injected (runtime/supervisor.py has the
full protocol; docs/robustness.md the operator's view)::

    python -m tpuic.supervise --watchdog-s 300 -- \\
        python train.py --datadir /data/imagefolder --model resnet50

The child is restarted with resume on retryable failures (crash, hang,
preemption flush) under an exponential-backoff restart budget; it is
NOT restarted on clean completion, on a non-retryable poison exit
(code 44 — e.g. rollback budget exhausted), or when the supervisor
itself receives SIGTERM (a shared eviction: the forwarded signal drives
the child's preemption flush and the supervisor exits with its code).
Liveness, hang escalation (SIGQUIT stack dump -> SIGTERM -> SIGKILL),
the exit-code contract, and the crash-loop policy live in
tpuic/runtime/supervisor.py.

``--chaos`` (used by scripts/chaos_soak.py and scripts/gang_soak.py)
assigns a per-attempt ``TPUIC_FAULTS`` spec, semicolon-separated:
attempt 0 gets the first spec, attempt 1 the second, …; attempts past
the list run fault-free.

``--gang N`` supervises N ranks as ONE unit (runtime/gang.py): per-rank
heartbeat watchdogs with rank-attributed hang escalation, coordinated
teardown + restart on any partial failure (survivors get the SIGTERM
flush window, then all ranks restart together), poison from any rank
stopping the gang, and — with ``--gang-ckpt`` — a fleet-agreed resume
step passed down via ``TPUIC_RESUME_STEP`` so no rank resumes ahead of
the fleet. ``{rank}`` in the child command is substituted per rank::

    python -m tpuic.supervise --gang 4 \\
        --gang-ckpt /work/cp{rank}/resnet50 -- \\
        python train.py --datadir /data --model resnet50 \\
            --ckpt-dir /work/cp{rank}

``--gang N --elastic`` switches rank loss from coordinated-restart to
degrade/rejoin (docs/parallelism.md, "Elastic data parallelism"):
survivors re-form from the fleet-agreed step IN PLACE (membership
published via ``TPUIC_MEMBERSHIP_FILE``, no survivor process restart),
a replacement rank rejoins at the next fleet boundary, and only a loss
below ``--min-ranks`` stops the gang (typed exit 46).
"""

from __future__ import annotations

import argparse
import sys

from tpuic.runtime.supervisor import Supervisor


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="python -m tpuic.supervise", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("--state-dir", default="tpuic-supervise",
                   help="heartbeat file, progress ledger (ledger.jsonl), "
                        "and per-attempt stack dumps land here")
    p.add_argument("--watchdog-s", type=float, default=300.0,
                   help="no heartbeat change for this long after the first "
                        "beat => the child is hung (SIGQUIT stack dump, "
                        "SIGTERM grace, SIGKILL). Must comfortably exceed "
                        "the longest legitimate silent span — a cold "
                        "backend compile or a full eval pass")
    p.add_argument("--startup-grace-s", type=float, default=1800.0,
                   help="liveness window before the FIRST heartbeat of an "
                        "attempt (imports, checkpoint restore, and the "
                        "first compile are legitimately silent)")
    p.add_argument("--quit-wait-s", type=float, default=3.0,
                   help="pause after SIGQUIT for faulthandler to finish "
                        "writing the stack dump")
    p.add_argument("--grace-s", type=float, default=30.0,
                   help="SIGTERM -> SIGKILL grace (the preemption-flush "
                        "window)")
    p.add_argument("--poll-s", type=float, default=0.5,
                   help="heartbeat/child poll interval")
    p.add_argument("--max-restarts", type=int, default=16,
                   help="retryable-failure restart budget for one "
                        "supervised run (clean preemption flushes restart "
                        "free: an eviction is the fleet working as "
                        "designed, not a crash)")
    p.add_argument("--backoff-s", type=float, default=1.0,
                   help="initial restart backoff (doubles per consecutive "
                        "no-progress failure, capped at --backoff-max-s; "
                        "clean preemption flushes restart immediately)")
    p.add_argument("--backoff-max-s", type=float, default=300.0)
    p.add_argument("--crash-loop-k", type=int, default=3,
                   help="consecutive restarts with no step progress before "
                        "declaring a crash loop and giving up (exit 45)")
    p.add_argument("--heartbeat-interval-s", type=float, default=1.0,
                   help="child-side heartbeat write throttle")
    p.add_argument("--chaos", default="",
                   help="per-attempt TPUIC_FAULTS specs, ';'-separated "
                        "(fault-injection soaks; see scripts/chaos_soak.py)")
    p.add_argument("--gang", type=int, default=0, metavar="N",
                   help="supervise N ranks as one unit (runtime/gang.py): "
                        "coordinated teardown + restart on partial failure, "
                        "per-rank watchdogs, fleet-agreed resume. '{rank}' "
                        "in the child command is substituted per rank")
    p.add_argument("--gang-ckpt", default="", metavar="DIR",
                   help="per-rank checkpoint MODEL dir template ('{rank}' "
                        "substituted), e.g. '/work/cp{rank}/resnet50' — "
                        "the dirs holding the *.manifest.json sidecars. "
                        "Enables restart-consistent resume: the newest "
                        "step every rank's committed manifest agrees on "
                        "is passed down via TPUIC_RESUME_STEP")
    p.add_argument("--elastic", action="store_true",
                   help="with --gang: treat rank loss as a DEGRADE event "
                        "instead of a coordinated restart (runtime/gang.py "
                        "elastic mode, docs/parallelism.md): survivors "
                        "re-form from the fleet-agreed step in place (no "
                        "process restart, membership published via "
                        "TPUIC_MEMBERSHIP_FILE), a replacement rank "
                        "rejoins at the next fleet boundary")
    p.add_argument("--min-ranks", type=int, default=1, metavar="M",
                   help="elastic floor: a loss that would leave fewer "
                        "than M active ranks stops the gang with the "
                        "typed below-min verdict (exit 46)")
    p.add_argument("--max-respawns", type=int, default=None, metavar="N",
                   help="per-rank replacement respawn budget in elastic "
                        "mode (default: --max-restarts); past it the rank "
                        "is declared lost and the fleet continues "
                        "permanently degraded")
    p.add_argument("--coordinator", default="", metavar="HOST:PORT",
                   help="TPUIC_COORDINATOR_ADDRESS for the ranks (also "
                        "sets TPUIC_PROCESS_ID/TPUIC_NUM_PROCESSES — the "
                        "jax.distributed env rendezvous, runtime/"
                        "distributed.py) for fleets with real "
                        "collectives; omit for independent-rank fleets "
                        "(the CPU CI soak)")
    p.add_argument("cmd", nargs=argparse.REMAINDER,
                   help="-- followed by the child command")
    return p


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    cmd = list(args.cmd)
    if cmd and cmd[0] == "--":
        cmd = cmd[1:]
    if not cmd:
        build_parser().print_usage(sys.stderr)
        print("supervise: no child command (everything after '--' is the "
              "command to supervise)", file=sys.stderr)
        return 2
    chaos = ([s.strip() for s in args.chaos.split(";")] if args.chaos
             else None)
    common = dict(
        watchdog_s=args.watchdog_s, startup_grace_s=args.startup_grace_s,
        quit_wait_s=args.quit_wait_s, grace_s=args.grace_s,
        poll_s=args.poll_s, max_restarts=args.max_restarts,
        backoff_s=args.backoff_s, backoff_max_s=args.backoff_max_s,
        crash_loop_k=args.crash_loop_k,
        heartbeat_interval_s=args.heartbeat_interval_s, chaos=chaos)
    if args.gang:
        from tpuic.runtime.gang import GangSupervisor
        return GangSupervisor(
            cmd, args.state_dir, ranks=args.gang,
            ckpt_dirs=args.gang_ckpt or None,
            coordinator=args.coordinator, elastic=args.elastic,
            min_ranks=args.min_ranks, max_respawns=args.max_respawns,
            **common).run()
    if args.elastic:
        print("supervise: --elastic requires --gang N", file=sys.stderr)
        return 2
    return Supervisor(cmd, args.state_dir, **common).run()


if __name__ == "__main__":
    sys.exit(main())
