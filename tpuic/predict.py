"""Batch inference CLI: classify an ImageFolder fold with a trained model.

The reference repo trains and validates but has no standalone prediction
path — its users run `val_epoch` (train.py:78-97) and read the printed
accuracy. This module is that capability as a first-class tool: load a
tpuic checkpoint (or a reference/torchvision torch checkpoint directly),
run the fold through the jitted eval forward, and write per-image
predictions to CSV.

    python -m tpuic.predict --datadir /data/x --model resnet50 \
        --ckpt-dir dtmodel/cp                  # best track by default
    python -m tpuic.predict --datadir /data/x --model inceptionv3 \
        --init-from best_model --fold val --out preds.csv --top-k 3

Output CSV columns: image_id, label (ground-truth class name, '' when the
fold carries none), pred (top-1 class name), prob (softmax of top-1), then
pred_2/prob_2..pred_k/prob_k when --top-k > 1. When labels exist, overall
accuracy is printed — the same exact global number val_epoch reports.

Single-process by design: prediction over an ImageFolder is host-IO bound
and the packed loader feeds one chip comfortably (docs/performance.md);
multi-host users run one instance per fold/shard.
"""

from __future__ import annotations

import argparse
import csv
import json
import os
import sys
from typing import Optional

import numpy as np


def build_predict_fn(model):
    """Jitted ``(variables, images) -> (probs, top_idx)`` forward.

    Kept for direct/one-shot callers; the fold-scoring loop below runs
    the same forward through tpuic.serve's bucketed AOT executables
    instead (fixed shapes, no per-batch-size recompiles)."""
    import jax

    from tpuic.serve import make_forward
    return jax.jit(make_forward(model))


def resolve_model_auto(ckpt_dir: str) -> dict:
    """'--model auto': find the single trained model under ``ckpt_dir``.

    Each Trainer writes ``{ckpt_dir}/{model}/config.json`` (the resolved
    run config) next to its best/latest tracks; with exactly one such
    model dir, its name/num_classes/resize come from there. Ambiguity
    (several models) or absence stays an explicit error rather than a
    guess.
    """
    import glob

    hits = sorted(glob.glob(os.path.join(ckpt_dir, "*", "config.json")))
    if not hits:
        raise FileNotFoundError(
            f"--model auto: no <model>/config.json under {ckpt_dir} "
            "(older checkpoints predate the sidecar — pass --model "
            "explicitly)")
    if len(hits) > 1:
        names = [os.path.basename(os.path.dirname(h)) for h in hits]
        raise ValueError(
            f"--model auto: {len(hits)} trained models under {ckpt_dir} "
            f"({names}) — pass --model explicitly")
    with open(hits[0]) as f:
        saved = json.load(f)
    return {"name": saved["model"]["name"],
            "num_classes": int(saved["model"]["num_classes"]),
            "resize_size": int(saved["data"]["resize_size"]),
            "ema_decay": float(saved.get("optim", {}).get("ema_decay", 0.0))}


def run_predict(cfg, *, fold: str, track: str, top_k: int,
                out_path: Optional[str], limit: int = 0) -> dict:
    """Programmatic entry; returns summary stats (rows written, accuracy)."""
    from tpuic.checkpoint.loading import load_inference_variables
    from tpuic.data.folder import ImageFolderDataset
    from tpuic.data.pipeline import Loader
    from tpuic.serve import InferenceEngine, default_buckets

    d = cfg.data
    # class_to_idx=None derives the canonical mapping from the train fold
    # when present (the order the checkpoint was trained with), else from
    # the requested fold (folder.py:53-59). A fold of images with NO class
    # subdirectories is served unlabeled (label -1, folder.py flat path).
    ds = ImageFolderDataset(d.data_dir, fold, d.resize_size, d,
                            allow_unlabeled=True)
    has_labels = ds.labeled
    if d.pack:
        from tpuic.data.pack import pack_dataset
        cache = d.cache_dir or os.path.join(d.data_dir, ".tpuic_pack")
        ds = pack_dataset(ds, cache, verbose=True)

    num_classes = cfg.model.num_classes or ds.num_classes
    if num_classes <= 0:
        raise ValueError("--num-classes is required for an unlabeled fold "
                         "with no train/ tree to infer the classes from")
    mcfg = cfg.model
    if num_classes != mcfg.num_classes:
        import dataclasses
        mcfg = dataclasses.replace(mcfg, num_classes=num_classes)
    # Checkpoint -> device-resident inference variables, with the strict
    # full-tree rules (missing track / partial merge = hard error) shared
    # with tpuic.serve (tpuic/checkpoint/loading.py).
    model, variables = load_inference_variables(
        cfg.replace(model=mcfg), track=track,
        log=lambda *a: print("[predict]", *a))
    # Class names come from the fold tree; an unlabeled flat fold has none,
    # so predictions fall back to the raw class index as a string.
    idx_to_class = {i: c for c, i in ds.class_to_idx.items()}
    for i in range(num_classes):
        idx_to_class.setdefault(i, str(i))
    k = max(1, min(top_k, num_classes))

    # augment=False: --fold train must be classified on CLEAN images; the
    # fold-derived default would rot90/flip/jitter them (ADVICE r3).
    batch_size = cfg.data.resolved_val_batch_size()
    loader = Loader(ds, batch_size, shuffle=False,
                    num_workers=d.num_workers, prefetch=d.prefetch,
                    augment=False)
    # Fold scoring runs through the serving engine: full batches hit the
    # one bucket == batch_size executable, and the tail batch submits only
    # its valid rows, padded to the next-smaller bucket — fixed shapes
    # everywhere, so no tail/batch-size-dependent recompiles and no
    # full-width forward wasted on epoch padding. max_wait_ms=0: offline
    # requests are already batch-sized, coalescing delay buys nothing.
    engine = InferenceEngine(model, variables, image_size=d.resize_size,
                             input_dtype=np.float32,
                             buckets=default_buckets(batch_size),
                             max_wait_ms=0.0, queue_size=8)
    rows, correct, count = [], 0, 0
    done = False

    def consume(fut, ids, labels_v):
        nonlocal correct, count, done
        probs, order = fut.result()
        for i, image_id in enumerate(ids):
            if done:
                return
            row = {"image_id": image_id,
                   "label": idx_to_class.get(int(labels_v[i]), "")
                            if has_labels else "",
                   "pred": idx_to_class.get(int(order[i, 0]), ""),
                   "prob": f"{probs[i, order[i, 0]]:.6f}"}
            for j in range(1, k):
                row[f"pred_{j + 1}"] = idx_to_class.get(int(order[i, j]), "")
                row[f"prob_{j + 1}"] = f"{probs[i, order[i, j]]:.6f}"
            rows.append(row)
            if has_labels:
                correct += int(order[i, 0] == labels_v[i])
                count += 1
            if limit and len(rows) >= limit:
                done = True

    import collections
    pending = collections.deque()
    try:
        for batch in loader.epoch(0):
            if done:
                break
            mask = np.asarray(batch["mask"]) > 0  # epoch padding rows
            if not mask.any():
                continue
            # Full batches pass through as-is — a packed-loader device
            # array stays ON DEVICE end to end (the engine's exact-fit
            # path ships it without a host bounce). Only the tail batch
            # materializes on host to drop its padding rows.
            imgs = batch["image"]
            labels_v = np.asarray(batch["label"])
            ids = batch.image_ids
            if not mask.all():  # tail batch: submit only the valid rows
                imgs = np.asarray(imgs)[mask]
                labels_v = labels_v[mask]
                ids = [iid for iid, m in zip(ids, mask) if m]
            # Keep ~2 requests in flight: batch N+1's host assembly and
            # H2D overlap batch N's device call (the engine pipelines
            # internally; the window caps host memory).
            pending.append((engine.submit(imgs), ids, labels_v))
            while len(pending) >= 3:
                consume(*pending.popleft())
        while pending:
            consume(*pending.popleft())
    finally:
        engine.close()

    if out_path:
        with open(out_path, "w", newline="") as f:
            w = csv.DictWriter(f, fieldnames=list(rows[0].keys()) if rows
                               else ["image_id", "label", "pred", "prob"])
            w.writeheader()
            w.writerows(rows)
        print(f"[predict] wrote {len(rows)} rows -> {out_path}")
    summary = {"rows": len(rows), "fold": fold,
               "engine": engine.stats.snapshot()}
    if has_labels and count:
        summary["accuracy"] = 100.0 * correct / count
        print(f"[predict] accuracy over {count} labeled samples: "
              f"{summary['accuracy']:.2f}%")
    return summary


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        description="Classify an ImageFolder fold with a trained checkpoint")
    p.add_argument("--datadir", required=True)
    p.add_argument("--fold", default="val")
    p.add_argument("--model", default="auto",
                   help="backbone name, or 'auto' to read the single "
                        "trained model's config.json under --ckpt-dir")
    p.add_argument("--num-classes", type=int, default=0,
                   help="0 = infer from the folder tree")
    p.add_argument("--resize", type=int, default=None,
                   help="image size (default: the checkpoint config's size "
                        "under --model auto, else the reference's 299)")
    p.add_argument("--batchsize", type=int, default=64)
    p.add_argument("--ckpt-dir", default="dtmodel/cp")
    p.add_argument("--track", default="best", choices=("best", "latest"))
    p.add_argument("--init-from", default="",
                   help="torch checkpoint instead of a tpuic one")
    p.add_argument("--out", default="", help="CSV output path")
    p.add_argument("--top-k", type=int, default=1)
    p.add_argument("--limit", type=int, default=0,
                   help="stop after N rows (smoke runs)")
    p.add_argument("--no-pack", action="store_true")
    args = p.parse_args(argv)

    from tpuic.config import (Config, DataConfig, ModelConfig, OptimConfig,
                              RunConfig)
    model, num_classes, resize = args.model, args.num_classes, args.resize
    ema_decay = 0.0
    if model == "auto":
        if args.init_from:
            raise SystemExit("predict: --model auto needs a tpuic "
                             "--ckpt-dir; with --init-from pass --model "
                             "explicitly")
        saved = resolve_model_auto(args.ckpt_dir)
        model = saved["name"]
        num_classes = num_classes or saved["num_classes"]
        ema_decay = saved["ema_decay"]  # EMA checkpoints predict with EMA
        if resize is None:  # explicit --resize always wins
            resize = saved["resize_size"]
        print(f"[predict] auto-resolved model '{model}' "
              f"(num_classes={num_classes}, resize={resize}) from "
              f"{args.ckpt_dir}")
    elif not args.init_from:
        # Explicit --model: still honor THIS model's config.json sidecar
        # for ema_decay, so an EMA-trained checkpoint scores its EMA
        # weights (the ones 'best' was selected on) instead of silently
        # falling back to the raw params.
        sidecar = os.path.join(args.ckpt_dir, model, "config.json")
        if os.path.isfile(sidecar):
            with open(sidecar) as f:
                ema_decay = float(
                    json.load(f).get("optim", {}).get("ema_decay", 0.0))
    if resize is None:
        resize = 299  # the reference's hard-coded size (train.py:110)
    cfg = Config(
        data=DataConfig(data_dir=args.datadir, resize_size=resize,
                        batch_size=args.batchsize,
                        val_batch_size=args.batchsize,
                        pack=not args.no_pack),
        model=ModelConfig(name=model, num_classes=num_classes),
        optim=OptimConfig(ema_decay=ema_decay),
        run=RunConfig(ckpt_dir=args.ckpt_dir, init_from=args.init_from),
    )
    summary = run_predict(cfg, fold=args.fold, track=args.track,
                          top_k=args.top_k, out_path=args.out or None,
                          limit=args.limit)
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
