"""TrainTelemetry — one training run's telemetry wiring.

Moved out of ``tpuic/telemetry/__init__.py`` so the package import
stays dependency-free (PEP 562 lazy exports, the tpuic/__init__.py
idiom): stdlib-only parents — the supervisor, the gang supervisor, and
the replica router (``tpuic/serve/router.py``) — import
``tpuic.telemetry.events`` / ``tpuic.telemetry.prom`` without pulling
jax into a process that must outlive any backend wedge.
"""

from __future__ import annotations

import os
from typing import Optional

from tpuic.telemetry.events import (JsonlSink, TensorBoardSink, bus,
                                    install_jax_compile_listener, publish)
from tpuic.telemetry.goodput import (GoodputTracker, analytic_flops_per_step,
                                     hbm_bandwidth, peak_flops)
from tpuic.telemetry.memory import MemorySampler
from tpuic.telemetry.slo import SLOTracker, parse_objectives
from tpuic.telemetry.steptime import StepTimer
from tpuic.telemetry.tracing import TraceTrigger


class TrainTelemetry:
    """One training run's telemetry wiring over the process-global bus.

    Owns the per-run subscribers (JSONL sink, step timer, goodput
    tracker, trace trigger, TensorBoard bridge); the emitters
    (checkpoint manager, dataset quarantine, jax compile listener)
    publish to the global bus without knowing any of this exists.

    Exactly one instance is live per process: constructing a new one
    closes the previous run's subscribers first, so a sweep driver (or
    a test session) building Trainer after Trainer never leaks bus
    subscriptions or appends run B's events into run A's JSONL file.
    """

    def __init__(self, run_cfg, *, model_name: str = "", image_size: int = 0,
                 global_batch: int = 0, n_devices: int = 1, device=None,
                 tb=None, compute_dtype: str = "") -> None:
        global _active
        if _active is not None:
            _active.close()
        _active = self
        self._sinks = []
        self._unsubs = []
        # Compile events (the jax.monitoring bridge) feed the goodput
        # compile bucket; idempotent, process-wide.
        install_jax_compile_listener()
        # Fleet view (telemetry/fleet.py, docs/observability.md): on a
        # multi-process run every event gains rank/ranks fields (one
        # dict merge at publish; single-process runs keep the tag off
        # and pay one attribute read).
        from tpuic.telemetry.fleet import rank_stream_path, tag_bus_with_rank
        self.rank, self.ranks = tag_bus_with_rank(bus)
        jsonl = getattr(run_cfg, "metrics_jsonl", "") or ""
        if jsonl:
            # Per-rank streams: rank 0 keeps the configured path (the
            # single-process contract every consumer was built on);
            # rank k writes '<stem>.rank<k>.jsonl' beside it — on a
            # shared filesystem the fleet's whole history lands in one
            # directory with no cross-process appends, and
            # 'python -m tpuic.telemetry.fleet <dir>' merges it into
            # straggler attribution offline.
            sink = JsonlSink(rank_stream_path(jsonl, self.rank))
            self._sinks.append(sink)
            self._unsubs.append(bus.subscribe(sink))
        # Supervised-liveness heartbeat (runtime/supervisor.py,
        # docs/robustness.md): when a supervisor parent set
        # TPUIC_HEARTBEAT_FILE for this process, mirror bus activity into
        # the atomically rewritten heartbeat file. Pure host-side
        # piggybacking on events the loop already publishes through its
        # deferred drain — zero device syncs, zero compiles added
        # (asserted in tests/test_supervisor.py with the
        # tpuic.analysis.runtime checkers).
        from tpuic.runtime.supervisor import HeartbeatWriter
        self.heartbeat = HeartbeatWriter.from_env(publish=publish)
        if self.heartbeat is not None:
            self._unsubs.append(bus.subscribe(self.heartbeat))
        self.steptime = StepTimer(bus)
        # Device-memory accounting (telemetry/memory.py): one host-side
        # metadata sample per step boundary — allocator counters where
        # the backend provides them, live-array bytes + RSS on CPU.
        # Zero device syncs, zero compiles (checker-asserted in
        # tests/test_fleet.py, the same discipline as the StepTimer).
        from tpuic.metrics.logging import host0_print
        self.memory = MemorySampler(publish=bus.publish, log=host0_print)
        self._unsubs.append(bus.subscribe(self.memory.on_event,
                                          kinds=("step",)))
        flops = analytic_flops_per_step(model_name, image_size, global_batch)
        # Dtype-aware roofline: an f32 run is judged against the f32 peak
        # (half the bf16 MXU rate on TPU), so MFU compares honestly
        # across --compute-dtype arms instead of flattering bf16 by 2x.
        peak = peak_flops(device, compute_dtype or "bf16") * max(
            1, int(n_devices))
        self.goodput = GoodputTracker(flops_per_step=flops, peak_flops=peak,
                                      global_batch=global_batch,
                                      compute_dtype=compute_dtype)
        self._unsubs.append(bus.subscribe(self.goodput.on_event))
        # Step-time SLOs (telemetry/slo.py): attainment + error-budget
        # burn over the 'step' events the StepTimer already publishes —
        # one more host-side subscriber, nothing new on the hot path.
        self.slo: Optional[SLOTracker] = None
        slo_specs = getattr(run_cfg, "slo", "") or ""
        if slo_specs:
            self.slo = SLOTracker(parse_objectives(
                slo_specs, allowed=("train_step",)))
            self._unsubs.append(self.slo.attach(bus))
        # Device-time attribution (telemetry/profile.py,
        # docs/observability.md "Device-time attribution"): with
        # run.trace_analyze set, captured trace windows are auto-analyzed
        # into a per-op-class waterfall ('profile' events) and a final
        # analysis runs at flush().  The Trainer wires the HLO provider
        # (the AOT-lowered train step) after construction; until then
        # the analyzer still ingests step device_ms — one deque append
        # per step, zero syncs, zero compiles (test-asserted on-vs-off).
        self.profile = None
        if getattr(run_cfg, "trace_analyze", False):
            # Imported lazily so `python -m tpuic.telemetry.profile`
            # does not re-import its own module through this package.
            from tpuic.telemetry.profile import CaptureAnalyzer
            # PER-DEVICE peak/bandwidth, NOT x n_devices: the analyzed
            # HLO is the SPMD-partitioned per-device program and the
            # measured step time is the wall clock of its parallel
            # execution — one device's roofline is the right ruler.
            self.profile = CaptureAnalyzer(
                peak=peak_flops(device),
                hbm_bytes_per_s=hbm_bandwidth(device),
                model_name=model_name, image_size=image_size,
                global_batch=global_batch,
                n_devices=max(1, int(n_devices)))
            # 'trace' too: steps measured inside a profiler window are
            # excluded from the waterfall's device distribution (the
            # analyzer's observer-effect taint).  Subscribed BEFORE the
            # tracer below, so the window-open/close ordering it sees is
            # exact.
            self._unsubs.append(bus.subscribe(self.profile.on_event,
                                              kinds=("step", "trace")))
        trace_dir = os.environ.get("TPUIC_TRACE", "") or \
            getattr(run_cfg, "trace_dir", "") or ""
        self.tracer: Optional[TraceTrigger] = None
        if trace_dir:
            self.tracer = TraceTrigger(
                trace_dir,
                threshold=float(getattr(run_cfg, "trace_threshold", 3.0)),
                trace_steps=int(getattr(run_cfg, "trace_steps", 3)),
                keep=int(getattr(run_cfg, "trace_keep", 4)),
                # TPUIC_TRACE=dir is the manual override: capture one
                # window immediately instead of waiting for a regression.
                force_first=bool(os.environ.get("TPUIC_TRACE")),
                on_capture=(self.profile.on_capture
                            if self.profile is not None else None))
            self._unsubs.append(bus.subscribe(self.tracer.on_event,
                                              kinds=("step",)))
        if tb is not None:
            tbs = TensorBoardSink(tb)
            # serve_batch/serve_span included: a train process never
            # publishes them, but a process embedding both a Trainer and
            # an InferenceEngine (predict-after-fit notebooks) gets its
            # serve latencies as scalars through the same sink.
            self._unsubs.append(bus.subscribe(
                tbs, kinds=("step", "skip", "rollback", "quarantine",
                            "goodput", "restart", "slo", "memory",
                            "serve_batch", "serve_span", "profile")))

    def flush(self) -> None:
        if self.profile is not None:
            # Run-end device-time analysis over the full step window
            # (final=True) BEFORE the sinks flush, so the event lands in
            # this run's JSONL.  The analyzer contains its own failures.
            self.profile.finalize()
        for s in self._sinks:
            s.flush()

    def close(self) -> None:
        """Unsubscribe this run's consumers and close its sinks (the
        global bus and emitters keep running for the process).
        Idempotent."""
        global _active
        for unsub in self._unsubs:
            unsub()
        self._unsubs = []
        for s in self._sinks:
            s.close()
        self._sinks = []
        if _active is self:
            _active = None


_active: Optional[TrainTelemetry] = None
