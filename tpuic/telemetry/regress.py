"""Noise-aware perf-regression gate: speed as a tested invariant.

PR 3 made every millisecond attributable (goodput buckets, MFU, serve
latency percentiles); nothing *enforced* any of it — a PR that halved
MFU or doubled serve p99 still merged green.  This module is the
enforcement: a pinned CPU smoke workload (train via ``train.py --steps``
on a synthetic ImageFolder, serve via the real InferenceEngine), a
committed baseline (``perf/regression_baseline.json``), and a comparison
that fails CI when a gated metric regresses past its tolerance::

    python -m tpuic.telemetry.regress --check            # CI gate
    python -m tpuic.telemetry.regress --write-baseline   # refresh baseline
    python -m tpuic.telemetry.regress --check \
        --inject slow_step,hang_device --expect-fail     # prove it fires

Noise discipline (CPU CI jitters; the gate must catch a 2x regression
without flaking on a 20% wobble):

- **Calibration scaling.**  Every run times a pinned single-thread numpy
  workload; absolute-time metrics are compared against ``baseline *
  (fresh_calibration / baseline_calibration)`` (rates against the
  inverse), so a CI runner that is simply 2x slower than the dev box
  that wrote the baseline does not read as a 2x regression.  The scale
  is clamped to [1/4, 4] — beyond that the machines are not comparable
  and the gate refuses to judge: a typed ``environment_mismatch``
  verdict (exit 3, distinct from regression exit 2), because on such a
  host every absolute-time row fails identically at seed and tip and a
  "REGRESSED" verdict would be noise wearing a gate's uniform.
- **Tolerance ladder.**  Per metric: ``tol = max(floor, NOISE_MULT x
  noise)`` where ``noise`` is the relative trial spread recorded at
  baseline-write time (the same spread discipline bench.py records) and
  ``floor`` is a per-metric-class minimum — ratio metrics (goodput
  fractions, pad efficiency) are machine-independent and get tight
  floors; single-run tail latencies get wide ones.
- **Exact counters** (steady-state serve compiles) tolerate nothing:
  one new compile in steady state IS the regression.

The ``--inject`` flag seeds the same deterministic faults the chaos
harness uses (``slow_step`` into the train child via TPUIC_FAULTS,
``hang_device`` into the in-process serve engine), which is how CI
proves the gate is *bidirectional*: the clean workload must pass AND the
seeded-slowdown workload must fail naming the regressed metric — a gate
that cannot fire is decoration (docs/observability.md,
"Perf-regression gate").
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time
from typing import Dict, Optional, Sequence

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SCHEMA = 1
NOISE_MULT = 4.0
CAL_CLAMP = 4.0

# name -> (direction, kind, floor_tolerance)
#   direction: "higher" = bigger is better, "lower" = smaller is better
#   kind: "ratio" machine-independent fraction — no calibration scaling;
#         "time"  absolute ms — scaled by fresh/base calibration;
#         "rate"  throughput-like — scaled by the inverse;
#         "count" exact counter — floor is an ABSOLUTE allowance, not
#                 relative (0.0 = any increase regresses).
METRIC_SPECS = {
    "train.mfu":              ("higher", "rate", 0.50),
    "train.step_p50_ms":      ("lower", "time", 0.50),
    "train.step_p99_ms":      ("lower", "time", 0.90),
    "train.frac_productive":  ("higher", "ratio", 0.30),
    "train.accounted_frac":   ("higher", "ratio", 0.05),
    "serve.latency_p50_ms":   ("lower", "time", 0.70),
    "serve.latency_p99_ms":   ("lower", "time", 1.00),
    # The quantized serve ladder's latency rows (docs/performance.md,
    # "Quantized serving"): a regression in a bf16/int8 rung — a
    # dequant fusion lost, a per-dtype executable falling out of the
    # AOT cache — must fail CI even when the fp32 rung stays fast.
    # Compiles of every rung fold into serve.steady_compiles (exact).
    "serve.bf16_latency_p50_ms": ("lower", "time", 0.70),
    "serve.bf16_latency_p99_ms": ("lower", "time", 1.00),
    "serve.int8_latency_p50_ms": ("lower", "time", 0.70),
    "serve.int8_latency_p99_ms": ("lower", "time", 1.00),
    "serve.throughput_images_per_sec": ("higher", "rate", 0.50),
    "serve.pad_efficiency":   ("higher", "ratio", 0.20),
    "serve.steady_compiles":  ("lower", "count", 0.0),
}


# -- machine-speed calibration ------------------------------------------------
def calibration_s(reps: int = 5, n: int = 2_000_000) -> float:
    """Seconds to ``np.sort`` a pinned random array, best of ``reps``
    (min is the noise-robust statistic for a lower-bounded timing).  The
    common-mode machine-speed reference absolute-time comparisons are
    normalized by.  Sort, not matmul, deliberately: numpy's sort is
    single-threaded everywhere, so the number does not swing with BLAS
    thread scheduling the way a matmul chain measurably does."""
    import numpy as np
    rng = np.random.default_rng(0)
    a = rng.standard_normal(n).astype(np.float32)
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        np.sort(a)
        best = min(best, time.perf_counter() - t0)
    return best


# -- the pinned workloads -----------------------------------------------------
def train_workload(steps: int = 8, *, faults: str = "",
                   keep_dir: Optional[str] = None) -> Dict[str, float]:
    """Run ``train.py --steps N`` on a synthetic ImageFolder in a
    subprocess (CPU pinned) and distill the gated train metrics from its
    telemetry JSONL.  ``faults`` seeds the child's TPUIC_FAULTS (the
    bidirectional proof).  Step percentiles skip the first two steps —
    compile/cache warmup is the goodput tracker's business, not a
    steady-state regression signal.  The scratch dir (dataset +
    checkpoints + JSONL) is removed afterwards unless the caller pins it
    with ``keep_dir`` (repeat runs reuse the dataset)."""
    work = keep_dir or tempfile.mkdtemp(prefix="tpuic_regress_train_")
    try:
        return _train_workload_in(work, steps, faults)
    finally:
        if keep_dir is None:
            shutil.rmtree(work, ignore_errors=True)


def _train_workload_in(work: str, steps: int,
                       faults: str) -> Dict[str, float]:
    from tpuic.data.synthetic import make_synthetic_imagefolder
    from tpuic.metrics.meters import quantiles
    data = os.path.join(work, "data")
    if not os.path.isdir(data):
        make_synthetic_imagefolder(data, classes=("a", "b", "c"),
                                   per_class=8, size=32)
    jsonl = os.path.join(work, "events.jsonl")
    if os.path.exists(jsonl):
        os.unlink(jsonl)
    env = dict(os.environ, JAX_PLATFORMS="cpu", TF_CPP_MIN_LOG_LEVEL="3")
    env.pop("TPUIC_TRACE", None)
    if faults:
        env["TPUIC_FAULTS"] = faults
    else:
        env.pop("TPUIC_FAULTS", None)
    cmd = [sys.executable, os.path.join(_REPO, "train.py"),
           "--datadir", data, "--model", "resnet18-cifar",
           "--resize", "32", "--batchsize", "2",
           "--epochs", str(steps // 12 + 1),
           "--optimizer", "adam", "--lr", "1e-3",
           "--no-class-weights", "--log-every-steps", "1",
           "--ckpt-dir", os.path.join(work, "cp"),
           "--steps", str(steps), "--metrics-jsonl", jsonl]
    proc = subprocess.run(cmd, cwd=_REPO, env=env, text=True,
                          capture_output=True, timeout=1200)
    if proc.returncode != 0:
        raise RuntimeError(
            f"train workload exited {proc.returncode}:\n"
            f"{proc.stdout[-1500:]}\n{proc.stderr[-1500:]}")
    # The shared tolerant reader (telemetry/events.read_jsonl): a child
    # killed by the timeout can leave a torn tail line; the gate should
    # then fail on its own "telemetry incomplete" diagnosis below, not
    # on a JSONDecodeError.
    from tpuic.telemetry.events import read_jsonl
    recs = read_jsonl(jsonl)
    step_evs = [r for r in recs if r["event"] == "step"]
    finals = [r for r in recs if r["event"] == "goodput" and r.get("final")]
    if len(finals) != 1 or len(step_evs) < 4:
        raise RuntimeError(
            f"train workload telemetry incomplete: {len(step_evs)} step "
            f"events, {len(finals)} final goodput reports")
    rep = finals[0]
    steady = [r["total_ms"] for r in step_evs[2:]]
    qs = quantiles(steady, (50, 99))
    out = {
        "train.step_p50_ms": qs["p50"],
        "train.step_p99_ms": qs["p99"],
        "train.frac_productive": rep.get("frac_productive"),
        "train.accounted_frac": rep.get("accounted_frac"),
        "train.mfu": rep.get("mfu"),
    }
    return {k: float(v) for k, v in out.items() if v is not None}


def serve_workload(requests: int = 48, *, size: int = 16,
                   buckets: Sequence[int] = (1, 4, 8),
                   max_wait_ms: float = 2.0, seed: int = 0,
                   forward_fn=None) -> Dict[str, float]:
    """Drive the real InferenceEngine with the pinned mixed-size request
    stream and distill the gated serve metrics.

    Two passes: an as-fast pass measures throughput, then a paced pass
    at HALF that throughput measures latency/pad efficiency — pacing
    relative to the machine's own capacity keeps the latency numbers
    comparable across machine speeds (the calibration scale covers the
    rest).  The real-model path then repeats the paced pass once per
    quantized ladder rung (bf16/int8 via tpuic.quant) for the
    ``serve.<dtype>_latency_*`` rows, with every rung's compiles folded
    into the exact ``serve.steady_compiles`` counter.  ``forward_fn``
    overrides the default small-model forward (tests use a stub to stay
    fast — the stub path skips the ladder rows, which then compare as
    'missing' rather than regressed)."""
    import numpy as np

    from tpuic.serve import InferenceEngine, loadgen

    variants = {}
    if forward_fn is None:
        import jax
        import jax.numpy as jnp

        from tpuic import quant
        from tpuic.models import create_model
        from tpuic.serve import make_forward
        model = create_model("resnet18-cifar", 10, dtype="float32")
        variables = model.init(jax.random.key(0),
                               jnp.zeros((1, size, size, 3), jnp.float32),
                               train=False)
        forward, fwd_vars = make_forward(model, normalize=True), variables
        variants = {k: v for k, v in quant.serve_variants(
            model, variables, ("fp32", "bf16", "int8"),
            normalize=True).items() if k != "fp32"}
    else:
        forward, fwd_vars = forward_fn, {}
    rng = np.random.default_rng(seed)
    reqs = [rng.integers(0, 256, (int(rng.integers(1, buckets[-1] + 1)),
                                  size, size, 3), np.uint8)
            for _ in range(requests)]
    engine = InferenceEngine(
        forward_fn=forward, variables=fwd_vars, image_size=size,
        input_dtype=np.uint8, buckets=tuple(buckets),
        max_wait_ms=max_wait_ms, queue_size=max(64, requests),
        variants=variants)
    try:
        # Shared warmup helper (tpuic/compiled/) — same registry-backed
        # AOT path bench_serve.py warms through.
        from tpuic.compiled import warm_engine
        warm_engine(engine)

        def run(rate: float, dtype=None) -> dict:
            # The shared bench/gate driver (tpuic/serve/loadgen.py): the
            # gate measures with exactly the harness bench_serve.py uses.
            offsets = ([i / rate for i in range(len(reqs))]
                       if rate > 0 else None)
            items = (reqs if dtype is None
                     else [(r, {"dtype": dtype}) for r in reqs])
            wall, _, snap = loadgen.run_stream(engine, items,
                                               offsets_s=offsets)
            snap["_wall_s"] = wall
            return snap

        fast = run(0.0)
        images = sum(r.shape[0] for r in reqs)
        throughput = images / fast["_wall_s"]
        paced_rate = max(1.0, 0.5 * (len(reqs) / fast["_wall_s"]))
        paced = run(paced_rate)
        # stats.reset() zeroes the compile counter per pass, so this is
        # exactly "executables built AFTER warmup" — the AOT contract.
        steady_compiles = fast["compiles"] + paced["compiles"]
        out = {
            "serve.latency_p50_ms": float(paced["latency_ms"]["p50"]),
            "serve.latency_p99_ms": float(paced["latency_ms"]["p99"]),
            "serve.throughput_images_per_sec": round(throughput, 2),
            "serve.pad_efficiency": float(paced["pad_efficiency"]),
        }
        for tag in sorted(variants):
            rung = run(paced_rate, dtype=tag)
            steady_compiles += rung["compiles"]
            out[f"serve.{tag}_latency_p50_ms"] = \
                float(rung["latency_ms"]["p50"])
            out[f"serve.{tag}_latency_p99_ms"] = \
                float(rung["latency_ms"]["p99"])
        out["serve.steady_compiles"] = float(steady_compiles)
        return out
    finally:
        engine.close()


def run_workloads(*, steps: int = 8, requests: int = 48,
                  inject: Sequence[str] = (), skip_train: bool = False,
                  skip_serve: bool = False,
                  serve_forward_fn=None) -> Dict[str, float]:
    """One fresh measurement of every gated metric.  ``inject`` seeds
    deterministic faults: ``slow_step`` (train child, 0.3 s/step) and
    ``hang_device`` (in-process serve engine, 0.25 s/dispatch) — each
    sized to overwhelm its metric's tolerance by a wide margin, so the
    bidirectional proof tests the gate, not the jitter."""
    from tpuic.runtime import faults

    metrics: Dict[str, float] = {}
    if not skip_train:
        train_faults = "slow_step#0.3" if "slow_step" in inject else ""
        metrics.update(train_workload(steps, faults=train_faults))
    if not skip_serve:
        armed = "hang_device" in inject
        if armed:
            faults.arm("hang_device", param=0.25)
        try:
            metrics.update(serve_workload(requests,
                                          forward_fn=serve_forward_fn))
        finally:
            if armed:
                faults.disarm("hang_device")
    return metrics


# -- baseline + comparison ----------------------------------------------------
def make_baseline(trials: Sequence[Dict[str, float]],
                  calibration: float, workload: dict) -> dict:
    """Median value + relative spread per metric across trials."""
    names = sorted({k for t in trials for k in t})
    metrics = {}
    for name in names:
        vals = sorted(t[name] for t in trials if name in t)
        if not vals:
            continue
        med = vals[len(vals) // 2]
        spread = ((vals[-1] - vals[0]) / abs(med)) if med else 0.0
        metrics[name] = {"value": med, "noise": round(spread, 4)}
    return {"schema": SCHEMA, "written_at_unix": int(time.time()),
            "calibration_s": round(calibration, 6),
            "trials": len(trials), "workload": workload,
            "metrics": metrics}


def compare(baseline: dict, fresh: Dict[str, float],
            fresh_calibration: float, specs: Optional[dict] = None) -> dict:
    """Fresh metrics vs the committed baseline under the tolerance
    ladder.  Returns a report dict; ``report["regressed"]`` is the gate
    verdict and each regressed row names its metric — the CI failure
    message is the report, not a bare exit code.

    ``specs`` overrides the gated-metric table (same shape as
    METRIC_SPECS) — the roofline gate (telemetry/profile.py) runs its
    op-class metrics through this exact machinery instead of growing a
    second calibration/tolerance implementation."""
    base_cal = float(baseline.get("calibration_s") or 0.0)
    scale = 1.0
    mismatch = None
    cal_note = "no baseline calibration — absolute comparison"
    if base_cal > 0 and fresh_calibration > 0:
        scale = fresh_calibration / base_cal
        cal_note = (f"machine speed scale {scale:.3f} "
                    f"(fresh {fresh_calibration * 1e3:.1f} ms / baseline "
                    f"{base_cal * 1e3:.1f} ms)")
        if 0.75 <= scale <= 1.33:
            # Same-machine band: the two calibrations agree within their
            # own noise, so scaling by their ratio would only inject that
            # noise into every expectation.  Snap to 1.
            scale = 1.0
            cal_note += " — within same-machine band, snapped to 1.0"
        elif not (1.0 / CAL_CLAMP <= scale <= CAL_CLAMP):
            # Beyond the comparability clamp the machines are NOT
            # comparable: every absolute-time row would fail (or pass)
            # identically at seed and tip, which reads as a regression
            # verdict but means nothing.  Typed environment_mismatch
            # verdict instead (main() exits 3, distinct from regression
            # exit 2); the rows below are still computed with the
            # clamped scale for the report's diagnostic value.
            mismatch = {"scale": round(scale, 4), "clamp": CAL_CLAMP,
                        "fresh_calibration_s": round(fresh_calibration, 6),
                        "baseline_calibration_s": round(base_cal, 6)}
            scale = min(max(scale, 1.0 / CAL_CLAMP), CAL_CLAMP)
            cal_note += f" — CLAMPED to {scale:.3f}: machines barely comparable"
    rows = []
    for name, (direction, kind, floor) in (specs or METRIC_SPECS).items():
        b = (baseline.get("metrics") or {}).get(name)
        f = fresh.get(name)
        if b is None or f is None:
            rows.append({"metric": name, "status": "missing",
                         "baseline": None if b is None else b["value"],
                         "fresh": f})
            continue
        base_v, noise = float(b["value"]), float(b.get("noise", 0.0))
        if kind == "time":
            expected = base_v * scale
        elif kind == "rate":
            expected = base_v / scale
        else:
            expected = base_v
        if kind == "count":
            # Exact counter: absolute allowance, no noise band.
            regressed = f > base_v + floor
            tol, ratio = floor, f - base_v
        else:
            tol = max(floor, NOISE_MULT * noise)
            ratio = (f / expected) if expected else float("inf")
            if direction == "lower":
                regressed = f > expected * (1.0 + tol)
            else:
                regressed = f < expected * (1.0 - tol)
        rows.append({"metric": name, "status":
                     "REGRESSED" if regressed else "ok",
                     "baseline": base_v, "expected": round(expected, 4),
                     "fresh": round(f, 4), "ratio": round(ratio, 4),
                     "tolerance": round(tol, 4), "direction": direction,
                     "kind": kind, "noise": noise})
    bad = [r for r in rows if r["status"] == "REGRESSED"]
    out = {"regressed": bool(bad),
           "regressed_metrics": [r["metric"] for r in bad],
           "calibration": cal_note, "scale": round(scale, 4),
           "rows": rows}
    if mismatch is not None:
        out["environment_mismatch"] = mismatch
    return out


def verdict_exit(report: dict, expect_fail: bool = False) -> int:
    """The gate's exit code for a :func:`compare` report.

    3 — ``environment_mismatch``: the host is outside the ``CAL_CLAMP``
        comparability clamp, so pass/fail would be identical at seed
        and tip; the typed verdict REFUSES to judge (and overrides
        ``--expect-fail``: a gate that cannot fire meaningfully cannot
        prove it fires either).  Distinct from regression exit 2, so CI
        and humans can tell "this PR is slow" from "this host is".
    2 — a gated metric regressed (or, under ``expect_fail``, the seeded
        slowdown failed to trip the gate).
    0 — clean (or, under ``expect_fail``, the expected failure fired).
    """
    if report.get("environment_mismatch"):
        return 3
    if expect_fail:
        return 0 if report["regressed"] else 2
    return 2 if report["regressed"] else 0


def _print_report(report: dict) -> None:
    print(f"[regress] {report['calibration']}")
    for r in report["rows"]:
        if r["status"] == "missing":
            print(f"[regress]   {r['metric']:<36} MISSING "
                  f"(baseline={r['baseline']}, fresh={r['fresh']})")
            continue
        arrow = "v" if r["direction"] == "lower" else "^"
        print(f"[regress]   {r['metric']:<36} {r['status']:<9} "
              f"base={r['baseline']:<10g} expected={r['expected']:<10g} "
              f"fresh={r['fresh']:<10g} ratio={r['ratio']:<7g} "
              f"tol={r['tolerance']:g} ({arrow} better)")
    if report["regressed"]:
        print(f"[regress] REGRESSION in: "
              f"{', '.join(report['regressed_metrics'])}")
    else:
        print("[regress] clean: no gated metric regressed")


def _force_cpu() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
    from tpuic.runtime.axon_guard import drop_axon_vars
    drop_axon_vars(os.environ)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, "tests", ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


DEFAULT_BASELINE = os.path.join(_REPO, "perf", "regression_baseline.json")


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tpuic.telemetry.regress", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    mode = p.add_mutually_exclusive_group(required=True)
    mode.add_argument("--check", action="store_true",
                      help="run the pinned workload and compare against "
                           "the committed baseline; exit 2 on regression, "
                           "3 when the host is outside the calibration "
                           "comparability clamp (environment_mismatch — "
                           "no verdict, not a regression)")
    mode.add_argument("--write-baseline", action="store_true",
                      help="run --trials trials of the workload and "
                           "(re)write the baseline file")
    p.add_argument("--baseline", default=DEFAULT_BASELINE)
    p.add_argument("--report", default="",
                   help="write the fresh-vs-baseline comparison JSON "
                        "here (the CI artifact)")
    p.add_argument("--trials", type=int, default=3,
                   help="trials for --write-baseline (noise bands)")
    p.add_argument("--steps", type=int, default=8,
                   help="train workload optimizer steps")
    p.add_argument("--requests", type=int, default=48,
                   help="serve workload request count")
    p.add_argument("--inject", default="",
                   help="comma list of faults to seed (slow_step, "
                        "hang_device) — the gate-can-fire proof")
    p.add_argument("--expect-fail", action="store_true",
                   help="with --check: exit 0 IFF the comparison "
                        "regressed (inverted gate, for CI to prove the "
                        "gate fires under --inject)")
    p.add_argument("--skip-train", action="store_true")
    p.add_argument("--skip-serve", action="store_true")
    args = p.parse_args(argv)

    _force_cpu()
    inject = tuple(s.strip() for s in args.inject.split(",") if s.strip())
    unknown = set(inject) - {"slow_step", "hang_device"}
    if unknown:
        p.error(f"--inject: unknown fault(s) {sorted(unknown)} "
                "(supported: slow_step, hang_device)")
    workload_desc = {"train_steps": args.steps,
                     "serve_requests": args.requests,
                     "serve_size": 16, "serve_buckets": [1, 4, 8]}

    if args.write_baseline:
        cal = calibration_s()
        trials = []
        for i in range(max(1, args.trials)):
            print(f"[regress] baseline trial {i + 1}/{args.trials} ...",
                  flush=True)
            trials.append(run_workloads(
                steps=args.steps, requests=args.requests,
                skip_train=args.skip_train, skip_serve=args.skip_serve))
        baseline = make_baseline(trials, cal, workload_desc)
        os.makedirs(os.path.dirname(args.baseline) or ".", exist_ok=True)
        with open(args.baseline, "w") as f:
            json.dump(baseline, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"[regress] baseline ({len(baseline['metrics'])} metrics, "
              f"{args.trials} trials, calibration "
              f"{cal * 1e3:.1f} ms) -> {args.baseline}")
        return 0

    # --check
    try:
        with open(args.baseline) as f:
            baseline = json.load(f)
    except OSError as e:
        print(f"[regress] cannot read baseline {args.baseline}: {e}\n"
              f"[regress] run --write-baseline first", file=sys.stderr)
        return 3
    if inject:
        print(f"[regress] seeding fault(s): {', '.join(inject)}")
    cal = calibration_s()
    fresh = run_workloads(steps=args.steps, requests=args.requests,
                          inject=inject, skip_train=args.skip_train,
                          skip_serve=args.skip_serve)
    report = compare(baseline, fresh, cal)
    report["fresh_metrics"] = fresh
    report["injected"] = list(inject)
    report["expect_fail"] = bool(args.expect_fail)
    _print_report(report)
    if args.report:
        with open(args.report, "w") as f:
            json.dump(report, f, indent=2)
        print(f"[regress] comparison -> {args.report}")
    code = verdict_exit(report, expect_fail=args.expect_fail)
    if code == 3:
        em = report["environment_mismatch"]
        print(f"[regress] ENVIRONMENT MISMATCH: this host's calibration "
              f"is {em['scale']:g}x the baseline's — beyond the "
              f"{em['clamp']:g}x comparability clamp. Seed and tip would "
              f"fail identically here; refusing a pass/fail verdict "
              f"(exit 3, distinct from regression exit 2). Re-baseline "
              f"on this host class or gate on a comparable runner.",
              file=sys.stderr)
    elif args.expect_fail:
        if code == 0:
            print("[regress] expected failure observed — the gate can "
                  "fire (bidirectional proof OK)")
        else:
            print("[regress] ERROR: seeded slowdown did NOT trip the "
                  "gate — the gate is decoration", file=sys.stderr)
    return code


if __name__ == "__main__":
    sys.exit(main())
