"""SLO accounting: latency objectives, rolling attainment, error budgets.

PR 3 gave serve and train latency *percentiles*; this module gives them
*objectives* — the difference between "p99 is 38 ms" and "p99 must stay
under 50 ms, and we are burning error budget 2.1x faster than allowed".
An objective is a threshold on a latency metric plus an attainment
target::

    serve_latency:p99<=50ms          # implies target 0.99 (from p99)
    train_step:p50<=400ms@0.95       # explicit attainment target

Semantics (the standard SRE framing, over a rolling window):

- **attainment** — fraction of samples meeting the threshold.  "p99 <=
  50 ms" is exactly "99% of requests finish within 50 ms", so the
  quantile in the spec doubles as the default target.
- **error budget** — the allowed violation fraction, ``1 - target``.
- **burn rate** — observed violation fraction / budget.  1.0 means the
  objective is being missed at exactly the allowed rate; 2.0 means the
  budget will be exhausted in half the window.
- **budget_remaining** — ``1 - burn_rate`` over the window (negative
  when the objective is blown; a scraper alerts on it crossing 0).

The tracker subscribes to the event bus: ``serve_latency`` objectives
consume the per-request ``serve_span`` ledger (tpuic/serve/engine.py —
subscribing is what switches span publishing on), ``train_step``
objectives consume the ``step`` events the StepTimer already publishes.
Everything is host-side arithmetic on event payloads — zero device
syncs, zero compiles, the PR-3 discipline.  Quantile reads are the
pinned nearest-rank helper shared with every other percentile in the
repo (tpuic.metrics.meters.quantile).

Exposure: ``report()`` feeds ``prom.slo_rows`` (both the serve and train
expositions take an ``slo=`` report), and every ``publish_every``
samples per objective the tracker publishes an ``slo`` event (JSONL /
TensorBoard scalars via the existing sinks).
"""

from __future__ import annotations

import dataclasses
import re
import threading
from collections import deque
from typing import Dict, List, Optional, Sequence, Tuple

from tpuic.metrics.meters import quantile, quantile_label

# metric name -> (event kind, payload field carrying milliseconds)
METRIC_EVENTS: Dict[str, Tuple[str, str]] = {
    "serve_latency": ("serve_span", "total_ms"),
    "train_step": ("step", "total_ms"),
}

_SPEC_RE = re.compile(
    r"^(?P<metric>[a-z_]+):p(?P<q>[0-9.]+)<=(?P<thresh>[0-9.]+)ms"
    r"(?:@(?P<target>[0-9.]+))?$")


@dataclasses.dataclass(frozen=True)
class Objective:
    """One latency objective: ``quantile`` of ``metric`` must stay under
    ``threshold_ms``, i.e. a ``target`` fraction of samples meet it."""
    metric: str          # key of METRIC_EVENTS
    quantile: float      # e.g. 99.0 — also the default target (0.99)
    threshold_ms: float
    target: float        # attainment target in (0, 1)
    name: str = ""       # exposition label; defaulted from the fields

    def __post_init__(self):
        if self.metric not in METRIC_EVENTS:
            raise ValueError(
                f"unknown SLO metric {self.metric!r} "
                f"(known: {', '.join(sorted(METRIC_EVENTS))})")
        if not 0.0 < self.target < 1.0:
            raise ValueError(
                f"SLO target must be in (0, 1), got {self.target}")
        if self.threshold_ms <= 0:
            raise ValueError("SLO threshold must be positive")
        if not self.name:
            object.__setattr__(
                self, "name",
                f"{self.metric}_{quantile_label(self.quantile)}")


def parse_objective(spec: str,
                    allowed: Optional[Sequence[str]] = None) -> Objective:
    """``metric:pQ<=Nms[@target]`` -> Objective (see module docstring).

    The quantile implies the default target (p99 -> 0.99); ``@target``
    overrides it.  Malformed specs raise ValueError naming the grammar —
    a typo'd SLO that silently never tracks would read as "no
    violations".  ``allowed`` restricts the metric to the ones the
    calling process actually emits: a serve_latency objective in a
    train process would subscribe to ``serve_span`` events that never
    fire and read as a silently dead SLO, so every construction point
    (train.py / TrainTelemetry / ``python -m tpuic.serve``) passes its
    own list and the mismatch fails up front."""
    m = _SPEC_RE.match(spec.strip())
    if not m:
        raise ValueError(
            f"bad SLO spec {spec!r} (expected metric:pQ<=Nms[@target], "
            f"e.g. serve_latency:p99<=50ms@0.99; metrics: "
            f"{', '.join(sorted(METRIC_EVENTS))})")
    q = float(m.group("q"))
    target = (float(m.group("target")) if m.group("target")
              else q / 100.0)
    obj = Objective(metric=m.group("metric"), quantile=q,
                    threshold_ms=float(m.group("thresh")), target=target)
    if allowed is not None and obj.metric not in allowed:
        raise ValueError(
            f"objective {obj.name!r} tracks {obj.metric!r}, which this "
            f"process never emits (emitted here: "
            f"{', '.join(sorted(allowed))}) — it would record nothing, "
            "forever")
    return obj


def parse_objectives(specs: str,
                     allowed: Optional[Sequence[str]] = None
                     ) -> List[Objective]:
    """Comma list of specs -> objectives (empty string -> []);
    ``allowed`` as in :func:`parse_objective`."""
    return [parse_objective(s, allowed=allowed)
            for s in specs.split(",") if s.strip()]


class _ObjState:
    __slots__ = ("met", "samples_win", "samples", "violations")

    def __init__(self, window: int) -> None:
        self.met: deque = deque(maxlen=window)        # bool per sample
        self.samples_win: deque = deque(maxlen=window)  # ms per sample
        self.samples = 0       # lifetime
        self.violations = 0    # lifetime


class SLOTracker:
    """Rolling attainment/burn-rate accounting over bus events.

    Thread-safe: serve spans arrive from the batcher thread while step
    events come from the train loop.  ``attach(bus)`` subscribes to
    exactly the event kinds the configured objectives need (which is
    also what turns per-request span publishing on in the serve engine)
    and returns an unsubscribe callable.
    """

    def __init__(self, objectives: Sequence[Objective], *,
                 window: int = 1024, publish_every: int = 64,
                 publish=None) -> None:
        if not objectives:
            raise ValueError("SLOTracker needs at least one objective")
        names = [o.name for o in objectives]
        if len(set(names)) != len(names):
            raise ValueError(f"duplicate SLO names: {names}")
        self.objectives = tuple(objectives)
        self._window = max(1, int(window))
        self._publish_every = max(1, int(publish_every))
        self._publish = publish
        self._lock = threading.Lock()
        self._state = {o.name: _ObjState(self._window)
                       for o in self.objectives}
        # event kind -> [(field, objective)] — one dict lookup per event.
        self._by_kind: Dict[str, List[Tuple[str, Objective]]] = {}
        for o in self.objectives:
            kind, field = METRIC_EVENTS[o.metric]
            self._by_kind.setdefault(kind, []).append((field, o))

    def kinds(self) -> Tuple[str, ...]:
        """The event kinds the configured objectives consume."""
        return tuple(self._by_kind)

    def attach(self, bus):
        """Subscribe to ``bus`` for exactly the kinds needed; defaults
        the ``slo``-event publisher to the same bus.  Returns the
        unsubscribe callable."""
        if self._publish is None:
            self._publish = bus.publish
        return bus.subscribe(self.on_event, kinds=self.kinds())

    # -- event intake ---------------------------------------------------
    def on_event(self, ev) -> None:
        matches = self._by_kind.get(ev.kind)
        if not matches:
            return
        pending = []
        with self._lock:
            for field, obj in matches:
                v = ev.data.get(field)
                if v is None:
                    continue
                ms = float(v)
                st = self._state[obj.name]
                ok = ms <= obj.threshold_ms
                st.met.append(ok)
                st.samples_win.append(ms)
                st.samples += 1
                if not ok:
                    st.violations += 1
                if st.samples % self._publish_every == 0:
                    pending.append(self._obj_report(obj, st))
        # Publish OUTSIDE the lock: sinks may be slow, and a sink that
        # re-enters the tracker (another slo subscriber) must not
        # deadlock.  The bus itself is re-entrancy-safe.
        if self._publish is not None:
            for rep in pending:
                self._publish("slo", **rep)

    # -- reads ----------------------------------------------------------
    def _obj_report(self, obj: Objective, st: _ObjState) -> dict:
        n = len(st.met)
        att = (sum(st.met) / n) if n else None
        budget = 1.0 - obj.target
        burn = None if att is None else (1.0 - att) / budget
        cur = (round(quantile(st.samples_win, obj.quantile), 3)
               if n else None)
        return {
            "name": obj.name, "metric": obj.metric,
            "quantile": obj.quantile,
            "threshold_ms": obj.threshold_ms, "target": obj.target,
            "samples": st.samples, "window_samples": n,
            "attainment": None if att is None else round(att, 6),
            "current_ms": cur,
            "burn_rate": None if burn is None else round(burn, 4),
            "budget_remaining": (None if burn is None
                                 else round(1.0 - burn, 4)),
        }

    def burn_rate(self, name: str) -> Optional[float]:
        """Current rolling burn rate of the named objective (None until
        it has samples) — the poll-side twin of the ``slo`` events the
        brownout controller (tpuic/serve/admission.py) consumes.  Raises
        KeyError for an unknown name: a brownout coupled to an objective
        this tracker doesn't carry would silently never tighten."""
        for obj in self.objectives:
            if obj.name == name:
                with self._lock:
                    return self._obj_report(
                        obj, self._state[obj.name])["burn_rate"]
        raise KeyError(
            f"no SLO objective named {name!r} "
            f"(configured: {', '.join(o.name for o in self.objectives)})")

    def report(self) -> dict:
        """{"objectives": [per-objective dicts]} — feed prom.slo_rows."""
        with self._lock:
            return {"objectives": [
                self._obj_report(o, self._state[o.name])
                for o in self.objectives]}

    def summary_line(self) -> str:
        """One log line: per objective, attainment vs target and burn."""
        parts = []
        for obj in self.report()["objectives"]:
            if obj["attainment"] is None:
                parts.append(f"{obj['name']}: no samples")
                continue
            parts.append(
                f"{obj['name']}: {100 * obj['attainment']:.2f}% "
                f"<= {obj['threshold_ms']:g}ms (target "
                f"{100 * obj['target']:g}%, burn {obj['burn_rate']:.2f}x)")
        return "; ".join(parts)
