"""tpuic.telemetry — unified observability subsystem.

The reference repo's only observability was an ``AverageMeter`` printed
per epoch; this reproduction grew a trainer, a serving engine, and a
fault-tolerance layer that each invented their own measurement (deferred
log drain, ServeStats, bench-script MFU math).  This package makes the
measurement a first-class subsystem — the layer every perf PR cites for
before/after evidence (docs/observability.md):

- ``events``   — structured publish/subscribe **event bus** with JSONL /
  in-memory / TensorBoard sinks.  train/loop.py, checkpoint/manager.py,
  data/folder.py, serve/engine.py, and the replica router
  (serve/router.py) emit typed events (``step``, ``epoch``, ``eval``,
  ``checkpoint_commit``, ``rollback``, ``skip``, ``quarantine``,
  ``compile``, ``serve_batch``, ``trace``, ``goodput``,
  ``router_*``) into it instead of ad-hoc log lines.
- ``steptime`` — per-step wall-clock **breakdown** (data-wait vs.
  dispatch vs. device) from dispatch timestamps + the existing deferred
  drain: zero new host syncs, zero new compiles (asserted in
  tests/test_telemetry.py, the PR-2 discipline).
- ``goodput``  — per-model analytic FLOPs (bench.py's math, now owned
  here and imported back by bench.py), running MFU, and a goodput
  report classifying wall time into productive / compile / checkpoint /
  skip / rollback / input-bound / eval buckets.
- ``tracing``  — triggered ``jax.profiler`` windows: arms automatically
  when step time regresses past a multiple of the rolling median (or
  via ``TPUIC_TRACE=dir``), writing to a bounded trace dir.
- ``prom``     — Prometheus-style text exposition of serve, train, and
  router counters (``--prom-dump/--prom-port``).
- ``memory``   — per-device **memory accounting** sampled at step
  boundaries (allocator counters where the backend provides them,
  live-array bytes + RSS on CPU): ``memory`` events, TensorBoard
  scalars, ``device_memory_bytes{device,kind}`` prom rows, one-shot
  low-headroom warning.
- ``flight``   — **crash flight recorder**: a bounded ring of the last
  N events, dumped as ``flightdump-<attempt>.jsonl`` on SIGQUIT /
  fatal exit alongside the supervisor's stack dumps.
- ``fleet``    — **per-rank fleet view**: rank-tagged events, per-rank
  JSONL streams, and the offline straggler-attribution aggregator
  (``python -m tpuic.telemetry.fleet <dir>``).
- ``wiring``   — ``TrainTelemetry``, one training run's subscriber set.

Everything is host-side: no module here ever calls ``jax.device_get``
or adds device work (test-asserted), so telemetry can stay on in
production hot loops.

Re-exports resolve lazily (PEP 562, the tpuic/__init__.py idiom) so
that importing this package — which stdlib-only parents do transitively
via ``tpuic.telemetry.events`` and ``tpuic.telemetry.prom`` — never
pulls jax/numpy into a supervisor or router process that must outlive
any backend wedge (the same rule runtime/supervisor.py documents).
"""

from __future__ import annotations

_LAZY = {
    # events (stdlib-only module — the cheap common case)
    "Event": ("tpuic.telemetry.events", "Event"),
    "EventBus": ("tpuic.telemetry.events", "EventBus"),
    "JsonlSink": ("tpuic.telemetry.events", "JsonlSink"),
    "MemorySink": ("tpuic.telemetry.events", "MemorySink"),
    "TensorBoardSink": ("tpuic.telemetry.events", "TensorBoardSink"),
    "bus": ("tpuic.telemetry.events", "bus"),
    "install_jax_compile_listener": ("tpuic.telemetry.events",
                                     "install_jax_compile_listener"),
    "publish": ("tpuic.telemetry.events", "publish"),
    "read_jsonl": ("tpuic.telemetry.events", "read_jsonl"),
    "subscribe": ("tpuic.telemetry.events", "subscribe"),
    # flight recorder
    "FlightRecorder": ("tpuic.telemetry.flight", "FlightRecorder"),
    "install_flight_recorder": ("tpuic.telemetry.flight",
                                "install_flight_recorder"),
    # goodput / roofline
    "GoodputTracker": ("tpuic.telemetry.goodput", "GoodputTracker"),
    "HBM_GBPS": ("tpuic.telemetry.goodput", "HBM_GBPS"),
    "PEAK_FLOPS": ("tpuic.telemetry.goodput", "PEAK_FLOPS"),
    "analytic_flops_per_step": ("tpuic.telemetry.goodput",
                                "analytic_flops_per_step"),
    "hbm_bandwidth": ("tpuic.telemetry.goodput", "hbm_bandwidth"),
    "peak_flops": ("tpuic.telemetry.goodput", "peak_flops"),
    "roofline_intensity": ("tpuic.telemetry.goodput",
                           "roofline_intensity"),
    # memory / slo / steptime / tracing
    "MemorySampler": ("tpuic.telemetry.memory", "MemorySampler"),
    "Objective": ("tpuic.telemetry.slo", "Objective"),
    "SLOTracker": ("tpuic.telemetry.slo", "SLOTracker"),
    "parse_objectives": ("tpuic.telemetry.slo", "parse_objectives"),
    "StepTimer": ("tpuic.telemetry.steptime", "StepTimer"),
    "TraceTrigger": ("tpuic.telemetry.tracing", "TraceTrigger"),
    # per-run wiring
    "TrainTelemetry": ("tpuic.telemetry.wiring", "TrainTelemetry"),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib
        module, attr = _LAZY[name]
        value = getattr(importlib.import_module(module), attr)
        globals()[name] = value  # cache: next access skips the import
        return value
    raise AttributeError(
        f"module 'tpuic.telemetry' has no attribute '{name}'")


def __dir__():
    return sorted(set(list(globals()) + list(_LAZY)))
