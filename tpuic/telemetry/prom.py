"""Prometheus text exposition of serve and train counters.

No client library (the container bakes none in): the exposition format
is lines of ``name{label="v"} value`` with ``# HELP``/``# TYPE``
comments — trivially hand-rendered and accepted by any Prometheus
scraper or ``promtool check metrics``.

Two producers:

- ``serve_exposition(stats.snapshot())`` — the InferenceEngine's
  counters: queue-wait and latency percentiles (sourced from the shared
  ``tpuic.metrics.LatencyMeter``), pad efficiency, bucket histogram,
  compile/cache counters, throughput.
- ``train_exposition(goodput.report(), steptime.summary())`` — goodput
  fractions, MFU, step-time percentiles.

Transport is the caller's choice: ``write_exposition`` dumps to a file
(``--prom-dump``, scrapeable via node_exporter's textfile collector),
``PromServer`` serves ``/metrics`` over HTTP (``--prom-port``).
"""

from __future__ import annotations

import os
import threading
from typing import Callable, Iterable, List, Optional, Tuple


def _fmt_labels(labels: Optional[dict]) -> str:
    if not labels:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in sorted(labels.items()))
    return "{" + inner + "}"


def render(rows: Iterable[Tuple], prefix: str = "tpuic") -> str:
    """rows: (name, value, type, help, labels-or-None).  Values of None
    are skipped (a percentile with no samples yet must not render as a
    bogus 0).  TYPE/HELP are emitted once per metric name."""
    seen = set()
    out: List[str] = []
    for name, value, mtype, help_, labels in rows:
        if value is None:
            continue
        full = f"{prefix}_{name}"
        if full not in seen:
            seen.add(full)
            out.append(f"# HELP {full} {help_}")
            out.append(f"# TYPE {full} {mtype}")
        out.append(f"{full}{_fmt_labels(labels)} {float(value):g}")
    return "\n".join(out) + "\n" if out else ""


def slo_rows(slo_report: Optional[dict]) -> List[Tuple]:
    """SLOTracker.report() -> exposition rows (telemetry/slo.py): per
    objective, the configured target/threshold plus rolling attainment,
    error-budget burn rate, and remaining budget.  Shared by the serve
    and train expositions; an empty/None report renders nothing."""
    rows: List[Tuple] = []
    for obj in (slo_report or {}).get("objectives", ()):
        labels = {"slo": obj.get("name", "slo")}
        for field, mtype, help_ in (
                ("target", "gauge",
                 "configured attainment target for this SLO"),
                ("threshold_ms", "gauge",
                 "latency threshold the SLO is measured against"),
                ("samples", "counter",
                 "samples observed in the rolling SLO window"),
                ("attainment", "gauge",
                 "rolling fraction of samples meeting the objective"),
                ("current_ms", "gauge",
                 "current value of the SLO's quantile over the window"),
                ("burn_rate", "gauge",
                 "error-budget burn rate (1.0 = burning exactly at "
                 "budget; >1 = on track to exhaust it)"),
                ("budget_remaining", "gauge",
                 "fraction of the rolling error budget left (can go "
                 "negative when the objective is blown)")):
            if obj.get(field) is not None:
                rows.append((f"slo_{field}", obj[field], mtype, help_,
                             labels))
    return rows


def memory_rows(memory: Optional[dict]) -> List[Tuple]:
    """MemorySampler.snapshot() -> exposition rows (telemetry/memory.py):
    per device, ``device_memory_bytes{device,kind}`` with kind in
    ``in_use|peak|limit`` plus a per-device headroom gauge — the HBM
    curve the multi-host/MFU roadmap items steer by.  Shared by the
    serve and train expositions; None renders nothing (CPU runs with no
    sample yet must not scrape as 0 bytes)."""
    rows: List[Tuple] = []
    for dev in (memory or {}).get("devices") or ():
        labels = {"device": str(dev.get("device", "?"))}
        for field, kind in (("bytes_in_use", "in_use"),
                            ("peak_bytes_in_use", "peak"),
                            ("bytes_limit", "limit")):
            if dev.get(field) is not None:
                rows.append(("device_memory_bytes", dev[field], "gauge",
                             "per-device memory bytes by kind "
                             "(in_use|peak|limit); source per "
                             "docs/observability.md 'Device memory'",
                             {**labels, "kind": kind}))
        if dev.get("headroom_frac") is not None:
            rows.append(("device_memory_headroom_frac",
                         dev["headroom_frac"], "gauge",
                         "1 - in_use/limit per device (alert low: the "
                         "next allocation spike is an OOM)", labels))
    return rows


def compile_cache_rows() -> List[Tuple]:
    """Compiled-program registry counters (tpuic/compiled/registry.py,
    docs/performance.md "Compiled-program registry") -> exposition rows.
    The registry is a process-wide singleton shared by train, serve, and
    bench, so both expositions render the same four rows: hit/miss/
    prewarm counters plus the live entry count.  Lazily imported so the
    telemetry tier keeps working if tpuic.compiled is absent."""
    try:
        from tpuic.compiled import registry
        c = registry.counters()
    except Exception:
        return []
    return [
        ("compile_cache_hits_total", c.get("hits", 0), "counter",
         "compiled-program registry lookups served from cache "
         "(no XLA compile)", None),
        ("compile_cache_misses_total", c.get("misses", 0), "counter",
         "compiled-program registry misses that lowered+compiled "
         "(includes prewarms)", None),
        ("compile_cache_prewarmed_total", c.get("prewarmed", 0), "counter",
         "registry entries compiled ahead of traffic from a prewarm "
         "manifest", None),
        ("compile_cache_entries", c.get("entries", 0), "gauge",
         "live executables in the compiled-program registry "
         "(generation GC retires them)", None),
    ]


_VERDICT_CODE = {"hbm-bound": 0.0, "compute-bound": 1.0, "overhead": -1.0}


def profile_rows(waterfall: Optional[dict]) -> List[Tuple]:
    """Device-time waterfall (telemetry/profile.py) -> exposition rows:
    per op class, ``device_time_ms{op_class}`` / ``device_time_frac``
    plus the roofline intensity and an encoded verdict
    (1 = compute-bound, 0 = hbm-bound, -1 = overhead — numeric so a
    dashboard can alert on a class flipping sides of the ridge).
    Shared by the serve and train expositions; None renders nothing (a
    run that never analyzed must not scrape as a zero waterfall)."""
    rows: List[Tuple] = []
    for cls, c in sorted((waterfall or {}).get("classes", {}).items()):
        if not isinstance(c, dict):
            continue
        labels = {"op_class": cls}
        rows.append(("device_time_ms", c.get("ms"), "gauge",
                     "device time per op class from the last waterfall "
                     "analysis (docs/observability.md, 'Device-time "
                     "attribution')", labels))
        rows.append(("device_time_frac", c.get("frac"), "gauge",
                     "fraction of the device bucket per op class",
                     labels))
        rows.append(("roofline_intensity", c.get("intensity"), "gauge",
                     "arithmetic intensity (FLOPs/HBM byte) per op class",
                     labels))
        rows.append(("roofline_verdict", _VERDICT_CODE.get(
            c.get("verdict")), "gauge",
            "roofline verdict per op class (1=compute-bound, "
            "0=hbm-bound, -1=overhead)", labels))
    if (waterfall or {}).get("device_ms_per_step") is not None:
        rows.append(("device_ms_per_step", waterfall["device_ms_per_step"],
                     "gauge", "mean measured device bucket the waterfall "
                     "sums to", None))
    return rows


def score_rows(report: Optional[dict]) -> List[Tuple]:
    """Bulk-scoring exposition rows (docs/observability.md, "Bulk
    scoring"): works off either a worker's ``score_done`` summary
    (tpuic/score/driver.py) or the fleet audit report
    (telemetry/fleet.py ``score_audit``) — the two share their key
    vocabulary; fields only one side carries render only there.  None
    renders nothing."""
    r = report or {}
    rows: List[Tuple] = []
    for field, mtype, help_ in (
            ("n", "gauge", "corpus rows the scoring plan covers"),
            ("shards", "gauge", "shards in the scoring plan"),
            ("shards_committed", "gauge",
             "shards with a verified result manifest"),
            ("shards_missing", "gauge",
             "planned shards with no ledger commit record (audit; "
             "alert nonzero: dropped work)"),
            ("shards_duplicated", "gauge",
             "shards with more than one ledger commit record (audit; "
             "alert nonzero: double-counted corpus)"),
            ("rows_scored", "counter", "corpus rows scored"),
            ("rows_quarantined", "counter",
             "corpus rows quarantined (undecodable at pack time or "
             "failing their packed row CRC at read time)"),
            ("recovered_records", "counter",
             "ledger commit records appended by a survivor for a dead "
             "winner (crash-window repair, not a violation)"),
            ("duplicate_score_events", "counter",
             "double-scored shard attempts deduped at commit (lease "
             "races cost throughput, not correctness)"),
            ("steady_compiles", "gauge",
             "executables compiled AFTER engine warmup during scoring "
             "(the zero-steady-state-compile contract; alert nonzero)"),
            ("steals_this_life", "counter",
             "expired/orphaned shard leases this worker stole"),
    ):
        if r.get(field) is not None:
            rows.append((f"score_{field}", r[field], mtype, help_, None))
    if r.get("ok") is not None:
        rows.append(("score_ledger_exact", 1.0 if r["ok"] else 0.0,
                     "gauge", "1 when the ledger audit held exactly "
                     "(scored + quarantined == corpus, zero duplicates, "
                     "zero drops)", None))
    return rows


def _process_rss_row() -> Tuple:
    """The ``process_rss_bytes`` gauge both expositions render — host
    memory next to the device curve it eventually takes down.  Lazy
    import keeps this module importable without the metrics stack."""
    from tpuic.metrics.meters import process_rss_bytes
    return ("process_rss_bytes", process_rss_bytes(), "gauge",
            "resident set size of this process", None)


def admission_rows(snapshot: dict,
                   admission: Optional[dict] = None) -> List[Tuple]:
    """The admission-control exposition (docs/serving.md, "Admission
    control and overload"): the ``rejected_total`` counter split by
    cause (``queue_full|deadline|quota|brownout``) and priority class —
    the labels every typed :class:`tpuic.serve.admission.AdmissionError`
    carries — plus, when an ``AdmissionController.state()`` dict is
    handed in, the brownout level and remaining quota tokens.  A cause
    that never fired renders no series (Prometheus treats an absent
    counter as 0); the unlabeled total lives on in
    ``snapshot()['rejected']`` for humans."""
    rows: List[Tuple] = []
    for cause, by_prio in (snapshot.get("rejected_by") or {}).items():
        for prio, n in (by_prio or {}).items():
            rows.append(("rejected_total", n, "counter",
                         "requests rejected or shed, by cause "
                         "(queue_full|deadline|quota|brownout) and "
                         "priority class",
                         {"cause": cause, "priority": prio}))
    brownout = (admission or {}).get("brownout") or {}
    if brownout.get("level") is not None:
        rows.append(("brownout_level", brownout["level"], "gauge",
                     "SLO-coupled brownout level (0 = admitting every "
                     "class; level L sheds the L lowest classes)",
                     {"slo": brownout.get("slo", "")}))
    for tenant, tokens in ((admission or {}).get("tenant_tokens")
                           or {}).items():
        rows.append(("quota_tokens", tokens, "gauge",
                     "remaining token-bucket quota per tenant",
                     {"tenant": tenant}))
    if (admission or {}).get("free_pool_tokens") is not None:
        rows.append(("quota_tokens", admission["free_pool_tokens"],
                     "gauge", "remaining token-bucket quota per tenant",
                     {"tenant": "*"}))
    return rows


def serve_exposition(snapshot: dict, prefix: str = "tpuic_serve",
                     heartbeat_age_s: Optional[float] = None,
                     slo: Optional[dict] = None,
                     admission: Optional[dict] = None,
                     memory: Optional[dict] = None,
                     profile: Optional[dict] = None) -> str:
    """ServeStats.snapshot() -> Prometheus text.

    ``heartbeat_age_s``: seconds since the supervised-liveness heartbeat
    file was last written (runtime/supervisor.py), when the server runs
    under ``python -m tpuic.supervise``; omitted (None) unsupervised —
    a scraper alerting on staleness must not see a bogus 0.
    ``slo``: an SLOTracker.report() to append (telemetry/slo.py).
    ``admission``: an AdmissionController.state() for brownout/quota
    gauges; the rejected_total{cause,priority} split renders from the
    snapshot itself.
    ``memory``: a MemorySampler.snapshot() for the per-device
    ``device_memory_bytes{device,kind}`` rows (telemetry/memory.py).
    ``profile``: a device-time waterfall (telemetry/profile.py — the
    engine's ``profile_waterfall()``) for ``device_time_ms{op_class}``
    rows."""
    rows: List[Tuple] = [
        _process_rss_row(),
        ("heartbeat_age_seconds", heartbeat_age_s, "gauge",
         "seconds since the liveness heartbeat file was last written "
         "(supervised runs only)", None),
        ("requests_total", snapshot.get("requests"), "counter",
         "requests resolved", None),
        ("images_total", snapshot.get("images"), "counter",
         "images scored", None),
        ("device_calls_total", snapshot.get("device_calls"), "counter",
         "bucketed device dispatches", None),
        ("compiles_total", snapshot.get("compiles"), "counter",
         "bucket executable compiles (0 after warmup = the AOT contract)",
         None),
        ("executable_cache_hits_total", snapshot.get("executable_cache_hits"),
         "counter", "steady-state executable cache hits", None),
        ("compile_seconds_total", snapshot.get("compile_s"), "counter",
         "cumulative compile wall time", None),
        ("pad_efficiency", snapshot.get("pad_efficiency"), "gauge",
         "valid rows / device rows (1.0 = no padding waste)", None),
        ("swaps_total", snapshot.get("swaps"), "counter",
         "atomic weight hot-swaps completed (docs/serving.md, "
         "'Model lifecycle')", None),
        ("generation", snapshot.get("generation"), "gauge",
         "weight generation: 0 at boot, +1 per hot-swap", None),
        ("throughput_images_per_sec", snapshot.get(
            "throughput_images_per_sec"), "gauge",
         "lifetime images/sec", None),
        ("elapsed_seconds", snapshot.get("elapsed_s"), "gauge",
         "seconds since stats reset", None),
    ]
    for src, name, help_ in (
            ("queue_wait_ms", "queue_wait_ms",
             "enqueue->dispatch wait percentiles over the sliding window"),
            ("latency_ms", "latency_ms",
             "enqueue->result latency percentiles over the sliding window")):
        for q, v in (snapshot.get(src) or {}).items():
            rows.append((name, v, "gauge", help_, {"quantile": q}))
    # Request span ledger percentiles (docs/observability.md, "Request
    # tracing"): one series per phase of a request's life.
    for phase, qs in (snapshot.get("span_ms") or {}).items():
        for q, v in (qs or {}).items():
            rows.append(("span_ms", v, "gauge",
                         "per-request span percentiles by phase "
                         "(queue/batch/staging/dispatch/device/scatter)",
                         {"phase": phase, "quantile": q}))
    for bucket, n in (snapshot.get("batch_hist") or {}).items():
        rows.append(("batches_total", n, "counter",
                     "device calls per padding bucket", {"bucket": bucket}))
    # Per-bucket executable cost analysis (serve/engine.py _compile):
    # FLOPs/bytes/intensity of each AOT bucket — the roofline context
    # for the span ledger's device phase.
    for bucket, c in sorted((snapshot.get("executable_cost")
                             or {}).items()):
        labels = {"bucket": str(bucket)}
        for field, help_ in (("flops", "compiled FLOPs per executable "
                              "call, by padding bucket"),
                             ("bytes", "compiled HBM bytes accessed per "
                              "executable call, by padding bucket"),
                             ("intensity", "arithmetic intensity "
                              "(FLOPs/byte) per bucket executable")):
            if c.get(field) is not None:
                rows.append((f"executable_{field}", c[field], "gauge",
                             help_, labels))
    if snapshot.get("model_digest"):
        # Info-style row (value 1, identity in the label): what weights
        # are serving — scrape-join it against the router's view.
        rows.append(("model_info", 1, "gauge",
                     "serving-weights identity (digest label; "
                     "generation row says how many swaps ago)",
                     {"digest": str(snapshot["model_digest"])}))
    rows.extend(profile_rows(profile))
    rows.extend(admission_rows(snapshot, admission))
    rows.extend(memory_rows(memory))
    rows.extend(slo_rows(slo))
    rows.extend(compile_cache_rows())
    return render(rows, prefix=prefix)


_BREAKER_CODE = {"closed": 0.0, "half_open": 0.5, "open": 1.0}
_REPLICA_STATE_CODE = {"starting": 0.0, "up": 1.0, "wedged": 2.0,
                       "down": 3.0, "failed": 4.0, "stopped": 5.0}


_ROLLOUT_PHASE_CODE = {"idle": 0.0, "gating": 1.0, "canary": 2.0,
                       "promoting": 3.0, "rolling_back": 4.0,
                       "promoted": 5.0, "rolled_back": 6.0,
                       "refused": 7.0, "aborted": 8.0}


def rollout_rows(rollout: Optional[dict]) -> List[Tuple]:
    """``CanaryRollout.state()`` -> tpuic_rollout_* rows
    (tpuic/serve/rollout.py, docs/serving.md "Model lifecycle").
    Phase is a numeric code (0=idle 1=gating 2=canary 3=promoting
    4=rolling_back 5=promoted 6=rolled_back 7=refused 8=aborted) so a
    dashboard alerts on 4+/6+ without string matching."""
    if not rollout:
        return []
    rows: List[Tuple] = [
        ("rollout_phase", _ROLLOUT_PHASE_CODE.get(rollout.get("phase")),
         "gauge", "rollout phase (0=idle 1=gating 2=canary 3=promoting "
         "4=rolling_back 5=promoted 6=rolled_back 7=refused 8=aborted)",
         None),
        ("rollout_stage_index", rollout.get("stage_index"), "gauge",
         "current canary stage index (-1 before the first stage)",
         None),
        ("rollout_stage_fraction", rollout.get("stage_fraction"),
         "gauge", "fraction of traffic routed to the canary", None),
        ("rollout_canary_errors_total", rollout.get("canary_errors"),
         "counter", "untyped errors observed on the canary (any one "
         "triggers rollback)", None),
    ]
    if rollout.get("objective"):
        labels = {"slo": str(rollout["objective"])}
        rows.append(("rollout_burn_rate", rollout.get("burn_rate"),
                     "gauge", "canary-scoped error-budget burn rate of "
                     "the watched objective", labels))
        rows.append(("rollout_canary_window_samples",
                     rollout.get("canary_window_samples"), "gauge",
                     "canary latency samples in the SLO window", labels))
    return rows


def router_exposition(snapshot: dict,
                      prefix: str = "tpuic_router",
                      rollout: Optional[dict] = None) -> str:
    """``Router.snapshot()`` -> Prometheus text (tpuic/serve/router.py,
    docs/serving.md "Replica routing and failover").

    Fleet-level counters (the exact offered-traffic ledger: ``offered ==
    requests + rejected + errors``), the retry budget gauge, end-to-end
    latency quantiles, and per-replica rows — health state and breaker
    state as numeric codes (state: 0=starting 1=up 2=wedged 3=down
    4=failed 5=stopped; breaker: 0=closed 0.5=half_open 1=open) so a
    dashboard can alert on a replica leaving 1/0.  ``rollout`` appends
    the tpuic_rollout_* rows (:func:`rollout_rows`) when a canary
    rollout driver is attached.  Deliberately no ``process_rss_bytes``
    row: that helper imports the jax-backed metrics stack, and the
    router process is stdlib-only by contract."""
    rows: List[Tuple] = [
        ("offered_total", snapshot.get("offered"), "counter",
         "requests offered to the router", None),
        ("requests_total", snapshot.get("requests"), "counter",
         "requests resolved with a result", None),
        ("errors_total", snapshot.get("errors"), "counter",
         "untyped request failures (decode errors, bugs)", None),
        ("retries_total", snapshot.get("retries"), "counter",
         "budgeted failover replays", None),
        ("failovers_total", snapshot.get("failovers"), "counter",
         "replica-loss failover events", None),
        ("failover_requeued_total", snapshot.get("failover_requeued"),
         "counter", "in-flight requests requeued to a survivor", None),
        ("failover_lost_total", snapshot.get("failover_lost"), "counter",
         "in-flight requests resolved replica_lost", None),
        ("duplicate_responses_total", snapshot.get("duplicates"),
         "counter", "late/duplicate replica responses dropped by the "
         "at-most-once id dedupe", None),
        ("wire_errors_total", snapshot.get("wire_errors"), "counter",
         "replica lines with an id the router never issued (torn "
         "framing / protocol errors — alert: not benign dedupe)", None),
        ("elapsed_seconds", snapshot.get("elapsed_s"), "gauge",
         "seconds since stats reset", None),
    ]
    for cause, by_prio in (snapshot.get("rejected_by") or {}).items():
        for prio, n in (by_prio or {}).items():
            rows.append(("rejected_total", n, "counter",
                         "typed verdicts by cause (queue_full|deadline|"
                         "quota|brownout|replica_lost) and priority",
                         {"cause": cause, "priority": prio}))
    budget = snapshot.get("retry_budget") or {}
    rows.append(("retry_budget_tokens", budget.get("tokens"), "gauge",
                 "remaining retry-budget tokens (deposits = ratio x "
                 "successes; one whole token per replay)", None))
    rows.append(("retry_budget_denied_total", budget.get("denied"),
                 "counter", "replays denied by a dry retry budget",
                 None))
    for q, v in (snapshot.get("latency_ms") or {}).items():
        rows.append(("latency_ms", v, "gauge",
                     "submit->resolve latency percentiles over the "
                     "sliding window", {"quantile": q}))
    for name, rep in sorted((snapshot.get("replicas") or {}).items()):
        labels = {"replica": name}
        rows.append(("replica_state", _REPLICA_STATE_CODE.get(
            rep.get("state")), "gauge",
            "replica health state (0=starting 1=up 2=wedged 3=down "
            "4=failed 5=stopped)", labels))
        rows.append(("replica_breaker_state", _BREAKER_CODE.get(
            (rep.get("breaker") or {}).get("state")), "gauge",
            "circuit-breaker state (0=closed 0.5=half_open 1=open)",
            labels))
        rows.append(("replica_breaker_transitions_total",
                     (rep.get("breaker") or {}).get("transitions"),
                     "counter", "breaker state transitions", labels))
        rows.append(("replica_inflight", rep.get("inflight"), "gauge",
                     "requests in flight on this replica", labels))
        rows.append(("replica_routed_total", rep.get("routed"),
                     "counter", "requests routed to this replica",
                     labels))
        rows.append(("replica_transport_failures_total",
                     rep.get("transport_failures"), "counter",
                     "transport failures (send errors, ping timeouts, "
                     "connection loss)", labels))
        rows.append(("replica_spill_limit", rep.get("spill_limit"),
                     "gauge", "in-flight ceiling before load spills "
                     "past this replica (Little's law at the committed "
                     "knee)", labels))
        rows.append(("replica_brownout_level", rep.get("brownout_level"),
                     "gauge", "brownout level scraped from the "
                     "replica's own exposition", labels))
        rows.append(("replica_queue_depth", rep.get("queue_depth"),
                     "gauge", "engine queue depth from the last pong",
                     labels))
        rows.append(("replica_heartbeat_age_seconds",
                     rep.get("heartbeat_age_s"), "gauge",
                     "age of the replica's supervisor heartbeat file",
                     labels))
        rows.append(("replica_spawns_total", rep.get("spawns"),
                     "counter", "times this replica was (re)spawned",
                     labels))
        rows.append(("replica_generation", rep.get("generation"),
                     "gauge", "replica weight generation (0 at boot, "
                     "+1 per hot-swap; from the live pong)", labels))
        rows.append(("replica_resolved_total", rep.get("resolved"),
                     "counter", "requests this replica resolved with a "
                     "result", labels))
        rows.append(("replica_typed_rejects_total",
                     rep.get("rejected_typed"), "counter",
                     "typed verdicts this replica returned", labels))
        rows.append(("replica_errors_total", rep.get("resp_errors"),
                     "counter", "untyped error responses from this "
                     "replica (the canary rollback trigger)", labels))
        rows.append(("replica_digest_ok",
                     (None if rep.get("digest") is None
                      else float(bool(rep.get("digest_ok")))), "gauge",
                     "1 = replica's model digest is in the fleet's "
                     "allowed set, 0 = refused traffic by the identity "
                     "gate (absent until the replica reports one)",
                     labels))
        if rep.get("digest"):
            rows.append(("replica_model_info", 1, "gauge",
                         "replica serving-weights identity (digest "
                         "label)", {**labels,
                                    "digest": str(rep["digest"])}))
    if snapshot.get("fleet_digest"):
        rows.append(("fleet_model_info", 1, "gauge",
                     "THE fleet model digest the identity gate "
                     "enforces (docs/serving.md, 'Model lifecycle')",
                     {"digest": str(snapshot["fleet_digest"])}))
    split = snapshot.get("traffic_split")
    rows.append(("traffic_split_fraction",
                 (split or {}).get("fraction"), "gauge",
                 "fraction of picks routed to the canary group (absent "
                 "outside a rollout)", None))
    rows.extend(rollout_rows(rollout))
    return render(rows, prefix=prefix)


def train_exposition(report: dict, steptime: Optional[dict] = None,
                     prefix: str = "tpuic_train",
                     heartbeat_age_s: Optional[float] = None,
                     slo: Optional[dict] = None,
                     memory: Optional[dict] = None,
                     profile: Optional[dict] = None) -> str:
    """GoodputTracker.report() (+ StepTimer.summary()) -> Prometheus text.

    ``heartbeat_age_s`` as in :func:`serve_exposition`; ``restart_count``
    comes from the report's ``restarts`` field (the supervisor restart
    this process announced at fit() start — runtime/supervisor.py).
    ``slo``: an SLOTracker.report() for the step-time objectives.
    ``memory``: a MemorySampler.snapshot() (telemetry/memory.py).
    ``profile``: the last device-time waterfall (telemetry/profile.py,
    ``CaptureAnalyzer.last``) for ``device_time_ms{op_class}`` rows."""
    rows: List[Tuple] = [
        _process_rss_row(),
        ("restart_count", report.get("restarts"), "counter",
         "supervisor restarts absorbed by this run "
         "(runtime/supervisor.py exit-code contract)", None),
        ("heartbeat_age_seconds", heartbeat_age_s, "gauge",
         "seconds since the liveness heartbeat file was last written "
         "(supervised runs only)", None),
        ("steps_total", report.get("steps"), "counter",
         "train steps dispatched", None),
        ("wall_seconds", report.get("wall_s"), "gauge",
         "goodput window wall time", None),
        ("mfu", report.get("mfu"), "gauge",
         "running model FLOPs utilization (analytic)", None),
        ("compiles_total", report.get("compiles"), "counter",
         "backend compiles observed (flat after step 1 = no retraces)",
         None),
        ("skipped_steps", report.get("skipped_steps_est"), "counter",
         "estimated non-finite guard-skipped steps", None),
        ("goodput_accounted_fraction", report.get("accounted_frac"),
         "gauge", "fraction of wall time the named buckets explain", None),
    ]
    for k, v in report.items():
        if k.startswith("frac_"):
            rows.append(("goodput_fraction", v, "gauge",
                         "fraction of wall time per goodput bucket",
                         {"bucket": k[5:]}))
    if report.get("compute_dtype"):
        # Info-style row (value 1, dtype as label): lets dashboards and
        # alerts split MFU/step-time series by precision arm.
        rows.append(("compute_dtype_info", 1, "gauge",
                     "active train compute dtype",
                     {"dtype": str(report["compute_dtype"])}))
    rows.append(("checkpoint_async_seconds",
                 report.get("checkpoint_async_s"), "gauge",
                 "checkpoint commit work overlapped with compute (async "
                 "commits; blocking stall is goodput_fraction "
                 "bucket=checkpoint)", None))
    for src, name in ((steptime or {}).get("total_ms"), "step_total_ms"), \
                     ((steptime or {}).get("data_ms"), "step_data_wait_ms"):
        for q, v in (src or {}).items():
            rows.append((name, v, "gauge",
                         "step-time percentiles over the sliding window",
                         {"quantile": q}))
    rows.extend(profile_rows(profile))
    rows.extend(memory_rows(memory))
    rows.extend(slo_rows(slo))
    rows.extend(compile_cache_rows())
    return render(rows, prefix=prefix)


def write_exposition(path: str, text: str) -> None:
    """Atomic dump (textfile-collector discipline: scrapers must never
    read a half-written exposition)."""
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        f.write(text)
    os.replace(tmp, path)


class PromServer:
    """Minimal /metrics HTTP endpoint around a ``collect() -> str``
    callable; runs in a daemon thread, ``close()`` shuts it down.

    Binds loopback by default (the node_exporter convention): the
    endpoint has no auth, so exposing it beyond the host is an explicit
    caller decision (``--prom-host`` in ``python -m tpuic.serve``)."""

    def __init__(self, port: int, collect: Callable[[], str],
                 host: str = "127.0.0.1") -> None:
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self_inner):  # noqa: N805
                if self_inner.path.rstrip("/") not in ("", "/metrics"):
                    self_inner.send_response(404)
                    self_inner.end_headers()
                    return
                try:
                    body = collect().encode()
                except Exception as e:  # collector bug -> 500, not crash
                    self_inner.send_response(500)
                    self_inner.end_headers()
                    self_inner.wfile.write(str(e).encode())
                    return
                self_inner.send_response(200)
                self_inner.send_header(
                    "Content-Type", "text/plain; version=0.0.4")
                self_inner.send_header("Content-Length", str(len(body)))
                self_inner.end_headers()
                self_inner.wfile.write(body)

            def log_message(self_inner, *a):  # quiet: stderr is for stats
                pass

        self._srv = ThreadingHTTPServer((host, int(port)), Handler)
        self.port = self._srv.server_address[1]  # resolved (port 0 = any)
        self._thread = threading.Thread(target=self._srv.serve_forever,
                                        daemon=True, name="tpuic-prom")
        self._thread.start()

    def close(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
        self._thread.join(timeout=5)
