"""Triggered profiler traces: capture the regression, not the baseline.

A standing ``jax.profiler`` trace is too heavy to leave on, and a trace
started by hand always misses the incident.  ``TraceTrigger`` watches
step totals (the ``step`` events the StepTimer publishes) and arms a
bounded trace window automatically when a step regresses past a
configurable multiple of the rolling median — so the trace on disk is
of the slow steps, captured while they were slow.

Semantics (docs/observability.md):

- **Trigger**: after ``warmup`` observed steps, a step whose total
  exceeds ``threshold x median(recent window)`` starts a trace that
  covers the next ``trace_steps`` steps.  The triggering step itself is
  already over (its timestamps are host-side history); regressions this
  exists for (input stall, new retrace, contended chip) persist across
  steps, which is exactly why a median trigger works.
- **Manual**: ``TPUIC_TRACE=dir`` (env) forces one window immediately
  at run start — the "trace me now" override, no regression needed.
- **Bounded**: traces land in ``trace_dir/trace-NNNN``; at most
  ``keep`` are retained (oldest deleted first), so a flapping trigger
  cannot fill a disk.
- **Cooldown**: after a window closes, the trigger sleeps for
  ``cooldown`` steps so one sustained regression yields one trace, not
  a trace per step.
- Every transition publishes a ``trace`` event
  (``action``: started/stopped/error, ``path``, ``reason``/``ratio``).
- **on_capture**: a hook called with the capture path after each window
  closes cleanly — the device-time analyzer
  (``tpuic.telemetry.profile.CaptureAnalyzer``) hangs here, so a
  triggered trace is auto-analyzed into a ``profile`` event instead of
  writing a directory and standing down.  A hook failure publishes a
  ``trace`` event (``action: analyze_error``) and does NOT disable the
  trigger: capture still works when analysis breaks.

A failure to start/stop the profiler (e.g. the fit-level
``--profile-dir`` trace already active) is published as an error event
and disables the trigger — observability must never kill the run; a
capture failure therefore still stands down cleanly, analyzed or not.
"""

from __future__ import annotations

import os
import shutil
import statistics
import time
from collections import deque
from typing import Optional


class TraceTrigger:
    def __init__(self, trace_dir: str, threshold: float = 3.0,
                 window: int = 64, warmup: int = 5, trace_steps: int = 3,
                 keep: int = 4, cooldown: int = 16, bus=None,
                 force_first: bool = False, on_capture=None) -> None:
        if bus is None:
            from tpuic.telemetry.events import bus as _global_bus
            bus = _global_bus
        self.bus = bus
        self.on_capture = on_capture
        self.trace_dir = trace_dir
        self.threshold = float(threshold)
        self.warmup = max(2, int(warmup))
        self.trace_steps = max(1, int(trace_steps))
        self.keep = max(1, int(keep))
        self.cooldown = max(0, int(cooldown))
        self._totals: deque = deque(maxlen=max(8, int(window)))
        self._active_path: Optional[str] = None
        self._remaining = 0
        self._cooldown_left = 0
        self._counter = 0
        self._force = bool(force_first)
        self._disabled = False
        self.fired = 0

    # -- bus hook ------------------------------------------------------
    def on_event(self, ev) -> None:
        if ev.kind == "step":
            self.observe(float(ev.data.get("total_ms", 0.0)) / 1000.0)

    def observe(self, total_s: float) -> None:
        """One step's total wall time; called from the loop thread (the
        profiler start/stop must stay on one thread)."""
        if self._disabled:
            return
        if self._active_path is not None:
            self._remaining -= 1
            if self._remaining <= 0:
                self._stop()
            return
        if self._cooldown_left > 0:
            self._cooldown_left -= 1
            self._totals.append(total_s)
            return
        if self._force:
            self._force = False
            self._totals.append(total_s)
            self._start(reason="TPUIC_TRACE", ratio=None)
            return
        ratio = None
        if (self.threshold > 0 and len(self._totals) >= self.warmup):
            med = statistics.median(self._totals)
            if med > 0 and total_s > self.threshold * med:
                ratio = total_s / med
        self._totals.append(total_s)
        if ratio is not None:
            self._start(reason="slow_step", ratio=round(ratio, 2))

    def finish(self) -> None:
        """Close any open window (end of fit — a trace must never leak
        past the run that started it)."""
        if self._active_path is not None:
            self._stop()

    # -- internals -----------------------------------------------------
    def _prune(self) -> None:
        # Oldest-first by mtime, NOT by name: the dir name starts with a
        # per-run counter, so across process restarts a fresh run's
        # trace-0000 sorts before the previous run's trace-0003 and a
        # name sort would delete the evidence just captured while
        # keeping the stale traces.
        try:
            names = [d for d in os.listdir(self.trace_dir)
                     if d.startswith("trace-")]
        except OSError:
            return

        def age(d: str):
            try:
                return os.path.getmtime(os.path.join(self.trace_dir, d))
            except OSError:
                return 0.0
        names.sort(key=lambda d: (age(d), d))
        for d in names[:max(0, len(names) - (self.keep - 1))]:
            shutil.rmtree(os.path.join(self.trace_dir, d),
                          ignore_errors=True)

    def _start(self, reason: str, ratio) -> None:
        os.makedirs(self.trace_dir, exist_ok=True)
        self._prune()
        path = os.path.join(self.trace_dir,
                            f"trace-{self._counter:04d}-{int(time.time())}")
        self._counter += 1
        try:
            import jax
            jax.profiler.start_trace(path)
        except Exception as e:
            # Another trace active (fit --profile-dir) or a backend
            # without profiler support: report and stand down.
            self._disabled = True
            self.bus.publish("trace", action="error", path=path,
                             reason=str(e)[:200])
            return
        self._active_path = path
        self._remaining = self.trace_steps
        self.fired += 1
        self.bus.publish("trace", action="started", path=path,
                         reason=reason, ratio=ratio,
                         steps=self.trace_steps)

    def _stop(self) -> None:
        path, self._active_path = self._active_path, None
        self._cooldown_left = self.cooldown
        try:
            import jax
            jax.profiler.stop_trace()
        except Exception as e:
            self._disabled = True
            self.bus.publish("trace", action="error", path=path,
                             reason=str(e)[:200])
            return
        self.bus.publish("trace", action="stopped", path=path)
        if self.on_capture is not None:
            # Auto-analysis of the capture (telemetry/profile.py). An
            # analyzer failure is reported, NOT escalated: the trigger
            # keeps capturing — raw traces beat no traces.
            try:
                self.on_capture(path)
            except Exception as e:
                self.bus.publish("trace", action="analyze_error",
                                 path=path, reason=str(e)[:200])
