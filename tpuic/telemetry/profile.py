"""Device-time attribution: per-op-class waterfall + roofline verdicts.

The telemetry layer attributes every *host*-side millisecond (goodput
buckets, step breakdown, fleet skew) — but ``device_ms``, the dominant
bucket at MFU 0.31 (BENCH_r05), stayed an opaque residual.  The
"MFU 0.31 → 0.5+" roadmap item cannot be earned without knowing which
ops are compute-bound vs HBM-bound; the 15-minute-ImageNet line
(arXiv 1711.04325) and every TPU scaling paper start from exactly this
per-op accounting.  This module is that accounting:

- **Trace analyzer** (:func:`parse_trace`): parses captured
  ``jax.profiler`` artifacts (the Chrome-trace ``*.trace.json[.gz]``
  every capture writes) into per-op-class device time — matmul/conv vs
  elementwise vs reduce vs copy/transpose vs collective — with a
  per-layer rollup from the ``jax.named_scope``/flax module paths in
  each op's metadata.  Device-side per-op events exist on TPU/GPU
  captures; a CPU capture carries none, and the analyzer says so
  (returns None) instead of fabricating a waterfall.
- **HLO cost model** (:func:`hlo_waterfall`): where the runtime exposes
  it, the already-AOT-lowered executables (train/step.py warmup,
  serve/engine.py buckets) yield ``compiled.as_text()`` +
  ``compiled.cost_analysis()``; the model classifies every entry-
  computation instruction, charges it HBM bytes from its operand/output
  shapes (a fusion's *boundary* bytes — interior traffic never reaches
  HBM, which is the point of fusing) and FLOPs apportioned from the
  compiler's total, and models its time as
  ``max(flops/peak, bytes/bandwidth)`` — the roofline.  Works on every
  backend, CPU CI included.
- **Attribution** (:func:`attribute_device_time`): the modeled class
  times are mapped onto the *measured* telemetry device bucket — the
  best (minimum) observed step is the program-time anchor, the
  mean-over-best excess books to the ``overhead`` class as ``stall_ms``
  (host time the step breakdown charges to the device residual: drains,
  injected sleeps, contention).  By construction the per-class times sum
  to the measured mean device bucket — the same "buckets sum to wall"
  invariant the goodput ledger carries, one level down.
- **Verdicts**: every class carries a roofline verdict
  (compute-bound / hbm-bound / overhead) from the shared
  ``goodput.roofline_intensity`` formula against the PEAK_FLOPS +
  HBM_GBPS tables.

Wiring (docs/observability.md, "Device-time attribution"):
``CaptureAnalyzer`` subscribes to ``step`` events, runs on every
triggered-trace capture (``TraceTrigger(on_capture=...)``) and once at
fit() end, and publishes a ``profile`` event (JSONL / TensorBoard /
``device_time_ms{op_class}`` prom rows on both expositions).  The
committed ``perf/roofline_baseline.json`` extends the PR-6 regression
gate: a silent shift of device time into copy/overhead fails CI the
same way a latency regression does::

    python -m tpuic.telemetry.profile --trace traces/trace-0000-...
    python -m tpuic.telemetry.profile --step-waterfall --model resnet50
    python -m tpuic.telemetry.profile --check          # CI roofline gate
    python -m tpuic.telemetry.profile --check --inject slow_step \
        --expect-fail                                  # prove it fires
    python -m tpuic.telemetry.profile --write-baseline

Analysis is strictly off the hot path: the analyzer runs in the capture
/ finalize hooks, never per step, and a failure publishes an error
field instead of killing the run (the tracing.py discipline).
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import re
import statistics
import sys
from collections import deque
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from tpuic.telemetry.goodput import (check_flops_drift, hbm_bandwidth,
                                     peak_flops, ridge_intensity,
                                     roofline_intensity, roofline_verdict)

# The op-class vocabulary.  'overhead' additionally absorbs the measured
# stall (mean-over-best device time) during attribution.
OP_CLASSES = ("matmul", "elementwise", "reduce", "copy", "collective",
              "overhead")

_MATMUL_OPS = frozenset({
    "dot", "convolution", "custom-call", "cholesky", "triangular-solve",
    "fft"})
_REDUCE_OPS = frozenset({
    "reduce", "reduce-window", "select-and-scatter", "sort", "topk",
    "reduce-precision"})
_COPY_OPS = frozenset({
    "copy", "copy-start", "copy-done", "transpose", "reshape", "bitcast",
    "concatenate", "slice", "dynamic-slice", "dynamic-update-slice",
    "gather", "scatter", "pad", "reverse", "broadcast"})
_COLLECTIVE_OPS = frozenset({
    "all-reduce", "all-reduce-start", "all-reduce-done", "all-gather",
    "all-gather-start", "all-gather-done", "reduce-scatter", "all-to-all",
    "collective-permute", "collective-permute-start",
    "collective-permute-done", "collective-broadcast", "send", "recv",
    "send-done", "recv-done"})
_OVERHEAD_OPS = frozenset({
    "parameter", "constant", "tuple", "get-tuple-element", "after-all",
    "add-dependency", "opt-barrier", "partition-id", "replica-id",
    "infeed", "outfeed", "call", "conditional", "while", "domain"})

_DTYPE_BYTES = {
    "pred": 1, "s8": 1, "u8": 1, "s16": 2, "u16": 2, "s32": 4, "u32": 4,
    "s64": 8, "u64": 8, "f16": 2, "bf16": 2, "f32": 4, "f64": 8,
    "c64": 8, "c128": 16, "f8e4m3fn": 1, "f8e5m2": 1, "s4": 1, "u4": 1,
}


def classify_op(opcode: str, category: Optional[str] = None) -> str:
    """HLO opcode (``fusion.3`` → ``fusion``) or profiler ``hlo_category``
    hint → op class.  The category hint (TPU traces label fusions e.g.
    'convolution fusion' / 'loop fusion') wins when present, because a
    trace event's bare name carries no called-computation to look into."""
    if category:
        c = category.lower()
        if any(k in c for k in ("conv", "dot", "gemm", "matmul", "einsum")):
            return "matmul"
        if "reduc" in c or "scan" in c or "sort" in c:
            return "reduce"
        if any(k in c for k in ("copy", "transpose", "reshape", "memcpy",
                                "data formatting")):
            return "copy"
        if any(k in c for k in ("all-", "all_", "collective", "permute",
                                "send", "recv")):
            return "collective"
        if "fusion" in c or "elementwise" in c or "loop" in c:
            return "elementwise"
    base = opcode.lstrip("%").split(".")[0].strip().lower()
    if base in _MATMUL_OPS:
        return "matmul"
    if base in _REDUCE_OPS:
        return "reduce"
    if base in _COPY_OPS:
        return "copy"
    if base in _COLLECTIVE_OPS:
        return "collective"
    if base in _OVERHEAD_OPS:
        return "overhead"
    return "elementwise"


def classify_fusion(called_opcodes: Sequence[str]) -> str:
    """A fusion is classified by the strongest op it contains: any
    dot/conv makes it matmul-class, else any reduce makes it
    reduce-class, else it is the elementwise/copy loop it lowered from
    (majority of movement ops → copy)."""
    bases = [o.lstrip("%").split(".")[0].lower() for o in called_opcodes]
    if any(b in _MATMUL_OPS for b in bases):
        return "matmul"
    if any(b in _REDUCE_OPS for b in bases):
        return "reduce"
    real = [b for b in bases if b not in _OVERHEAD_OPS]
    if real and sum(b in _COPY_OPS for b in real) > len(real) / 2:
        return "copy"
    return "elementwise"


# -- scope / layer attribution ------------------------------------------------
# Two wrapper families in jax scope paths: staging wrappers whose
# payload is a FUNCTION name (``jit(train_step)``, ``jit(main)``) —
# dropped whole, the payload is not a layer — and autodiff/remat
# wrappers whose payload is the scope the op belongs to
# (``transpose(jvp(Classifier))``) — unwrapped, so forward and backward
# ops of the same layer land in the same bucket (the backward's extra
# time is part of that layer's cost).
_DROP_WRAPPERS = re.compile(r"^(jit|pjit|xla_call|vmap|pmap|shard_map|"
                            r"while|body|cond)\b")
_UNWRAP_WRAPPERS = re.compile(r"^(transpose|jvp|vjp|remat|checkpoint|"
                              r"rematted_computation|custom_jvp|"
                              r"custom_vjp|named)\b")


def scope_segments(op_name: str) -> List[str]:
    """Meaningful scope segments of an HLO metadata ``op_name`` (or a
    trace event's long name); see the wrapper-family note above."""
    out: List[str] = []
    for seg in str(op_name).split("/"):
        seg = seg.strip()
        if not seg:
            continue
        while True:
            m = re.match(r"^([\w\-.]+)\((.*)\)$", seg)
            if m is None:
                break
            if _DROP_WRAPPERS.match(m.group(1)):
                seg = ""
                break
            if _UNWRAP_WRAPPERS.match(m.group(1)):
                seg = m.group(2)
            else:
                break
        if not seg or _DROP_WRAPPERS.match(seg) \
                or _UNWRAP_WRAPPERS.match(seg):
            continue
        out.append(seg)
    return out


def layer_of(op_name: str, depth: int = 3) -> str:
    """Rollup key of an op's scope path: the first ``depth`` meaningful
    segments minus the trailing primitive name — e.g.
    ``jit(train_step)/Classifier/backbone/layer2_0/conv2/conv`` →
    ``Classifier/backbone/layer2_0`` at depth 3.  Unattributed ops roll
    up under ``(unattributed)``."""
    segs = scope_segments(op_name)
    if len(segs) > 1:
        segs = segs[:-1]  # drop the primitive leaf
    segs = segs[:max(1, depth)]
    return "/".join(segs) if segs else "(unattributed)"


# -- chrome-trace parsing (real captures) -------------------------------------
def _trace_files(path: str) -> List[str]:
    """Trace JSON files of a capture: accepts the session dir a
    TraceTrigger wrote (``trace-NNNN-<ts>/``), the ``plugins`` parent, or
    a direct ``*.trace.json[.gz]`` file."""
    if os.path.isfile(path):
        return [path]
    pats = (os.path.join(path, "plugins", "profile", "*", "*.trace.json*"),
            os.path.join(path, "*", "*.trace.json*"),
            os.path.join(path, "*.trace.json*"))
    for pat in pats:
        hits = sorted(glob.glob(pat))
        if hits:
            return hits
    return []


def _load_trace_events(path: str) -> List[dict]:
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt") as f:
        data = json.load(f)
    if isinstance(data, dict):
        return list(data.get("traceEvents") or ())
    return list(data) if isinstance(data, list) else []


def parse_trace(path: str, layer_depth: int = 3) -> Optional[dict]:
    """Per-op-class device time from a jax.profiler capture.

    Selects processes whose ``process_name`` names a device (contains
    ``/device:`` — the TPU/GPU op-timeline convention; the ``/host:CPU``
    python/runtime timelines are never device time) and sums complete
    ('X') event durations per op class and per layer.  Returns None when
    the capture carries **no device op events at all** — a CPU capture —
    so callers fall back to the HLO cost model instead of reading an
    empty waterfall as "zero device time"."""
    files = _trace_files(path)
    if not files:
        return None
    classes: Dict[str, float] = {}
    layers: Dict[str, float] = {}
    n_ops = 0
    for f in files:
        try:
            events = _load_trace_events(f)
        except (OSError, ValueError):
            continue
        device_pids = set()
        for e in events:
            if (e.get("ph") == "M" and e.get("name") == "process_name"
                    and "/device:" in str(
                        (e.get("args") or {}).get("name", ""))):
                device_pids.add(e.get("pid"))
        if not device_pids:
            continue
        for e in events:
            if e.get("ph") != "X" or e.get("pid") not in device_pids:
                continue
            dur_us = float(e.get("dur", 0.0))
            if dur_us <= 0:
                continue
            args = e.get("args") or {}
            cls = classify_op(str(e.get("name", "")),
                              category=args.get("hlo_category"))
            classes[cls] = classes.get(cls, 0.0) + dur_us / 1000.0
            n_ops += 1
            scope = next((str(v) for k in ("long_name", "tf_op", "op_name",
                                           "name")
                          if "/" in str(args.get(k, ""))
                          for v in (args[k],)), None)
            if scope:
                key = layer_of(scope, depth=layer_depth)
                layers[key] = layers.get(key, 0.0) + dur_us / 1000.0
    if not classes:
        return None
    total = sum(classes.values())
    return {"source": "trace", "device_ms_total": round(total, 3),
            "ops": n_ops,
            "classes": {k: round(v, 3) for k, v in sorted(classes.items())},
            "layers": {k: round(v, 3) for k, v in sorted(
                layers.items(), key=lambda kv: -kv[1])}}


# -- HLO text cost model ------------------------------------------------------
_SHAPE_RE = re.compile(r"([a-z][a-z0-9]*)\[([0-9,]*)\]")
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?[\w.\-]+\s*=\s*(\([^=]*?\)|[\w\[\]{},]+)\s+"
    r"([\w\-]+)\(")
_CALLS_RE = re.compile(r"calls=%?([\w.\-]+)")
_OPNAME_RE = re.compile(r'op_name="([^"]+)"')


def _shape_stats(text: str) -> Tuple[float, float]:
    """(bytes, elems) summed over every shape literal in ``text``."""
    total_b = total_e = 0.0
    for dtype, dims in _SHAPE_RE.findall(text):
        if dtype not in _DTYPE_BYTES:
            continue
        elems = 1.0
        for d in dims.split(","):
            if d:
                elems *= int(d)
        total_e += elems
        total_b += elems * _DTYPE_BYTES[dtype]
    return total_b, total_e


def _parse_hlo(hlo_text: str):
    """(entry_instructions, computations): each instruction is a dict
    ``{op, out_bytes, out_elems, opnd_bytes, opnd_elems, op_name,
    calls}``; ``computations`` maps computation name → list of opcodes
    (for fusion classification)."""
    comps: Dict[str, List[str]] = {}
    entry: List[dict] = []
    cur: Optional[List[str]] = None
    cur_entry = False
    for line in hlo_text.splitlines():
        stripped = line.strip()
        if stripped.endswith("{") and ("->" in stripped
                                       or stripped.startswith("ENTRY")):
            name = stripped.split()[1] if stripped.startswith("ENTRY") \
                else stripped.split()[0]
            cur = comps.setdefault(name.lstrip("%").split("(")[0], [])
            cur_entry = stripped.startswith("ENTRY")
            continue
        if stripped == "}":
            cur, cur_entry = None, False
            continue
        m = _INSTR_RE.match(line)
        if m is None or cur is None:
            continue
        out_type, opcode = m.group(1), m.group(2)
        cur.append(opcode)
        if not cur_entry:
            continue
        rest = line[m.end():]
        out_b, out_e = _shape_stats(out_type)
        opnd_b, opnd_e = _shape_stats(rest.split(", metadata=")[0]
                                      .split(", calls=")[0])
        nm = _OPNAME_RE.search(line)
        calls = _CALLS_RE.search(line)
        entry.append({"op": opcode, "out_bytes": out_b, "out_elems": out_e,
                      "opnd_bytes": opnd_b, "opnd_elems": opnd_e,
                      "op_name": nm.group(1) if nm else "",
                      "calls": calls.group(1) if calls else None})
    return entry, comps


def hlo_waterfall(hlo_text: str, *, total_flops: Optional[float] = None,
                  peak: float = 1e12, hbm_bytes_per_s: float = 50e9,
                  layer_depth: int = 3) -> dict:
    """Analytic per-op-class waterfall of one compiled program.

    Every ENTRY-computation instruction is classified (fusions by their
    called computation's contents), charged its **boundary** HBM bytes
    (operand + output shapes — a fusion's interior traffic never reaches
    HBM, which is exactly the benefit of fusing), and given a FLOPs
    share: elementwise ops ~1 flop/output element, reduces ~1
    flop/input element, and the matmul class takes the remainder of the
    compiler's ``cost_analysis()['flops']`` total apportioned by output
    size — matmul/conv is where the flops live, by definition.  Modeled
    time per instruction is the roofline ``max(flops/peak, bytes/bw)``;
    classes and layers are rollups of the same per-instruction model, so
    the two views always agree."""
    entry, comps = _parse_hlo(hlo_text)
    # First pass: classify + cheap flop estimates.
    ew_flops = red_flops = mm_out = 0.0
    for ins in entry:
        cls = (classify_fusion(comps.get(ins["calls"], ()))
               if ins["op"] == "fusion" else classify_op(ins["op"]))
        ins["class"] = cls
        if cls in ("overhead",):
            # Parameters/tuples move no HBM bytes at runtime.
            ins["opnd_bytes"] = ins["out_bytes"] = 0.0
        if cls == "elementwise":
            ins["flops"] = ins["out_elems"]
            ew_flops += ins["flops"]
        elif cls == "reduce":
            ins["flops"] = ins["opnd_elems"]
            red_flops += ins["flops"]
        else:
            ins["flops"] = 0.0
            if ins["class"] == "matmul":
                mm_out += ins["out_elems"]
    mm_flops = max(0.0, float(total_flops or 0.0) - ew_flops - red_flops)
    for ins in entry:
        if ins["class"] == "matmul" and mm_out > 0:
            ins["flops"] = mm_flops * ins["out_elems"] / mm_out
        ins["bytes"] = ins["opnd_bytes"] + ins["out_bytes"]
        ins["ms"] = 1000.0 * max(ins["flops"] / max(peak, 1.0),
                                 ins["bytes"] / max(hbm_bytes_per_s, 1.0))
    classes: Dict[str, dict] = {}
    layers: Dict[str, float] = {}
    for ins in entry:
        c = classes.setdefault(ins["class"], {"ms": 0.0, "flops": 0.0,
                                              "bytes": 0.0, "ops": 0})
        c["ms"] += ins["ms"]
        c["flops"] += ins["flops"]
        c["bytes"] += ins["bytes"]
        c["ops"] += 1
        # Layer rollup over ops that cost something: parameters/tuples
        # carry argument-path metadata, not layer scopes.
        if ins["op_name"] and ins["ms"] > 0 and ins["class"] != "overhead":
            key = layer_of(ins["op_name"], depth=layer_depth)
            layers[key] = layers.get(key, 0.0) + ins["ms"]
    total_ms = sum(c["ms"] for c in classes.values())
    for name, c in classes.items():
        c["ms"] = round(c["ms"], 4)
        c["frac"] = round(c["ms"] / total_ms, 4) if total_ms > 0 else 0.0
        inten = roofline_intensity(c["flops"], c["bytes"])
        c["intensity"] = round(inten, 3) if inten is not None else None
        c["verdict"] = ("overhead" if name == "overhead" else
                        roofline_verdict(c["flops"], c["bytes"], peak,
                                         hbm_bytes_per_s))
    return {"source": "hlo_cost_model",
            "modeled_ms_total": round(total_ms, 4),
            "peak_flops": peak, "hbm_bytes_per_s": hbm_bytes_per_s,
            "ridge_intensity": round(ridge_intensity(peak, hbm_bytes_per_s),
                                     3),
            "total_flops": float(total_flops or 0.0),
            "classes": classes,
            # Top layers only: the event must stay a bounded record, not
            # a whole-program dump (the full HLO is one --step-waterfall
            # away).
            "layers": {k: round(v, 4) for k, v in sorted(
                layers.items(), key=lambda kv: -kv[1])[:48]}}


def attribute_device_time(model_wf: dict,
                          device_ms_steps: Sequence[float]) -> dict:
    """Map a modeled waterfall onto the measured telemetry device bucket.

    The best (minimum) observed step is the closest observable to pure
    program time (the noise-robust statistic every calibration here
    uses); modeled class times are scaled onto it, and the mean-over-
    best excess — host stalls the step breakdown books to the device
    residual — lands in the ``overhead`` class as ``stall_ms``.  The
    per-class times therefore **sum to the measured mean device bucket
    by construction** (the acceptance invariant the CI profile smoke
    asserts), and a fault that stalls *some* steps shifts the class
    distribution toward overhead — which is what the roofline gate
    fires on."""
    steps = [float(s) for s in device_ms_steps if s > 0]
    if not steps:
        return dict(model_wf)
    best = min(steps)
    mean = statistics.fmean(steps)
    stall = max(0.0, mean - best)
    modeled_total = sum(c["ms"] for c in model_wf["classes"].values())
    scale = best / modeled_total if modeled_total > 0 else 0.0
    out = {k: v for k, v in model_wf.items() if k not in ("classes",
                                                          "layers")}
    out["source"] = model_wf.get("source", "hlo_cost_model") + "+measured"
    out["steps"] = len(steps)
    out["device_ms_best"] = round(best, 3)
    out["device_ms_per_step"] = round(mean, 3)
    out["stall_ms"] = round(stall, 3)
    out["model_scale"] = round(scale, 4)
    classes = {}
    for name, c in model_wf["classes"].items():
        classes[name] = dict(c)
        classes[name]["ms"] = round(c["ms"] * scale, 4)
    oh = classes.setdefault("overhead", {"ms": 0.0, "flops": 0.0,
                                         "bytes": 0.0, "ops": 0,
                                         "verdict": "overhead",
                                         "intensity": None})
    oh["ms"] = round(oh["ms"] + stall, 4)
    total = sum(c["ms"] for c in classes.values())
    for c in classes.values():
        c["frac"] = round(c["ms"] / total, 4) if total > 0 else 0.0
    out["classes"] = classes
    out["layers"] = {k: round(v * scale, 4)
                     for k, v in model_wf.get("layers", {}).items()}
    return out


def waterfall_summary(wf: dict) -> str:
    """One log line: per-class ms + verdict initials."""
    parts = []
    for name in OP_CLASSES:
        c = wf.get("classes", {}).get(name)
        if c is None:
            continue
        v = {"compute-bound": "C", "hbm-bound": "M",
             "overhead": "-"}.get(c.get("verdict"), "?")
        parts.append(f"{name} {c['ms']:.1f}ms[{v}]")
    head = wf.get("device_ms_per_step") or wf.get("device_ms_total") \
        or wf.get("modeled_ms_total")
    return f"device {head}ms/step: " + ", ".join(parts)


# -- the capture analyzer (bus wiring) ----------------------------------------
class CaptureAnalyzer:
    """Runs the analyzer on every triggered-trace capture and once at
    run end, publishing ``profile`` events.

    Subscribes to ``step`` events (host-side floats only — the zero-
    syncs/zero-compiles discipline is test-asserted on-vs-off);
    ``on_capture`` is handed to :class:`tpuic.telemetry.tracing.
    TraceTrigger`, ``finalize()`` runs from TrainTelemetry.flush().  The
    HLO provider (Trainer wires the real train step's AOT lowering) is
    called lazily ONCE and cached — compiling for analysis is off the
    hot path by construction, and on CPU it is a persistent-cache hit.
    Every failure publishes a ``profile`` event with an ``error`` field
    and stands down: observability must never kill the run."""

    def __init__(self, *, hlo_provider: Optional[Callable] = None,
                 peak: float = 1e12, hbm_bytes_per_s: float = 50e9,
                 bus=None, window: int = 1024, warmup_steps: int = 2,
                 model_name: str = "", image_size: int = 0,
                 global_batch: int = 0, n_devices: int = 1,
                 layer_depth: int = 3) -> None:
        if bus is None:
            from tpuic.telemetry.events import bus as _global_bus
            bus = _global_bus
        self.bus = bus
        self.hlo_provider = hlo_provider
        self.peak = float(peak)
        self.hbm = float(hbm_bytes_per_s)
        self.warmup_steps = int(warmup_steps)
        self.layer_depth = int(layer_depth)
        self.model_name = model_name
        self.image_size = int(image_size)
        self.global_batch = int(global_batch)
        self.n_devices = max(1, int(n_devices))
        self._device_ms: deque = deque(maxlen=max(16, int(window)))
        self._model_wf: Optional[dict] = None
        self._model_err: Optional[str] = None
        self._drift: Optional[float] = None
        self._tracing = False      # a profiler window is open
        self._taint_next = 0       # steps to skip after a window closes
        self._finalized = False
        self.tainted_steps = 0
        self.last: Optional[dict] = None
        self.analyses = 0

    # -- bus hooks -----------------------------------------------------
    def on_event(self, ev) -> None:
        if ev.kind == "step":
            if self._tracing or self._taint_next > 0:
                # Observer effect: steps measured while a profiler
                # window is open (and the step whose span absorbed the
                # stop/serialize) are not representative of steady-state
                # device time — on CPU the python tracer alone is a
                # 10-100x slowdown.  Excluded, and counted so the
                # exclusion is visible in the published event.
                self._taint_next = max(0, self._taint_next - 1)
                self.tainted_steps += 1
                return
            self._device_ms.append(float(ev.data.get("device_ms", 0.0)))
        elif ev.kind == "trace":
            action = ev.data.get("action")
            if action == "started":
                self._tracing = True
            elif action in ("stopped", "error"):
                if self._tracing:
                    self._taint_next = 1
                self._tracing = False

    def on_capture(self, trace_path: str) -> None:
        self._analyze(trace_path=trace_path, final=False)

    def finalize(self) -> None:
        """The run-end analysis over the full step window (published
        with ``final: true`` — the record the roofline gate reads).
        Idempotent: the Trainer finalizes BEFORE its final goodput
        event (so the last --prom-dump refresh carries the waterfall)
        and flush() calls it again as the backstop for other callers —
        only the first call publishes."""
        if self._finalized:
            return
        self._finalized = True
        self._analyze(trace_path=None, final=True)

    # -- internals -----------------------------------------------------
    def _model(self) -> Optional[dict]:
        if self._model_wf is not None or self._model_err is not None:
            return self._model_wf
        if self.hlo_provider is None:
            self._model_err = "no HLO provider wired"
            return None
        try:
            hlo_text, cost = self.hlo_provider()
            flops = float(cost.get("flops", 0.0)) if cost else 0.0
            self._model_wf = hlo_waterfall(
                hlo_text, total_flops=flops, peak=self.peak,
                hbm_bytes_per_s=self.hbm, layer_depth=self.layer_depth)
            if self.model_name and flops > 0:
                # Ride-along cross-check: the analytic MFU table vs the
                # compiler's count — loud warning on >10% drift.  Under
                # SPMD the compiled program (and its cost analysis) is
                # PER-DEVICE, so the analytic side is scaled to the
                # per-device batch slice — comparing global analytic
                # FLOPs against one shard read as a false n_devices-x
                # drift (caught on the 8-device CPU mesh).
                self._drift = check_flops_drift(
                    self.model_name, self.image_size,
                    max(1, self.global_batch // self.n_devices), flops)
        except Exception as e:  # analysis must never kill the run
            self._model_err = str(e)[:200]
        return self._model_wf

    def _steps_window(self) -> List[float]:
        steps = [s for s in self._device_ms if s > 0]
        if len(steps) > self.warmup_steps + 2:
            steps = steps[self.warmup_steps:]
        return steps

    def _analyze(self, trace_path: Optional[str], final: bool) -> None:
        try:
            wf = None
            trace_wf = (parse_trace(trace_path, layer_depth=self.layer_depth)
                        if trace_path else None)
            model = self._model()
            if trace_wf is not None:
                # Real per-op device timings: the measured waterfall,
                # enriched with the model's verdicts where classes match.
                wf = {**trace_wf, "final": final}
                wf["classes"] = {
                    k: {"ms": v,
                        "frac": round(v / trace_wf["device_ms_total"], 4)
                        if trace_wf["device_ms_total"] else 0.0,
                        **({f: model["classes"][k][f]
                            for f in ("verdict", "intensity", "flops",
                                      "bytes")}
                           if model and k in model.get("classes", {}) else
                           {"verdict": "overhead" if k == "overhead"
                            else "unmodeled", "intensity": None})}
                    for k, v in trace_wf["classes"].items()}
            elif model is not None:
                steps = self._steps_window()
                wf = attribute_device_time(model, steps) if steps \
                    else dict(model)
                wf["final"] = final
            if wf is None:
                self.bus.publish("profile", final=final,
                                 trace_path=trace_path,
                                 error=self._model_err
                                 or "no device ops in trace and no model")
                return
            if trace_path:
                wf["trace_path"] = trace_path
            if self._drift is not None:
                wf["analytic_flops_drift"] = round(self._drift, 4)
            if self.tainted_steps:
                wf["tainted_steps_excluded"] = self.tainted_steps
            self.last = wf
            self.analyses += 1
            self.bus.publish("profile", **wf)
        except Exception as e:
            self.bus.publish("profile", final=final, trace_path=trace_path,
                             error=str(e)[:200])


# -- roofline regression gate -------------------------------------------------
# Gate specs in telemetry/regress.py's vocabulary (direction, kind,
# floor): class fractions are machine-independent ratios; the absolute
# per-step device bucket is calibration-scaled time.  frac_overhead's
# floor is wide — on a quiet run it is min-vs-mean jitter — but the
# seeded stall shifts it several-fold past any band.
PROFILE_SPECS = {
    "profile.frac_matmul":        ("higher", "ratio", 0.30),
    "profile.frac_copy":          ("lower", "ratio", 0.60),
    "profile.frac_overhead":      ("lower", "ratio", 1.00),
    "profile.device_ms_per_step": ("lower", "time", 0.90),
}

_REPO = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
DEFAULT_BASELINE = os.path.join(_REPO, "perf", "roofline_baseline.json")
WORKLOAD_STEPS = 12
# Stall mid-run loop steps 4-8 only: a PARTIAL stall, so the tail steps
# stay fast and anchor the best-step program time — the injected time
# then lands in the overhead class, shifting the op-class distribution
# (what the roofline gate exists to catch; a uniform slowdown is the
# PR-6 regression gate's slow_step case instead).  Steps 0-3 are inside
# the forced trace window and excluded as tainted anyway.
_INJECT_FAULTS = {"slow_step": "slow_step@4-8#0.4"}


def metrics_from_event(ev: dict) -> Dict[str, float]:
    """Gate metrics distilled from one final ``profile`` event."""
    out: Dict[str, float] = {}
    classes = ev.get("classes") or {}
    for name in ("matmul", "copy", "overhead"):
        c = classes.get(name)
        if c is not None and c.get("frac") is not None:
            out[f"profile.frac_{name}"] = float(c["frac"])
    out.setdefault("profile.frac_overhead", 0.0)
    out.setdefault("profile.frac_copy", 0.0)
    if ev.get("device_ms_per_step") is not None:
        out["profile.device_ms_per_step"] = float(ev["device_ms_per_step"])
    return out


def profile_workload(steps: int = WORKLOAD_STEPS, *, faults: str = "",
                     keep_dir: Optional[str] = None) -> Tuple[Dict[str,
                                                                   float],
                                                              dict]:
    """The pinned CPU roofline workload: a real ``train.py`` run with a
    forced trace window (``TPUIC_TRACE``) and ``--trace-analyze``, so the
    metrics come from the REAL wiring end to end — trigger → capture →
    on_capture → ``profile`` events in the metrics JSONL.  Returns
    (gate metrics, the final waterfall event)."""
    import shutil
    import subprocess
    import tempfile

    from tpuic.data.synthetic import make_synthetic_imagefolder
    from tpuic.telemetry.events import read_jsonl
    work = keep_dir or tempfile.mkdtemp(prefix="tpuic_roofline_")
    try:
        data = os.path.join(work, "data")
        if not os.path.isdir(data):
            make_synthetic_imagefolder(data, classes=("a", "b", "c"),
                                       per_class=8, size=32)
        jsonl = os.path.join(work, "events.jsonl")
        if os.path.exists(jsonl):
            os.unlink(jsonl)
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   TF_CPP_MIN_LOG_LEVEL="3",
                   TPUIC_TRACE=os.path.join(work, "traces"))
        if faults:
            env["TPUIC_FAULTS"] = faults
        else:
            env.pop("TPUIC_FAULTS", None)
        cmd = [sys.executable, os.path.join(_REPO, "train.py"),
               "--datadir", data, "--model", "resnet18-cifar",
               "--resize", "32", "--batchsize", "2",
               "--epochs", str(steps // 12 + 1),
               "--optimizer", "adam", "--lr", "1e-3",
               "--no-class-weights", "--log-every-steps", "1",
               "--ckpt-dir", os.path.join(work, "cp"),
               "--steps", str(steps), "--metrics-jsonl", jsonl,
               "--trace-analyze"]
        proc = subprocess.run(cmd, cwd=_REPO, env=env, text=True,
                              capture_output=True, timeout=1200)
        if proc.returncode != 0:
            raise RuntimeError(
                f"roofline workload exited {proc.returncode}:\n"
                f"{proc.stdout[-1500:]}\n{proc.stderr[-1500:]}")
        recs = read_jsonl(jsonl)
        finals = [r for r in recs
                  if r["event"] == "profile" and r.get("final")
                  and not r.get("error")]
        if not finals:
            errs = [r for r in recs if r["event"] == "profile"]
            raise RuntimeError(
                "roofline workload produced no final profile event "
                f"(profile events seen: {errs[-2:]})")
        return metrics_from_event(finals[-1]), finals[-1]
    finally:
        if keep_dir is None:
            shutil.rmtree(work, ignore_errors=True)


def main(argv=None) -> int:
    import argparse

    p = argparse.ArgumentParser(
        prog="python -m tpuic.telemetry.profile", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    mode = p.add_mutually_exclusive_group(required=True)
    mode.add_argument("--trace", default="",
                      help="analyze a captured jax.profiler trace dir")
    mode.add_argument("--step-waterfall", action="store_true",
                      help="cost-model waterfall of the real AOT-lowered "
                           "train step on this backend")
    mode.add_argument("--check", action="store_true",
                      help="run the pinned roofline workload and compare "
                           "against the committed baseline; exit 2 on "
                           "regression")
    mode.add_argument("--write-baseline", action="store_true")
    p.add_argument("--baseline", default=DEFAULT_BASELINE)
    p.add_argument("--report", default="",
                   help="write the comparison / waterfall JSON here")
    p.add_argument("--trials", type=int, default=3)
    p.add_argument("--steps", type=int, default=WORKLOAD_STEPS)
    p.add_argument("--model", default="resnet18-cifar",
                   help="--step-waterfall only (the gate workload is "
                        "pinned)")
    p.add_argument("--image-size", type=int, default=32,
                   help="--step-waterfall only")
    p.add_argument("--batch", type=int, default=2,
                   help="--step-waterfall only")
    p.add_argument("--layer-depth", type=int, default=3)
    p.add_argument("--inject", default="",
                   help="seed 'slow_step' (a partial stall) — the "
                        "gate-can-fire proof")
    p.add_argument("--expect-fail", action="store_true",
                   help="with --check: exit 0 IFF the comparison "
                        "regressed")
    args = p.parse_args(argv)

    def _dump(obj) -> None:
        text = json.dumps(obj, indent=2, sort_keys=True)
        print(text)
        if args.report:
            with open(args.report, "w") as f:
                f.write(text + "\n")

    if args.trace:
        wf = parse_trace(args.trace, layer_depth=args.layer_depth)
        if wf is None:
            print(f"[profile] no device op events in {args.trace} "
                  "(CPU captures carry none; use --step-waterfall for "
                  "the cost-model view)", file=sys.stderr)
            return 1
        _dump(wf)
        return 0

    if args.step_waterfall:
        wf = train_step_waterfall(args.model, args.image_size, args.batch,
                                  layer_depth=args.layer_depth)
        print(f"[profile] {waterfall_summary(wf)}", file=sys.stderr)
        _dump(wf)
        return 0

    if (args.model, args.image_size, args.batch) != \
            ("resnet18-cifar", 32, 2):
        # Scope guard: the roofline gate runs a PINNED workload — the
        # committed baseline would silently gate the wrong model if
        # these flags were accepted and ignored.
        p.error("--model/--image-size/--batch apply to --step-waterfall "
                "only; the --check/--write-baseline workload is pinned "
                "(resnet18-cifar @32, batch 2)")

    # --check / --write-baseline share regress.py's noise machinery:
    # calibration scaling + the tolerance ladder (one gate discipline).
    from tpuic.telemetry import regress

    inject = tuple(s.strip() for s in args.inject.split(",") if s.strip())
    unknown = set(inject) - set(_INJECT_FAULTS)
    if unknown:
        p.error(f"--inject: unknown fault(s) {sorted(unknown)} "
                f"(supported: {sorted(_INJECT_FAULTS)})")
    faults = ",".join(_INJECT_FAULTS[i] for i in inject)

    if args.write_baseline:
        cal = regress.calibration_s()
        trials, last_wf = [], None
        for i in range(max(1, args.trials)):
            print(f"[profile] baseline trial {i + 1}/{args.trials} ...",
                  flush=True)
            metrics, last_wf = profile_workload(args.steps)
            trials.append(metrics)
        baseline = regress.make_baseline(
            trials, cal, {"train_steps": args.steps,
                          "model": "resnet18-cifar", "image_size": 32,
                          "global_batch": 2})
        baseline["waterfall"] = last_wf
        os.makedirs(os.path.dirname(args.baseline) or ".", exist_ok=True)
        with open(args.baseline, "w") as f:
            json.dump(baseline, f, indent=2, sort_keys=True)
            f.write("\n")
        print(f"[profile] roofline baseline ({len(baseline['metrics'])} "
              f"metrics, {args.trials} trials) -> {args.baseline}")
        print(f"[profile] {waterfall_summary(last_wf)}")
        return 0

    # --check
    try:
        with open(args.baseline) as f:
            baseline = json.load(f)
    except OSError as e:
        print(f"[profile] cannot read baseline {args.baseline}: {e}\n"
              f"[profile] run --write-baseline first", file=sys.stderr)
        return 3
    if faults:
        print(f"[profile] seeding fault(s): {faults}")
    cal = regress.calibration_s()
    fresh, wf = profile_workload(args.steps, faults=faults)
    report = regress.compare(baseline, fresh, cal, specs=PROFILE_SPECS)
    report["fresh_metrics"] = fresh
    report["waterfall"] = wf
    report["injected"] = list(inject)
    print(f"[profile] {waterfall_summary(wf)}")
    regress._print_report(report)
    if args.report:
        with open(args.report, "w") as f:
            json.dump(report, f, indent=2)
        print(f"[profile] comparison -> {args.report}")
    if args.expect_fail:
        if report["regressed"]:
            print("[profile] expected failure observed — the roofline "
                  "gate can fire (bidirectional proof OK)")
            return 0
        print("[profile] ERROR: seeded stall did NOT trip the roofline "
              "gate — the gate is decoration", file=sys.stderr)
        return 2
    return 2 if report["regressed"] else 0


def train_step_waterfall(model_name: str, image_size: int,
                         global_batch: int, *,
                         layer_depth: int = 3) -> dict:
    """Cost-model waterfall of the REAL train step, AOT-lowered on the
    current backend — the ``--step-waterfall`` CLI and the
    cost-analysis-extraction test both go through here."""
    import jax

    from tpuic.config import ModelConfig, OptimConfig
    from tpuic.telemetry.goodput import cost_analysis_dict
    from tpuic.train.optimizer import make_optimizer
    from tpuic.train.state import create_train_state
    from tpuic.train.step import make_train_step

    from tpuic.models import create_model
    mcfg = ModelConfig(name=model_name, num_classes=10, dtype="float32")
    ocfg = OptimConfig(optimizer="sgd", learning_rate=0.1,
                       class_weights=(), milestones=())
    model = create_model(mcfg.name, mcfg.num_classes, dtype=mcfg.dtype)
    state = create_train_state(
        model, make_optimizer(ocfg), jax.random.key(0),
        (global_batch, image_size, image_size, 3))
    sds = jax.ShapeDtypeStruct
    import numpy as np
    batch = {"image": sds((global_batch, image_size, image_size, 3),
                          np.float32),
             "label": sds((global_batch,), np.int32),
             "mask": sds((global_batch,), np.float32)}
    step = make_train_step(ocfg, mcfg, None, donate=False)
    compiled = step.lower(state, batch).compile()
    try:
        cost = cost_analysis_dict(compiled)
    except Exception:
        cost = {}
    dev = jax.devices()[0]
    wf = hlo_waterfall(compiled.as_text(),
                       total_flops=float(cost.get("flops", 0.0)),
                       peak=peak_flops(dev),
                       hbm_bytes_per_s=hbm_bandwidth(dev),
                       layer_depth=layer_depth)
    wf["model"] = model_name
    if cost.get("flops"):
        drift = check_flops_drift(model_name, image_size, global_batch,
                                  float(cost["flops"]))
        if drift is not None:
            wf["analytic_flops_drift"] = round(drift, 4)
    return wf


if __name__ == "__main__":
    sys.exit(main())
