"""Per-rank fleet view: rank-tagged event streams + straggler attribution.

Everything the telemetry layer measures (PRs 3/6) attributes one
process's milliseconds; a data-parallel fleet lives or dies on the
cross-rank question — *which rank* is slow, and how much fleet time its
lateness costs.  The 15-minute-ImageNet line of work (arXiv 1711.04325,
1511.00175) and every ZeRO-style scale-out (arXiv 2004.13336) treat
straggler attribution as table stakes.  This module is that half:

- **Rank tagging** (:func:`tag_bus_with_rank`): on a multi-process run
  (``runtime/distributed.py``), every event published on the bus gains
  ``rank``/``ranks`` fields — one dict merge at publish, nothing on
  single-process runs (the tag stays ``None`` and publish is
  unchanged).  Zero host syncs, zero compiles: the rank is two ints
  read once at wiring time.
- **Per-rank JSONL streams** (:func:`rank_stream_path`): rank 0 keeps
  the configured ``--metrics-jsonl`` path (single-process back-compat);
  rank k writes ``<stem>.rank<k>.jsonl`` next to it — on a shared
  filesystem the fleet's whole event history lands in one directory
  with no cross-process appends.
- **Offline aggregator** (``python -m tpuic.telemetry.fleet <dir>``):
  merges the streams (the shared tolerant ``events.read_jsonl``) and
  computes the skew ledger over the steps every rank reported:
  per-step cross-rank spread (max − min total_ms), the slowest-rank
  histogram, and each rank's **estimated collective wait** — its step
  time minus the fleet minimum for that step, summed.  In a
  synchronous data-parallel step every other rank's device waits for
  the slowest arrival, so a rank's excess over the fleet floor is the
  stall it *exports* to the fleet; the rank with the dominant share is
  the straggler verdict.

Measurement caveat (documented, not hidden): the per-step events are
HOST-side walls.  With the deferred drain at ``--log-every-steps 1``
every host blocks on cross-rank metrics each step, so host step times
equalize and the skew hides in each rank's ``device_ms`` residual.  At
the production logging cadence (the default 50), hosts run free between
drains and the per-step skew is visible — the fleet smoke
(scripts/fleet_smoke.py) runs that way and proves a seeded
``slow_step#`` rank is attributed.
"""

from __future__ import annotations

import argparse
import json
import os
import re
import sys
from typing import Dict, List, Optional, Sequence, Tuple

_RANK_FILE_RE = re.compile(r"\.rank(\d+)\.[^.]+$")


# -- rank tagging ------------------------------------------------------------
# Launcher override: a fleet whose rank bookkeeping lives OUTSIDE
# jax.distributed (independent per-rank workers, a launcher on a backend
# without multiprocess collectives — this container's CPU jax, which the
# CI fleet smoke runs on) declares itself via env.  The live runtime
# (runtime/distributed.py) stays the default source.
ENV_FLEET_RANK = "TPUIC_FLEET_RANK"
ENV_FLEET_RANKS = "TPUIC_FLEET_RANKS"


def tag_bus_with_rank(bus=None, rank: Optional[int] = None,
                      ranks: Optional[int] = None) -> Tuple[int, int]:
    """Tag ``bus`` (default: the process-global one) with this process's
    (rank, world size): explicit arguments win, then the
    ``TPUIC_FLEET_RANK``/``TPUIC_FLEET_RANKS`` launcher override, then
    ``runtime/distributed.py``'s live process_index/process_count.
    Returns the pair.  Single-process runs (``ranks == 1``) leave the
    tag unset — the common path stays untouched and single-process
    JSONL schemas don't grow fleet fields."""
    if bus is None:
        from tpuic.telemetry.events import bus as _bus
        bus = _bus
    if (rank is None) != (ranks is None):
        # Same rule as the env override below: half a fleet identity is
        # not an identity — silently rederiving both would drop the
        # caller's value and can collapse every worker to rank 0/1.
        raise ValueError(
            f"tag_bus_with_rank: pass both rank and ranks or neither "
            f"(got rank={rank!r}, ranks={ranks!r})")
    if rank is None:
        er = os.environ.get(ENV_FLEET_RANK)
        ew = os.environ.get(ENV_FLEET_RANKS)
        if (er is None) != (ew is None):
            # A half-set override would silently collapse every worker
            # to the runtime default (rank 0 of 1) — k processes then
            # append interleaved, untagged events into ONE stream,
            # exactly the corruption per-rank paths exist to prevent.
            raise ValueError(
                f"fleet launcher override is half-set: {ENV_FLEET_RANK}="
                f"{er!r}, {ENV_FLEET_RANKS}={ew!r} — set both or neither")
        if er is not None:
            rank, ranks = int(er), int(ew)
        else:
            from tpuic.runtime.distributed import runtime_info
            info = runtime_info()
            rank, ranks = info.process_index, info.process_count
    rank, ranks = int(rank), int(ranks)
    bus.rank_tag = ({"rank": rank, "ranks": ranks} if ranks > 1 else None)
    return rank, ranks


def rank_stream_path(path: str, rank: int) -> str:
    """Per-rank stream path: rank 0 keeps ``path`` (back-compat with
    every single-process consumer); rank k gets ``<stem>.rank<k><ext>``
    (``events.jsonl`` -> ``events.rank3.jsonl``)."""
    if int(rank) == 0:
        return path
    root, ext = os.path.splitext(path)
    return f"{root}.rank{int(rank)}{ext or '.jsonl'}"


# -- stream loading ----------------------------------------------------------
def _infer_rank(path: str) -> Optional[int]:
    m = _RANK_FILE_RE.search(os.path.basename(path))
    return int(m.group(1)) if m else None


def load_streams(paths: Sequence[str]) -> Dict[int, List[dict]]:
    """Read JSONL event streams (files, or directories expanded to their
    ``*.jsonl``) and group records by rank: the record's own ``rank``
    field wins (the tagged streams), else the ``.rank<k>.`` filename
    convention, else rank 0 — so pre-fleet single-process streams load
    as a one-rank fleet instead of failing."""
    from tpuic.telemetry.events import read_jsonl

    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(
                os.path.join(p, f) for f in os.listdir(p)
                if f.endswith(".jsonl")))
        else:
            files.append(p)
    streams: Dict[int, List[dict]] = {}
    for f in files:
        fallback = _infer_rank(f)
        for rec in read_jsonl(f):
            r = rec.get("rank", fallback)
            streams.setdefault(int(r) if r is not None else 0,
                               []).append(rec)
    return streams


# -- elastic membership timelines --------------------------------------------
def membership_timeline(ledger_path: str) -> dict:
    """Parse an elastic gang ledger (runtime/gang.py ``ledger.jsonl``)
    into the fleet's membership timeline: every ``membership`` record
    (init/degrade/rejoin/restart transitions with their active sets)
    plus the union of every rank that was EVER a member or spawned.

    The aggregator's coverage gate uses this for elastic runs: under
    ``--require-ranks N`` a degraded fleet looks like a dead rank's
    missing stream and a replacement looks like an unexpected one —
    both hard failures — but against the timeline a shrink/grow is
    LEGAL as long as the streams cover exactly the ranks the ledger
    says ever ran (a stream from a rank the ledger never admitted, or
    no stream from a rank it did, stays loud)."""
    from tpuic.telemetry.events import read_jsonl

    ever: set = set()
    transitions: List[dict] = []
    for rec in read_jsonl(ledger_path):
        ev = rec.get("event")
        if ev == "membership":
            active = [int(r) for r in rec.get("active", [])]
            ever.update(active)
            transitions.append({
                "version": rec.get("version"),
                "reason": rec.get("reason"),
                "active": active, "rank": rec.get("rank"),
                "resume_step": rec.get("resume_step"),
                "t": rec.get("t")})
        elif ev in ("spawn", "respawn") and rec.get("rank") is not None:
            ever.add(int(rec["rank"]))
    return {"ever_ranks": sorted(ever), "transitions": transitions}


# -- bulk-score ledger audit -------------------------------------------------
def score_audit(streams: Dict[int, List[dict]]) -> dict:
    """Audit a bulk-scoring job's ledger streams (tpuic/score/):
    **scored + quarantined == corpus**, per shard and in total, with
    every violation named — the offline proof the elastic scorer's
    exactly-once machinery actually held.

    Checks, all loud:

    - a ``score_plan`` record exists and every worker's plan agrees
      (n, shard count, corpus token) — mixed-job streams fail here;
    - every planned shard has EXACTLY one ``score_commit`` record
      fleet-wide: missing shards are dropped work, >1 records are the
      double-count a ``lease_skew``-style race would smuggle in;
    - each commit's ``scored + quarantined`` equals its shard's row
      count, and the totals sum to the corpus size;
    - commits for shards the plan never defined fail (wrong workdir).

    ``score_duplicate`` events (double work the commit layer deduped)
    and ``recovered`` commits (records appended by a survivor for a
    dead winner) are REPORTED but are not violations — they are the
    recovery machinery working as designed.
    """
    recs = [r for rs in streams.values() for r in rs]
    plans = [r for r in recs if r.get("event") == "score_plan"]
    errors: List[str] = []
    if not plans:
        return {"ok": False, "errors":
                ["no score_plan record in any stream — not a scoring "
                 "ledger (or the planner's stream is missing)"]}
    plan = plans[0]
    for p in plans[1:]:
        for key in ("n", "shards", "shard_size", "corpus_token"):
            if p.get(key) != plan.get(key):
                errors.append(
                    f"score_plan disagreement: {key}={p.get(key)!r} vs "
                    f"{plan.get(key)!r} — streams from different jobs")
                break
    n = int(plan.get("n") or 0)
    table = {i: (int(lo), int(hi)) for i, (lo, hi)
             in enumerate(plan.get("shard_table") or [])}
    nshards = int(plan.get("shards") or len(table))

    by_shard: Dict[int, List[dict]] = {}
    for r in recs:
        if r.get("event") == "score_commit" and r.get("shard") is not None:
            by_shard.setdefault(int(r["shard"]), []).append(r)
    dup_events = sum(1 for r in recs if r.get("event") == "score_duplicate")

    missing = sorted(s for s in range(nshards) if s not in by_shard)
    duplicated = {s: len(v) for s, v in sorted(by_shard.items())
                  if len(v) > 1}
    unknown = sorted(s for s in by_shard if s < 0 or s >= nshards)
    if missing:
        errors.append(f"{len(missing)} shard(s) have NO commit record "
                      f"(dropped work): {missing[:10]}"
                      + ("..." if len(missing) > 10 else ""))
    for s, k in duplicated.items():
        errors.append(f"shard {s} committed {k} times — duplicate "
                      "records would double-count the corpus")
    if unknown:
        errors.append(f"commit record(s) for shard(s) the plan never "
                      f"defined: {unknown} — wrong workdir or torn plan")

    total_scored = total_quar = recovered = 0
    bad_rows: List[str] = []
    for s, commits in sorted(by_shard.items()):
        if s in unknown:
            continue
        c = commits[0]  # duplicates already failed above; audit the first
        scored = int(c.get("scored") or 0)
        quar = int(c.get("quarantined") or 0)
        total_scored += scored
        total_quar += quar
        recovered += sum(1 for x in commits if x.get("recovered"))
        lo, hi = table.get(s, (c.get("lo"), c.get("hi")))
        if lo is not None and hi is not None \
                and scored + quar != int(hi) - int(lo):
            bad_rows.append(
                f"shard {s}: scored {scored} + quarantined {quar} != "
                f"{int(hi) - int(lo)} rows [{lo}, {hi})")
    errors.extend(bad_rows)
    if not missing and not unknown and total_scored + total_quar != n:
        errors.append(f"totals: scored {total_scored} + quarantined "
                      f"{total_quar} != corpus {n}")
    return {"ok": not errors, "errors": errors, "n": n,
            "shards": nshards, "shards_committed": len(by_shard),
            "shards_missing": len(missing),
            "shards_duplicated": len(duplicated),
            "rows_scored": total_scored, "rows_quarantined": total_quar,
            "recovered_records": recovered,
            "duplicate_score_events": dup_events,
            "dtype": plan.get("dtype")}


def score_summary_lines(report: dict) -> List[str]:
    """Human rendering of :func:`score_audit` (the CLI's stdout)."""
    if "n" not in report:
        return [f"[fleet] score ledger: FAIL — {e}"
                for e in report.get("errors", ["unauditable"])]
    lines = [
        f"[fleet] score ledger: {report['shards_committed']}/"
        f"{report['shards']} shard(s) committed, "
        f"{report['rows_scored']} scored + "
        f"{report['rows_quarantined']} quarantined vs corpus "
        f"{report['n']}" + (f" (dtype {report['dtype']})"
                            if report.get("dtype") else "")]
    if report.get("recovered_records"):
        lines.append(f"[fleet] score ledger: "
                     f"{report['recovered_records']} commit record(s) "
                     "recovered by a survivor (crash-window repair)")
    if report.get("duplicate_score_events"):
        lines.append(f"[fleet] score ledger: "
                     f"{report['duplicate_score_events']} double-scored "
                     "shard attempt(s) deduped at commit (lease races "
                     "cost throughput, not correctness)")
    for e in report.get("errors", []):
        lines.append(f"[fleet] score ledger FAIL: {e}")
    if report["ok"]:
        lines.append("[fleet] score ledger: exact — zero duplicates, "
                     "zero drops")
    return lines


# -- the skew ledger ---------------------------------------------------------
def aggregate(streams: Dict[int, List[dict]], warmup: int = 0) -> dict:
    """Merge per-rank event streams into the straggler-attribution
    report (module docstring).  ``warmup`` drops the first N common
    steps (compile/cache warmup is per-rank noise, not skew signal —
    the regress-gate convention).

    Only steps reported by EVERY rank enter the skew math: a partial
    step (one rank died mid-epoch) has no fleet-wide wall to compare.
    """
    from tpuic.metrics.meters import quantiles

    ranks = sorted(streams)
    per_step: Dict[int, Dict[int, dict]] = {}
    step_counts = {r: 0 for r in ranks}
    duplicates = {r: 0 for r in ranks}
    for rank, recs in streams.items():
        for rec in recs:
            if rec.get("event") != "step":
                continue
            try:
                step, total = int(rec["step"]), float(rec["total_ms"])
            except (KeyError, TypeError, ValueError):
                continue
            step_counts[rank] += 1
            if rank in per_step.get(step, ()):
                # A supervised restart replays steps into the same
                # appended stream; last occurrence wins (the value that
                # stuck), but the collapse is COUNTED and surfaced —
                # mixed-attempt walls soften the skew math's meaning.
                duplicates[rank] += 1
            per_step.setdefault(step, {})[rank] = {
                "total_ms": total,
                "data_ms": float(rec.get("data_ms", 0.0) or 0.0),
                "dispatch_ms": float(rec.get("dispatch_ms", 0.0) or 0.0),
                "device_ms": float(rec.get("device_ms", 0.0) or 0.0),
            }
    common = sorted(s for s, by in per_step.items()
                    if len(by) == len(ranks))[warmup:]
    spreads: List[float] = []
    slowest = {r: 0 for r in ranks}
    excess = {r: 0.0 for r in ranks}
    for s in common:
        by = per_step[s]
        totals = {r: by[r]["total_ms"] for r in ranks}
        lo = min(totals.values())
        spreads.append(max(totals.values()) - lo)
        slowest[max(totals, key=totals.get)] += 1
        for r, v in totals.items():
            excess[r] += v - lo

    per_rank = {}
    for r in ranks:
        row = {"steps": step_counts[r], "common_steps": len(common)}
        totals = [per_step[s][r]["total_ms"] for s in common]
        if totals:
            q = quantiles(totals, (50, 99))
            row.update(
                mean_ms=round(sum(totals) / len(totals), 3),
                p50_ms=round(q["p50"], 3), p99_ms=round(q["p99"], 3),
                slowest_steps=slowest[r],
                est_collective_wait_ms=round(excess[r], 3))
            for phase in ("data_ms", "dispatch_ms", "device_ms"):
                vals = [per_step[s][r][phase] for s in common]
                row[f"mean_{phase}"] = round(sum(vals) / len(vals), 3)
        per_rank[str(r)] = row

    straggler = None
    if common and len(ranks) >= 2:
        worst = max(ranks, key=lambda r: excess[r])
        total_excess = sum(excess.values())
        straggler = {
            "rank": worst,
            "excess_share": (round(excess[worst] / total_excess, 4)
                             if total_excess > 0 else 0.0),
            "slowest_step_frac": round(slowest[worst] / len(common), 4),
            "est_collective_wait_ms": round(excess[worst], 3),
        }
    out = {"ranks": ranks, "steps_common": len(common), "warmup": warmup,
           "per_rank": per_rank, "straggler": straggler}
    if any(duplicates.values()):
        out["duplicate_steps"] = {str(r): n for r, n in duplicates.items()
                                  if n}
    if spreads:
        q = quantiles(spreads, (50, 99))
        out["spread_ms"] = {"p50": round(q["p50"], 3),
                            "p99": round(q["p99"], 3),
                            "max": round(max(spreads), 3)}
    return out


def summary_lines(report: dict) -> List[str]:
    """Human rendering of :func:`aggregate` (the CLI's stdout)."""
    lines = [f"[fleet] {len(report['ranks'])} rank(s), "
             f"{report['steps_common']} common step(s)"
             + (f" (warmup {report['warmup']} dropped)"
                if report.get("warmup") else "")]
    dup = report.get("duplicate_steps")
    if dup:
        lines.append(
            f"[fleet] WARNING: duplicate step records (restart replays?) "
            f"collapsed last-wins: {dup} — per-step walls may mix "
            f"attempts; prefer per-attempt stream dirs for exact skew")
    sp = report.get("spread_ms")
    if sp:
        lines.append(f"[fleet] per-step cross-rank spread: "
                     f"p50 {sp['p50']:g} ms, p99 {sp['p99']:g} ms, "
                     f"max {sp['max']:g} ms")
    for r in report["ranks"]:
        row = report["per_rank"][str(r)]
        if "mean_ms" not in row:
            lines.append(f"[fleet] rank {r}: {row['steps']} step event(s), "
                         "none fleet-common")
            continue
        lines.append(
            f"[fleet] rank {r}: p50 {row['p50_ms']:g} ms "
            f"(data {row['mean_data_ms']:g} / dispatch "
            f"{row['mean_dispatch_ms']:g} / device "
            f"{row['mean_device_ms']:g}), slowest in "
            f"{row['slowest_steps']}/{row['common_steps']} step(s), "
            f"est collective wait {row['est_collective_wait_ms']:g} ms")
    s = report.get("straggler")
    if s:
        lines.append(
            f"[fleet] straggler: rank {s['rank']} — slowest in "
            f"{100 * s['slowest_step_frac']:.0f}% of steps, "
            f"{100 * s['excess_share']:.0f}% of fleet excess, "
            f"~{s['est_collective_wait_ms']:g} ms exported stall")
    return lines


def main(argv=None) -> int:
    p = argparse.ArgumentParser(
        prog="python -m tpuic.telemetry.fleet", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    p.add_argument("paths", nargs="+",
                   help="per-rank JSONL stream files, or directories "
                        "whose *.jsonl are the fleet's streams")
    p.add_argument("--warmup", type=int, default=0,
                   help="drop the first N common steps (compile/cache "
                        "warmup is per-rank noise, not skew)")
    p.add_argument("--json", default="",
                   help="write the full report JSON here")
    p.add_argument("--expect-straggler", type=int, default=None,
                   help="exit 1 unless the straggler verdict names this "
                        "rank (the CI fleet smoke's assertion)")
    p.add_argument("--require-ranks", type=int, default=0, metavar="N",
                   help="exit 1 unless the streams cover exactly ranks "
                        "0..N-1 — a fleet run missing a rank's stream "
                        "entirely (dead rank, wrong path) must fail "
                        "loudly, not have its skew silently computed "
                        "over whichever ranks showed up (the gang soak "
                        "and multi-host runs pass their fleet size here). "
                        "The STRICT gate — fixed-membership fleets; "
                        "elastic runs pass --membership instead")
    p.add_argument("--membership", default="", metavar="LEDGER",
                   help="elastic coverage gate: the gang ledger "
                        "(ledger.jsonl) whose membership timeline says "
                        "which ranks legally joined/left mid-run — the "
                        "streams must cover exactly the ranks that EVER "
                        "ran (a shrink/grow is legal; a stream the "
                        "ledger never admitted, or a missing member "
                        "stream, still fails). Mutually exclusive with "
                        "--require-ranks")
    p.add_argument("--score-ledger", action="store_true",
                   help="audit mode for bulk-scoring ledgers "
                        "(tpuic/score/): scored + quarantined == corpus "
                        "per shard and in total, exactly one commit "
                        "record per shard, duplicates and drops loud — "
                        "exit 1 on any violation")
    p.add_argument("--prom-dump", default="", metavar="PATH",
                   help="with --score-ledger: write the tpuic_score_* "
                        "Prometheus exposition of the audit here")
    args = p.parse_args(argv)

    if args.score_ledger:
        streams = load_streams(args.paths)
        if not streams:
            print("[fleet] no event streams found", file=sys.stderr)
            return 2
        report = score_audit(streams)
        for line in score_summary_lines(report):
            print(line, file=sys.stdout if report["ok"] else sys.stderr)
        if args.json:
            with open(args.json, "w") as f:
                json.dump(report, f, indent=2)
            print(f"[fleet] report -> {args.json}")
        if args.prom_dump:
            from tpuic.telemetry.prom import (render, score_rows,
                                              write_exposition)
            write_exposition(args.prom_dump, render(score_rows(report)))
            print(f"[fleet] prom exposition -> {args.prom_dump}")
        return 0 if report["ok"] else 1

    if args.require_ranks and args.membership:
        print("[fleet] --require-ranks (strict) and --membership "
              "(elastic timeline) are mutually exclusive",
              file=sys.stderr)
        return 2
    streams = load_streams(args.paths)
    if not streams:
        print("[fleet] no event streams found", file=sys.stderr)
        return 2
    timeline = None
    if args.require_ranks:
        expected = set(range(args.require_ranks))
        missing = sorted(expected - set(streams))
        extra = sorted(set(streams) - expected)
        if missing or extra:
            print(f"[fleet] FAIL: --require-ranks {args.require_ranks}: "
                  + (f"missing rank stream(s) {missing}" if missing else "")
                  + (" and " if missing and extra else "")
                  + (f"unexpected rank(s) {extra}" if extra else "")
                  + f" (found ranks {sorted(streams)})", file=sys.stderr)
            return 1
    if args.membership:
        timeline = membership_timeline(args.membership)
        expected = set(timeline["ever_ranks"])
        if not expected:
            print(f"[fleet] FAIL: --membership {args.membership}: ledger "
                  "carries no membership/spawn records — nothing to "
                  "gate against", file=sys.stderr)
            return 2
        missing = sorted(expected - set(streams))
        extra = sorted(set(streams) - expected)
        if missing or extra:
            print(f"[fleet] FAIL: --membership: "
                  + (f"missing stream(s) for ledger member(s) {missing}"
                     if missing else "")
                  + (" and " if missing and extra else "")
                  + (f"stream(s) from rank(s) the ledger never admitted "
                     f"{extra}" if extra else "")
                  + f" (found ranks {sorted(streams)}, ever-members "
                  f"{sorted(expected)})", file=sys.stderr)
            return 1
        n_tr = len(timeline["transitions"])
        print(f"[fleet] membership timeline: {len(expected)} ever-"
              f"member(s), {n_tr} transition(s) — elastic coverage OK")
    report = aggregate(streams, warmup=max(0, args.warmup))
    if timeline is not None:
        report["membership"] = timeline
    for line in summary_lines(report):
        print(line)
    if args.json:
        with open(args.json, "w") as f:
            json.dump(report, f, indent=2)
        print(f"[fleet] report -> {args.json}")
    if args.expect_straggler is not None:
        s = report.get("straggler")
        if s is None or int(s["rank"]) != args.expect_straggler:
            print(f"[fleet] FAIL: expected straggler rank "
                  f"{args.expect_straggler}, verdict is "
                  f"{s and s['rank']}", file=sys.stderr)
            return 1
        print(f"[fleet] straggler verdict matches expected rank "
              f"{args.expect_straggler}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
