"""Crash flight recorder: the last N events, dumped when the run dies.

The supervisor's hang escalation (runtime/supervisor.py) already
captures *where* a wedged child is stuck — SIGQUIT makes faulthandler
write all-thread stacks.  What the stacks can't say is *what happened
on the way in*: the step cadence collapsing, a quarantine storm, the
last checkpoint commit, a compile that never returned.  This module is
that record — a bounded ring buffer subscribed to the telemetry bus,
dumped as JSONL next to the stack dump when the process is killed or
dies with an unhandled exception.

Protocol (mirrors the stack-dump artifact):

- The supervisor sets ``TPUIC_FLIGHT_DUMP`` to
  ``<state_dir>/flightdump-<attempt>.jsonl`` per attempt;
  :func:`install_flight_recorder` (called by train.py and
  ``python -m tpuic.serve``) reads it, subscribes a
  :class:`FlightRecorder` to the process-global bus, and registers the
  dump on SIGQUIT and on unhandled exceptions.  Unsupervised processes
  (no env var) get ``None`` back and pay nothing.
- **Order matters**: the SIGQUIT handler must be registered *before*
  ``install_stack_dump_handler(chain=True)`` — faulthandler saves the
  previously-installed handler at registration time and, with
  ``chain=True``, invokes it after the C-level stack dump.  One SIGQUIT
  then yields stacks (always — C level, survives a wedged interpreter)
  plus the event timeline (whenever the main thread still executes
  bytecode, which covers every sleep/IO-shaped hang).
- The dump is written atomically (tmp + rename): the supervisor's
  escalation SIGKILLs a few seconds later, and a torn dump would defeat
  the artifact's whole purpose.  Each dump ends with a trailer record
  ``{"event": "flight_dump", "t": <dump time>, "reason", "events"}`` —
  the chaos soak asserts every recorded event precedes it.

Everything here is stdlib-only host-side plumbing (the module imports
neither jax nor numpy): recording an event is one deque append under a
lock, and an idle bus delivers nothing.
"""

from __future__ import annotations

import json
import os
import signal
import sys
import threading
import time
from collections import deque
from typing import Optional


class FlightRecorder:
    """Bounded ring of the last ``capacity`` bus events + dump-on-demand.

    A bus sink (``bus.subscribe(recorder)``); thread-safe — events
    arrive from the train loop, the serve batcher, and producer threads
    alike.  ``dump()`` snapshots the ring and writes it as JSONL; it is
    safe to call from a signal handler (plain file I/O only — no locks,
    no bus publishing; see its docstring).

    ``exclude_kinds`` (default: ``serve_span``) drops per-request
    firehose kinds from the ring: at a few hundred rps, spans would
    evict the coarse timeline (serve_batch/admission/slo/memory) within
    seconds — exactly the longer-horizon record the dump exists for.
    Aggregate span percentiles are already in the stats snapshot.
    """

    def __init__(self, path: str, capacity: int = 1024,
                 exclude_kinds=("serve_span",)) -> None:
        self.path = path
        self._ring: deque = deque(maxlen=max(1, int(capacity)))
        self._lock = threading.Lock()
        self._exclude = frozenset(exclude_kinds or ())
        self.dumps = 0

    def __call__(self, ev) -> None:
        if ev.kind in self._exclude:
            return
        with self._lock:
            self._ring.append((ev.kind, ev.time, ev.data))

    def __len__(self) -> int:
        with self._lock:
            return len(self._ring)

    def subscribe(self, bus):
        """Subscribe to every kind on ``bus`` (the exclusion list is
        applied at record time, so unregistered/custom kinds are still
        captured).  Returns the unsubscribe callable."""
        return bus.subscribe(self)

    def dump(self, reason: str = "manual") -> Optional[str]:
        """Write the ring as JSONL to ``self.path`` (atomic), ending
        with the ``flight_dump`` trailer record; returns the path.
        Never raises — a failing dump must not mask the signal or the
        exception that triggered it.

        Deliberately LOCK-FREE and BUS-FREE: the SIGQUIT handler runs
        on the main thread, which may have been interrupted *inside*
        ``__call__`` (or any other sink's ``__call__``) with a
        non-reentrant lock held — taking ``self._lock`` here, or
        publishing an announcement event back through the bus into
        those same sinks, would deadlock exactly when the dump matters
        most.  ``list(deque)`` is a single C-level call that never
        releases the GIL, so the snapshot is safe against both producer
        threads and the interrupted frame; the trailer record in the
        file IS the announcement."""
        events = list(self._ring)
        trailer = {"event": "flight_dump", "t": round(time.time(), 6),
                   "reason": reason, "events": len(events),
                   "pid": os.getpid()}
        tmp = f"{self.path}.{os.getpid()}.tmp"
        try:
            d = os.path.dirname(os.path.abspath(self.path))
            os.makedirs(d, exist_ok=True)
            with open(tmp, "w") as f:
                for kind, t, data in events:
                    f.write(json.dumps({"event": kind, "t": round(t, 6),
                                        **data}, default=str) + "\n")
                f.write(json.dumps(trailer) + "\n")
            os.replace(tmp, self.path)
        except Exception:
            try:
                os.unlink(tmp)
            except OSError:
                pass
            return None
        self.dumps += 1
        return self.path

    # -- triggers -------------------------------------------------------
    def install_signal_handler(self) -> bool:
        """Dump on SIGQUIT, then chain to whatever Python-level handler
        was there before (none, usually — faulthandler registers at the
        C level and is not visible here).  Main-thread only; returns
        False when registration is impossible."""
        if not hasattr(signal, "SIGQUIT"):
            return False
        prev = signal.getsignal(signal.SIGQUIT)

        def _on_sigquit(signum, frame):
            self.dump(reason="sigquit")
            if callable(prev):
                try:
                    prev(signum, frame)
                except Exception:
                    pass

        try:
            signal.signal(signal.SIGQUIT, _on_sigquit)
        except (ValueError, OSError):  # non-main thread / exotic platform
            return False
        return True

    def install_excepthook(self) -> None:
        """Dump on a fatal (unhandled) exception, then defer to the
        previous excepthook — the crash report itself is untouched."""
        prev = sys.excepthook

        def _hook(exc_type, exc, tb):
            self.dump(reason=f"unhandled:{exc_type.__name__}")
            prev(exc_type, exc, tb)

        sys.excepthook = _hook


def install_flight_recorder(bus=None, capacity: int = 1024
                            ) -> Optional[FlightRecorder]:
    """The one-call wiring for supervised entry points (train.py,
    ``python -m tpuic.serve``): when the supervisor set
    ``TPUIC_FLIGHT_DUMP``, build a recorder on the process-global bus,
    register the SIGQUIT + excepthook dumps, and return it.  Call
    ``install_stack_dump_handler(chain=True)`` *after* this so the
    faulthandler stack dump chains into the flight dump.  Returns None
    (and installs nothing) unsupervised."""
    from tpuic.runtime.supervisor import ENV_FLIGHT_DUMP
    path = os.environ.get(ENV_FLIGHT_DUMP, "")
    if not path:
        return None
    if bus is None:
        from tpuic.telemetry.events import bus as _bus
        bus = _bus
    rec = FlightRecorder(path, capacity=capacity)
    rec.subscribe(bus)
    rec.install_signal_handler()
    rec.install_excepthook()
    return rec
