"""Structured event bus: lightweight publish/subscribe of typed events.

The train loop, checkpoint manager, dataset quarantine, serving engine,
and the jax compile monitor all publish here instead of (only) printing
ad-hoc log lines; sinks subscribe — JSONL for machines, memory for
tests, TensorBoard for dashboards.  docs/observability.md documents the
event schema.

Design constraints (the hot-loop discipline):

- **Free when idle**: ``publish`` on a bus with no subscribers is one
  attribute read and a falsy check — telemetry wiring can stay in the
  per-step path unconditionally.
- **Host-only**: nothing in this module touches JAX arrays.  Event data
  values must be plain JSON-able scalars/strings the caller already has
  on host; publishing never forces a device sync (test-asserted).
- **Thread-safe**: emitters run in producer threads, the serve batcher,
  and the train loop; subscription mutates under a lock while publish
  reads an immutable snapshot tuple.
- **Sink failures are contained**: a sink raising must not take down
  the training step or the batcher — the error is counted
  (``bus.sink_errors``) and the event is delivered to the remaining
  subscribers.

This module deliberately imports neither jax nor numpy, so low-level
emitters (data/folder.py, checkpoint/manager.py) can import it with no
dependency cost; the jax.monitoring bridge imports jax lazily.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
import time
from collections import deque
from typing import Callable, Dict, Iterable, Optional, Tuple

# The typed vocabulary (ISSUE 3).  Publishing an unlisted kind is allowed
# (the bus is a transport, not a validator) but the canonical emitters
# stick to these; docs/observability.md is the schema reference.
EVENT_KINDS = ("step", "epoch", "eval", "drain", "checkpoint_commit",
               "rollback", "skip", "quarantine", "compile", "serve_batch",
               "serve_span", "slo", "admission", "trace", "goodput",
               "restart", "heartbeat", "memory", "flight_dump", "profile",
               # Replica-router tier (tpuic/serve/router.py,
               # docs/serving.md "Replica routing and failover"):
               # per-replica lifecycle/health transitions, circuit-breaker
               # state changes, budgeted retries, and in-flight failover.
               "router_replica", "router_breaker", "router_retry",
               "router_failover",
               # Model-lifecycle tier (docs/serving.md, "Model
               # lifecycle: hot-swap, canary, rollback"): one 'swap'
               # event per engine weight flip (generation, digest,
               # executable reuse vs prewarm), one 'rollout' event per
               # canary-rollout transition (start/stage/rollback/
               # promote/refused — tpuic/serve/rollout.py).
               "swap", "rollout",
               # Elastic data parallelism (runtime/gang.py elastic mode,
               # docs/parallelism.md): one 'reform' event per membership
               # transition the trainer acted on — a degrade restores
               # the fleet-agreed step in place (no process restart), a
               # rejoin is noted without a restore.
               "reform",
               # Bulk-scoring tier (tpuic/score/, docs/robustness.md
               # "Bulk scoring"): one 'score_plan' per worker life (the
               # shard table), one 'score_shard' per shard attempt
               # (score/rescore_corrupt/adopt), exactly one
               # 'score_commit' per committed shard fleet-wide (the
               # audited ledger row; recovered=true when appended by a
               # survivor for a dead winner), 'score_duplicate' when
               # the link-arbitrated commit deduped double work, and
               # one 'score_done' per worker life (totals + the
               # steady-compile counter).
               "score_plan", "score_shard", "score_commit",
               "score_duplicate", "score_done",
               # Compiled-program registry (tpuic/compiled/,
               # docs/performance.md "Compiled-program registry"): one
               # 'compile_cache' event per registry action — a miss that
               # compiled (action=compile), a manifest-driven prewarm
               # compile (action=prewarm), a generation retirement
               # (action=retire), and the trainer's prewarm summary
               # (action=prewarm_done).
               "compile_cache")


@dataclasses.dataclass(frozen=True)
class Event:
    kind: str
    time: float          # wall clock (time.time()) at publish
    data: Dict[str, object]


class EventBus:
    """Synchronous pub/sub.  Subscribers run inline in the publishing
    thread (ordering is therefore the emission order); anything slow or
    blocking belongs in the subscriber's own buffering, not here."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # Immutable snapshot: publish iterates without the lock.
        self._subs: Tuple[Tuple[Optional[frozenset], Callable], ...] = ()
        self.published = 0
        self.sink_errors = 0
        # Fleet rank tag (telemetry/fleet.py): when set (a plain dict,
        # e.g. {"rank": 2, "ranks": 8}), every published event's data is
        # merged over it, so per-rank JSONL streams are attributable
        # offline. None (the single-process default) costs one attribute
        # read per publish.  Emitter-provided keys win on collision.
        self.rank_tag: Optional[Dict[str, object]] = None

    def subscribe(self, fn: Callable[[Event], None],
                  kinds: Optional[Iterable[str]] = None) -> Callable[[], None]:
        """Register ``fn`` for ``kinds`` (None = every kind); returns an
        idempotent unsubscribe callable."""
        entry = (None if kinds is None else frozenset(kinds), fn)
        with self._lock:
            self._subs = self._subs + (entry,)

        def unsubscribe() -> None:
            with self._lock:
                self._subs = tuple(e for e in self._subs if e is not entry)
        return unsubscribe

    def active(self, kind: Optional[str] = None) -> bool:
        """Whether anything would receive ``kind`` (None: any subscriber
        at all) — lets emitters skip building expensive event data."""
        subs = self._subs
        if kind is None:
            return bool(subs)
        return any(k is None or kind in k for k, _ in subs)

    def publish(self, kind: str, **data) -> Optional[Event]:
        subs = self._subs
        if not subs:
            return None
        tag = self.rank_tag
        if tag is not None:
            data = {**tag, **data}
        ev = Event(kind, time.time(), data)
        delivered = False
        for kinds, fn in subs:
            if kinds is not None and kind not in kinds:
                continue
            delivered = True
            try:
                fn(ev)
            except Exception:
                # A broken sink must never kill the train loop or the
                # serve batcher; the counter makes the breakage visible.
                self.sink_errors += 1
        if delivered:
            self.published += 1
        return ev

    def reset(self) -> None:
        """Drop every subscriber (test isolation — the process-global
        bus otherwise accumulates them across constructed Trainers)."""
        with self._lock:
            self._subs = ()
            self.published = 0
            self.sink_errors = 0
            self.rank_tag = None


def read_jsonl(path: str, on_torn: Optional[Callable[[str], None]] = None
               ) -> list:
    """Tolerant JSONL reader: parse every line of ``path`` that parses.

    THE shared reader for event streams written by :class:`JsonlSink`
    and friends (chaos soak, perf-regression gate, fleet aggregator —
    one implementation, one torn-line policy).  A SIGKILL can tear a
    line mid-write and the next attempt appends its first event onto
    the fragment; such lines are skipped (reported via ``on_torn`` when
    given) instead of crashing the verdict path.  A missing or
    unreadable file reads as an empty stream — absence is the caller's
    assertion to make, not an exception to catch.
    """
    out: list = []
    try:
        fh = open(path)
    except OSError:
        return out
    with fh:
        for ln in fh:
            ln = ln.strip()
            if not ln:
                continue
            try:
                out.append(json.loads(ln))
            except json.JSONDecodeError:
                if on_torn is not None:
                    on_torn(ln)
    return out


# -- sinks -------------------------------------------------------------------
class MemorySink:
    """Bounded in-memory event recorder (tests, REPL debugging)."""

    def __init__(self, maxlen: int = 4096) -> None:
        self.events: deque = deque(maxlen=maxlen)

    def __call__(self, ev: Event) -> None:
        self.events.append(ev)

    def kinds(self) -> list:
        return [e.kind for e in self.events]

    def of(self, kind: str) -> list:
        return [e for e in self.events if e.kind == kind]


class JsonlSink:
    """One JSON line per event: ``{"event": kind, "t": ..., **data}``.

    Durability ladder (the chaos soak used to tolerate torn tail lines a
    SIGKILLed run simply lost; this sink stops losing them up front):

    - ``flush_every`` bounds buffered lines (1 = flush each event — the
      default, so a killed process loses nothing; per-line flush of an
      already-buffered file is microseconds against millisecond steps).
    - ``flush_interval_s`` bounds buffered *time* when ``flush_every``
      is raised for very hot event streams: the first write after the
      interval elapses flushes everything buffered.  The bound holds
      while events keep flowing (the hot-stream case it exists for);
      a stream that stops emitting holds its tail until the next
      ``flush()``/``close()`` — which every drain path calls — because
      the sink deliberately has no background timer thread.
    - ``fsync=True`` additionally fsyncs at every flush — survives a
      machine (not just process) kill; off by default, it is a real
      per-event disk round trip.
    - ``close()`` flushes (and fsyncs, if configured) before closing, so
      a clean drain never leaves a torn tail; it is idempotent and
      write-after-close is a no-op.

    Thread-safe: serve-thread and loop-thread events interleave whole
    lines, never bytes.
    """

    def __init__(self, path: str, flush_every: int = 1,
                 flush_interval_s: float = 0.5,
                 fsync: bool = False) -> None:
        self.path = path
        self._fh = open(path, "a")
        self._lock = threading.Lock()
        self._since_flush = 0
        self._flush_every = max(1, int(flush_every))
        self._flush_interval = max(0.0, float(flush_interval_s))
        self._fsync = bool(fsync)
        self._last_flush = time.monotonic()

    def _flush_locked(self) -> None:
        self._fh.flush()
        if self._fsync:
            try:
                os.fsync(self._fh.fileno())
            except OSError:
                pass  # durability best-effort; never kill the loop
        self._since_flush = 0
        self._last_flush = time.monotonic()

    def __call__(self, ev: Event) -> None:
        rec = {"event": ev.kind, "t": round(ev.time, 6), **ev.data}
        line = json.dumps(rec, default=str) + "\n"
        with self._lock:
            if self._fh is None:
                return
            self._fh.write(line)
            self._since_flush += 1
            if (self._since_flush >= self._flush_every
                    or time.monotonic() - self._last_flush
                    >= self._flush_interval):
                self._flush_locked()

    def flush(self) -> None:
        with self._lock:
            if self._fh is not None:
                self._flush_locked()

    def close(self) -> None:
        with self._lock:
            if self._fh is not None:
                try:
                    self._flush_locked()
                finally:
                    self._fh.close()
                    self._fh = None


class TensorBoardSink:
    """Bus -> TensorBoard bridge: skip/rollback/quarantine counts,
    goodput fractions, supervisor restarts, serve batch/span latencies,
    device-memory gauges, and SLO attainment become scalars instead of
    being log-only.

    Wraps an existing ``tpuic.metrics.tensorboard.TensorBoardWriter``
    (the MetricLogger's); subscribes to ``step`` only to track the
    current global step so step-less events (quarantine fires in a
    producer thread) land at a sensible x-coordinate.  Serve events have
    no train step at all, so they ride their own monotonic counters.
    """

    def __init__(self, writer) -> None:
        self._tb = writer
        self._step = 0
        self._quarantined = 0
        self._rollbacks = 0
        self._serve_batches = 0
        self._serve_spans = 0

    def __call__(self, ev: Event) -> None:
        if self._tb is None:
            return
        d = ev.data
        if ev.kind == "step":
            self._step = int(d.get("step", self._step))
            return
        if ev.kind == "skip":
            self._tb.scalars(int(d.get("step", self._step)),
                             skip_streak=float(d.get("streak", 0)))
        elif ev.kind == "rollback":
            self._rollbacks += 1
            self._tb.scalars(self._step, rollbacks=float(self._rollbacks))
        elif ev.kind == "quarantine":
            # Accumulate per event rather than trusting the publisher's
            # 'count': that figure is dataset-local (train and val each
            # keep their own), so taking the last event's value would
            # regress the scalar whenever more than one dataset (or an
            # out-of-order producer thread) quarantines.
            self._quarantined += 1
            self._tb.scalars(self._step,
                             quarantined_total=float(self._quarantined))
        elif ev.kind == "goodput":
            scalars = {f"goodput_{k[5:]}": float(v) for k, v in d.items()
                       if k.startswith("frac_")}
            if "mfu" in d and d["mfu"] is not None:
                scalars["mfu"] = float(d["mfu"])
            if d.get("compute_dtype"):
                # Info-style scalar (constant 1, dtype in the tag): TB
                # has no string scalars, and runs compared side by side
                # need the precision arm visible.
                scalars[f"compute_dtype_{d['compute_dtype']}"] = 1.0
            if d.get("checkpoint_async_s") is not None:
                scalars["goodput_checkpoint_async_s"] = float(
                    d["checkpoint_async_s"])
            if scalars:
                self._tb.scalars(int(d.get("step", self._step)), **scalars)
        elif ev.kind == "restart":
            # Supervisor restart (runtime/supervisor.py): the count and
            # the downtime it cost, at the step the resumed run re-opened.
            self._tb.scalars(self._step,
                             restarts=float(d.get("restart", 0)),
                             restart_downtime_s=float(
                                 d.get("downtime_s", 0.0)))
        elif ev.kind == "serve_batch":
            self._serve_batches += 1
            self._tb.scalars(self._serve_batches,
                             serve_batch_latency_ms=float(
                                 d.get("latency_ms", 0.0)),
                             serve_batch_images=float(d.get("images", 0)),
                             serve_batch_bucket=float(d.get("bucket", 0)))
        elif ev.kind == "serve_span":
            # One point per request: end-to-end latency plus the two
            # spans that dominate tuning decisions (queue wait = load,
            # device = model cost); the full ledger stays in JSONL.
            self._serve_spans += 1
            self._tb.scalars(self._serve_spans,
                             serve_request_total_ms=float(
                                 d.get("total_ms", 0.0)),
                             serve_request_queue_ms=float(
                                 d.get("queue_ms", 0.0)),
                             serve_request_device_ms=float(
                                 d.get("device_ms", 0.0)))
        elif ev.kind == "memory":
            # Device-memory accounting (telemetry/memory.py): the
            # aggregate gauges become scalars; the per-device split
            # stays in JSONL/prom (a per-device TB curve per chip would
            # be noise on a pod).
            scalars = {}
            for field in ("bytes_in_use", "peak_bytes_in_use",
                          "process_rss_bytes"):
                if d.get(field) is not None:
                    scalars[f"memory_{field}"] = float(d[field])
            if d.get("headroom_frac") is not None:
                scalars["memory_headroom_frac"] = float(d["headroom_frac"])
            if scalars:
                self._tb.scalars(int(d.get("step", self._step)), **scalars)
        elif ev.kind == "slo":
            name = str(d.get("name", "slo"))
            scalars = {}
            for field in ("attainment", "burn_rate", "budget_remaining"):
                if d.get(field) is not None:
                    scalars[f"slo_{name}_{field}"] = float(d[field])
            if scalars:
                self._tb.scalars(int(d.get("step", self._step)), **scalars)
        elif ev.kind == "profile":
            # Device-time waterfall (telemetry/profile.py): per-op-class
            # device milliseconds as scalars; layer rollups and verdicts
            # stay in JSONL/prom (a per-layer TB curve per analysis
            # would be noise).
            scalars = {}
            for cls, c in (d.get("classes") or {}).items():
                if isinstance(c, dict) and c.get("ms") is not None:
                    scalars[f"device_time_ms_{cls}"] = float(c["ms"])
            if d.get("device_ms_per_step") is not None:
                scalars["device_ms_per_step"] = float(
                    d["device_ms_per_step"])
            if scalars:
                self._tb.scalars(self._step, **scalars)


# -- the process-global bus --------------------------------------------------
bus = EventBus()


def publish(kind: str, **data) -> Optional[Event]:
    return bus.publish(kind, **data)


def subscribe(fn: Callable[[Event], None],
              kinds: Optional[Iterable[str]] = None) -> Callable[[], None]:
    return bus.subscribe(fn, kinds)


# -- jax.monitoring bridge ---------------------------------------------------
_COMPILE_PREFIX = "/jax/core/compile/"
_monitor_lock = threading.Lock()
_monitor_installed = False


def install_jax_compile_listener() -> bool:
    """Bridge jax's compile-duration monitoring into ``compile`` events.

    jax 0.4.x reports each compilation as three sequential phase
    durations (jaxpr trace, MLIR lowering, backend compile) under
    ``/jax/core/compile/*``; the listener republishes each phase as a
    ``compile`` event (``key``, ``duration_s``), so the goodput tracker
    can subtract compile time from the step it stalled and tests can
    count ``backend_compile`` events as a compile counter.  Idempotent;
    returns False when jax.monitoring is unavailable.  The listener is
    process-wide and permanent (jax has no unregister), but an idle bus
    makes each callback a single falsy check.
    """
    global _monitor_installed
    with _monitor_lock:
        if _monitor_installed:
            return True
        try:
            from jax import monitoring as _jm
        except Exception:
            return False

        def _listener(key: str, duration: float, **kw) -> None:
            if key.startswith(_COMPILE_PREFIX):
                publish("compile", key=key[len(_COMPILE_PREFIX):],
                        duration_s=round(float(duration), 6))

        _jm.register_event_duration_secs_listener(_listener)
        _monitor_installed = True
        return True
