"""Device-memory accounting: where does HBM go, per device, per step.

The ROADMAP's multi-host and MFU items both need this gauge before they
can move: weight-update sharding (arXiv:2004.13336) is *about* optimizer
memory, and every "fit a bigger batch" experiment is a bet against an
OOM that today only manifests as a crash.  This module samples
per-device memory at step boundaries and publishes it as a ``memory``
event on the telemetry bus, so HBM pressure is a curve in the JSONL /
TensorBoard / Prometheus record instead of a post-mortem.

Sources, in preference order (per device):

- ``device.memory_stats()`` — the PJRT allocator's own counters
  (``bytes_in_use`` / ``peak_bytes_in_use`` / ``bytes_limit``), provided
  by the TPU and GPU backends.  A pure host-side metadata read: it never
  blocks on the device stream.
- ``jax.live_arrays()`` fallback — the CPU backend returns no
  ``memory_stats()``; summing the live arrays' ``nbytes`` per device is
  an honest lower bound (arrays only; no allocator slack), labeled
  ``source: "live_arrays"`` so dashboards don't read the two as the
  same quantity.  No limit is known, so ``headroom_frac`` is omitted.

Every sample also carries the process RSS (the shared
``tpuic.metrics.meters.process_rss_bytes`` helper) — host-side leaks
(pinned staging buffers, an unbounded queue) show up next to the device
curve they eventually take down.

Hot-loop discipline (the PR-2/PR-3 contract, checker-asserted in
tests/test_fleet.py): sampling adds **zero host syncs and zero
compiles** — ``memory_stats`` and the live-array walk are host-side
metadata reads, RSS is a ``/proc`` read, and nothing here touches array
*values*.  The ``jax.device_get`` count and the jit cache are identical
with the sampler on vs. off.

Low-headroom warning: the first sample that sees any device's
``headroom_frac`` under ``warn_headroom_frac`` carries
``warning: "low_headroom"`` (and logs one line) — a one-shot latch, so
a run hovering at 95% HBM warns once instead of once per step.
"""

from __future__ import annotations

import threading
from typing import Optional

from tpuic.metrics.meters import process_rss_bytes

# memory_stats() key -> event field.  PJRT backends agree on these names
# (tpu/gpu); anything absent is simply omitted from the sample.
_STAT_FIELDS = (("bytes_in_use", "bytes_in_use"),
                ("peak_bytes_in_use", "peak_bytes_in_use"),
                ("bytes_limit", "bytes_limit"))


def _device_label(dev) -> str:
    return str(getattr(dev, "id", dev))


def _stats_sample(dev) -> Optional[dict]:
    """One device's allocator counters via ``memory_stats()``; None when
    the backend provides none (CPU) or the call is unavailable."""
    stats_fn = getattr(dev, "memory_stats", None)
    if stats_fn is None:
        return None
    try:
        stats = stats_fn()
    except Exception:
        return None
    if not stats:
        return None
    out = {"device": _device_label(dev),
           "kind": str(getattr(dev, "device_kind", "unknown"))}
    for src, field in _STAT_FIELDS:
        v = stats.get(src)
        if v is not None:
            out[field] = int(v)
    if out.get("bytes_limit") and out.get("bytes_in_use") is not None:
        out["headroom_frac"] = round(
            1.0 - out["bytes_in_use"] / out["bytes_limit"], 4)
    return out if "bytes_in_use" in out else None


def _live_array_sample(devices) -> tuple:
    """CPU fallback: per-device sum of live jax.Array nbytes.  An array
    sharded over k devices is charged nbytes/k to each.  Host-side walk
    of the liveness registry — no device work, no syncs — but O(live
    arrays): the sampler auto-throttles its cadence when the registry
    is large (see ``MemorySampler``).  Returns (rows, n_arrays)."""
    import jax

    per_dev = {_device_label(d): 0.0 for d in devices}
    kinds = {_device_label(d): str(getattr(d, "device_kind", "cpu"))
             for d in devices}
    try:
        live = jax.live_arrays()
    except Exception:
        live = ()
    for arr in live:
        try:
            devs = list(arr.devices())
            share = arr.nbytes / max(1, len(devs))
        except Exception:
            continue  # deleted/donated under us — racing is fine, skip
        for d in devs:
            label = _device_label(d)
            if label in per_dev:
                per_dev[label] += share
    return ([{"device": label, "kind": kinds[label],
              "bytes_in_use": int(n)} for label, n in per_dev.items()],
            len(live))


class MemorySampler:
    """Samples per-device memory and publishes ``memory`` events.

    Wired by ``TrainTelemetry`` as a bus subscriber on ``step`` events
    (one sample per ``every`` step boundaries, default every step —
    the ``memory_stats`` read is microseconds of host metadata, and the
    O(live-arrays) CPU fallback auto-throttles its cadence on large
    liveness registries), and called directly at scrape time by the
    serve driver's Prometheus collector.  The last
    sample is kept for :meth:`snapshot` so the prom exposition renders
    ``device_memory_bytes{device,kind}`` rows without re-sampling.
    """

    def __init__(self, publish=None, devices=None, every: int = 1,
                 warn_headroom_frac: float = 0.05, log=None,
                 fallback_throttle_arrays: int = 1024,
                 fallback_stride: int = 8) -> None:
        if publish is None:
            from tpuic.telemetry.events import bus as _bus
            publish = _bus.publish
        self._publish = publish
        self._devices = devices
        self._every = max(1, int(every))
        self._warn_frac = float(warn_headroom_frac)
        self._log = log
        self._lock = threading.Lock()
        self._warned = False
        self._seen_steps = 0
        # The live_arrays fallback is O(live arrays) per sample — fine
        # for the small-model CPU runs it exists for, but a huge
        # param/opt tree would pay a real per-step walk.  Once a walk
        # sees more than ``fallback_throttle_arrays`` arrays, step-
        # boundary sampling strides by ``fallback_stride`` (direct
        # sample() calls are never throttled; the memory_stats path is
        # one cheap allocator read and never throttles either).
        self._fb_throttle = int(fallback_throttle_arrays)
        self._fb_stride = max(1, int(fallback_stride))
        self._stride = 1
        self.samples = 0
        self.last: Optional[dict] = None

    def _resolve_devices(self):
        if self._devices is None:
            import jax
            self._devices = jax.local_devices()
        return self._devices

    # -- bus hook (TrainTelemetry subscribes this for 'step') -----------
    def on_event(self, ev) -> None:
        self._seen_steps += 1
        if (self._seen_steps - 1) % (self._every * self._stride):
            return
        self.sample(step=ev.data.get("step"))

    # -- the sample -----------------------------------------------------
    def sample(self, step=None) -> Optional[dict]:
        """Take one sample, publish it as a ``memory`` event, return it
        (None when no device yields anything — never raises into the
        loop: memory accounting must not take down the run)."""
        try:
            devices = self._resolve_devices()
        except Exception:
            return None
        rows = []
        source = "memory_stats"
        for dev in devices:
            row = _stats_sample(dev)
            if row is not None:
                rows.append(row)
        if not rows:
            source = "live_arrays"
            rows, n_live = _live_array_sample(devices)
            if n_live > self._fb_throttle:
                self._stride = self._fb_stride
        if not rows:
            return None
        out = {"source": source, "devices": rows}
        if step is not None:
            out["step"] = int(step)
        out["bytes_in_use"] = sum(r.get("bytes_in_use", 0) for r in rows)
        peaks = [r["peak_bytes_in_use"] for r in rows
                 if r.get("peak_bytes_in_use") is not None]
        if peaks:
            out["peak_bytes_in_use"] = sum(peaks)
        limits = [r["bytes_limit"] for r in rows
                  if r.get("bytes_limit") is not None]
        if limits:
            out["bytes_limit"] = sum(limits)
        headrooms = [r["headroom_frac"] for r in rows
                     if r.get("headroom_frac") is not None]
        if headrooms:
            # The aggregate headroom is the WORST device's: one full
            # chip OOMs the step regardless of the others' slack.
            out["headroom_frac"] = min(headrooms)
        rss = process_rss_bytes()
        if rss is not None:
            out["process_rss_bytes"] = int(rss)
        with self._lock:
            warn = (not self._warned and headrooms
                    and min(headrooms) < self._warn_frac)
            if warn:
                self._warned = True
            self.samples += 1
            self.last = out
        if warn:
            worst = min((r for r in rows
                         if r.get("headroom_frac") is not None),
                        key=lambda r: r["headroom_frac"])
            out["warning"] = "low_headroom"
            if self._log is not None:
                self._log(
                    f"[memory] LOW HEADROOM: device {worst['device']} "
                    f"({worst['kind']}) at "
                    f"{100 * (1 - worst['headroom_frac']):.1f}% of "
                    f"{worst.get('bytes_limit', 0) / 2**30:.2f} GiB — "
                    f"the next allocation spike is an OOM")
        self._publish("memory", **out)
        return out

    def snapshot(self) -> Optional[dict]:
        """The most recent sample (for the Prometheus expositions)."""
        with self._lock:
            return self.last
