"""MFU + goodput accounting: where did the wall time go?

The large-scale training literature attributes its wins to exactly this
bookkeeping — FireCaffe (arXiv 1511.00175) and the 15-minute ImageNet
run (arXiv 1711.04325) both measure, then shrink, the comm/input
fraction of step time.  This module owns:

- the **peak-FLOPs table** and **analytic per-model FLOPs** that
  bench.py previously kept private (bench.py now imports them back, so
  the bench headline and the in-band MFU share one formula);
- a **GoodputTracker** that subscribes to the event bus and classifies
  wall time into buckets::

      productive   device/dispatch time of non-skipped train steps
      input        loader wait (the input-bound fraction)
      compile      jaxpr/MLIR/backend compile (jax.monitoring bridge),
                   subtracted from the step/eval span it stalled
      checkpoint   checkpoint stage + commit spans
      skip         estimated time of guard-skipped (non-finite) steps
      rollback     checkpoint-restore spans after a non-finite streak
      eval         validation epochs
      other        wall - all of the above (setup, logging gaps)

  ``report()`` returns the buckets, their fractions, the accounted
  fraction (tier-1 CI asserts the named buckets sum to ~100% of wall on
  a synthetic run), and running MFU when the model's FLOPs are known.

Accounting notes (documented, not hidden):

- Skip time is an **estimate**: the skip streak is only observed at the
  deferred drain (the price of a sync-free hot path), so skipped steps
  are charged at the rolling mean step time and moved out of
  ``productive``.  At ``log_every_steps=1`` the estimate is exact.
- MFU counts only productive (non-skipped) steps: a guard-skipped step
  runs the FLOPs but trains nothing, so counting it would inflate the
  number goodput exists to keep honest.
"""

from __future__ import annotations

import threading
import time
from typing import Optional

# bf16 peak FLOP/s per chip by device kind (public spec sheets).
# Moved from bench.py (which imports it back) — single source of truth
# for every MFU number this repo reports.
PEAK_FLOPS = {
    "TPU v5 lite": 197e12,   # v5e
    "TPU v5e": 197e12,
    "TPU v5": 459e12,        # v5p
    "TPU v4": 275e12,
    "TPU v6 lite": 918e12,   # v6e / Trillium
    "cpu": 1e12,             # nominal, keeps the metric finite in CI
}

# f32 peak FLOP/s: TPU MXUs run f32 matmuls at half the bf16 rate
# (spec-sheet convention — the same systolic array issues one f32 or
# two bf16 MACs per cell per cycle).  An f32 run judged against the
# bf16 roofline would under-report MFU by exactly 2x, which is how a
# "bf16 doubled our MFU" claim lies: same math, different denominator.
# The cpu entry stays nominal — CI only needs the metric finite.
PEAK_FLOPS_F32 = {k: (v / 2.0 if k != "cpu" else v)
                  for k, v in PEAK_FLOPS.items()}

_DTYPE_PEAKS = {"bf16": PEAK_FLOPS, "bfloat16": PEAK_FLOPS,
                "f32": PEAK_FLOPS_F32, "float32": PEAK_FLOPS_F32}


def peak_flops(device, dtype: str = "bf16") -> float:
    """Peak FLOP/s for a jax device at ``dtype`` ('bf16' default, 'f32'
    for the half-rate f32 roofline; 1e12 nominal fallback)."""
    table = _DTYPE_PEAKS.get(str(dtype).lower())
    if table is None:
        raise ValueError(f"peak_flops: unknown dtype {dtype!r} "
                         "(want 'bf16' or 'f32')")
    kind = getattr(device, "device_kind", "cpu") if device is not None else "cpu"
    for k, v in table.items():
        if str(kind).lower().startswith(k.lower()):
            return v
    return 1e12


# HBM bandwidth in GB/s per chip by device kind (public spec sheets) —
# the second axis of the roofline every device-time verdict in
# telemetry/profile.py is judged against.  Golden-value-pinned in
# tests/test_profile.py exactly like PEAK_FLOPS above: an MFU claim and
# a "this op class is HBM-bound" claim must come from the same tables.
HBM_GBPS = {
    "TPU v5 lite": 819,     # v5e
    "TPU v5e": 819,
    "TPU v5": 2765,         # v5p
    "TPU v4": 1228,
    "TPU v6 lite": 1640,    # v6e / Trillium
    "cpu": 50,              # nominal DDR-class; keeps the metric finite in CI
}


def hbm_bandwidth(device) -> float:
    """HBM bytes/s for a jax device (50 GB/s nominal fallback)."""
    kind = getattr(device, "device_kind", "cpu") if device is not None else "cpu"
    for k, v in HBM_GBPS.items():
        if str(kind).lower().startswith(k.lower()):
            return v * 1e9
    return 50e9


def roofline_intensity(flops: float, bytes_accessed: float) -> Optional[float]:
    """Arithmetic intensity (FLOPs per HBM byte), or None when no bytes
    move.  THE shared formula: telemetry/profile.py's per-class verdicts
    and bench.py's detail both call this instead of growing two."""
    if not bytes_accessed or bytes_accessed <= 0:
        return None
    return float(flops) / float(bytes_accessed)


def ridge_intensity(peak: float, hbm_bytes_per_s: float) -> float:
    """The roofline ridge point (FLOPs/byte): below it a kernel at peak
    bandwidth cannot reach peak FLOPs — it is HBM-bound by arithmetic."""
    return float(peak) / max(1.0, float(hbm_bytes_per_s))


def roofline_verdict(flops: float, bytes_accessed: float, peak: float,
                     hbm_bytes_per_s: float) -> str:
    """'compute-bound' | 'hbm-bound' | 'overhead' for a (FLOPs, bytes)
    workload on a (peak, bandwidth) machine.  'overhead' means neither
    axis is exercised (no flops AND no bytes — control flow, tuples,
    host stalls booked to the device bucket)."""
    if (not flops or flops <= 0) and (not bytes_accessed
                                      or bytes_accessed <= 0):
        return "overhead"
    inten = roofline_intensity(flops, bytes_accessed)
    if inten is None:  # flops but no bytes: register-resident compute
        return "compute-bound"
    return ("compute-bound"
            if inten >= ridge_intensity(peak, hbm_bytes_per_s)
            else "hbm-bound")


def cost_analysis_dict(compiled) -> dict:
    """``compiled.cost_analysis()`` normalized to one flat dict.

    jax returns a list of per-device dicts on some backends (CPU) and a
    plain dict on others; every consumer here (bench.py, profile.py,
    serve/engine.py) wants the first device's view.  Raises whatever the
    runtime raises when cost analysis is unsupported — callers guard."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return dict(ca)


def check_flops_drift(model_name: str, image_size: int, global_batch: int,
                      compiled_flops: float, *, train: bool = True,
                      tol: float = 0.10, warn=None) -> Optional[float]:
    """Cross-check the analytic FLOPs table against the compiler's count.

    Returns the relative drift ``|analytic - compiled| / compiled`` (None
    when the model is unknown or the compiled count is unusable) and
    WARNS — loudly, never raises — when it exceeds ``tol``: the analytic
    table feeding every in-band MFU number silently mis-reports once the
    models or the table drift apart, and until now nothing compared them
    where both are available (bench.py and the profile analyzer do now).
    """
    if not compiled_flops or compiled_flops <= 0:
        return None
    analytic = analytic_flops_per_step(model_name, image_size, global_batch,
                                       train=train)
    if analytic is None:
        return None
    drift = abs(analytic - float(compiled_flops)) / float(compiled_flops)
    if drift > tol:
        import warnings
        (warn or warnings.warn)(
            f"analytic FLOPs table drifts {100.0 * drift:.1f}% from the "
            f"compiler's count for model={model_name!r} "
            f"(analytic {analytic:.3e} vs cost_analysis "
            f"{float(compiled_flops):.3e} per step): MFU numbers derived "
            "from the table are off by the same factor — update "
            "FWD_FLOPS_PER_IMAGE in tpuic/telemetry/goodput.py")
    return drift


# Analytic forward GFLOPs per image at a canonical resolution
# (2x the published per-model GMAC figures; prefix-matched so
# '-s2d'/'-cifar' variants inherit the family figure unless listed).
# The training step is fwd + bwd ~= 3x forward.
#
# Every entry is cross-checked against the compiler's own count by
# tests/test_flops_zoo.py (forward-only compile at the canonical shape,
# drift must stay under check_flops_drift's 10% warning threshold).
# That sweep is what caught the table's original sin TWICE: the 0.56e9
# resnet18-cifar entry (PR 10, 43% drift) and then the ENTIRE rest of
# the zoo (PR 16) were literature GMAC counts pasted as FLOPs — 2x low
# across the board, flattering-halving every analytic-table MFU number.
# The vit-tiny entry was worse: the DeiT-Ti literature figure pasted
# onto this repo's test-scale ViT (patch 4, hidden 64, depth 2), a
# model with ~5x that cost at 224px (patch-4 token counts make the
# quadratic attention term dominate); its entry is the compiled count.
FWD_FLOPS_PER_IMAGE = {
    # 1.11e9 = 2 * 0.56 GMACs (CIFAR-ResNet18).  Fwd-only drift vs the
    # compiler is ~15% (compiled fwd ~0.97e9/img at 32px) — the one
    # entry tests/test_flops_zoo.py carries a documented wider bound
    # for; the profile smoke's train-side drift stays ~7% because the
    # compiled bwd runs ~2.7x fwd, absorbing the overshoot.
    "resnet18-cifar": (1.11e9, 32),
    "resnet18": (3.64e9, 224),
    "resnet34": (7.34e9, 224),
    "resnet50": (8.2e9, 224),
    "resnet101": (15.6e9, 224),
    "resnet152": (23.0e9, 224),
    "inceptionv3": (11.4e9, 299),
    "efficientnet-b0": (0.78e9, 224),
    "efficientnet-b3": (3.6e9, 300),
    "efficientnet-b7": (74e9, 600),
    "vit-tiny": (6.3e9, 224),
    "vit-s16": (9.2e9, 224),
    "vit-b16": (35.2e9, 224),
    "vit-b32": (8.8e9, 224),
    "vit-l16": (123.2e9, 224),
    "vit-l32": (30.8e9, 224),
}


def analytic_flops_per_step(model_name: str, image_size: int,
                            global_batch: int,
                            train: bool = True) -> Optional[float]:
    """Analytic FLOPs of one step, or None for an unknown model.

    Longest-prefix match over FWD_FLOPS_PER_IMAGE, scaled by
    ``(image_size / canonical)^2`` (conv/attention cost is ~quadratic in
    side length; an approximation, stated as such in
    docs/observability.md — XLA's compiled cost analysis, when
    available, stays the bench headline's preferred source).
    """
    if not model_name or not global_batch:
        return None
    name = model_name.lower()
    best = None
    for key, (gf, base) in FWD_FLOPS_PER_IMAGE.items():
        if name.startswith(key) and (best is None or len(key) > len(best[0])):
            best = (key, gf, base)
    if best is None:
        return None
    _, gf, base = best
    scale = (float(image_size) / base) ** 2 if image_size else 1.0
    fwd = gf * scale * global_batch
    return 3.0 * fwd if train else fwd


_BUCKETS = ("productive", "input", "compile", "checkpoint", "skip",
            "rollback", "eval", "restart")


class GoodputTracker:
    """Wall-time classifier over bus events (see module docstring).

    Thread-safe: ``compile`` events arrive from whatever thread compiled
    (the serve batcher included) while ``step`` events come from the
    train loop.
    """

    def __init__(self, flops_per_step: Optional[float] = None,
                 peak_flops: float = 1e12, global_batch: int = 0,
                 compute_dtype: str = "") -> None:
        self._lock = threading.Lock()
        self.flops_per_step = flops_per_step
        self.peak = max(1.0, float(peak_flops))
        self.compute_dtype = str(compute_dtype)
        self.global_batch = int(global_batch)
        self._t0: Optional[float] = None
        self.buckets = {k: 0.0 for k in _BUCKETS}
        self.steps = 0
        self.skipped_est = 0.0   # estimated skipped steps (from streaks)
        self.compiles = 0        # backend_compile count
        self.restarts = 0        # supervisor restart count of this run
        self._pending_compile = 0.0
        self._step_total_s = 0.0  # for the rolling mean (skip estimate)
        self.ckpt_async_s = 0.0   # deferred commits (overlapped, not wall)

    # -- event intake --------------------------------------------------
    def start(self) -> None:
        """Open the measurement window (idempotent: first call wins, so
        a resumed fit() keeps its original origin)."""
        with self._lock:
            if self._t0 is None:
                self._t0 = time.monotonic()

    def on_event(self, ev) -> None:
        kind, d = ev.kind, ev.data
        with self._lock:
            if self._t0 is None:
                self._t0 = time.monotonic()
            if kind == "step":
                total = float(d.get("total_ms", 0.0)) / 1000.0
                data = min(float(d.get("data_ms", 0.0)) / 1000.0, total)
                attr = total - data
                c = min(self._pending_compile, attr)
                self._pending_compile -= c
                self.buckets["compile"] += c
                self.buckets["productive"] += attr - c
                self.buckets["input"] += data
                self.steps += 1
                self._step_total_s += total
            elif kind == "compile":
                dur = float(d.get("duration_s", 0.0))
                self._pending_compile += dur
                if str(d.get("key", "")).startswith("backend_compile"):
                    self.compiles += 1
            elif kind == "eval":
                dur = float(d.get("duration_s", 0.0))
                c = min(self._pending_compile, dur)
                self._pending_compile -= c
                self.buckets["compile"] += c
                self.buckets["eval"] += dur - c
            elif kind == "drain":
                # Post-loop blocking drain (break paths): device time of
                # the final dispatched step, after its step event closed
                # — productive, the same work just billed late.
                dur = float(d.get("duration_s", 0.0))
                c = min(self._pending_compile, dur)
                self._pending_compile -= c
                self.buckets["compile"] += c
                self.buckets["productive"] += dur - c
            elif kind == "checkpoint_commit":
                # A deferred (async) commit ran concurrently with compute
                # — it consumed no wall clock the step loop could have
                # used, so charging it to the 'checkpoint' bucket would
                # double-book seconds already in 'productive'.  Tracked
                # separately so report() still shows the overlapped work.
                if d.get("blocking", True):
                    self.buckets["checkpoint"] += float(
                        d.get("duration_s", 0.0))
                else:
                    self.ckpt_async_s += float(d.get("duration_s", 0.0))
            elif kind == "rollback":
                self.buckets["rollback"] += float(d.get("duration_s", 0.0))
            elif kind == "restart":
                # Supervised restart (runtime/supervisor.py): the
                # downtime — previous child's death through backoff,
                # respawn, re-init, restore — happened BEFORE this
                # process's measurement window opened. Extend the window
                # back over it and book it to 'restart', so a run that
                # lost 40s to a crash reports frac_restart instead of a
                # wall clock that silently forgot the outage.
                down = max(0.0, float(d.get("downtime_s", 0.0)))
                self.restarts = int(d.get("restart", self.restarts + 1))
                self._t0 -= down
                self.buckets["restart"] += down
            elif kind == "skip":
                # Streak delta observed at the deferred drain; charge the
                # skipped steps at the rolling mean step time and move
                # them out of 'productive' (they were booked there when
                # their step events arrived).
                delta = max(0, int(d.get("delta", 0)))
                if delta and self.steps:
                    est = delta * (self._step_total_s / self.steps)
                    est = min(est, self.buckets["productive"])
                    self.buckets["productive"] -= est
                    self.buckets["skip"] += est
                    self.skipped_est += delta

    # -- reads ---------------------------------------------------------
    def mfu(self, wall_s: Optional[float] = None) -> Optional[float]:
        """Running MFU: productive-step FLOPs / (peak * wall)."""
        if not self.flops_per_step:
            return None
        if wall_s is None:
            wall_s = (time.monotonic() - self._t0) if self._t0 else 0.0
        if wall_s <= 0:
            return None
        productive_steps = max(0.0, self.steps - self.skipped_est)
        return self.flops_per_step * productive_steps / (self.peak * wall_s)

    def report(self, step: Optional[int] = None) -> dict:
        """Snapshot: buckets (s), fractions of wall, accounted fraction,
        and MFU.  ``accounted_frac`` ~ 1.0 means the named buckets cover
        the wall clock (the tier-1 acceptance gate); the gap is reported
        honestly as ``other_s`` (setup, logging, epoch turnaround)."""
        with self._lock:
            wall = (time.monotonic() - self._t0) if self._t0 else 0.0
            named = sum(self.buckets.values()) + self._pending_compile
            out = {"wall_s": round(wall, 3), "steps": self.steps}
            if step is not None:
                out["step"] = int(step)
            buckets = dict(self.buckets)
            # Compile time not yet absorbed by a step/eval span (e.g. a
            # warmup compile before the loop) is still compile time.
            buckets["compile"] += self._pending_compile
            for k in _BUCKETS:
                out[f"{k}_s"] = round(buckets[k], 3)
            out["other_s"] = round(max(0.0, wall - named), 3)
            if wall > 0:
                for k in _BUCKETS:
                    out[f"frac_{k}"] = round(buckets[k] / wall, 4)
                out["frac_other"] = round(max(0.0, wall - named) / wall, 4)
                out["accounted_frac"] = round(min(named / wall, 1.0), 4)
            if self.global_batch:
                out["images"] = self.steps * self.global_batch
            out["skipped_steps_est"] = round(self.skipped_est, 1)
            out["compiles"] = self.compiles
            out["restarts"] = self.restarts
            out["checkpoint_async_s"] = round(self.ckpt_async_s, 3)
            if self.compute_dtype:
                out["compute_dtype"] = self.compute_dtype
            m = self.mfu(wall)
            if m is not None:
                out["mfu"] = round(m, 4)
            return out

    def summary_line(self) -> str:
        """One epoch-log line: the headline fractions."""
        r = self.report()
        parts = [f"wall {r['wall_s']:.1f}s"]
        for k in _BUCKETS + ("other",):
            f = r.get(f"frac_{k}")
            if f:
                parts.append(f"{k} {100.0 * f:.1f}%")
        if r.get("mfu") is not None:
            parts.append(f"mfu {r['mfu']:.4f}")
        return ", ".join(parts)
