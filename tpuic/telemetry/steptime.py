"""Per-step wall-clock breakdown: data-wait vs. dispatch vs. device.

The round-5 VERDICT called the cross-round ResNet MFU drift
*unfalsifiable* because nothing in-band recorded where step time goes;
this module is the in-band record.  The split uses only dispatch
timestamps plus the loop's existing deferred drain — the exact
discipline PR-2 established for the skip guard:

- **data wait**: time spent inside the loader iterator's ``__next__``
  (``wrap_epoch``).  Includes the first batch's device-cache upload and
  any producer-thread stall — the input-bound fraction FireCaffe-style
  accounting wants isolated (arXiv 1511.00175 §5).
- **dispatch**: the ``train_step`` call itself.  Under JAX's async
  dispatch this returns as soon as the work is enqueued, so in steady
  state it is microseconds; a blocking compile (first step, retrace)
  shows up here and the goodput tracker reattributes it using the
  ``compile`` events from the jax.monitoring bridge.
- **device**: the residual of the step's wall time — dominated by the
  deferred log drain blocking on metric handles (one interval behind,
  so the host is throttled to device speed) plus loop bookkeeping.

No new host syncs, no new compiles: everything here is
``time.perf_counter`` arithmetic (asserted in tests/test_telemetry.py
by counting ``jax.device_get`` calls and the jit cache size with
telemetry on vs. off).

Every completed step publishes one ``step`` event:
``{step, total_ms, data_ms, dispatch_ms, device_ms}``.  Percentile
summaries ride the shared ``tpuic.metrics.LatencyMeter`` — the same
primitive serve's queue-wait/latency stats and bench.py's per-step
spread use.
"""

from __future__ import annotations

import time
from typing import Iterable, Iterator, Optional

from tpuic.metrics.meters import LatencyMeter


class StepTimer:
    """Accumulates one step's phase timings and publishes the breakdown.

    Usage (tpuic/train/loop.py)::

        timer.epoch_start()
        it = timer.wrap_epoch(loader.epoch(e))   # times __next__ = data wait
        for step, batch in enumerate(it):
            timer.dispatch_start()
            state, metrics = train_step(state, batch)   # async dispatch
            timer.dispatch_end()
            ...deferred drain, bookkeeping...
            timer.step_end(global_step)
    """

    def __init__(self, bus=None, window: int = 4096) -> None:
        if bus is None:
            from tpuic.telemetry.events import bus as _global_bus
            bus = _global_bus
        self.bus = bus
        self.total = LatencyMeter(window)
        self.data_wait = LatencyMeter(window)
        self.dispatch = LatencyMeter(window)
        self.steps = 0
        self.last_step = 0  # last published global step number
        self._t_mark: Optional[float] = None
        self._data_s = 0.0
        self._dispatch_s = 0.0
        self._t_dispatch: Optional[float] = None

    # -- loop hooks ----------------------------------------------------
    def epoch_start(self) -> None:
        """Step-boundary reset: the first step's total is measured from
        here, so epoch setup (permutation, resident-cache upload inside
        the first ``__next__``) is attributed, not lost."""
        self._t_mark = time.perf_counter()
        self._data_s = 0.0
        self._dispatch_s = 0.0

    def wrap_epoch(self, it: Iterable) -> Iterator:
        """Pass-through iterator that accumulates time spent waiting on
        the loader into the upcoming step's data-wait."""
        it = iter(it)
        while True:
            t0 = time.perf_counter()
            try:
                item = next(it)
            except StopIteration:
                return
            self._data_s += time.perf_counter() - t0
            yield item

    def dispatch_start(self) -> None:
        self._t_dispatch = time.perf_counter()

    def dispatch_end(self) -> None:
        if self._t_dispatch is not None:
            self._dispatch_s += time.perf_counter() - self._t_dispatch
            self._t_dispatch = None

    def step_end(self, step: int) -> dict:
        """Close the step: compute the breakdown, publish the ``step``
        event, reset the accumulators.  Returns the breakdown dict."""
        now = time.perf_counter()
        if self._t_mark is None:
            self._t_mark = now
        total = max(0.0, now - self._t_mark)
        self._t_mark = now
        data = min(self._data_s, total)
        disp = min(self._dispatch_s, max(0.0, total - data))
        device = max(0.0, total - data - disp)
        self._data_s = 0.0
        self._dispatch_s = 0.0
        self.steps += 1
        self.last_step = int(step)
        self.total.update(total)
        self.data_wait.update(data)
        self.dispatch.update(disp)
        out = {"step": int(step),
               "total_ms": round(1000.0 * total, 3),
               "data_ms": round(1000.0 * data, 3),
               "dispatch_ms": round(1000.0 * disp, 3),
               "device_ms": round(1000.0 * device, 3)}
        self.bus.publish("step", **out)
        return out

    # -- reads ---------------------------------------------------------
    def mean_total_s(self) -> float:
        return self.total.total / self.total.count if self.total.count else 0.0

    def summary(self) -> dict:
        """Percentile summary over the window (shared-meter semantics:
        recent behavior, not lifetime)."""
        return {
            "steps": self.steps,
            "total_ms": self.total.percentiles_ms(),
            "data_ms": self.data_wait.percentiles_ms(),
            "dispatch_ms": self.dispatch.percentiles_ms(),
            "data_frac": (round(self.data_wait.total
                                / max(self.total.total, 1e-12), 4)
                          if self.total.count else None),
        }
