"""tpuic — TPU-native distributed image-classification training framework.

A ground-up JAX / XLA / pjit re-design of the capabilities of
``RanjanBalappa/pytorch-imageclassification-distributed`` (a PyTorch
DistributedDataParallel trainer over NCCL; see SURVEY.md for the full
structural analysis):

- ``tpuic.config``      — every constant the reference hard-codes, as dataclass fields
- ``tpuic.runtime``     — multi-host init + device-mesh construction (replaces
                          ``dist.init_process_group('nccl')``, reference train.py:102)
- ``tpuic.parallel``    — mesh/sharding helpers and collective utilities (replaces
                          reference ddp_utils.py)
- ``tpuic.data``        — ImageFolder pipeline: decode/resize/augment/normalize with
                          seeded RNG and per-host sharding (replaces reference
                          dp/loader.py + DistributedSampler)
- ``tpuic.models``      — Flax backbones (see ``tpuic.models.available_models()``)
                          + the MLP classifier head (replaces reference
                          nn/classifier.py)
- ``tpuic.train``       — compiled train/eval steps with cross-replica gradient and
                          BatchNorm reductions (replaces reference train.py:36-97 and
                          DDP/SyncBN, train.py:124,128)
- ``tpuic.checkpoint``  — best/latest checkpointing with lenient partial restore
                          (replaces reference train.py:131-188)
- ``tpuic.metrics``     — AverageMeter / accuracy / host-0 logging (replaces reference
                          utils.py)
- ``tpuic.ops``         — Pallas TPU kernels for fused hot ops
- ``tpuic.serve``       — dynamic-batching AOT inference engine (request
                          queue + micro-batcher, padding buckets, compiled-
                          executable cache; ``python -m tpuic.serve``)
"""

__version__ = "0.1.0"

from tpuic.config import Config  # noqa: F401

# Heavyweight entry points resolve lazily (PEP 562) so `import tpuic`
# stays cheap (Config is pure dataclasses; Trainer pulls jax/flax).
_LAZY = {
    "Trainer": ("tpuic.train.loop", "Trainer"),
    "create_model": ("tpuic.models", "create_model"),
    "available_models": ("tpuic.models", "available_models"),
    "run_predict": ("tpuic.predict", "run_predict"),
    "InferenceEngine": ("tpuic.serve", "InferenceEngine"),
}


def __getattr__(name):
    if name in _LAZY:
        import importlib
        mod, attr = _LAZY[name]
        return getattr(importlib.import_module(mod), attr)
    raise AttributeError(f"module 'tpuic' has no attribute '{name}'")


def __dir__():
    return sorted(list(globals()) + list(_LAZY))
