"""ImageFolder dataset: index + per-sample load.

Re-design of reference ``ImageDataset`` (dp/loader.py:15-61):

- Layout: ``data_dir/{fold}/{class_name}/{image}.png`` globbed the same way
  (dp/loader.py:20-21).
- Class mapping: the reference initializes ``self.mapping = {}`` and never
  populates it (dp/loader.py:29) — a latent bug that makes ``num_classes`` 0
  and ``__getitem__`` raise. The intended behavior, built here: class names are
  the sorted subdirectory names of the TRAIN fold, mapped to contiguous ids
  (sorted => identical on every host; the train fold is canonical so val
  shares the mapping).
- ``image_id``: filename stem (dp/loader.py:43 strips '.png'; here any
  extension is stripped).
- The reference shuffles its file list unseeded, per-rank, at init
  (dp/loader.py:23) — ranks disagree about the index order, so
  DistributedSampler shards overlap/miss samples. Here the index order is
  deterministic (sorted); shuffling belongs to the sampler (pipeline.py) with
  an epoch-folded global seed.
"""

from __future__ import annotations

import os
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np
from PIL import Image

from tpuic.config import DataConfig
from tpuic.data import transforms as T

_IMAGE_EXTS = {".png", ".jpg", ".jpeg", ".bmp", ".ppm", ".webp"}


def _is_image(path: str) -> bool:
    return os.path.splitext(path)[1].lower() in _IMAGE_EXTS


class ImageFolderDataset:
    def __init__(self, data_dir: str, fold: str, resize_size: int,
                 cfg: Optional[DataConfig] = None,
                 class_to_idx: Optional[Dict[str, int]] = None,
                 allow_unlabeled: bool = False) -> None:
        self.cfg = cfg or DataConfig()
        self.data_dir = data_dir
        self.fold = fold
        self.train = fold == "train"
        self.resize_size = resize_size
        root = os.path.join(data_dir, fold)
        if not os.path.isdir(root):
            raise FileNotFoundError(f"no such fold: {root}")
        # Canonical class mapping from the train fold (see module docstring).
        if class_to_idx is None:
            map_root = os.path.join(data_dir, "train")
            if not os.path.isdir(map_root):
                map_root = root
            classes = sorted(d for d in os.listdir(map_root)
                             if os.path.isdir(os.path.join(map_root, d)))
            class_to_idx = {c: i for i, c in enumerate(classes)}
        self.class_to_idx: Dict[str, int] = dict(class_to_idx)
        self.classes: List[str] = sorted(self.class_to_idx,
                                         key=self.class_to_idx.get)
        samples: List[Tuple[str, int]] = []
        for cls in sorted(os.listdir(root)):
            cdir = os.path.join(root, cls)
            if not os.path.isdir(cdir) or cls not in self.class_to_idx:
                continue
            for fname in sorted(os.listdir(cdir)):
                fpath = os.path.join(cdir, fname)
                if _is_image(fpath):
                    samples.append((fpath, self.class_to_idx[cls]))
        # Flat (unlabeled) fold: images directly under the fold dir, no
        # class subdirectories. Label is -1. Opt-in (tpuic.predict passes
        # allow_unlabeled=True): training on label -1 would silently
        # produce a zero one-hot target and a degenerate loss, so for the
        # Trainer a flat fold stays the hard error it always was.
        self.labeled = bool(samples)
        if not samples and allow_unlabeled:
            samples = [(os.path.join(root, f), -1)
                       for f in sorted(os.listdir(root))
                       if _is_image(os.path.join(root, f))]
        if not samples:
            raise ValueError(f"no images under {root}")
        self.samples = samples

    def __len__(self) -> int:
        return len(self.samples)

    @property
    def num_classes(self) -> int:
        """Reference dp/loader.py:34-36 (fixed: mapping is populated)."""
        return len(self.class_to_idx)

    def image_id(self, index: int) -> str:
        path, _ = self.samples[index]
        return os.path.splitext(os.path.basename(path))[0]

    def class_counts(self) -> np.ndarray:
        """[num_classes] int64 sample count per class id."""
        labels = np.asarray([lb for _, lb in self.samples])
        return np.bincount(labels[labels >= 0],
                           minlength=self.num_classes).astype(np.int64)

    def load(self, index: int, rng: Optional[np.random.Generator] = None
             ) -> Tuple[np.ndarray, int, str]:
        """Decode → RGB → resize → [augment] → normalize. Returns
        (HWC float32 image, label, image_id) — reference dp/loader.py:39-61,
        minus the CHW transpose (TPU convs are NHWC).

        Augment decisions are drawn ONCE (transforms.draw_augment, the single
        source of the RNG stream) and then executed either by the fused
        native pass (tpuic/native, when built and cfg.native) or by the NumPy
        transforms — identical output per (seed, epoch, index) either way."""
        path, label = self.samples[index]
        with Image.open(path) as im:
            img = np.asarray(im.convert("RGB") if im.mode not in ("RGB",)
                             else im)
        img = T.to_rgb(img)
        c = self.cfg
        if self.train and rng is not None:
            k, vflip, hflip, color, factor = T.draw_augment(
                rng, p_vflip=c.p_vflip, p_hflip=c.p_hflip,
                p_saturation=c.p_saturation, p_brightness=c.p_brightness,
                p_contrast=c.p_contrast, jitter_lo=c.jitter_lo,
                jitter_hi=c.jitter_hi)
        else:
            k = vflip = hflip = color = 0
            factor = 1.0
        if c.native:
            from tpuic import native
            out = native.prep_image(
                np.ascontiguousarray(img), self.resize_size, rot_k=k,
                vflip=vflip, hflip=hflip, color_op=color, factor=factor,
                mean=c.mean, std=c.std)
            if out is not None:
                return out, label, self.image_id(index)
        img = T.resize_nearest(img, self.resize_size)
        img = T.apply_augment(img, k, vflip, hflip, color, factor)
        img = T.normalize(img, c.mean, c.std)
        return img, label, self.image_id(index)
