"""ImageFolder dataset: index + per-sample load.

Re-design of reference ``ImageDataset`` (dp/loader.py:15-61):

- Layout: ``data_dir/{fold}/{class_name}/{image}.png`` globbed the same way
  (dp/loader.py:20-21).
- Class mapping: the reference initializes ``self.mapping = {}`` and never
  populates it (dp/loader.py:29) — a latent bug that makes ``num_classes`` 0
  and ``__getitem__`` raise. The intended behavior, built here: class names are
  the sorted subdirectory names of the TRAIN fold, mapped to contiguous ids
  (sorted => identical on every host; the train fold is canonical so val
  shares the mapping).
- ``image_id``: filename stem (dp/loader.py:43 strips '.png'; here any
  extension is stripped).
- The reference shuffles its file list unseeded, per-rank, at init
  (dp/loader.py:23) — ranks disagree about the index order, so
  DistributedSampler shards overlap/miss samples. Here the index order is
  deterministic (sorted); shuffling belongs to the sampler (pipeline.py) with
  an epoch-folded global seed.
- **Sample quarantine** (docs/robustness.md): a decode failure (truncated
  JPEG, bit-rot, file mid-copy) used to propagate out of the Loader's
  producer thread and abort the whole epoch. Now ``load`` retries with a
  short backoff (the transient-read case), then substitutes a
  deterministic same-class replacement sample and counts the event
  (``quarantine_count`` / ``quarantined``) — one corrupt file out of a
  million degrades the epoch by one sample instead of killing the run.
  ``DataConfig.quarantine=False`` restores fail-fast propagation.
"""

from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional, Tuple

import numpy as np
from PIL import Image

from tpuic.config import DataConfig
from tpuic.data import transforms as T
from tpuic.runtime import faults as _faults

# Everything PIL raises for unreadable/corrupt image bytes:
# UnidentifiedImageError and "image file is truncated" are OSError
# subclasses; zlib/decoder failures surface as ValueError; ancient PIL
# raised SyntaxError for broken PNG chunks.
_DECODE_ERRORS = (OSError, ValueError, SyntaxError)


def quarantined_decode(dataset, index: int, decode):
    """THE quarantine policy, shared by the per-sample path (``load``) and
    the pack build (pack.py): try ``decode(index)``; on a decode error
    retry ``cfg.quarantine_retries`` times with ``cfg.quarantine_backoff_s``
    between attempts (a file mid-copy becomes readable), then — with
    ``cfg.quarantine`` on — record the event and walk up to 8 same-class
    replacement candidates (corruption is correlated: interrupted copies
    land on neighbors, so the first candidate may be corrupt too).

    Returns ``(value, actual_index)`` — the caller takes the REPLACEMENT's
    label/id when ``actual_index != index``. Re-raises the original error
    when quarantine is off or every candidate fails. Only
    ``_DECODE_ERRORS`` engage the policy: programming errors (bad shapes,
    type bugs) propagate immediately instead of masquerading as mass
    corruption."""
    cfg = dataset.cfg
    try:
        return decode(index), index
    except _DECODE_ERRORS:
        for _ in range(max(0, int(cfg.quarantine_retries))):
            time.sleep(max(0.0, float(cfg.quarantine_backoff_s)))
            try:
                return decode(index), index
            except _DECODE_ERRORS:
                continue
        if not cfg.quarantine:
            raise
        dataset._record_quarantine(dataset.samples[index][0])
        j = index
        for _ in range(8):
            j = dataset.quarantine_replacement(j)
            try:
                return decode(j), j
            except _DECODE_ERRORS:
                continue
        raise  # every candidate corrupt: surface the original error

_IMAGE_EXTS = {".png", ".jpg", ".jpeg", ".bmp", ".ppm", ".webp"}


def _is_image(path: str) -> bool:
    return os.path.splitext(path)[1].lower() in _IMAGE_EXTS


class ImageFolderDataset:
    def __init__(self, data_dir: str, fold: str, resize_size: int,
                 cfg: Optional[DataConfig] = None,
                 class_to_idx: Optional[Dict[str, int]] = None,
                 allow_unlabeled: bool = False) -> None:
        self.cfg = cfg or DataConfig()
        self.data_dir = data_dir
        self.fold = fold
        self.train = fold == "train"
        self.resize_size = resize_size
        root = os.path.join(data_dir, fold)
        if not os.path.isdir(root):
            raise FileNotFoundError(f"no such fold: {root}")
        # Canonical class mapping from the train fold (see module docstring).
        if class_to_idx is None:
            map_root = os.path.join(data_dir, "train")
            if not os.path.isdir(map_root):
                map_root = root
            classes = sorted(d for d in os.listdir(map_root)
                             if os.path.isdir(os.path.join(map_root, d)))
            class_to_idx = {c: i for i, c in enumerate(classes)}
        self.class_to_idx: Dict[str, int] = dict(class_to_idx)
        self.classes: List[str] = sorted(self.class_to_idx,
                                         key=self.class_to_idx.get)
        samples: List[Tuple[str, int]] = []
        for cls in sorted(os.listdir(root)):
            cdir = os.path.join(root, cls)
            if not os.path.isdir(cdir) or cls not in self.class_to_idx:
                continue
            for fname in sorted(os.listdir(cdir)):
                fpath = os.path.join(cdir, fname)
                if _is_image(fpath):
                    samples.append((fpath, self.class_to_idx[cls]))
        # Flat (unlabeled) fold: images directly under the fold dir, no
        # class subdirectories. Label is -1. Opt-in (tpuic.predict passes
        # allow_unlabeled=True): training on label -1 would silently
        # produce a zero one-hot target and a degenerate loss, so for the
        # Trainer a flat fold stays the hard error it always was.
        self.labeled = bool(samples)
        if not samples and allow_unlabeled:
            samples = [(os.path.join(root, f), -1)
                       for f in sorted(os.listdir(root))
                       if _is_image(os.path.join(root, f))]
        if not samples:
            raise ValueError(f"no images under {root}")
        self.samples = samples
        # Quarantine bookkeeping: total replacement events and per-path
        # counts (a path appearing here means its bytes failed to decode
        # after retries and a substitute was served). Lock because loads
        # run on the Loader's worker threads.
        self.quarantine_count = 0
        self.quarantined: Dict[str, int] = {}
        self._quarantine_lock = threading.Lock()

    def __len__(self) -> int:
        return len(self.samples)

    @property
    def num_classes(self) -> int:
        """Reference dp/loader.py:34-36 (fixed: mapping is populated)."""
        return len(self.class_to_idx)

    def image_id(self, index: int) -> str:
        path, _ = self.samples[index]
        return os.path.splitext(os.path.basename(path))[0]

    def class_counts(self) -> np.ndarray:
        """[num_classes] int64 sample count per class id."""
        labels = np.asarray([lb for _, lb in self.samples])
        return np.bincount(labels[labels >= 0],
                           minlength=self.num_classes).astype(np.int64)

    def _decode(self, path: str) -> np.ndarray:
        with Image.open(path) as im:
            return np.asarray(im.convert("RGB") if im.mode not in ("RGB",)
                              else im)

    def _decode_sized(self, index: int) -> np.ndarray:
        """Decode sample ``index`` — through the native core when built
        (``native.decode_resize``: libjpeg/libpng + the SAME
        nearest-resize index math as ``transforms.resize_nearest``, so
        PNG output is bitwise the PIL+NumPy path's), else PIL at full
        resolution (the downstream ``resize_nearest`` no-ops when the
        native path already returned target-size pixels).

        This is the prefetch-worker decode (Loader workers call ``load``
        off-thread): with the native core the per-sample cost drops to
        one C decode+gather, so the telemetry ``input`` bucket on the
        decode (--no-pack) path shrinks toward zero
        (perf/native_prefetch.json).  JPEG decodes DCT-scaled — the
        same pixels the packed cache (pack.py) already serves.  A
        corrupt/truncated file makes the native decoder return None and
        the PIL fallback raise, so the quarantine ladder engages
        exactly as on the pure-NumPy path (tests/test_native.py)."""
        path = self.samples[index][0]
        if self.cfg.native:
            from tpuic import native
            if native.decode_available():
                try:
                    with open(path, "rb") as f:
                        data = f.read()
                except OSError:
                    data = b""
                if data:
                    out = native.decode_resize(data, self.resize_size)
                    if out is not None:
                        return out
        return self._decode(path)

    def quarantine_replacement(self, index: int) -> int:
        """Deterministic substitute for a sample whose file won't decode:
        the next index (cyclic) carrying the SAME label — the label stays
        honest and the batch stays in-distribution — falling back to the
        plain next index for a single-sample class (its real label rides
        along, so training never sees a mislabeled row)."""
        label = self.samples[index][1]
        n = len(self.samples)
        for off in range(1, n):
            j = (index + off) % n
            if self.samples[j][1] == label:
                return j
        return (index + 1) % n

    def _record_quarantine(self, path: str) -> None:
        with self._quarantine_lock:
            self.quarantine_count += 1
            self.quarantined[path] = self.quarantined.get(path, 0) + 1
            count = self.quarantine_count
        # Typed event (docs/observability.md): fires from the producer
        # thread at the moment of replacement, so the TensorBoard bridge
        # and JSONL sink see corruption when it happens, not only at the
        # trainer's per-epoch summary line.
        from tpuic.telemetry.events import publish as _tm_publish
        _tm_publish("quarantine", path=path, count=count)

    def load(self, index: int, rng: Optional[np.random.Generator] = None
             ) -> Tuple[np.ndarray, int, str]:
        """Decode → RGB → resize → [augment] → normalize. Returns
        (HWC float32 image, label, image_id) — reference dp/loader.py:39-61,
        minus the CHW transpose (TPU convs are NHWC).

        Augment decisions are drawn ONCE (transforms.draw_augment, the single
        source of the RNG stream) and then executed either by the fused
        native pass (tpuic/native, when built and cfg.native) or by the NumPy
        transforms — identical output per (seed, epoch, index) either way.

        An undecodable file goes through ``quarantined_decode``: retry with
        backoff, then serve a deterministic same-class replacement — its
        image, ITS label, its id — and count the event. The augment RNG
        stream is the caller's (seed, epoch, index) generator either way,
        so the substitution is bitwise deterministic too."""
        def _decode_index(i: int) -> np.ndarray:
            # Deterministic injection point ('decode_error' keyed by
            # dataset index) — a corrupt file without a corrupt file.
            # Checked per ATTEMPT: armed without a times cap it models
            # persistent corruption (retries fail too -> quarantine);
            # armed with times=1 it models a transient read (the retry
            # recovers).
            if _faults.fire("decode_error", step=i):
                raise OSError(f"injected decode error for index {i}")
            return self._decode_sized(i)

        img, index = quarantined_decode(self, index, _decode_index)
        path, label = self.samples[index]
        c = self.cfg
        img = T.to_rgb(img)
        if self.train and rng is not None:
            k, vflip, hflip, color, factor = T.draw_augment(
                rng, p_vflip=c.p_vflip, p_hflip=c.p_hflip,
                p_saturation=c.p_saturation, p_brightness=c.p_brightness,
                p_contrast=c.p_contrast, jitter_lo=c.jitter_lo,
                jitter_hi=c.jitter_hi)
        else:
            k = vflip = hflip = color = 0
            factor = 1.0
        if c.native:
            from tpuic import native
            out = native.prep_image(
                np.ascontiguousarray(img), self.resize_size, rot_k=k,
                vflip=vflip, hflip=hflip, color_op=color, factor=factor,
                mean=c.mean, std=c.std)
            if out is not None:
                return out, label, self.image_id(index)
        img = T.resize_nearest(img, self.resize_size)
        img = T.apply_augment(img, k, vflip, hflip, color, factor)
        img = T.normalize(img, c.mean, c.std)
        return img, label, self.image_id(index)
