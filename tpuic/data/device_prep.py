"""Device-side augmentation + normalization (TPU does the per-epoch math).

With the packed uint8 cache (tpuic/data/pack.py) the host's per-epoch work
is reduced to batch assembly; the whole per-sample transform chain of the
reference — rot90^k / vflip / hflip (dp/loader.py:63-71), the if/elif color
jitter (dp/loader.py:74-81), and /255 + ImageNet standardization
(dp/loader.py:86-91) — runs on the TPU as one jitted elementwise program
over the batch. This also cuts H2D traffic 4x (uint8 ships instead of
float32).

Augmentation *decisions* are still drawn on the host from the
(seed, epoch, index) RNG stream (transforms.draw_augment — the single
source of truth shared with the NumPy and native paths), so a sample's
augmentation is identical no matter which path executed it. This module
only *applies* pre-drawn decisions, vectorized per sample:

- geometry: the four rot90 variants are computed batch-wise (transpose +
  reverse are free layout ops for XLA) and selected per sample, then
  conditional v/h flips — a permutation, bitwise-equal to the NumPy path.
- color: same f32 arithmetic as transforms.adjust_* (clip to [0,255]);
  reduction order in the contrast mean may differ from NumPy's pairwise
  sums at the last-ulp level (tests/test_pack.py::
  test_device_prep_matches_numpy_all_paths pins the tolerance).
- normalize: x/255 (true division), then (x-mean)/std, f32.
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np

from tpuic.data.transforms import IMAGENET_MEAN, IMAGENET_STD, _LUMA


def apply_batch_augment(images_u8: jnp.ndarray, params: Dict[str, jnp.ndarray],
                        mean=None, std=None,
                        out_dtype=jnp.float32) -> jnp.ndarray:
    """[B,S,S,3] uint8 + per-sample params -> [B,S,S,3] normalized float.

    params: {'rot': [B] i32 (k in 0..3), 'vflip': [B] i32, 'hflip': [B] i32,
    'color': [B] i32 (0 none / 1 sat / 2 bright / 3 contrast),
    'factor': [B] f32}. Traced; call under jit (make_device_prep)."""
    x = images_u8.astype(jnp.float32)
    rot = params["rot"].astype(jnp.int32)[:, None, None, None]
    # np.rot90(m, k, axes=(0,1)) parity: out_k[i,j] selected per sample.
    xt = jnp.swapaxes(x, 1, 2)
    r1 = jnp.flip(xt, axis=1)                 # out[i,j] = m[j, S-1-i]
    r2 = jnp.flip(jnp.flip(x, axis=1), axis=2)
    r3 = jnp.flip(xt, axis=2)                 # out[i,j] = m[S-1-j, i]
    g = jnp.where(rot == 1, r1, jnp.where(rot == 2, r2,
                                          jnp.where(rot == 3, r3, x)))
    vf = params["vflip"].astype(bool)[:, None, None, None]
    hf = params["hflip"].astype(bool)[:, None, None, None]
    g = jnp.where(vf, jnp.flip(g, axis=1), g)
    g = jnp.where(hf, jnp.flip(g, axis=2), g)

    color = params["color"].astype(jnp.int32)[:, None, None, None]
    factor = params["factor"].astype(jnp.float32)[:, None, None, None]
    luma = jnp.asarray(_LUMA, jnp.float32)
    gray = jnp.sum(g * luma, axis=-1, keepdims=True)
    sat = jnp.clip(gray + (g - gray) * factor, 0.0, 255.0)
    bright = jnp.clip(g * factor, 0.0, 255.0)
    gmean = jnp.mean(g, axis=(1, 2, 3), keepdims=True)
    contrast = jnp.clip(gmean + (g - gmean) * factor, 0.0, 255.0)
    y = jnp.where(color == 1, sat, jnp.where(color == 2, bright,
                                             jnp.where(color == 3, contrast,
                                                       g)))
    mean = jnp.asarray(IMAGENET_MEAN if mean is None else mean, jnp.float32)
    std = jnp.asarray(IMAGENET_STD if std is None else std, jnp.float32)
    y = (y / 255.0 - mean) / std
    return y.astype(out_dtype)


def identity_params(batch: int) -> Dict[str, np.ndarray]:
    """No-op augmentation (val / non-train folds): normalize only."""
    return {
        "rot": np.zeros((batch,), np.int32),
        "vflip": np.zeros((batch,), np.int32),
        "hflip": np.zeros((batch,), np.int32),
        "color": np.zeros((batch,), np.int32),
        "factor": np.ones((batch,), np.float32),
    }


PARAM_KEYS = ("rot", "vflip", "hflip", "color", "factor")


def pack_params(params: Dict[str, np.ndarray]) -> np.ndarray:
    """[B,5] f32 row per sample — ONE host->device transfer instead of five
    (per-transfer RPC latency dominates on tunneled dev hosts)."""
    return np.stack([np.asarray(params[k], np.float32)
                     for k in PARAM_KEYS], axis=1)


def _unpack_params(packed: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    cols = {k: packed[:, i] for i, k in enumerate(PARAM_KEYS)}
    return {k: (cols[k].astype(jnp.int32) if k != "factor" else cols[k])
            for k in PARAM_KEYS}


def make_device_prep(mean=None, std=None, out_dtype=jnp.float32,
                     sharding: Optional[jax.sharding.NamedSharding] = None):
    """Jitted (images_u8, packed_params [B,5] f32) -> normalized batch.

    ``sharding``: the batch's data-axis NamedSharding under a mesh — the
    prep is elementwise per sample, so it runs shard-local with no
    collectives."""
    fn = lambda imgs, packed: apply_batch_augment(
        imgs, _unpack_params(packed), mean=mean, std=std,
        out_dtype=out_dtype)
    if sharding is None:
        return jax.jit(fn)
    return jax.jit(fn, in_shardings=(sharding, sharding),
                   out_shardings=sharding, donate_argnums=(0,))


def make_resident_prep(mean=None, std=None, out_dtype=jnp.float32,
                       sharding: Optional[jax.sharding.NamedSharding] = None,
                       replicated=None):
    """Jitted (dataset_u8 [N,S,S,3], indices [B] i32, packed_params) ->
    normalized batch, for the DEVICE-RESIDENT dataset cache.

    The whole packed uint8 dataset lives in HBM (uploaded once, replicated
    under a mesh); a batch costs one [B]-row gather + augment + normalize
    ON DEVICE. Per-step host->device traffic is the index/param vectors —
    a few KB — instead of the image bytes. This is what makes the training
    loop immune to host-link bandwidth (measured round 3: the tunneled dev
    chip sustains only ~35 MB/s H2D under concurrent compute, capping a
    per-batch-upload loop at ~230 img/s vs the chip's 2,674)."""
    def fn(data, idx, packed):
        imgs = jnp.take(data, idx, axis=0)
        return apply_batch_augment(imgs, _unpack_params(packed), mean=mean,
                                   std=std, out_dtype=out_dtype)
    if sharding is None:
        return jax.jit(fn)
    return jax.jit(fn, in_shardings=(replicated, sharding, sharding),
                   out_shardings=sharding)
