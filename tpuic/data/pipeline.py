"""Per-host sharded input pipeline with threaded prefetch.

The TPU-native replacement for the reference's
``DataLoader(num_workers=6, pin_memory=True) + DistributedSampler``
(train.py:112-118, SURVEY.md §2b):

- **Sampler**: one global, epoch-seeded permutation shared by every host
  (``set_epoch`` semantics of train.py:164, minus the reference's per-rank
  unseeded pre-shuffle bug, dp/loader.py:23). The index list is padded by
  wrapping to a multiple of the global batch — like DistributedSampler — but
  padded positions carry ``mask=0`` so eval reductions stay exact instead of
  double-counting duplicates.
- **Workers**: a thread pool decodes/augments samples (PIL/NumPy release the
  GIL for the heavy parts); a producer thread assembles batches and keeps a
  bounded prefetch queue ahead of the device — the analogue of pinned-memory
  prefetch, feeding ``jax.make_array_from_process_local_data`` so each host
  only materializes its own shard of the global batch.
- Per-sample augmentation RNG is ``(seed, epoch, global_index)``-derived:
  bitwise reproducible regardless of worker count or scheduling.
"""

from __future__ import annotations

import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from typing import Dict, Iterator, List, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuic.data.folder import ImageFolderDataset


class Batch(dict):
    """dict with .image_ids attached (host-side strings never hit the device;
    the reference ships image_id through the tensor path, dp/loader.py:61)."""
    image_ids: List[str]


def _epoch_indices(n: int, epoch: int, seed: int, shuffle: bool,
                   global_batch: int) -> np.ndarray:
    """Global order for one epoch, padded by wrapping to a batch multiple.

    Returns int64 array whose length is a multiple of global_batch; entries
    are sample indices, with a parallel validity implied by position >= n
    after an argsort-free wrap (the caller masks positions >= n of the
    *unpadded* order)."""
    if shuffle:
        rng = np.random.default_rng(np.random.SeedSequence([seed, epoch]))
        order = rng.permutation(n)
    else:
        order = np.arange(n)
    pad = (-n) % global_batch
    if pad:
        order = np.concatenate([order, order[:pad]])
    return order, n  # (padded order, number of valid entries)


class Loader:
    """Iterates globally-sharded device batches for one process.

    global_batch must be divisible by (process_count * local shard layout);
    each host materializes rows [rank*local : (rank+1)*local] of every global
    batch, where local = global_batch / process_count.
    """

    def __init__(self, dataset: ImageFolderDataset, global_batch: int,
                 mesh: Optional[Mesh] = None, shuffle: Optional[bool] = None,
                 seed: int = 0, num_workers: int = 6, prefetch: int = 2,
                 drop_last: bool = False,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None) -> None:
        self.dataset = dataset
        self.global_batch = int(global_batch)
        self.mesh = mesh
        self.shuffle = dataset.train if shuffle is None else shuffle
        self.seed = seed
        self.num_workers = max(1, num_workers)
        self.prefetch = max(1, prefetch)
        self.drop_last = drop_last
        # Injectable host topology (defaults to the live JAX process grid):
        # multi-host shard math is pure in (rank, count), so tests simulate
        # N ranks in one process and assert shard disjointness/coverage —
        # the bug class the reference actually shipped (dp/loader.py:23).
        self.process_index = (jax.process_index() if process_index is None
                              else int(process_index))
        self.process_count = (jax.process_count() if process_count is None
                              else int(process_count))
        if self.global_batch % self.process_count:
            raise ValueError("global batch must divide across processes")
        self.local_batch = self.global_batch // self.process_count
        self._sharding = (NamedSharding(mesh, P("data")) if mesh is not None
                          else None)

    def __len__(self) -> int:
        n = len(self.dataset)
        if self.drop_last:
            return n // self.global_batch
        return -(-n // self.global_batch)

    def steps_per_epoch(self) -> int:
        return len(self)

    def _load_one(self, position: int, index: int, valid: bool, epoch: int):
        rng = np.random.default_rng(
            np.random.SeedSequence([self.seed, epoch, int(index)]))
        img, label, image_id = self.dataset.load(int(index), rng)
        return position, img, label, image_id, valid

    def epoch(self, epoch: int) -> Iterator[Batch]:
        """Yield batches for this epoch (the set_epoch(e) equivalent)."""
        n = len(self.dataset)
        order, n_valid = _epoch_indices(n, epoch, self.seed, self.shuffle,
                                        self.global_batch)
        n_batches = len(order) // self.global_batch
        if self.drop_last and n % self.global_batch:
            n_batches -= 1
        out_q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()

        def _put(item) -> bool:
            """Bounded put that aborts when the consumer abandons the epoch
            (otherwise the producer would park forever in a full queue)."""
            while not stop.is_set():
                try:
                    out_q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                _produce_loop()
                _put(None)
            except BaseException as e:  # surface worker errors to the consumer
                _put(e)

        def _produce_loop():
            with ThreadPoolExecutor(self.num_workers) as pool:
                for b in range(n_batches):
                    if stop.is_set():
                        break
                    lo = b * self.global_batch + self.process_index * self.local_batch
                    futs = []
                    for i in range(self.local_batch):
                        gpos = lo + i
                        futs.append(pool.submit(
                            self._load_one, i, order[gpos],
                            gpos < n_valid, epoch))
                    imgs = np.empty((self.local_batch,
                                     self.dataset.resize_size,
                                     self.dataset.resize_size, 3), np.float32)
                    labels = np.zeros((self.local_batch,), np.int32)
                    mask = np.zeros((self.local_batch,), np.float32)
                    ids = [""] * self.local_batch
                    for f in futs:
                        pos, img, label, image_id, valid = f.result()
                        imgs[pos] = img
                        labels[pos] = label
                        mask[pos] = 1.0 if valid else 0.0
                        ids[pos] = image_id
                    if not _put((imgs, labels, mask, ids)):
                        return

        producer = threading.Thread(target=produce, daemon=True)
        producer.start()
        try:
            # Device-side double buffering: batch N+1's host->device transfer
            # is dispatched (jax transfers are async) before batch N is
            # yielded, so H2D overlaps the consumer's step instead of
            # sitting on its critical path.
            pending: Optional[Batch] = None
            while True:
                item = out_q.get()
                if item is None:
                    break
                if isinstance(item, BaseException):
                    raise item
                imgs, labels, mask, ids = item
                batch = Batch(image=self._to_global(imgs),
                              label=self._to_global(labels),
                              mask=self._to_global(mask))
                batch.image_ids = ids
                if pending is not None:
                    yield pending
                pending = batch
            if pending is not None:
                yield pending
        finally:
            stop.set()
            producer.join(timeout=5.0)

    def _to_global(self, local: np.ndarray):
        if self._sharding is None:
            return local
        return jax.make_array_from_process_local_data(self._sharding, local)
