"""Per-host sharded input pipeline with threaded prefetch.

The TPU-native replacement for the reference's
``DataLoader(num_workers=6, pin_memory=True) + DistributedSampler``
(train.py:112-118, SURVEY.md §2b):

- **Sampler**: one global, epoch-seeded permutation shared by every host
  (``set_epoch`` semantics of train.py:164, minus the reference's per-rank
  unseeded pre-shuffle bug, dp/loader.py:23). The index list is padded by
  wrapping to a multiple of the global batch — like DistributedSampler — but
  padded positions carry ``mask=0`` so eval reductions stay exact instead of
  double-counting duplicates.
- **Workers**: a thread pool decodes/augments samples (PIL/NumPy release the
  GIL for the heavy parts); a producer thread assembles batches and keeps a
  bounded prefetch queue ahead of the device — the analogue of pinned-memory
  prefetch, feeding ``jax.make_array_from_process_local_data`` so each host
  only materializes its own shard of the global batch.
- **Packed fast path** (round 3): when the dataset is a
  tpuic.data.pack.PackedDataset (memory-mapped uint8 cache), the producer
  skips decode entirely — a sample is one memmap row copy — and ships the
  batch to the device as uint8 (4x less H2D than float32) together with
  per-sample augmentation decisions; rot90/flips/jitter/normalize run ON
  the TPU (tpuic/data/device_prep.py). This is how a 1-core host (measured
  nproc=1) feeds a v5e chip: per-epoch host work is batch assembly only.
- Per-sample augmentation RNG is ``(seed, epoch, global_index)``-derived:
  bitwise reproducible regardless of worker count, scheduling, or which
  path (NumPy / native C++ / device) applied it.
"""

from __future__ import annotations

import os
import queue
import threading
from concurrent.futures import ThreadPoolExecutor
from functools import partial
from typing import Iterator, List, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from tpuic.data.folder import ImageFolderDataset

# Resident-cache uploads go to the device in bounded slices. One giant
# device_put of the whole uint8 dataset is a single multi-hundred-MB
# transfer; on a slow/flaky host->device link (the tunneled dev platform)
# that is the observed wedge trigger. Chunks are written into the final
# buffer in place (donated updates, synchronized per chunk), so the device
# peak stays at data_bytes + one chunk — see _upload_resident_chunked.
_UPLOAD_CHUNK_BYTES = int(os.environ.get("TPUIC_UPLOAD_CHUNK_MB", "64")) << 20


@partial(jax.jit, donate_argnums=(0,))
def _write_chunk(buf, chunk, start):
    return jax.lax.dynamic_update_slice_in_dim(buf, chunk, start, axis=0)


def _upload_resident_chunked(arr) -> jax.Array:
    """Single-device upload of a [N, ...] host array in ~chunk-sized slices.

    ``arr`` may be a np.memmap (the packed cache) — slices are materialized
    one chunk at a time, so host RSS stays bounded too. Chunks are written
    into a preallocated buffer through a donated update, so the peak device
    footprint is data_bytes + ONE chunk (the r3 concatenate version held
    every chunk alive while building the copy — a transient 2x peak the
    resident-cache fit check didn't budget for; ADVICE r3)."""
    import jax.numpy as jnp

    row_bytes = max(1, int(arr.nbytes // max(1, len(arr))))
    rows = max(1, _UPLOAD_CHUNK_BYTES // row_bytes)
    if len(arr) <= rows:
        return jax.device_put(np.ascontiguousarray(arr))
    out = jnp.zeros(arr.shape, arr.dtype)
    for lo in range(0, len(arr), rows):
        chunk = jax.device_put(np.ascontiguousarray(arr[lo:lo + rows]))
        # start is a traced scalar: one compile for full chunks, one for
        # the tail, regardless of chunk count.
        out = _write_chunk(out, chunk, np.int32(lo))
        # Synchronize per chunk: async dispatch would otherwise enqueue
        # every chunk's device buffer before any write retires, recreating
        # the 2x peak (and the in-flight pileup is the wedge trigger on
        # the flaky link). One-time setup cost; correctness of the budget
        # check depends on this bound.
        out.block_until_ready()
    return out


class Batch(dict):
    """dict with host-side sample identity attached (the reference ships
    image_id through the tensor path, dp/loader.py:61; strings never hit
    the device here):

    - ``image_ids``: ids of THIS host's rows of the global batch.
    - ``indices``: the full global batch's dataset indices — identical on
      every host (the epoch order is host-replicated), so any host can map
      a global batch position to an image id (the fixed-shape redesign of
      the reference's ragged cross-rank gather; see
      make_eval_step(per_sample=True))."""
    image_ids: List[str]
    indices: np.ndarray


def _epoch_indices(n: int, epoch: int, seed: int, shuffle: bool,
                   global_batch: int) -> np.ndarray:
    """Global order for one epoch, padded by wrapping to a batch multiple.

    Returns int64 array whose length is a multiple of global_batch; entries
    are sample indices, with a parallel validity implied by position >= n
    after an argsort-free wrap (the caller masks positions >= n of the
    *unpadded* order)."""
    if shuffle:
        rng = np.random.default_rng(np.random.SeedSequence([seed, epoch]))
        order = rng.permutation(n)
    else:
        order = np.arange(n)
    pad = (-n) % global_batch
    if pad:
        # np.resize tiles cyclically — correct even when pad > n (a dataset
        # smaller than the global batch still yields one full padded batch).
        order = np.resize(order, n + pad)
    return order, n  # (padded order, number of valid entries)


class Loader:
    """Iterates globally-sharded device batches for one process.

    global_batch must be divisible by (process_count * local shard layout);
    each host materializes rows [rank*local : (rank+1)*local] of every global
    batch, where local = global_batch / process_count.
    """

    def __init__(self, dataset: ImageFolderDataset, global_batch: int,
                 mesh: Optional[Mesh] = None, shuffle: Optional[bool] = None,
                 seed: int = 0, num_workers: int = 6, prefetch: int = 2,
                 drop_last: bool = False,
                 process_index: Optional[int] = None,
                 process_count: Optional[int] = None,
                 device_cache_bytes: Optional[int] = None,
                 augment: Optional[bool] = None) -> None:
        """``device_cache_bytes`` overrides DataConfig.device_cache_mb for
        THIS loader — the budget is a per-process total, so a caller that
        builds several loaders (Trainer: train + val) must split it
        (see Trainer.__init__) rather than let each loader claim the full
        amount.

        ``augment`` overrides the dataset's fold-derived default
        (``dataset.train``): inference over the train fold must see clean
        images (predict.py), while the default keeps the reference's
        train-fold-augments contract (dp/loader.py:39-52)."""
        self.dataset = dataset
        self.global_batch = int(global_batch)
        self.mesh = mesh
        self.shuffle = dataset.train if shuffle is None else shuffle
        self.augment = dataset.train if augment is None else bool(augment)
        if self.augment and not dataset.train:
            # The decode path (folder.py load) draws augments only for a
            # train fold; honoring augment=True on val would silently
            # diverge between the packed and decode executors. Disabling
            # is the supported override (predict); forcing is not.
            raise ValueError("augment=True is only valid on a train fold")
        self.seed = seed
        self.num_workers = max(1, num_workers)
        self.prefetch = max(1, prefetch)
        self.drop_last = drop_last
        # Injectable host topology (defaults to the live JAX process grid):
        # multi-host shard math is pure in (rank, count), so tests simulate
        # N ranks in one process and assert shard disjointness/coverage —
        # the bug class the reference actually shipped (dp/loader.py:23).
        self.process_index = (jax.process_index() if process_index is None
                              else int(process_index))
        self.process_count = (jax.process_count() if process_count is None
                              else int(process_count))
        if self.global_batch % self.process_count:
            raise ValueError("global batch must divide across processes")
        self.local_batch = self.global_batch // self.process_count
        self._sharding = (NamedSharding(mesh, P("data")) if mesh is not None
                          else None)
        # Packed fast path: uint8 memmap rows + device-side augmentation.
        # Two flavors:
        # - resident: the whole uint8 dataset fits DataConfig.device_cache_mb
        #   of HBM -> upload ONCE (replicated under a mesh); a batch ships
        #   only [B] indices + [B,5] augment params and gathers on device.
        # - streaming: per-batch uint8 upload + device augment (4x less H2D
        #   than float, still host-link-bound on slow links).
        self.packed = hasattr(dataset, "raw")
        self.resident = False
        self.resident_bytes = 0
        self._device_prep = None
        self._resident_prep = None
        self._data_dev = None
        if self.packed:
            from tpuic.data.device_prep import (make_device_prep,
                                                make_resident_prep)
            c = dataset.cfg
            s = dataset.resize_size
            data_bytes = len(dataset) * s * s * 3
            budget = (int(getattr(c, "device_cache_mb", 0)) << 20
                      if device_cache_bytes is None
                      else int(device_cache_bytes))
            if budget and data_bytes <= budget:
                arr = dataset.array()
                if mesh is None:
                    self._data_dev = _upload_resident_chunked(arr)
                    repl = None
                else:
                    # Multi-device: lazy per-device puts (replication may
                    # target non-addressable devices on multi-host, which
                    # device_put of a host array cannot express).
                    arr = np.asarray(arr)
                    repl = NamedSharding(mesh, P())
                    self._data_dev = jax.make_array_from_callback(
                        arr.shape, repl, lambda idx: arr[idx])
                self._resident_prep = make_resident_prep(
                    mean=c.mean, std=c.std, sharding=self._sharding,
                    replicated=repl)
                self.resident = True
                self.resident_bytes = data_bytes
            else:
                self._device_prep = make_device_prep(
                    mean=c.mean, std=c.std, sharding=self._sharding)

    def __len__(self) -> int:
        n = len(self.dataset)
        if self.drop_last:
            return n // self.global_batch
        return -(-n // self.global_batch)

    @property
    def quarantine_count(self) -> int:
        """Total samples the dataset served a quarantine replacement for
        (docs/robustness.md): decode failures the producer absorbed instead
        of aborting the epoch. Monotonic across epochs; the Trainer logs
        the per-epoch delta."""
        return int(getattr(self.dataset, "quarantine_count", 0) or 0)

    def steps_per_epoch(self) -> int:
        return len(self)

    def _load_one(self, position: int, index: int, valid: bool, epoch: int):
        rng = (np.random.default_rng(
            np.random.SeedSequence([self.seed, epoch, int(index)]))
            if self.augment else None)  # rng=None -> clean eval load
        img, label, image_id = self.dataset.load(int(index), rng)
        return position, img, label, image_id, valid

    def epoch(self, epoch: int, start_step: int = 0) -> Iterator[Batch]:
        """Yield batches for this epoch (the set_epoch(e) equivalent).

        ``start_step`` skips the first batches — step-exact resume: the
        epoch order is a (seed, epoch)-deterministic permutation and the
        augment stream is (seed, epoch, index)-keyed, so the skipped
        prefix is exactly the batches a preempted run already trained and
        the remainder is served bit-identically to the uninterrupted
        epoch."""
        n = len(self.dataset)
        order, n_valid = _epoch_indices(n, epoch, self.seed, self.shuffle,
                                        self.global_batch)
        n_batches = len(order) // self.global_batch
        if self.drop_last and n % self.global_batch:
            n_batches -= 1
        if not 0 <= start_step <= n_batches:
            raise ValueError(f"start_step {start_step} outside this epoch's "
                             f"0..{n_batches} steps")
        out_q: "queue.Queue" = queue.Queue(maxsize=self.prefetch)
        stop = threading.Event()

        def _put(item) -> bool:
            """Bounded put that aborts when the consumer abandons the epoch
            (otherwise the producer would park forever in a full queue)."""
            while not stop.is_set():
                try:
                    out_q.put(item, timeout=0.1)
                    return True
                except queue.Full:
                    continue
            return False

        def produce():
            try:
                _produce_loop()
                _put(None)
            except BaseException as e:  # surface worker errors to the consumer
                _put(e)

        def _produce_packed_loop():
            """Packed fast path: augment decisions drawn host-side from the
            SAME (seed, epoch, index) stream as the decode path, applied on
            device. Resident mode skips even the memmap row copy — the
            batch payload is the [local_batch] index vector."""
            from tpuic.data import transforms as T
            from tpuic.data.device_prep import pack_params
            ds, c = self.dataset, self.dataset.cfg
            s = ds.resize_size
            augment = self.augment
            for b in range(start_step, n_batches):
                if stop.is_set():
                    break
                lo = b * self.global_batch + self.process_index * self.local_batch
                # Batch assembly is vectorized (one C-level gather per
                # array) — on the 1-core host the per-row Python loop was
                # 2x slower; only the per-sample augment RNG draws remain a
                # loop, because the (seed, epoch, index) stream is the
                # parity contract with the decode/native paths.
                idx = np.asarray(order[lo:lo + self.local_batch], np.int32)
                imgs = None if self.resident else ds.raw_batch(idx)
                labels = ds.label_batch(idx).astype(np.int32)
                gpos = np.arange(lo, lo + self.local_batch)
                mask = (gpos < n_valid).astype(np.float32)
                ids = [ds.image_id(int(j)) for j in idx]
                params = {"rot": np.zeros((self.local_batch,), np.int32),
                          "vflip": np.zeros((self.local_batch,), np.int32),
                          "hflip": np.zeros((self.local_batch,), np.int32),
                          "color": np.zeros((self.local_batch,), np.int32),
                          "factor": np.ones((self.local_batch,), np.float32)}
                if augment:
                    for i, index in enumerate(idx):
                        rng = np.random.default_rng(np.random.SeedSequence(
                            [self.seed, epoch, int(index)]))
                        k, vf, hf, color, factor = T.draw_augment(
                            rng, p_vflip=c.p_vflip, p_hflip=c.p_hflip,
                            p_saturation=c.p_saturation,
                            p_brightness=c.p_brightness,
                            p_contrast=c.p_contrast, jitter_lo=c.jitter_lo,
                            jitter_hi=c.jitter_hi)
                        params["rot"][i] = k
                        params["vflip"][i] = int(vf)
                        params["hflip"][i] = int(hf)
                        params["color"][i] = color
                        params["factor"][i] = factor
                payload = idx if self.resident else imgs
                gidx = order[b * self.global_batch:(b + 1) * self.global_batch]
                if not _put((payload, labels, mask, ids,
                             pack_params(params), gidx)):
                    return

        def _produce_loop():
            if self.packed:
                return _produce_packed_loop()
            with ThreadPoolExecutor(self.num_workers) as pool:
                for b in range(start_step, n_batches):
                    if stop.is_set():
                        break
                    lo = b * self.global_batch + self.process_index * self.local_batch
                    futs = []
                    for i in range(self.local_batch):
                        gpos = lo + i
                        futs.append(pool.submit(
                            self._load_one, i, order[gpos],
                            gpos < n_valid, epoch))
                    imgs = np.empty((self.local_batch,
                                     self.dataset.resize_size,
                                     self.dataset.resize_size, 3), np.float32)
                    labels = np.zeros((self.local_batch,), np.int32)
                    mask = np.zeros((self.local_batch,), np.float32)
                    ids = [""] * self.local_batch
                    for f in futs:
                        pos, img, label, image_id, valid = f.result()
                        imgs[pos] = img
                        labels[pos] = label
                        mask[pos] = 1.0 if valid else 0.0
                        ids[pos] = image_id
                    gidx = order[b * self.global_batch:
                                 (b + 1) * self.global_batch]
                    if not _put((imgs, labels, mask, ids, None, gidx)):
                        return

        producer = threading.Thread(target=produce, daemon=True)
        producer.start()
        try:
            # Device-side double buffering: batch N+1's host->device transfer
            # is dispatched (jax transfers are async) before batch N is
            # yielded, so H2D overlaps the consumer's step instead of
            # sitting on its critical path.
            pending: Optional[Batch] = None
            while True:
                item = out_q.get()
                if item is None:
                    break
                if isinstance(item, BaseException):
                    raise item
                payload, labels, mask, ids, params, gidx = item
                if params is None:            # decode path: host float32
                    image = self._to_global(payload)
                elif self.resident:           # indices + params only (KBs)
                    image = self._resident_prep(
                        self._data_dev, self._to_device(payload),
                        self._to_device(params))
                else:                         # streaming uint8 + params
                    image = self._device_prep(self._to_device(payload),
                                              self._to_device(params))
                batch = Batch(image=image,
                              label=self._to_global(labels),
                              mask=self._to_global(mask))
                batch.image_ids = ids
                batch.indices = np.asarray(gidx)
                if pending is not None:
                    yield pending
                pending = batch
            if pending is not None:
                yield pending
        finally:
            stop.set()
            producer.join(timeout=5.0)

    def _to_global(self, local: np.ndarray):
        if self._sharding is None:
            return local
        return jax.make_array_from_process_local_data(self._sharding, local)

    def _to_device(self, local: np.ndarray):
        """Device placement for packed-path inputs: the jitted device prep
        needs device arrays even in the no-mesh case."""
        if self._sharding is None:
            return jax.device_put(local)
        return jax.make_array_from_process_local_data(self._sharding, local)
