from tpuic.data.folder import ImageFolderDataset  # noqa: F401
from tpuic.data.pipeline import Loader  # noqa: F401
from tpuic.data.synthetic import make_synthetic_imagefolder  # noqa: F401
