"""Packed dataset cache: decode once, serve every epoch at memory bandwidth.

Why this exists (round 3, VERDICT r2 weak #2): the training host has ONE
CPU core (measured ``nproc=1``), so the reference's scaling strategy —
``DataLoader(num_workers=6)`` worker processes (train.py:114) — cannot work
here even in principle: 6 workers on 1 core is still ~220 images/sec of
PIL decode while the chip consumes ~2,200/sec. The TPU-native answer is to
take decode OFF the per-epoch path entirely:

- ``pack_dataset`` decodes + nearest-resizes every image ONCE (native
  libjpeg/libpng core when available, PIL fallback) into a flat uint8
  ``.bin`` alongside a JSON meta file (labels, image ids, class mapping,
  source fingerprint for invalidation).
- ``PackedDataset`` memory-maps the ``.bin``; a per-epoch sample costs one
  150KB memcpy instead of a PNG inflate. Augmentation moves to the TPU
  (tpuic/data/device_prep.py), so the host's per-epoch work is batch
  assembly only.

The cache layout is append-only and position-stable: row i of the memmap is
sample i of the (sorted, deterministic) ImageFolderDataset index, so the
epoch-seeded global permutation (tpuic/data/pipeline.py) and the
(seed, epoch, index) augmentation RNG contract are unchanged.

Reference analogue: dp/loader.py:39-61 decodes every sample every epoch;
the pack is the cache the reference never had, and is the only way a
1-core host feeds a v5e chip.
"""

from __future__ import annotations

import json
import os
import zlib
from typing import Dict, List, Optional, Tuple

import numpy as np

from tpuic.data.folder import ImageFolderDataset, quarantined_decode
from tpuic.data import transforms as T

# v2: per-row CRC32s in the meta sidecar, so long-lived caches can be
# verified row-by-row at READ time (the bulk scorer quarantines rows
# whose .bin bytes rotted at rest — tpuic/score/driver.py) instead of
# trusting a fingerprint that only covers the source files. The bump
# invalidates v1 caches cleanly (the reuse check below).
_PACK_VERSION = 2


def _pack_paths(cache_dir: str, fold: str, size: int) -> Tuple[str, str]:
    base = os.path.join(cache_dir, f"pack-{fold}-{size}")
    return base + ".bin", base + ".json"


def _fingerprint(dataset: ImageFolderDataset) -> List[Tuple[str, int, int]]:
    out = []
    for path, _ in dataset.samples:
        st = os.stat(path)
        out.append((os.path.basename(path), int(st.st_mtime), st.st_size))
    return out


def _decode_one(path: str, size: int) -> np.ndarray:
    """Decode + nearest-resize one file to [size, size, 3] uint8.

    Native path first (libjpeg DCT-scaled / libpng); PIL fallback matches
    the PNG path bitwise and the JPEG path at full IDCT scale."""
    from tpuic import native
    if native.decode_available():
        with open(path, "rb") as f:
            data = f.read()
        out = native.decode_resize(data, size)
        if out is not None:
            return out
    from PIL import Image
    with Image.open(path) as im:
        img = np.asarray(im.convert("RGB") if im.mode not in ("RGB",) else im)
    return T.resize_nearest(T.to_rgb(img), size)


def pack_dataset(dataset: ImageFolderDataset, cache_dir: str,
                 force: bool = False, verbose: bool = True) -> "PackedDataset":
    """Build (or reuse) the packed cache for ``dataset`` and return it.

    Reuse requires a matching (version, fold, size, n, source fingerprint);
    anything else rebuilds. Writing is atomic: .bin.tmp + .json rename."""
    size = dataset.resize_size
    bin_path, meta_path = _pack_paths(cache_dir, dataset.fold, size)
    fp = _fingerprint(dataset)
    if not force and os.path.exists(bin_path) and os.path.exists(meta_path):
        try:
            with open(meta_path) as f:
                meta = json.load(f)
            if (meta.get("version") == _PACK_VERSION
                    and meta.get("n") == len(dataset)
                    and meta.get("size") == size
                    and [tuple(x) for x in meta.get("fingerprint", [])] == fp):
                return PackedDataset(bin_path, meta, train=dataset.train,
                                     cfg=dataset.cfg)
        except (OSError, ValueError):
            pass
    os.makedirs(cache_dir, exist_ok=True)
    n = len(dataset)
    row = size * size * 3
    # Globally unique tmp: concurrent packers (multi-process AND multi-host
    # on a shared filesystem — Trainer packs on every host) each build the
    # identical content in their own file; the atomic rename means last
    # writer wins with a complete file. PID alone is NOT unique across
    # hosts.
    import uuid
    token = uuid.uuid4().hex
    tmp = f"{bin_path}.tmp.{token}"
    mm = np.memmap(tmp, np.uint8, "w+", shape=(n, row))
    import time
    t0 = time.perf_counter()
    # Pack-time sample quarantine (docs/robustness.md): the cache is built
    # ONCE over the whole fold, so one truncated file used to abort the
    # entire pack (and with it the Trainer). Same policy as the per-sample
    # path (folder.py load): retry with backoff, then store a deterministic
    # same-class replacement row — WITH the replacement's label, so the
    # packed labels stay honest — and count the event.
    labels = [int(l) for _, l in dataset.samples]
    image_ids = [dataset.image_id(i) for i in range(n)]
    row_crc32: List[int] = []
    quarantined = 0
    for i in range(n):
        # Shared quarantine policy (folder.quarantined_decode): retry with
        # backoff, then cascade through same-class replacements. The packed
        # row takes the replacement's pixels, LABEL, and IMAGE ID —
        # identical semantics to the unpacked path, so per-sample records
        # keyed by id agree between packed and decode runs.
        img, j = quarantined_decode(
            dataset, i, lambda idx: _decode_one(dataset.samples[idx][0],
                                                size))
        if j != i:
            labels[i] = int(dataset.samples[j][1])
            image_ids[i] = dataset.image_id(j)
            quarantined += 1
        mm[i] = img.reshape(-1)
        row_crc32.append(zlib.crc32(np.ascontiguousarray(img).tobytes()))
        if verbose and i and i % 2000 == 0:
            rate = i / (time.perf_counter() - t0)
            print(f"[pack] {dataset.fold}: {i}/{n} ({rate:.0f} img/s)",
                  flush=True)
    if verbose and quarantined:
        print(f"[pack] {dataset.fold}: quarantined {quarantined} "
              f"undecodable file(s); packed same-class replacements",
              flush=True)
    mm.flush()
    del mm
    os.replace(tmp, bin_path)
    meta = {
        "version": _PACK_VERSION,
        "fold": dataset.fold,
        "size": size,
        "n": n,
        "labels": labels,
        "image_ids": image_ids,
        "class_to_idx": dataset.class_to_idx,
        "fingerprint": fp,
        "row_crc32": row_crc32,
    }
    with open(f"{meta_path}.tmp.{token}", "w") as f:
        json.dump(meta, f)
    os.replace(f"{meta_path}.tmp.{token}", meta_path)
    if verbose:
        dt = time.perf_counter() - t0
        print(f"[pack] {dataset.fold}: packed {n} images @ {size}px in "
              f"{dt:.1f}s ({n / max(dt, 1e-9):.0f} img/s) -> {bin_path}",
              flush=True)
    packed = PackedDataset(bin_path, meta, train=dataset.train,
                           cfg=dataset.cfg)
    packed.quarantine_count = quarantined
    return packed


class PackedDataset:
    """Memory-mapped uint8 image cache with the ImageFolderDataset surface.

    ``raw(i)`` returns the stored [S,S,3] uint8 view (zero-copy); ``load``
    keeps full API compatibility with ImageFolderDataset.load (decode →
    augment → normalize on host) for callers that want host-side floats,
    but the fast path is Loader's packed branch: raw batch + device-side
    augment/normalize."""

    def __init__(self, bin_path: str, meta: Dict, train: bool,
                 cfg=None) -> None:
        from tpuic.config import DataConfig
        self.cfg = cfg or DataConfig()
        self.bin_path = bin_path
        self.train = train
        self.fold = meta["fold"]
        self.resize_size = int(meta["size"])
        self._labels = np.asarray(meta["labels"], np.int32)
        self._image_ids = list(meta["image_ids"])
        self.class_to_idx: Dict[str, int] = dict(meta["class_to_idx"])
        self.classes: List[str] = sorted(self.class_to_idx,
                                         key=self.class_to_idx.get)
        # Flat/unlabeled source folds store label -1 per sample
        # (folder.py flat path); mirror ImageFolderDataset.labeled.
        self.labeled = bool(len(self._labels) == 0
                            or int(self._labels.min()) >= 0)
        n, s = int(meta["n"]), self.resize_size
        self._mm = np.memmap(bin_path, np.uint8, "r", shape=(n, s, s, 3))
        # Per-row CRC32s (v2 metas); a pre-v2 cache verifies as
        # trusted-unverifiable (verify_row True) rather than quarantined.
        self._row_crc32 = meta.get("row_crc32") or None
        # Pack-time quarantine events (pack_dataset sets the real count on
        # a fresh build; a cache hit reports 0 — the cache's rows were all
        # decodable when written). Epoch-log surfacing reads this.
        self.quarantine_count = 0
        self.quarantined: Dict[str, int] = {}

    def __len__(self) -> int:
        return self._mm.shape[0]

    @property
    def num_classes(self) -> int:
        return len(self.class_to_idx)

    def image_id(self, index: int) -> str:
        return self._image_ids[index]

    def label(self, index: int) -> int:
        return int(self._labels[index])

    def class_counts(self) -> np.ndarray:
        """[num_classes] int64 sample count per class id."""
        return np.bincount(self._labels[self._labels >= 0],
                           minlength=self.num_classes).astype(np.int64)

    def raw(self, index: int) -> np.ndarray:
        return self._mm[index]

    def row_crc32(self, index: int) -> Optional[int]:
        """The pack-time CRC32 of row ``index`` (None on a pre-v2 meta)."""
        if self._row_crc32 is None:
            return None
        return int(self._row_crc32[index])

    def verify_row(self, index: int) -> bool:
        """Whether row ``index``'s bytes still hash to their pack-time
        CRC32 — the at-rest bit-rot check the bulk scorer quarantines
        on (tpuic/score/driver.py).  True when the meta predates row
        CRCs: absence of evidence is not a quarantine verdict."""
        if self._row_crc32 is None:
            return True
        import zlib
        row = np.ascontiguousarray(self._mm[index])
        return zlib.crc32(row.tobytes()) == int(self._row_crc32[index])

    def raw_batch(self, indices) -> np.ndarray:
        """[B,S,S,3] uint8 gather — one C-level fancy-index copy (2x the
        per-row Python loop on the 1-core host)."""
        return self._mm[np.asarray(indices, np.int64)]

    def label_batch(self, indices) -> np.ndarray:
        return self._labels[np.asarray(indices, np.int64)]

    def array(self) -> np.ndarray:
        """The full [N,S,S,3] uint8 memmap (zero-copy view) — used by the
        Loader's device-resident cache to upload the dataset to HBM."""
        return self._mm

    def load(self, index: int, rng: Optional[np.random.Generator] = None
             ) -> Tuple[np.ndarray, int, str]:
        """Host-side float path (API parity with ImageFolderDataset.load)."""
        img = np.asarray(self._mm[index])
        c = self.cfg
        if self.train and rng is not None:
            k, vflip, hflip, color, factor = T.draw_augment(
                rng, p_vflip=c.p_vflip, p_hflip=c.p_hflip,
                p_saturation=c.p_saturation, p_brightness=c.p_brightness,
                p_contrast=c.p_contrast, jitter_lo=c.jitter_lo,
                jitter_hi=c.jitter_hi)
            img = T.apply_augment(img, k, vflip, hflip, color, factor)
        return (T.normalize(img, c.mean, c.std), int(self._labels[index]),
                self._image_ids[index])
