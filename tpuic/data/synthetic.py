"""Synthetic ImageFolder trees + in-memory batches for tests and benches.

The reference has no test assets at all (SURVEY.md §4); these generators stand
in for the tiny 2-class PNG tree its integration story needs.
"""

from __future__ import annotations

import os
from typing import Sequence

import numpy as np
from PIL import Image


def make_synthetic_imagefolder(root: str, classes: Sequence[str] = ("cat", "dog"),
                               per_class: int = 8, size: int = 40,
                               folds: Sequence[str] = ("train", "val"),
                               seed: int = 0) -> str:
    """Write data_dir/{fold}/{class}/{class}_{i}.png with class-correlated
    pixel statistics (so a model can actually overfit it)."""
    rng = np.random.default_rng(seed)
    for fold in folds:
        for ci, cls in enumerate(classes):
            d = os.path.join(root, fold, cls)
            os.makedirs(d, exist_ok=True)
            for i in range(per_class):
                base = np.full((size, size, 3),
                               40 + 150 * ci // max(1, len(classes) - 1),
                               np.uint8)
                noise = rng.integers(0, 60, (size, size, 3), np.uint8)
                img = np.clip(base.astype(np.int32) + noise, 0, 255).astype(np.uint8)
                Image.fromarray(img).save(
                    os.path.join(d, f"{cls}_{fold}_{i}.png"))
    return root


def synthetic_batch(batch: int, size: int, num_classes: int, seed: int = 0):
    """Random normalized batch dict for step-level tests/benches."""
    rng = np.random.default_rng(seed)
    return {
        "image": rng.standard_normal((batch, size, size, 3)).astype(np.float32),
        "label": rng.integers(0, num_classes, (batch,)).astype(np.int32),
        "mask": np.ones((batch,), np.float32),
    }
