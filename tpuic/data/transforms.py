"""Image transforms: decode, resize, augment, normalize.

Numeric-semantics parity with reference dp/loader.py:39-91, as pure NumPy
functions with explicit RNG (the reference uses the global ``np.random`` state
inside fork-server DataLoader workers — unseeded and irreproducible; here every
sample's augmentation derives from (seed, epoch, index)):

- decode: keep first 3 channels (dp/loader.py:45); grayscale broadcast to 3.
- resize: nearest-neighbor to (S, S) (cv2.INTER_NEAREST, dp/loader.py:45).
- augment (train only, dp/loader.py:63-83): random rot90 k∈{0..3}; vertical
  flip p=.5; horizontal flip p=.5; then an if/elif chain — saturation p=.05,
  elif brightness p≈.05, elif contrast p≈.05 — factor ~ U[0.9, 1.1). The
  chain structure (at most ONE color op per sample, with conditional
  probabilities) is preserved exactly.
- normalize: /255 then per-channel (x-mean)/std with ImageNet stats
  (dp/loader.py:86-91).

The color ops (saturation/brightness/contrast) come from a module the
reference imports but does not ship (``bs.dp.augumentation_utils``,
dp/loader.py:12); standard definitions (ITU-R 601 luma for
grayscale blending) are used as the build target.
"""

from __future__ import annotations

import numpy as np

IMAGENET_MEAN = np.array([0.485, 0.456, 0.406], np.float32)
IMAGENET_STD = np.array([0.229, 0.224, 0.225], np.float32)

_LUMA = np.array([0.299, 0.587, 0.114], np.float32)


def to_rgb(img: np.ndarray) -> np.ndarray:
    """HW or HWC uint8 -> HW3, keeping the first 3 channels (dp/loader.py:45)."""
    if img.ndim == 2:
        img = np.stack([img] * 3, axis=-1)
    return img[:, :, :3]


def resize_nearest(img: np.ndarray, size: int) -> np.ndarray:
    """Nearest-neighbor resize to (size, size); matches cv2.INTER_NEAREST."""
    h, w = img.shape[:2]
    if h == size and w == size:
        return img
    # cv2 nearest: src index = floor(dst * scale) with scale = src/dst.
    rows = np.minimum((np.arange(size) * (h / size)).astype(np.int64), h - 1)
    cols = np.minimum((np.arange(size) * (w / size)).astype(np.int64), w - 1)
    return img[rows][:, cols]


def adjust_brightness(img: np.ndarray, factor: float) -> np.ndarray:
    """img * factor (float image in [0,255] space)."""
    return np.clip(img.astype(np.float32) * factor, 0.0, 255.0)


def adjust_contrast(img: np.ndarray, factor: float) -> np.ndarray:
    """Blend with the global gray mean."""
    mean = img.astype(np.float32).mean()
    return np.clip(mean + (img.astype(np.float32) - mean) * factor, 0.0, 255.0)


def adjust_saturation(img: np.ndarray, factor: float) -> np.ndarray:
    """Blend with the per-pixel luma grayscale."""
    gray = (img.astype(np.float32) @ _LUMA)[..., None]
    return np.clip(gray + (img.astype(np.float32) - gray) * factor, 0.0, 255.0)


def draw_augment(rng: np.random.Generator,
                 p_vflip: float = 0.5, p_hflip: float = 0.5,
                 p_saturation: float = 0.05, p_brightness: float = 0.05,
                 p_contrast: float = 0.05, jitter_lo: float = 0.9,
                 jitter_hi: float = 1.1):
    """Draw the augmentation decisions (reference dp/loader.py:63-83 RNG
    order: rot90 k, vflip, hflip, color branch, factor). Single source of
    truth for BOTH the NumPy and the native (tpuic/native) execution paths —
    per (seed, epoch, index) a sample is identical whichever path ran.

    Returns (k, vflip, hflip, color_op, factor); color_op: 0 none,
    1 saturation, 2 brightness, 3 contrast."""
    k = int(rng.integers(0, 4))  # rot90 k in {0,1,2,3} (dp/loader.py:64-65)
    vflip = rng.random() < p_vflip   # dp/loader.py:67-68
    hflip = rng.random() < p_hflip   # dp/loader.py:70-71
    # if/elif color chain (dp/loader.py:74-81): at most one op fires.
    r = rng.random()
    factor = jitter_lo + (jitter_hi - jitter_lo) * rng.random()
    if r < p_saturation:
        color = 1
    elif r < p_saturation + p_brightness:
        color = 2
    elif r < p_saturation + p_brightness + p_contrast:
        color = 3
    else:
        color = 0
    return k, vflip, hflip, color, factor


def apply_augment(img: np.ndarray, k: int, vflip: bool, hflip: bool,
                  color: int, factor: float) -> np.ndarray:
    """Apply pre-drawn augmentation decisions (NumPy path)."""
    if k:
        img = np.rot90(img, k, axes=(0, 1))
    if vflip:
        img = img[::-1, :, :]
    if hflip:
        img = img[:, ::-1, :]
    if color == 1:
        img = adjust_saturation(img, factor)
    elif color == 2:
        img = adjust_brightness(img, factor)
    elif color == 3:
        img = adjust_contrast(img, factor)
    return np.ascontiguousarray(img)


def augment(img: np.ndarray, rng: np.random.Generator, **kw) -> np.ndarray:
    """Train-time augmentation chain, reference dp/loader.py:63-83."""
    return apply_augment(img, *draw_augment(rng, **kw))


def normalize(img: np.ndarray, mean=IMAGENET_MEAN, std=IMAGENET_STD) -> np.ndarray:
    """/255 then per-channel standardize (dp/loader.py:86-91). HWC float32.

    Output layout stays HWC — TPU conv layout — rather than the reference's
    CHW transpose (dp/loader.py:59), which exists only for torch convention.
    """
    img = img.astype(np.float32) / 255.0
    return (img - np.asarray(mean, np.float32)) / np.asarray(std, np.float32)
