"""Small pytree utilities."""

from __future__ import annotations

import jax


def tree_size(tree) -> int:
    """Total number of array elements in a pytree."""
    return sum(x.size for x in jax.tree_util.tree_leaves(tree))


def tree_bytes(tree) -> int:
    """Total bytes of a pytree's arrays."""
    return sum(x.size * x.dtype.itemsize for x in jax.tree_util.tree_leaves(tree))
