"""Small pytree utilities."""

from __future__ import annotations

import jax
import jax.numpy as jnp


def tree_size(tree) -> int:
    """Total number of array elements in a pytree."""
    return sum(x.size for x in jax.tree_util.tree_leaves(tree))


def tree_bytes(tree) -> int:
    """Total bytes of a pytree's arrays."""
    return sum(x.size * x.dtype.itemsize for x in jax.tree_util.tree_leaves(tree))


def cast_floats(tree, dtype):
    """Cast floating-point leaves of a pytree to dtype, leaving ints alone."""
    def _cast(x):
        if jnp.issubdtype(x.dtype, jnp.floating):
            return x.astype(dtype)
        return x
    return jax.tree_util.tree_map(_cast, tree)
