from tpuic.utils.trees import tree_size, tree_bytes  # noqa: F401
