#!/usr/bin/env python
"""CLI entry point — the TPU-native counterpart of reference train.py.

The reference is launched as
``python -m torch.distributed.launch --nproc_pre_node=4 train.py --datadir …``
(reference README.md:6) with three flags (train.py:27-31). Here a single
process per host drives all local TPU chips; multi-host pods need no launcher
flags at all (the TPU runtime carries the topology — tpuic/runtime/
distributed.py). Every constant the reference hard-codes is a flag with the
same default (see tpuic/config.py for the line-by-line mapping).

Examples:
  python train.py --datadir /data/imagefolder                 # reference defaults
  python train.py --datadir /data/cifar --model resnet18-cifar \
      --resize 32 --batchsize 128 --lr 1e-3 --no-class-weights
"""

from __future__ import annotations

import argparse
import dataclasses

from tpuic.config import (Config, DataConfig, MeshConfig, ModelConfig,
                          OptimConfig, RunConfig)


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(description=__doc__,
                                formatter_class=argparse.RawDescriptionHelpFormatter)
    # The reference's three flags (train.py:27-31).
    p.add_argument("--datadir", required=True, help="ImageFolder root with train/ and val/")
    p.add_argument("--batchsize", type=int, default=4,
                   help="per-device batch size (reference default 4)")
    p.add_argument("--local_rank", type=int, default=0,
                   help="accepted for launch-command compatibility; unused — "
                        "one JAX process drives all local chips")
    # Everything the reference hard-codes (train.py:110-183).
    p.add_argument("--model", default="inceptionv3",
                   help="backbone name (see tpuic.models.available_models()); "
                        "default matches the reference's hard-coded "
                        "'inceptionv3' (train.py:122). The perf-tracking "
                        "config (BASELINE.md) uses --model resnet50.")
    p.add_argument("--num-classes", type=int, default=0,
                   help="0 = infer from the folder tree")
    p.add_argument("--resize", type=int, default=299)
    p.add_argument("--epochs", type=int, default=100)
    p.add_argument("--lr", type=float, default=0.5e-5)
    p.add_argument("--optimizer", default="adam",
                   choices=["adam", "lars", "lamb", "sgd"],
                   help="'lars'/'lamb' are the layer-wise trust-ratio "
                        "large-batch optimizers (arXiv:1708.03888 / "
                        "1904.00962); pair them with --base-batch for "
                        "the linear-scaling warmup")
    p.add_argument("--milestones", type=int, nargs="*", default=[50, 80])
    p.add_argument("--gamma", type=float, default=0.5)
    p.add_argument("--weight-decay", type=float, default=0.0)
    p.add_argument("--clip-grad-norm", type=float, default=0.0,
                   help="clip gradients to this global L2 norm before the "
                        "optimizer update (0 = off; standard in ViT/large-"
                        "batch recipes)")
    p.add_argument("--mixup", type=float, default=0.0, metavar="ALPHA",
                   help="mixup Beta(alpha, alpha) image/label mixing, "
                        "applied on-device in the train step (0 = off)")
    p.add_argument("--cutmix", type=float, default=0.0, metavar="ALPHA",
                   help="cutmix box mixing, on-device (0 = off; with "
                        "--mixup, one is chosen per step 50/50)")
    p.add_argument("--random-erase", type=float, default=0.0, metavar="P",
                   help="per-sample probability of erasing a random box "
                        "on-device in the train step (0 = off)")
    p.add_argument("--warmup-epochs", type=int, default=0)
    p.add_argument("--base-batch", type=int, default=0, metavar="N",
                   help="Goyal linear-scaling rule: peak LR = --lr * "
                        "global_batch / N, reached by a linear warmup "
                        "from --lr over --warmup-epochs (0 = off). The "
                        "global batch tracks the data-parallel extent, "
                        "so one config survives fleet growth and "
                        "elastic degrade alike")
    p.add_argument("--grad-accum-steps", type=int, default=1,
                   help="accumulate gradients over K steps before one "
                        "optimizer update (effective batch = K * global)")
    p.add_argument("--class-weights", type=str, nargs="*",
                   default=["3", "3", "10", "1", "4", "4", "5"],
                   help="CE class weights (reference train.py:157), or the "
                        "single word 'auto' to derive inverse-frequency "
                        "weights from the train fold's class counts")
    p.add_argument("--no-class-weights", action="store_true")
    p.add_argument("--ckpt-dir", default="dtmodel/cp")
    p.add_argument("--save-period", type=int, default=5)
    p.add_argument("--no-resume", action="store_true")
    p.add_argument("--init-from", default="",
                   help="initialize from a torch checkpoint (reference "
                        "best_model/latest_model file or a torchvision/"
                        "efficientnet_pytorch state_dict); backbone family "
                        "is auto-detected and weights merge leniently")
    p.add_argument("--workers", type=int, default=6)
    p.add_argument("--val-batchsize", type=int, default=0,
                   help="per-device val batch (0 = same as --batchsize; the "
                        "reference pins 1, train.py:118 — only needed there "
                        "for its per-sample gather)")
    p.add_argument("--prefetch", type=int, default=2,
                   help="host-side prefetch depth (batches in flight)")
    p.add_argument("--device-cache-mb", type=int, default=4096,
                   help="HBM budget for the device-resident dataset cache "
                        "(0 disables; see docs/performance.md)")
    p.add_argument("--log-every-steps", type=int, default=50,
                   help="metric readback cadence; 1 = reference-style "
                        "per-step logging (serializes dispatch)")
    p.add_argument("--label-smoothing", type=float, default=0.0)
    p.add_argument("--ema-decay", type=float, default=0.0,
                   help="EMA of params (0 off; typical 0.9999); validation "
                        "and best-checkpoint selection use EMA weights")
    p.add_argument("--freeze-backbone", action="store_true",
                   help="train only the MLP head (pairs with --init-from); "
                        "gradient-level freeze, BN stats still update")
    p.add_argument("--fused-loss", action="store_true",
                   help="use the Pallas fused weighted-CE kernel "
                        "(tpuic/kernels/cross_entropy.py)")
    p.add_argument("--no-augment", action="store_true",
                   help="disable the train-fold rot90/flip/jitter chain "
                        "(orientation-sensitive datasets, e.g. digits); "
                        "normalization and val behavior are unchanged")
    p.add_argument("--no-native", action="store_true",
                   help="disable the native C++ decode/prep core "
                        "(tpuic/native) and run the pure-NumPy input "
                        "path — the parity reference the native "
                        "kernels are pinned against")
    p.add_argument("--no-pack", action="store_true",
                   help="disable the packed uint8 cache + device-side "
                        "augmentation; decode every epoch like the reference")
    p.add_argument("--cache-dir", default="",
                   help="packed-cache dir (default {datadir}/.tpuic_pack)")
    p.add_argument("--collect-misclassified", action="store_true",
                   help="gather misclassified val image ids each epoch "
                        "(the reference's per-sample all_gather capability)")
    p.add_argument("--per-class-metrics", action="store_true",
                   help="log exact global per-class val accuracy and save "
                        "the [C,C] confusion matrix beside metrics.jsonl")
    p.add_argument("--dtype", default="bfloat16",
                   choices=["bfloat16", "float32"])
    p.add_argument("--compute-dtype", default="", dest="compute_dtype",
                   choices=["", "bf16", "f32"],
                   help="training compute-dtype policy: 'bf16' runs "
                        "forward/backward in bfloat16 with f32 master "
                        "weights, f32 optimizer moments and f32 "
                        "checkpoints (the mixed-precision tier, parity-"
                        "gated in CI); 'f32' forces full float32 (the "
                        "parity reference arm); '' defers to --dtype")
    p.add_argument("--loss-scale", type=float, default=1.0,
                   help="static loss scaling for --compute-dtype bf16 "
                        "(loss x N before backward, grads / N after; "
                        "1.0 = off — bf16 with f32 master weights "
                        "rarely needs it; overflow rides the skip "
                        "guard)")
    p.add_argument("--fused-optimizer", action="store_true",
                   help="use the fused one-pass Pallas optimizer-update "
                        "kernel for lars/lamb "
                        "(tpuic/kernels/optimizer_update.py; jnp "
                        "fallback off-TPU)")
    p.add_argument("--no-async-checkpoint", action="store_true",
                   help="commit checkpoints synchronously (block the "
                        "step timeline on manifest + rotation) instead "
                        "of on the background commit thread")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--model-axis", type=int, default=1,
                   help="mesh model-axis size (1 = pure data parallel; >1 = "
                        "Megatron tensor parallelism from the models' "
                        "logical axis annotations)")
    p.add_argument("--seq-axis", type=int, default=1,
                   help="mesh seq-axis size for sequence-parallel attention "
                        "(ring/ulysses; attention-bearing backbones only)")
    p.add_argument("--fsdp", action="store_true",
                   help="shard params + optimizer moments over the data axis "
                        "(ZeRO-3 semantics)")
    p.add_argument("--zero1", action="store_true",
                   help="shard ONLY the optimizer moments over the data axis "
                        "(weight-update sharding: params stay replicated, "
                        "1/N Adam memory; subsumed by --fsdp)")
    from tpuic.models import ATTENTION_IMPLS
    p.add_argument("--attention", default="dense",
                   choices=list(ATTENTION_IMPLS),
                   help="attention implementation for ViT backbones")
    p.add_argument("--remat", action="store_true",
                   help="rematerialize the forward in backward (trade FLOPs "
                        "for activation memory/bandwidth)")
    p.add_argument("--remat-policy", default="dots",
                   choices=["dots", "attention", "blocks", "gelu"],
                   help="what --remat saves: 'dots' recomputes all "
                        "activation-sized tensors; 'attention' recomputes "
                        "ONLY the [B,H,N,N] attention logits/probs (ViT); "
                        "'blocks' saves only encoder-block inputs (ViT "
                        "long-context memory mode); 'gelu' drops only the "
                        "ViT MLP pre-activations (lightest — one fewer "
                        "[B,N,4D] HBM write/read per block)")
    p.add_argument("--drop-path", type=float, default=0.0,
                   help="stochastic-depth rate for ViT backbones (last "
                        "block; linear DeiT ramp from 0)")
    p.add_argument("--bn-bf16-stats", action="store_true",
                   help="accumulate BatchNorm batch statistics in bf16 "
                        "instead of f32 (ResNet family; HBM-bandwidth "
                        "experiment — see ModelConfig.bn_f32_stats)")
    p.add_argument("--profile-dir", default="",
                   help="write a jax.profiler trace of the first epoch here")
    p.add_argument("--log-dir", default="", help="metrics.jsonl directory")
    p.add_argument("--no-skip-guard", action="store_true",
                   help="disable the in-graph non-finite step guard (a "
                        "NaN/Inf batch then poisons the optimizer state "
                        "permanently — see docs/robustness.md)")
    p.add_argument("--skip-threshold", type=int, default=10,
                   help="consecutive non-finite (skipped) steps before the "
                        "trainer rolls back to the last good checkpoint "
                        "(0 disables detection)")
    p.add_argument("--no-rollback", action="store_true",
                   help="never roll back on a non-finite streak (keep "
                        "skipping instead)")
    p.add_argument("--rewarm-steps", type=int, default=0,
                   help="after a rollback, ramp the LR linearly back to "
                        "its schedule over this many steps (0 = resume at "
                        "full schedule LR)")
    p.add_argument("--no-quarantine", action="store_true",
                   help="fail fast on undecodable images instead of "
                        "serving a deterministic same-class replacement")
    # Telemetry (tpuic/telemetry, docs/observability.md).
    p.add_argument("--steps", type=int, default=0,
                   help="stop after this many optimizer steps regardless "
                        "of --epochs (0 = no cap; smoke runs and the CI "
                        "telemetry gate use it)")
    p.add_argument("--metrics-jsonl", default="",
                   help="telemetry event JSONL sink: per-step time "
                        "breakdown, skip/rollback/quarantine/checkpoint/"
                        "compile events, and the final goodput report")
    p.add_argument("--trace-dir", default="",
                   help="triggered jax.profiler traces land here when a "
                        "step regresses past --trace-threshold x the "
                        "rolling median (TPUIC_TRACE=dir forces one "
                        "immediate window)")
    p.add_argument("--trace-threshold", type=float, default=3.0,
                   help="step-time regression multiple that arms a trace "
                        "(0 disables the automatic trigger)")
    p.add_argument("--trace-steps", type=int, default=3,
                   help="steps each triggered trace window covers")
    p.add_argument("--trace-analyze", action="store_true",
                   help="auto-analyze captured trace windows (and the "
                        "full run at exit) into a per-op-class device-"
                        "time waterfall with roofline verdicts "
                        "(telemetry/profile.py): 'profile' events in "
                        "the metrics JSONL, TensorBoard scalars, and "
                        "device_time_ms{op_class} rows in --prom-dump")
    p.add_argument("--prom-dump", default="",
                   help="write the train Prometheus exposition (goodput "
                        "fractions, MFU, step-time percentiles, restart "
                        "count, heartbeat age) to this file atomically at "
                        "every goodput report — the textfile-collector "
                        "transport, same as tpuic.serve's flag")
    p.add_argument("--slo", default="",
                   help="step-time SLOs, comma list of "
                        "'train_step:pQ<=Nms[@target]' specs "
                        "(telemetry/slo.py): rolling attainment and "
                        "error-budget burn rate land in the metrics "
                        "JSONL ('slo' events), TensorBoard, and the "
                        "--prom-dump exposition")
    return p


def config_from_args(args: argparse.Namespace) -> Config:
    auto_weights = (not args.no_class_weights
                    and list(args.class_weights) == ["auto"])
    if args.no_class_weights or auto_weights:
        weights = ()
    else:
        try:
            weights = tuple(float(w) for w in args.class_weights)
        except ValueError:
            raise SystemExit(
                "train.py: error: --class-weights expects numbers or the "
                f"single word 'auto' (got {args.class_weights!r})")
    if args.slo:
        # Validate the SLO grammar up front: a typo'd objective must fail
        # the command line, not crash Trainer construction minutes later.
        from tpuic.telemetry.slo import parse_objectives
        try:
            parse_objectives(args.slo, allowed=("train_step",))
        except ValueError as e:
            raise SystemExit(f"train.py: error: --slo: {e}")
    return Config(
        data=DataConfig(data_dir=args.datadir, resize_size=args.resize,
                        batch_size=args.batchsize, num_workers=args.workers,
                        val_batch_size=args.val_batchsize,
                        prefetch=args.prefetch,
                        device_cache_mb=args.device_cache_mb,
                        pack=not args.no_pack, cache_dir=args.cache_dir,
                        augment=not args.no_augment,
                        native=not args.no_native,
                        quarantine=not args.no_quarantine),
        model=ModelConfig(name=args.model, num_classes=args.num_classes,
                          dtype=args.dtype, attention=args.attention,
                          remat=args.remat, remat_policy=args.remat_policy,
                          drop_path=args.drop_path,
                          bn_f32_stats=not args.bn_bf16_stats,
                          compute_dtype=args.compute_dtype),
        optim=OptimConfig(optimizer=args.optimizer, learning_rate=args.lr,
                          milestones=tuple(args.milestones), gamma=args.gamma,
                          class_weights=weights,
                          auto_class_weights=auto_weights,
                          weight_decay=args.weight_decay,
                          grad_clip_norm=args.clip_grad_norm,
                          mixup_alpha=args.mixup,
                          cutmix_alpha=args.cutmix,
                          random_erase=args.random_erase,
                          warmup_epochs=args.warmup_epochs,
                          base_batch_size=args.base_batch,
                          grad_accum_steps=args.grad_accum_steps,
                          label_smoothing=args.label_smoothing,
                          ema_decay=args.ema_decay,
                          freeze_backbone=args.freeze_backbone,
                          fused_loss=args.fused_loss,
                          fused_optimizer=args.fused_optimizer,
                          loss_scale=args.loss_scale,
                          skip_nonfinite=not args.no_skip_guard),
        run=RunConfig(epochs=args.epochs, ckpt_dir=args.ckpt_dir,
                      save_period=args.save_period, resume=not args.no_resume,
                      init_from=args.init_from,
                      log_every_steps=args.log_every_steps,
                      collect_misclassified=args.collect_misclassified,
                      per_class_metrics=args.per_class_metrics,
                      profile_dir=args.profile_dir, seed=args.seed,
                      skip_threshold=args.skip_threshold,
                      rollback=not args.no_rollback,
                      rollback_rewarm_steps=args.rewarm_steps,
                      max_steps=args.steps,
                      metrics_jsonl=args.metrics_jsonl,
                      trace_dir=args.trace_dir,
                      trace_threshold=args.trace_threshold,
                      trace_steps=args.trace_steps,
                      trace_analyze=args.trace_analyze,
                      slo=args.slo,
                      async_checkpoint=not args.no_async_checkpoint),
        mesh=MeshConfig(model=args.model_axis, seq=args.seq_axis,
                        fsdp=args.fsdp, zero1=args.zero1),
    )


def main(argv=None) -> int:
    args = build_parser().parse_args(argv)
    # Supervision protocol (runtime/supervisor.py, docs/robustness.md):
    # register the SIGQUIT handlers FIRST — a hang anywhere after this
    # line, including inside the backend probe or the first compile,
    # must still be explainable when the supervisor's watchdog
    # escalates. The flight recorder (telemetry/flight.py) registers
    # its Python-level dump BEFORE the faulthandler stack dump, which
    # chains into it: one SIGQUIT yields stacks + the event timeline
    # leading into the wedge. Costs nothing unsupervised (no
    # TPUIC_FLIGHT_DUMP -> no recorder, chain=False as before); the
    # import pulls no backend init — only the guard below may do that.
    from tpuic.runtime.supervisor import (EXIT_POISON, EXIT_PREEMPTED,
                                          NonRetryableError,
                                          install_stack_dump_handler)
    from tpuic.telemetry.flight import install_flight_recorder
    flight = install_flight_recorder()
    install_stack_dump_handler(chain=flight is not None)
    # Dev-image guard: probe the tunneled TPU backend (whose init HANGS,
    # not errors, when the tunnel is down) and fall back to CPU with a
    # message instead of hanging the training command.
    from tpuic.runtime.axon_guard import ensure_reachable_or_cpu
    ensure_reachable_or_cpu()
    from tpuic.metrics.logging import host0_print
    from tpuic.runtime.distributed import initialize
    from tpuic.train.loop import Trainer

    info = initialize()
    host0_print(f"[tpuic] {info.process_count} process(es), "
                f"{info.global_device_count} {info.platform} device(s)")
    cfg = config_from_args(args)
    try:
        # Construction is in the poison scope too: a --resume restore
        # that finds every checkpoint rung corrupt raises here, before
        # fit() — it must exit 44, not crash-loop the supervisor through
        # the same corrupt rungs.
        trainer = Trainer(cfg, log_dir=args.log_dir or None)
    except NonRetryableError as e:
        host0_print(f"[tpuic] NON-RETRYABLE: {e}")
        return EXIT_POISON
    host0_print(f"[tpuic] model={trainer.model.backbone.__class__.__name__} "
                f"classes={trainer.model.num_classes} "
                f"mesh={dict(trainer.mesh.shape)}")
    if args.prom_dump:
        # Textfile-collector exposition, refreshed at each goodput report
        # (per epoch + final): the trainer already publishes the full
        # report as a 'goodput' event, so the dump is one more host-side
        # bus subscriber — no new syncs, no polling thread.
        from tpuic.metrics.logging import is_host0
        from tpuic.telemetry.events import subscribe
        from tpuic.telemetry.prom import train_exposition, write_exposition
        if is_host0():
            def _prom_dump(ev) -> None:
                hb = trainer.telemetry.heartbeat
                slo = trainer.telemetry.slo
                prof = trainer.telemetry.profile
                write_exposition(args.prom_dump, train_exposition(
                    dict(ev.data),
                    trainer.telemetry.steptime.summary(),
                    heartbeat_age_s=hb.age_s() if hb is not None else None,
                    slo=slo.report() if slo is not None else None,
                    memory=trainer.telemetry.memory.snapshot(),
                    profile=prof.last if prof is not None else None))
            subscribe(_prom_dump, kinds=("goodput",))
    try:
        best = trainer.fit()
    except NonRetryableError as e:
        # The poison half of the exit-code contract: a supervisor restart
        # cannot fix this (rollback budget exhausted, every checkpoint
        # rung corrupt) — exit 44 so it reports instead of crash-looping.
        host0_print(f"[tpuic] NON-RETRYABLE: {e}")
        return EXIT_POISON
    if cfg.run.handle_preemption and trainer.preemption.triggered:
        # Clean preemption flush: the step-exact 'latest' checkpoint is
        # committed — exit 43 so a supervisor restarts with resume
        # (immediately, no backoff) instead of booking a crash.
        host0_print(f"[tpuic] preempted (flushed); best val accuracy "
                    f"{best:.4f}")
        return EXIT_PREEMPTED
    if getattr(trainer.telemetry, "slo", None) is not None:
        host0_print(f"[slo] {trainer.telemetry.slo.summary_line()}")
    host0_print(f"[tpuic] done; best val accuracy {best:.4f}")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
