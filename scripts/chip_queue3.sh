#!/bin/bash
# Remainder of the round-4 chip queue after the N=1025 flash hang took the
# tunnel down mid-chip_queue2 (see PERF_ANALYSIS.md §10). Safe items first;
# the long-N flash probe (the wedge trigger's family) runs LAST, after the
# 384-block palette fix, so a repeat can't cost the other rows.
set -x -o pipefail
failures=0
cd /root/repo
probe() { python -c "
from tpuic.runtime.axon_guard import tpu_reachable
import sys; sys.exit(0 if tpu_reachable(150) else 1)"; }

probe || { echo "chip_queue3: tunnel down ($failures failures so far)"; exit $((90 + failures)); }
# 1. ViT MFU push at the b64 sweet spot: fused CE, flash, both.
python scripts/perf_sweep.py --batches 64 --model vit-b16 --fused-loss \
  --out perf/vit_fusedce.json 2>&1 | tail -3 || failures=$((failures+1))
python scripts/perf_sweep.py --batches 64 --model vit-b16 --attention flash \
  --out perf/vit_flash.json 2>&1 | tail -3 || failures=$((failures+1))
python scripts/perf_sweep.py --batches 64 --model vit-b16 --attention flash --fused-loss \
  --out perf/vit_flash_fusedce.json 2>&1 | tail -3 || failures=$((failures+1))

probe || { echo "chip_queue3: tunnel down ($failures failures so far)"; exit $((90 + failures)); }
# 1b. Selective attention remat at the batches where dense-ViT MFU FELL
#     (allocator pressure from the [B,H,N,N] intermediates, §10b): recompute
#     only those, keep everything else resident.
python scripts/perf_sweep.py --batches 128,256 --model vit-b16 \
  --remat --remat-policy attention \
  --out perf/vit_remat_attn.json 2>&1 | tail -4 || failures=$((failures+1))

probe || { echo "chip_queue3: tunnel down ($failures failures so far)"; exit $((90 + failures)); }
# 1c. ViT-B/16 b64 per-op profile: where the 0.537 -> 0.70 MFU gap lives
#     (attention bytes vs matmul shape vs something else).
python scripts/perf_profile.py --model vit-b16 --batch 64 \
  --trace-dir perf/vit_trace --out perf/vit_profile.json 2>&1 | tail -3 || failures=$((failures+1))

probe || { echo "chip_queue3: tunnel down ($failures failures so far)"; exit $((90 + failures)); }
# 2. SPMD-vs-plain reconciliation row (VERDICT r3 item 6).
python scripts/perf_sweep.py --batches 128 --model resnet50 --spmd \
  --out perf/sweep_spmd.json 2>&1 | tail -3 || failures=$((failures+1))

probe || { echo "chip_queue3: tunnel down ($failures failures so far)"; exit $((90 + failures)); }
# 3. BN bf16-stat accumulation row (VERDICT r3 item 7).
python scripts/perf_sweep.py --batches 128 --model resnet50 --bn-bf16-stats \
  --out perf/sweep_bnbf16.json 2>&1 | tail -3 || failures=$((failures+1))

probe || { echo "chip_queue3: tunnel down ($failures failures so far)"; exit $((90 + failures)); }
# 4. Retry the N=1025 flash point with power-of-two blocks (the hang was the
#    one 384-block config), then the long-N OOM probe. Each child now gets
#    SIGTERM+grace on timeout and the driver aborts if the tunnel dies.
python scripts/long_seq_bench.py --sizes 512 --batch 32 \
  --out perf/long_seq_512_retry.json 2>&1 | tail -4 || failures=$((failures+1))

probe || { echo "chip_queue3: tunnel down ($failures failures so far)"; exit $((90 + failures)); }
python scripts/long_seq_bench.py --sizes 768,1024 --batch 16 --remat \
  --out perf/long_seq_4k.json 2>&1 | tail -6 || failures=$((failures+1))

echo "chip_queue3: $failures item(s) failed"
exit $failures
