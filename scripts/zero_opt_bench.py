#!/usr/bin/env python
"""Per-replica optimizer-state memory under ZeRO-1 sharding, measured.

The 1/R claim behind elastic data parallelism (docs/parallelism.md;
arXiv:2004.13336): with ``--zero1`` the optimizer moments shard over the
``data`` axis, so the optimizer bytes RESIDENT on one replica shrink
~1/R while the replicated reference pays the full state everywhere.
This script measures it on the CPU fleet this container has — R virtual
devices via ``xla_force_host_platform_device_count`` — by walking every
optimizer-state leaf's addressable shards on device 0
(``tpuic.train.state.opt_state_device_bytes``), plus the process-level
view from the telemetry memory sampler for the honest cross-check.

Writes ``perf/elastic_zero.json``. The committed artifact carries the
caveat in-band: these are CPU-fleet numbers (virtual devices, real
shardings, real orbax round-trip semantics) — the chip measurement is
pending, and on a real pod the same shard walk runs per-host.

    python scripts/zero_opt_bench.py [--out perf/elastic_zero.json]
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

MODELS = {"resnet18": (16, "adam"), "resnet50": (32, "adam")}
REPLICAS = (1, 2, 4, 8)


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default=os.path.join(_REPO, "perf",
                                                 "elastic_zero.json"))
    args = p.parse_args()

    import jax
    from tpuic.config import OptimConfig
    from tpuic.models import create_model
    from tpuic.parallel.sharding import shard_state, state_shardings
    from tpuic.runtime.mesh import replica_mesh
    from tpuic.telemetry.memory import MemorySampler
    from tpuic.train.optimizer import make_optimizer
    from tpuic.train.state import (create_train_state, opt_state_bytes,
                                   opt_state_device_bytes)
    from tpuic.utils import tree_bytes

    dev0 = jax.devices()[0]
    sampler = MemorySampler(publish=lambda *a, **k: None, devices=[dev0])
    out = {"schema": "tpuic.elastic_zero.v1",
           "platform": jax.devices()[0].platform,
           "devices": jax.device_count(),
           "caveat": ("CPU fleet measurement (virtual XLA host devices, "
                      "real NamedShardings): per-replica bytes are the "
                      "sum of optimizer-state shards resident on device "
                      "0. Chip (v5e) measurement pending — same shard "
                      "walk, per-host. The memory-sampler RSS row is the "
                      "process-level cross-check, noisy by nature "
                      "(allocator slack, XLA buffers)."),
           "models": {}}
    for name, (size, opt) in MODELS.items():
        ocfg = OptimConfig(optimizer=opt, class_weights=(), milestones=())
        model = create_model(name, 7, dtype="float32")
        state = create_train_state(model, make_optimizer(ocfg),
                                   jax.random.key(0), (2, size, size, 3))
        rows = {}
        for r in REPLICAS:
            mesh = replica_mesh(r)
            if mesh.size > 1:
                sh = state_shardings(state, mesh, tp=False, fsdp=False,
                                     zero1=True)
                st = shard_state(state, sh)
            else:
                st = state
            mem = sampler.sample()
            rows[str(r)] = {
                "opt_bytes_global": opt_state_bytes(st),
                "opt_bytes_device0": opt_state_device_bytes(st, dev0),
                "frac_of_global": round(
                    opt_state_device_bytes(st, dev0)
                    / max(1, opt_state_bytes(st)), 4),
                "sampler_rss_bytes": (mem or {}).get("process_rss_bytes"),
            }
            del st
        out["models"][name] = {
            "param_bytes": tree_bytes(state.params),
            "optimizer": opt,
            "per_replica": rows,
        }
        del state
        r1 = out["models"][name]["per_replica"]
        print(f"[zero] {name}: global "
              f"{r1['1']['opt_bytes_global'] / 1e6:.1f} MB opt state; "
              f"device-0 resident "
              + ", ".join(f"R={r} {r1[str(r)]['opt_bytes_device0'] / 1e6:.1f} MB"
                          f" ({r1[str(r)]['frac_of_global']:.2f}x)"
                          for r in REPLICAS))
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
    print(f"[zero] artifact -> {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
