#!/usr/bin/env python
"""Chaos soak (ISSUE 5 acceptance; runs in tier-1 CI).

The end-to-end proof of the whole robustness stack: a REAL supervised
training run (`tpuic.runtime.supervisor.Supervisor` driving the real
`train.py` CLI as a child, CPU, synthetic data) under a seeded
per-attempt fault schedule —

- ``nan_batch``   — in-graph skip guard (fires in every attempt that
                    replays its step, so the trajectory stays bitwise
                    comparable to the baseline, which arms it too)
- ``ckpt_kill``   — process dies mid checkpoint-commit (attempt 0)
- ``hard_crash``  — SIGKILL to self mid-epoch (attempt 1)
- ``hang_step``   — wedged step; the watchdog must SIGQUIT a stack dump,
                    then SIGTERM, then SIGKILL (attempt 2)
- ``sigterm``     — clean preemption flush, exit 43, immediate restart
                    with step-exact resume (attempt 3)

— and an UNDISTURBED baseline run (same config, same ``nan_batch``)
raced in parallel. The soak then asserts the supervised run converged to
the *identical* end state:

- same final global optimizer step (checkpoint meta + max step event),
- same per-epoch eval accuracy (exact float equality — resume is
  bitwise),
- >= 2 automatic restarts observed, zero ledger violations (no step ever
  skipped past the best previously observed step + 1 — nothing lost,
  nothing double-counted),
- the hang produced a non-empty faulthandler stack dump artifact,
- the sigterm attempt exited with the contract's code 43,

plus the crash-loop policy: a child that fails deterministically makes
the supervisor give up with exit 45 after ``crash_loop_k`` no-progress
restarts instead of restarting forever.

Exit 0 on success.   python scripts/chaos_soak.py [--keep] [-v]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from tpuic.runtime.supervisor import (EXIT_CRASH_LOOP,  # noqa: E402
                                      EXIT_PREEMPTED, Supervisor)

# Fault keys are host-tracked global step numbers (step0 + loop index,
# 0-based). With 24 train images / global batch 4 there are 6 loop steps
# per epoch; the nan_batch skip at key 2 means the optimizer step counter
# ends at 11 after 2 epochs, and epoch 1's keys are 5..10 (key 5 is
# ambiguous — it is also epoch 0's last — so epoch-1 faults use >= 6).
PER_CLASS = 12          # x2 classes = 24 train images
BATCH = 4               # 6 steps/epoch on the single CPU device
EPOCHS = 2
NAN_SPEC = "nan_batch@2"
CHAOS = [
    NAN_SPEC + ",ckpt_kill*1",   # dies committing epoch 0's best
    NAN_SPEC + ",hard_crash@8",  # SIGKILL mid epoch 1 (replays epoch 0)
    "hang_step@9",               # wedge; watchdog SIGQUIT/SIGTERM/SIGKILL
    "sigterm@10",                # clean flush, exit 43, step-exact resume
    "",                          # fault-free final attempt completes
]


def _train_cmd(data: str, ckpt: str, cache: str, jsonl: str) -> list:
    return [sys.executable, os.path.join(_REPO, "train.py"),
            "--datadir", data, "--model", "resnet18-cifar",
            "--resize", "24", "--batchsize", str(BATCH),
            "--epochs", str(EPOCHS), "--optimizer", "sgd", "--lr", "0.01",
            "--no-class-weights", "--log-every-steps", "1",
            "--save-period", "1", "--workers", "2",
            "--ckpt-dir", ckpt, "--cache-dir", cache,
            "--metrics-jsonl", jsonl]


def _events(path: str) -> list:
    # A SIGKILL fault can tear a JSONL line mid-write, and the next
    # attempt appends its first event onto the fragment; the SHARED
    # tolerant reader (telemetry/events.read_jsonl — also behind the
    # regress gate and the fleet aggregator) skips lines that don't
    # parse rather than crashing the verdict path.
    from tpuic.telemetry.events import read_jsonl
    return read_jsonl(path, on_torn=lambda ln: print(
        f"  [soak] skipping torn jsonl line in {path}: {ln[:80]!r}"))


def _evals(recs: list) -> dict:
    """{epoch: accuracy}, last occurrence wins (replayed epochs re-emit
    the identical value — that identity is itself asserted below)."""
    out = {}
    for r in recs:
        if r["event"] == "eval":
            out[int(r["epoch"])] = r["accuracy"]
    return out


def _final_meta_step(ckpt: str):
    # The optimizer step of the committed checkpoint lives in the commit
    # manifest (the meta sidecar carries only the resume keys). None
    # when the run died before committing one — the verdict path must
    # print its per-assertion diagnosis, not a traceback.
    try:
        man = json.load(open(os.path.join(ckpt, "resnet18-cifar",
                                          "latest.manifest.json")))
        return int(man["step"])
    except (OSError, ValueError, KeyError, TypeError):
        return None


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--watchdog-s", type=float, default=20.0,
                   help="hang-detection window; must exceed the longest "
                        "legitimately silent span (eval execution — "
                        "compiles beat via the jax.monitoring bridge)")
    p.add_argument("--keep", action="store_true",
                   help="keep the temp workdir for inspection")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="stream child stdout/stderr instead of hiding it")
    args = p.parse_args()

    t_start = time.monotonic()
    work = tempfile.mkdtemp(prefix="tpuic_chaos_")
    failures: list = []

    def check(ok: bool, msg: str) -> None:
        print(("  ok  " if ok else "  FAIL") + f" {msg}")
        if not ok:
            failures.append(msg)

    try:
        # -- crash-loop policy (pure stdlib, ~1 s) ----------------------
        print("[soak] crash-loop policy: deterministic failure must make "
              "the supervisor give up, not restart forever")
        sup0 = Supervisor(
            [sys.executable, "-c", "import sys; sys.exit(7)"],
            os.path.join(work, "crashloop"), watchdog_s=30.0,
            startup_grace_s=30.0, poll_s=0.05, max_restarts=10,
            backoff_s=0.05, backoff_max_s=0.1, crash_loop_k=2)
        rc = sup0.run()
        check(rc == EXIT_CRASH_LOOP,
              f"gave up with exit {EXIT_CRASH_LOOP} (got {rc})")
        check(len(sup0.attempts) == 2 and sup0.restarts == 1,
              f"stopped after crash_loop_k=2 no-progress attempts "
              f"({len(sup0.attempts)} attempts, {sup0.restarts} restart)")

        # -- dataset + parallel baseline --------------------------------
        from tpuic.data.synthetic import make_synthetic_imagefolder
        data = os.path.join(work, "data")
        make_synthetic_imagefolder(data, classes=("a", "b"),
                                   per_class=PER_CLASS, size=24)
        # XLA_FLAGS overridden (not popped): the Supervisor builds its
        # child env as os.environ + these overrides, so an inherited
        # fake-device flag would otherwise leak into the supervised run
        # only and desync the two trajectories' device counts. The
        # persistent compile cache is shared by every attempt AND the
        # baseline (identical env => identical trajectories): the 6
        # process startups would otherwise each repay the same XLA
        # compiles. cpu + cache + skip-guard auto-disables state
        # donation (train/step.py's bisected aliasing gate) — same on
        # both sides, so the bitwise comparison holds.
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   TF_CPP_MIN_LOG_LEVEL="3", XLA_FLAGS="",
                   JAX_COMPILATION_CACHE_DIR=os.path.join(work,
                                                          "jax_cache"))

        base_jsonl = os.path.join(work, "baseline.jsonl")
        base_ckpt = os.path.join(work, "ckpt_base")
        base_cmd = _train_cmd(data, base_ckpt,
                              os.path.join(work, "cache_base"), base_jsonl)
        sink = None if args.verbose else subprocess.DEVNULL
        print("[soak] baseline (undisturbed, nan_batch only) started "
              "in parallel")
        baseline = subprocess.Popen(
            base_cmd, cwd=_REPO, env=dict(env, TPUIC_FAULTS=NAN_SPEC),
            stdout=sink, stderr=sink)

        # -- the supervised chaos run -----------------------------------
        print(f"[soak] supervised run: {len(CHAOS)} scheduled attempts "
              f"({', '.join(s or 'fault-free' for s in CHAOS)})")
        sup_jsonl = os.path.join(work, "supervised.jsonl")
        sup_ckpt = os.path.join(work, "ckpt_sup")
        state_dir = os.path.join(work, "supervise")
        sup = Supervisor(
            _train_cmd(data, sup_ckpt, os.path.join(work, "cache_sup"),
                       sup_jsonl),
            state_dir, watchdog_s=args.watchdog_s, startup_grace_s=600.0,
            quit_wait_s=2.0, grace_s=5.0, poll_s=0.25, max_restarts=8,
            backoff_s=0.25, backoff_max_s=2.0, crash_loop_k=3,
            heartbeat_interval_s=0.2, chaos=CHAOS,
            env=dict(env, PYTHONPATH=_REPO))
        rc = sup.run()
        base_rc = baseline.wait(timeout=900)

        # -- the verdict -------------------------------------------------
        print("[soak] supervised run finished "
              f"(exit {rc}, {len(sup.attempts)} attempts, "
              f"{sup.restarts} restarts, best step {sup.best_step}); "
              f"baseline exit {base_rc}")
        check(rc == 0, "supervised run completed cleanly (exit 0)")
        check(base_rc == 0, "baseline completed cleanly (exit 0)")
        check(sup.restarts >= 2,
              f"{sup.restarts} automatic restarts observed (>= 2)")
        check(sup.violations == 0,
              "zero progress-ledger violations (no step lost or "
              "double-counted)")
        hung = [a for a in sup.attempts if a.hung]
        check(len(hung) == 1, "exactly the hang_step attempt was "
              f"watchdog-killed (got {[a.attempt for a in hung]})")
        if hung:
            dump = os.path.join(state_dir, f"stackdump-{hung[0].attempt}.txt")
            body = open(dump).read() if os.path.exists(dump) else ""
            check("File" in body and len(body) > 50,
                  f"hang produced a faulthandler stack dump ({dump}, "
                  f"{len(body)} bytes)")
            # Flight recorder (telemetry/flight.py): the same SIGQUIT
            # must also have dumped the event timeline leading into the
            # wedge — non-empty, parseable, and every recorded event
            # stamped BEFORE the dump trailer (i.e. before the SIGQUIT
            # was handled): stacks say where, the flight dump says what
            # happened on the way in.
            fdump = os.path.join(state_dir,
                                 f"flightdump-{hung[0].attempt}.jsonl")
            frecs = _events(fdump)
            trailer = frecs[-1] if frecs else {}
            body_evs = [r for r in frecs if r.get("event") != "flight_dump"]
            check(trailer.get("event") == "flight_dump"
                  and trailer.get("reason") == "sigquit",
                  f"flight dump ends with a sigquit trailer ({fdump}, "
                  f"{len(frecs)} records)")
            check(len(body_evs) > 0 and any(
                      r.get("event") == "step" for r in body_evs),
                  f"flight dump carries the event timeline "
                  f"({len(body_evs)} events incl. steps)")
            check(bool(body_evs) and bool(trailer) and all(
                      r.get("t", 1e18) <= trailer.get("t", 0)
                      for r in body_evs),
                  "every flight-dump event precedes the SIGQUIT trailer")
        codes = [a.returncode for a in sup.attempts]
        check(EXIT_PREEMPTED in codes,
              f"sigterm attempt exited {EXIT_PREEMPTED} per the contract "
              f"(attempt codes: {codes})")

        b_recs, s_recs = _events(base_jsonl), _events(sup_jsonl)
        # default=None: a run that died before its first step event must
        # degrade into check() failures below, not a bare-max ValueError
        # that replaces the whole diagnosis with a traceback.
        b_step = max((r["step"] for r in b_recs if r["event"] == "step"),
                     default=None)
        s_step = max((r["step"] for r in s_recs if r["event"] == "step"),
                     default=None)
        b_meta, s_meta = _final_meta_step(base_ckpt), _final_meta_step(sup_ckpt)
        check(b_meta is not None and s_meta == b_meta,
              f"final checkpointed optimizer step matches baseline "
              f"({s_meta} == {b_meta})")
        check(sup.best_step == b_step == s_step,
              f"max step event + supervisor ledger agree with baseline "
              f"(ledger {sup.best_step}, events {s_step}, "
              f"baseline {b_step})")
        b_eval, s_eval = _evals(b_recs), _evals(s_recs)
        check(set(b_eval) == set(s_eval) == set(range(EPOCHS)),
              f"both runs evaluated every epoch (baseline {sorted(b_eval)}, "
              f"supervised {sorted(s_eval)})")
        check(b_eval == s_eval,
              f"per-epoch eval accuracy identical to baseline "
              f"({s_eval} == {b_eval})")
        # Replayed epochs must have re-produced the identical eval value
        # (bitwise resume): every supervised eval event for one epoch
        # carries one accuracy.
        per_epoch: dict = {}
        for r in s_recs:
            if r["event"] == "eval":
                per_epoch.setdefault(int(r["epoch"]), set()).add(r["accuracy"])
        check(all(len(v) == 1 for v in per_epoch.values()),
              f"replayed evals were bitwise identical ({per_epoch})")
        restarts = [r for r in s_recs if r["event"] == "restart"]
        check(len(restarts) == sup.restarts,
              f"every restart announced itself as a 'restart' event "
              f"({len(restarts)} == {sup.restarts})")

        took = time.monotonic() - t_start
        if failures:
            print(f"\nFAIL: {len(failures)} assertion(s) in {took:.1f}s")
            for f in failures:
                print(f"  - {f}")
            return 1
        print(f"\nOK: chaos soak green in {took:.1f}s — "
              f"{len(sup.attempts)} attempts, {sup.restarts} restarts, "
              f"final step {s_meta}, eval metrics identical to the "
              f"undisturbed baseline")
        return 0
    finally:
        if args.keep:
            print(f"workdir kept: {work}")
        else:
            shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
