#!/bin/bash
# Round-4 remainder of the chip queue (items 1-3 of chip_queue.sh ran at
# 01:00 UTC before the tunnel flapped; see PERF_ANALYSIS.md §10).
# Ordering: headline first (fit_proof with the deferred-readback fix),
# then a fresh bench line, then kernels/long-seq, then the one-row probes.
set -x -o pipefail
failures=0
cd /root/repo
probe() { python -c "
from tpuic.runtime.axon_guard import tpu_reachable
import sys; sys.exit(0 if tpu_reachable(150) else 1)"; }

probe || { echo "chip_queue2: tunnel down ($failures failures so far)"; exit $((90 + failures)); }
# 1. fit_proof rerun: loop should now match bench (deferred readbacks, 279e8f3).
TPUIC_FIT_EPOCHS=3 python scripts/fit_proof.py 2>&1 | tail -20 || failures=$((failures+1))

probe || { echo "chip_queue2: tunnel down ($failures failures so far)"; exit $((90 + failures)); }
# 2. Fresh live-TPU bench line early, in case the tunnel flaps again.
python bench.py 2>&1 | tail -2 || failures=$((failures+1))

probe || { echo "chip_queue2: tunnel down ($failures failures so far)"; exit $((90 + failures)); }
# 3. Kernel microbench rerun: flash with length-adaptive blocks.
python scripts/pallas_smoke.py 2>&1 | tail -4 || failures=$((failures+1))

probe || { echo "chip_queue2: tunnel down ($failures failures so far)"; exit $((90 + failures)); }
# 4. Dense-vs-flash crossover + long-N probe where dense should OOM.
python scripts/long_seq_bench.py --sizes 224,384,512 --batch 32 2>&1 | tail -8 || failures=$((failures+1))
python scripts/long_seq_bench.py --sizes 768,1024 --batch 16 --remat \
  --out perf/long_seq_4k.json 2>&1 | tail -6 || failures=$((failures+1))

probe || { echo "chip_queue2: tunnel down ($failures failures so far)"; exit $((90 + failures)); }
# 5. ViT MFU push at the b64 sweet spot: fused CE, then flash attention.
python scripts/perf_sweep.py --batches 64 --model vit-b16 --fused-loss \
  --out perf/vit_fusedce.json 2>&1 | tail -3 || failures=$((failures+1))
python scripts/perf_sweep.py --batches 64 --model vit-b16 --attention flash \
  --out perf/vit_flash.json 2>&1 | tail -3 || failures=$((failures+1))
python scripts/perf_sweep.py --batches 64 --model vit-b16 --attention flash --fused-loss \
  --out perf/vit_flash_fusedce.json 2>&1 | tail -3 || failures=$((failures+1))

probe || { echo "chip_queue2: tunnel down ($failures failures so far)"; exit $((90 + failures)); }
# 6. SPMD-vs-plain reconciliation row (VERDICT r3 item 6).
python scripts/perf_sweep.py --batches 128 --model resnet50 --spmd \
  --out perf/sweep_spmd.json 2>&1 | tail -3 || failures=$((failures+1))

probe || { echo "chip_queue2: tunnel down ($failures failures so far)"; exit $((90 + failures)); }
# 7. BN bf16-stat accumulation row (VERDICT r3 item 7).
python scripts/perf_sweep.py --batches 128 --model resnet50 --bn-bf16-stats \
  --out perf/sweep_bnbf16.json 2>&1 | tail -3 || failures=$((failures+1))

echo "chip_queue2: $failures item(s) failed"
exit $failures
