#!/bin/bash
# Round-5 queue #6: work stranded by the 08:52Z mid-queue4 tunnel flap,
# plus the ViT-L follow-ups the fresh 0.543 datapoint motivates.
#
# Ran in the 08:32-08:52Z window (committed artifacts): bench x2
# (2,559 / 2,537 img/s), true blocks-remat N=4097 (flash trains at
# 1,843 ms; dense OOM 33 GB), ViT-L/16 MFU sweep (b16/32/64 =
# 0.508/0.495/0.543), pallas_smoke with the PACKED kernels' first
# Mosaic execution (fwd 4.2e-7 / bwd 3.4e-4 vs dense-HIGHEST, green).
#
# NOTE: a poller started before this file existed parsed its queue list
# at startup and will NEVER run queue6 — restart the poller (kill + re-
# nohup chip_poller5.sh) after its current queue pass stamps out.
#
# Stranded there (items 1-4 below), plus all of chip_queue5 (the poller
# stamped it after its items failed fast on the unreachable guard), plus
# new ViT-L probes: 0.543 at b64 says width alone doesn't move the
# plateau; gelu-remat frees the [B,N,4D] mlp_up residuals, so the
# b96/b128 rows can test whether more per-matmul work does.
set -x -o pipefail
failures=0
cd /root/repo
. scripts/chip_wait.sh
chip_wait "$MEASURE_PAT" "chip_queue6"

# Between items, yield to any driver-initiated bench.py (bench itself
# waits only 180 s bounded; the queue can afford the full wait). The
# pattern is anchored on a separator so it cannot substring-match
# long_seq_bench.py (a queue item!) or bench_data.py.
yield_to_bench() { chip_wait '[ /]bench\.py' "chip_queue6-yield"; }

# -- stranded from chip_queue4 ------------------------------------------
# Skip any row the resumed queue4 already produced ON CHIP (the hung-at-
# init sweep completes if the tunnel comes back while it still lives);
# existing CPU-platform artifacts do NOT count as done.
have_tpu() {  # $1: perf json path -> exit 0 iff it records a CLEAN tpu run
  # An artifact with any "error" key does not count: long_seq_bench and
  # perf_sweep write their --out file even when individual rows failed
  # (e.g. a timeout after the first row), and skipping on that would
  # strand exactly the measurement this requeue exists to capture.
  python - "$1" <<'EOF'
import json, sys
try:
    d = json.load(open(sys.argv[1]))
except Exception:
    sys.exit(1)
text = json.dumps(d)
ok = ('"tpu"' in text or 'TPU v5' in text) and '"error"' not in text
sys.exit(0 if ok else 1)
EOF
}

yield_to_bench
have_tpu perf/packed_valid_smoke.json \
  || python scripts/packed_valid_smoke.py 2>&1 | tail -2 \
  || failures=$((failures+1))
yield_to_bench
have_tpu perf/vit_flash_folded.json \
  || TPUIC_FLASH_PACKED=0 python scripts/perf_sweep.py --batches 64 \
    --model vit-b16 --attention flash \
    --out perf/vit_flash_folded.json 2>&1 | tail -3 \
  || failures=$((failures+1))
yield_to_bench
have_tpu perf/vit_flash_packed.json \
  || python scripts/perf_sweep.py --batches 64 --model vit-b16 \
    --attention flash \
    --out perf/vit_flash_packed.json 2>&1 | tail -3 \
  || failures=$((failures+1))
yield_to_bench
have_tpu perf/long_seq_2305_packed.json \
  || python scripts/long_seq_bench.py --sizes 768 --batch 16 --remat \
    --remat-policy blocks \
    --out perf/long_seq_2305_packed.json 2>&1 | tail -4 \
  || failures=$((failures+1))

# -- stranded chip_queue5 (all items failed fast on the 08:52Z flap) ----
# Same skip rule: the old poller still lists queue5 and re-runs it on
# recovery before this script; whatever it lands on chip stays landed.
yield_to_bench
have_tpu perf/convergence_digits.json \
  || python scripts/convergence_digits.py --skip-control 2>&1 | tail -6 \
  || failures=$((failures+1))
yield_to_bench
have_tpu perf/resume_cache_proof.json \
  || python scripts/resume_cache_proof.py 2>&1 | tail -6 \
  || failures=$((failures+1))
yield_to_bench
have_tpu perf/bench_cache_timing.json \
  || python scripts/bench_cache_timing.py 2>&1 | tail -2 \
  || failures=$((failures+1))
yield_to_bench
have_tpu perf/vit_gelu_remat.json \
  || python scripts/perf_sweep.py --batches 64,128 --model vit-b16 \
    --remat --remat-policy gelu \
    --out perf/vit_gelu_remat.json 2>&1 | tail -4 \
  || failures=$((failures+1))

# Refresh the loop-vs-bench ratio against a same-session bench line (the
# tracked-number rule: every ratio cites the freshest live bench). No
# have_tpu guard — the committed artifact IS a TPU run (r4); the point
# is recomputing it against today's line.
yield_to_bench
python scripts/fit_proof.py 2>&1 | tail -4 || failures=$((failures+1))

# -- new: ViT-L frontier probes motivated by the 0.543 plateau ----------
# gelu-remat drops the twelve [B,N,4D] mlp_up pre-activations (1.2 GB at
# b64), opening batch headroom past the 12.7-of-15.75 GB dense b64 peak.
# b96 AND b128 (PERF_ANALYSIS §13d cites both probes): b128 is the AI~170
# point the §13d target band assumes — if it OOMs even under gelu-remat,
# that row's absence is itself the datapoint.
yield_to_bench
python scripts/perf_sweep.py --batches 64,96,128 --model vit-l16 \
  --remat --remat-policy gelu \
  --out perf/vitl_gelu_remat.json 2>&1 | tail -4 || failures=$((failures+1))

echo "chip_queue6: $failures item(s) failed"
exit $failures
