#!/usr/bin/env python
"""Rollout soak: the zero-downtime model-lifecycle CI gate
(docs/serving.md, "Model lifecycle: hot-swap, canary, rollback").

Two REAL engine replicas behind the stdlib router, a shared-harness
Poisson storm at the committed knee flowing the whole time, and THREE
lifecycle arms — the gate is bidirectional like every gate in this
repo:

1. **Clean canary promotes.**  A real committed checkpoint (CRC
   manifest and all) rolls out 50% → 100% behind the canary driver and
   promotes.  Asserted: verdict ``promoted``; BOTH replicas' live pongs
   report the candidate digest; the ledger is exact (resolved +
   typed-rejected == offered, zero untyped errors, zero duplicates,
   every outcome hook fired); and **zero steady-state compiles across
   the swap** — each replica's scraped ``tpuic_serve_compiles_total``
   is flat from pre-rollout to post-promote (the aval-matched swap
   reuses the AOT executables), and the soak process itself runs under
   ``assert_compiles_flat``.
2. **Corrupt artifact refused at the gate.**  A copy of the candidate
   with one payload file bit-flipped (``faults.corrupt_file`` — the
   manifest now lies about the bytes) is offered to the same fleet:
   the canary's swap gate must refuse it with the typed
   ``swap_corrupt`` verdict, BEFORE any traffic stage — no split, no
   digest change, and the follow-up wave is still exact.
3. **Degraded canary auto-rolls-back on SLO burn.**  A second fleet is
   spawned with ``canary_degrade`` armed (fires only on non-boot
   weights — exactly the canary, runtime/faults.py): the candidate
   gates clean, goes live on the canary, serves slow, burns the error
   budget, and the driver rolls back.  Asserted: verdict
   ``rolled_back`` (reason ``slo_burn``); the canary's pong is back on
   the boot digest; the ledger is exact through the whole storm (the
   degraded requests RESOLVE — slow, never dropped); and a
   post-rollback wave is healthy and exact.

Artifacts for CI upload on failure: both router state dirs (ledgers
include the ``rollout`` events), the per-replica logs, and the verdict
JSON.

    python scripts/rollout_soak.py --workdir rollout-soak-work
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import threading
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

CACHE_DIR = os.path.join(_REPO, "tests", ".jax_cache")


def _force_cpu() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
    from tpuic.runtime.axon_guard import drop_axon_vars
    drop_axon_vars(os.environ)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def _committed_knee() -> float:
    try:
        with open(os.path.join(_REPO, "perf", "bench_serve.json")) as f:
            return float(json.load(f)["open_loop_knee_req_per_sec"] or 0.0)
    except (OSError, ValueError, KeyError, TypeError):
        return 0.0


def _scrape_counter(port, name: str) -> float:
    import urllib.request
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=2.0) as resp:
            text = resp.read().decode("utf-8", "replace")
    except Exception:
        return float("nan")
    for ln in text.splitlines():
        if ln.startswith(name) and not ln.startswith("#"):
            try:
                return float(ln.rsplit(None, 1)[1])
            except (ValueError, IndexError):
                pass
    return float("nan")


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--workdir", default="rollout-soak-work")
    p.add_argument("--model", default="resnet18-cifar")
    p.add_argument("--size", type=int, default=24)
    p.add_argument("--buckets", default="1,4")
    p.add_argument("--requests", type=int, default=700,
                   help="storm length per rollout arm")
    p.add_argument("--storm-factor", type=float, default=0.8,
                   help="drive = factor x per-replica capacity anchor "
                        "— at the committed knee, NOT past it: the "
                        "lifecycle proof wants mostly-resolved traffic "
                        "feeding the canary's SLO window")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--spawn-timeout-s", type=float, default=600.0)
    args = p.parse_args(argv)

    _force_cpu()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpuic.analysis.runtime import assert_compiles_flat
    from tpuic.checkpoint.manager import CheckpointManager
    from tpuic.config import OptimConfig
    from tpuic.models import create_model
    from tpuic.runtime import faults
    from tpuic.serve import InferenceEngine, make_forward
    from tpuic.serve.loadgen import probe_unbatched_rps, run_stream
    from tpuic.serve.rollout import CanaryRollout
    from tpuic.serve.router import Router
    from tpuic.train.optimizer import make_optimizer
    from tpuic.train.state import create_train_state

    workdir = os.path.abspath(args.workdir)
    shutil.rmtree(workdir, ignore_errors=True)
    os.makedirs(workdir, exist_ok=True)
    failures = []
    verdicts = {}

    def fail(msg: str) -> None:
        failures.append(msg)
        print(f"[rollout_soak] FAIL: {msg}", file=sys.stderr)

    # ---- capacity anchor + hot compile cache (router_soak discipline)
    buckets = tuple(int(b) for b in args.buckets.split(","))
    model = create_model(args.model, 10, dtype="float32")
    variables = model.init(jax.random.key(0),
                           jnp.zeros((1, args.size, args.size, 3),
                                     jnp.float32), train=False)
    probe_engine = InferenceEngine(
        forward_fn=make_forward(model, normalize=True),
        variables=variables, image_size=args.size, input_dtype=np.uint8,
        buckets=buckets, max_wait_ms=5.0, queue_size=256)
    probe_engine.warmup()
    rng = np.random.default_rng(args.seed)
    reqs = [rng.integers(0, 256, (1, args.size, args.size, 3), np.uint8)
            for _ in range(max(args.requests, 400))]
    local_rps, service_s, _, _ = probe_unbatched_rps(probe_engine, reqs)
    probe_engine.close()
    anchor = max(_committed_knee(), local_rps)
    drive_rps = args.storm_factor * anchor

    # ---- the candidate artifact: a REAL committed checkpoint --------
    # Same architecture, different weights (seed 1) — the hot-swap
    # case: aval-identical, so the flip must reuse every executable.
    ckpt_clean = os.path.join(workdir, "ckpt_candidate")
    ocfg = OptimConfig(optimizer="adam", learning_rate=1e-3,
                       class_weights=(), milestones=())
    cand_state = create_train_state(
        model, make_optimizer(ocfg), jax.random.key(1),
        (1, args.size, args.size, 3))
    mgr = CheckpointManager(ckpt_clean, args.model)
    mgr.save_latest(cand_state, epoch=0, best_score=0.0)
    mgr.wait()
    # The corrupt twin: same artifact, one payload file bit-flipped
    # AFTER the manifest was committed — the manifest now lies.
    ckpt_corrupt = os.path.join(workdir, "ckpt_corrupt")
    shutil.copytree(ckpt_clean, ckpt_corrupt)
    track_dir = os.path.join(ckpt_corrupt, args.model, "latest")
    victim, size = None, -1
    for dirpath, _, files in os.walk(track_dir):
        for fn in files:
            fp = os.path.join(dirpath, fn)
            if os.path.getsize(fp) > size:
                victim, size = fp, os.path.getsize(fp)
    faults.corrupt_file(victim)

    replica_cmd = [
        sys.executable, "-m", "tpuic.serve",
        "--synthetic-init", "--model", args.model, "--num-classes", "10",
        "--resize", str(args.size), "--buckets", args.buckets,
        "--max-wait-ms", "5", "--queue-size", "256",
        "--listen", "127.0.0.1:0", "--prom-port", "-1",
        "--compile-cache-dir", CACHE_DIR,
        "--drain-timeout", "10",
    ]
    candidate = {"ckpt_dir": ckpt_clean, "track": "latest"}
    incumbent = {"synthetic_seed": 0}

    def storm(router, n, on_done=None):
        """Shared-harness Poisson storm in a thread; returns a join()
        that yields the settled snapshot."""
        items = [(r, {"timeout": 0}) for r in reqs[:n]]
        offsets = np.cumsum(rng.exponential(1.0 / drive_rps, size=n))
        box = {}

        def run():
            box["out"] = run_stream(router, items, offsets_s=offsets,
                                    result_timeout_s=240.0,
                                    on_done=on_done)

        t = threading.Thread(target=run, daemon=True)
        t.start()

        def join(timeout=600.0):
            t.join(timeout=timeout)
            if t.is_alive():
                raise TimeoutError("storm never settled")
            return box["out"]

        return join

    def check_ledger(arm, snap, offered, outcomes=None):
        if snap["requests"] + snap["rejected"] != offered \
                or snap["errors"] != 0:
            fail(f"{arm}: ledger violation — {snap['requests']} resolved"
                 f" + {snap['rejected']} rejected (+{snap['errors']} "
                 f"untyped) != {offered} offered")
        if snap["duplicates"] or snap["wire_errors"]:
            fail(f"{arm}: at-most-once violated — {snap['duplicates']} "
                 f"duplicates, {snap['wire_errors']} wire errors")
        if outcomes is not None and len(outcomes) != offered:
            fail(f"{arm}: outcome hook fired {len(outcomes)}/{offered} "
                 "— some request neither resolved nor got a verdict")

    def wait_digest(router, name, digest, timeout=30.0) -> bool:
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            for rep in router.replicas:
                if rep.name == name and rep.digest == digest:
                    return True
            time.sleep(0.05)
        return False

    # The canary-scoped SLO: machine-relative threshold off the probe
    # (the overload-soak discipline) with a 0.9 target, so a healthy
    # canary holds burn << the 2.0 rollback trigger while a degraded
    # one saturates it.
    thresh_ms = max(250.0, 12_000.0 * service_s)
    slo = f"serve_latency:p99<={thresh_ms:.0f}ms@0.9"
    degrade_s = 3.0 * thresh_ms / 1000.0
    print(f"[rollout_soak] anchors: drive {drive_rps:.1f} req/s, slo "
          f"{slo}, degrade {degrade_s:.2f}s/batch", file=sys.stderr)

    def rollout_for(router, cand):
        return CanaryRollout(
            router, cand, incumbent, objective=slo,
            stages=(0.5, 1.0), hold_s=2.0, min_samples=15,
            burn_rollback=2.0, rollback_after=2, poll_s=0.1,
            stage_timeout_s=120.0, swap_timeout_s=180.0)

    # ================= fleet 1: clean promote + corrupt refusal ======
    router = Router(
        replica_cmd=replica_cmd, n_replicas=2,
        state_dir=os.path.join(workdir, "router"),
        knee_rps=anchor, breaker_threshold=3, breaker_cooldown_s=0.5,
        ping_interval_s=0.1, ping_timeout_s=3.0, wedge_timeout_s=60.0,
        spawn_timeout_s=args.spawn_timeout_s, respawn_backoff_s=0.2,
        grace_s=15.0, drain_timeout_s=30.0)
    router.start(timeout_s=args.spawn_timeout_s)
    try:
        boot_digest = router.fleet_digest
        ports = [r.prom_port for r in router.replicas]
        # warm the socket path, then pin compiles across the WHOLE
        # promote arm (storm + gate + swap + post-promote traffic).
        warm_join = storm(router, 50)
        warm_join()
        compiles0 = [_scrape_counter(pt, "tpuic_serve_compiles_total")
                     for pt in ports]

        outcomes = []
        join = storm(router, args.requests,
                     on_done=lambda i, ok, s: outcomes.append(ok))
        with assert_compiles_flat(0, what="rollout soak promote arm "
                                          "(soak process)"):
            v1 = rollout_for(router, candidate).run()
        _, _, snap1 = join()
        verdicts["promote"] = v1
        if v1.get("verdict") != "promoted":
            fail(f"promote arm: verdict {v1}")
        else:
            cand_digest = v1["digest"]
            if cand_digest == boot_digest:
                fail("promote arm: candidate digest equals boot digest "
                     "— the swap proved nothing")
            for rep in router.replicas:
                if not wait_digest(router, rep.name, cand_digest):
                    fail(f"promote arm: {rep.name} never reported the "
                         f"candidate digest {cand_digest}")
            if router.fleet_digest != cand_digest:
                fail("promote arm: fleet digest not promoted")
        check_ledger("promote arm", snap1, args.requests, outcomes)
        compiles1 = [_scrape_counter(pt, "tpuic_serve_compiles_total")
                     for pt in ports]
        for name, c0, c1 in zip(("r0", "r1"), compiles0, compiles1):
            if c0 != c0 or c1 != c1:
                fail(f"promote arm: {name} compile counter unscrapable")
            elif c1 != c0:
                fail(f"promote arm: {name} compiled {c1 - c0:g} "
                     "executable(s) across the swap — the aval-matched "
                     "hot-swap must reuse the AOT cache")

        # ---- corrupt arm: refused at the gate, pre-traffic ----------
        join = storm(router, 150)
        v2 = rollout_for(router,
                         {"ckpt_dir": ckpt_corrupt,
                          "track": "latest"}).run()
        _, _, snap2 = join()
        verdicts["corrupt"] = v2
        if v2.get("verdict") != "refused" \
                or v2.get("cause") != "swap_corrupt":
            fail(f"corrupt arm: expected a swap_corrupt refusal, got "
                 f"{v2}")
        if router.fleet_digest != verdicts["promote"].get("digest"):
            fail("corrupt arm: fleet digest moved on a refused swap")
        if router.snapshot()["traffic_split"] is not None:
            fail("corrupt arm: a refused candidate left a traffic split")
        check_ledger("corrupt arm", snap2, 150)
        events = []
        try:
            with open(router.ledger_path) as f:
                events = [json.loads(ln) for ln in f if ln.strip()]
        except OSError:
            fail("router ledger unreadable")
        ro = [e for e in events if e.get("event") == "rollout"]
        if not any(e.get("action") == "promote" for e in ro):
            fail("ledger: no rollout promote event")
        refusal = [e for e in ro if e.get("action") == "refused"]
        if not refusal or refusal[-1].get("cause") != "swap_corrupt":
            fail(f"ledger: corrupt refusal not recorded ({refusal})")
        n_stages = [e for e in ro if e.get("action") == "stage"]
        if len(n_stages) != 2:
            fail(f"ledger: expected exactly 2 stage events (the clean "
                 f"arm's), got {len(n_stages)} — a refused candidate "
                 "must never get a traffic stage")
    finally:
        router.close()

    # ================= fleet 2: degraded canary auto-rollback ========
    os.environ["TPUIC_FAULTS"] = f"canary_degrade#{degrade_s:.3f}"
    try:
        router2 = Router(
            replica_cmd=replica_cmd, n_replicas=2,
            state_dir=os.path.join(workdir, "router2"),
            knee_rps=anchor, breaker_threshold=3,
            breaker_cooldown_s=0.5, ping_interval_s=0.1,
            ping_timeout_s=3.0, wedge_timeout_s=60.0,
            spawn_timeout_s=args.spawn_timeout_s,
            respawn_backoff_s=0.2, grace_s=15.0, drain_timeout_s=30.0)
        router2.start(timeout_s=args.spawn_timeout_s)
    finally:
        os.environ.pop("TPUIC_FAULTS", None)
    try:
        boot2 = router2.fleet_digest
        outcomes3 = []
        join = storm(router2, args.requests,
                     on_done=lambda i, ok, s: outcomes3.append(ok))
        v3 = rollout_for(router2, candidate).run()
        _, _, snap3 = join()
        verdicts["degrade"] = v3
        if v3.get("verdict") != "rolled_back" \
                or v3.get("reason") != "slo_burn":
            fail(f"degrade arm: expected slo_burn rollback, got {v3}")
        if v3.get("swap_back_failed"):
            fail(f"degrade arm: rollback swap-back failed on "
                 f"{v3['swap_back_failed']}")
        check_ledger("degrade arm", snap3, args.requests, outcomes3)
        canary = v3.get("canary", "r0")
        if not wait_digest(router2, canary, boot2):
            fail(f"degrade arm: canary {canary} never returned to the "
                 f"boot digest {boot2} after rollback")
        if router2.fleet_digest != boot2:
            fail("degrade arm: fleet digest moved on a rolled-back "
                 "candidate")
        # post-rollback wave: the fault stood down (boot weights), the
        # fleet is healthy and the ledger exact.
        join = storm(router2, 150)
        _, _, snap4 = join()
        check_ledger("post-rollback wave", snap4, 150)
        if snap4["requests"] == 0:
            fail("post-rollback wave: nothing resolved")
        events2 = []
        try:
            with open(router2.ledger_path) as f:
                events2 = [json.loads(ln) for ln in f if ln.strip()]
        except OSError:
            fail("router2 ledger unreadable")
        ro2 = [e for e in events2 if e.get("event") == "rollout"]
        rb = [e for e in ro2 if e.get("action") == "rollback"]
        if not rb or rb[-1].get("reason") != "slo_burn":
            fail(f"ledger2: rollback event missing/wrong ({rb})")
        if not any(e.get("action") == "digest_disallow"
                   for e in events2):
            fail("ledger2: candidate digest never disallowed on "
                 "rollback")
    finally:
        router2.close()

    verdict = {
        "anchors": {"drive_rps": round(drive_rps, 2),
                    "slo": slo,
                    "degrade_s_per_batch": round(degrade_s, 3),
                    "probe_service_s": round(service_s, 5)},
        "verdicts": verdicts,
        "failures": failures,
    }
    with open(os.path.join(workdir, "rollout_soak_verdict.json"),
              "w") as f:
        json.dump(verdict, f, indent=2, default=str)
    print(json.dumps(verdict, indent=2, default=str))

    if failures:
        for msg in failures:
            print(f"[rollout_soak] FAIL: {msg}", file=sys.stderr)
        return 1
    print("[rollout_soak] OK: clean canary promoted with zero dropped "
          "requests and compiles flat across the swap; corrupt "
          "artifact refused swap_corrupt pre-traffic; degraded canary "
          "rolled back on SLO burn with the ledger exact both arms",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
