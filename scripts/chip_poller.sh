#!/bin/bash
# Recovery poller: probe the tunnel every ~7 min; when it answers, wait for
# any running pytest to finish (this is a 1-core host — CPU contention skews
# the perf measurements), then run the queue script given as $1 exactly once.
# Usage: nohup bash scripts/chip_poller.sh scripts/chip_queue3.sh &
set -o pipefail
queue="${1:?usage: chip_poller.sh <queue-script>}"
cd /root/repo
while true; do
  if python -c "
from tpuic.runtime.axon_guard import tpu_reachable
import sys; sys.exit(0 if tpu_reachable(150) else 1)"; then
    while pgrep -f "pytest" > /dev/null; do
      echo "$(date -u +%FT%TZ) tunnel up; waiting for pytest to finish"
      sleep 60
    done
    echo "$(date -u +%FT%TZ) tunnel up; running $queue"
    bash "$queue"
    echo "$(date -u +%FT%TZ) $queue exited rc=$?"
    exit 0
  fi
  echo "$(date -u +%FT%TZ) tunnel down; sleeping"
  sleep 420
done
