#!/bin/bash
# Remainder of chip_queue3 after the first three ViT rows (fusedce, flash,
# flash+fusedce — the last of which may still be running as an orphan when
# this starts: we wait for it). Run detached:
#   setsid nohup bash scripts/chip_queue3b.sh > perf/chip_queue3b.log 2>&1 &
set -x -o pipefail
failures=0
cd /root/repo
probe() { python -c "
from tpuic.runtime.axon_guard import tpu_reachable
import sys; sys.exit(0 if tpu_reachable(150) else 1)"; }

# Wait for any in-flight perf_sweep orphan from the first queue segment.
while pgrep -f "perf_sweep.py" > /dev/null; do sleep 20; done

probe || { echo "chip_queue3b: tunnel down"; exit 90; }
# 1b. Selective attention remat at the batches where dense-ViT MFU FELL.
python scripts/perf_sweep.py --batches 128,256 --model vit-b16 \
  --remat --remat-policy attention \
  --out perf/vit_remat_attn.json 2>&1 | tail -4 || failures=$((failures+1))

probe || { echo "chip_queue3b: tunnel down ($failures)"; exit $((90 + failures)); }
# 1c. ViT-B/16 b64 per-op profile.
python scripts/perf_profile.py --model vit-b16 --batch 64 \
  --trace-dir perf/vit_trace --out perf/vit_profile.json 2>&1 | tail -3 || failures=$((failures+1))

probe || { echo "chip_queue3b: tunnel down ($failures)"; exit $((90 + failures)); }
# 2. SPMD-vs-plain reconciliation row (VERDICT r3 item 6).
python scripts/perf_sweep.py --batches 128 --model resnet50 --spmd \
  --out perf/sweep_spmd.json 2>&1 | tail -3 || failures=$((failures+1))

probe || { echo "chip_queue3b: tunnel down ($failures)"; exit $((90 + failures)); }
# 3. BN bf16-stat accumulation row (VERDICT r3 item 7).
python scripts/perf_sweep.py --batches 128 --model resnet50 --bn-bf16-stats \
  --out perf/sweep_bnbf16.json 2>&1 | tail -3 || failures=$((failures+1))

probe || { echo "chip_queue3b: tunnel down ($failures)"; exit $((90 + failures)); }
# 4. N=512 flash retry with power-of-two blocks, then the long-N probe.
python scripts/long_seq_bench.py --sizes 512 --batch 32 \
  --out perf/long_seq_512_retry.json 2>&1 | tail -4 || failures=$((failures+1))

probe || { echo "chip_queue3b: tunnel down ($failures)"; exit $((90 + failures)); }
python scripts/long_seq_bench.py --sizes 768,1024 --batch 16 --remat \
  --out perf/long_seq_4k.json 2>&1 | tail -6 || failures=$((failures+1))

echo "chip_queue3b: $failures item(s) failed"
exit $failures
