#!/usr/bin/env python
"""Pallas kernels on the REAL TPU: compile, numerics, and microbench.

VERDICT r2 weak #3: both Pallas kernels (flash attention fwd+bwd, fused
weighted CE) had only ever run in CPU interpret mode; Mosaic-specific
failures (scratch shapes, SMEM operands, dimension_semantics) only surface
on hardware. This script:

1. flash attention fwd+bwd at ViT-B/16 shapes ([B, 197->pad, 12, 64]),
   compiled to Mosaic on the chip, numerics vs the dense einsum path;
2. fused CE fwd+grad at [B, 1000] (+ the reference 7-class weighted config),
   numerics vs the reference loss;
3. microbench: dense vs flash attention, reference vs fused CE;
4. ViT-B/16 full train-step bench, attention='dense' vs 'flash' and
   fused_loss on/off.

Writes perf/pallas_smoke.json; prints a summary. Exits nonzero on any
numerics failure, so the committed artifact is proof the kernels RAN.
"""

from __future__ import annotations

import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def bench(fn, *args, iters=20):
    out = fn(*args)  # compile + warm
    jax_block(out)
    t0 = time.perf_counter()
    for _ in range(iters):
        out = fn(*args)
    jax_block(out)
    return (time.perf_counter() - t0) / iters * 1000  # ms


def jax_block(x):
    import jax
    jax.block_until_ready(x)


def main():
    from tpuic.runtime.axon_guard import exit_if_unreachable
    exit_if_unreachable()

    import jax
    import jax.numpy as jnp
    import numpy as np

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, "tests", ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

    from tpuic.kernels import fused_weighted_cross_entropy, flash_attention
    from tpuic.train.loss import weighted_cross_entropy

    platform = jax.devices()[0].platform
    on_tpu = platform == "tpu"
    result = {"platform": platform,
              "device": getattr(jax.devices()[0], "device_kind", "?"),
              "interpret": not on_tpu}
    rng = np.random.default_rng(0)

    # ---- 1. flash attention fwd + bwd, ViT-B shapes (padded 197 -> 256) ---
    B, N, H, D = 8, 197, 12, 64
    pad = 256  # kernel pads internally to block multiples; use real N
    q = jnp.asarray(rng.normal(size=(B, N, H, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, N, H, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, N, H, D)), jnp.float32)

    def make_dense(precision):
        def dense_attn(q, k, v):
            s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                           precision=precision) / np.sqrt(D)
            p = jax.nn.softmax(s, axis=-1)
            return jnp.einsum("bhqk,bkhd->bqhd", p, v, precision=precision)
        return dense_attn

    # Numerics reference at HIGHEST precision (TPU default einsum precision
    # is bf16-on-MXU, ~1e-3 off in f32 terms — that error belongs to the
    # baseline, not the kernel). Timing comparison uses the default-precision
    # dense path, which is what the dense model config actually runs.
    dense_hi = jax.jit(make_dense(jax.lax.Precision.HIGHEST))
    dense = jax.jit(make_dense(None))
    flash = jax.jit(lambda q, k, v: flash_attention(q, k, v))
    o_f, o_d = flash(q, k, v), dense_hi(q, k, v)
    fwd_diff = float(jnp.max(jnp.abs(o_f - o_d)))

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v) ** 2)

    def loss_dense(q, k, v):
        return jnp.sum(make_dense(jax.lax.Precision.HIGHEST)(q, k, v) ** 2)

    g_f = jax.jit(jax.grad(loss_flash, argnums=(0, 1, 2)))(q, k, v)
    g_d = jax.jit(jax.grad(loss_dense, argnums=(0, 1, 2)))(q, k, v)
    bwd_diff = max(float(jnp.max(jnp.abs(a - b))) for a, b in zip(g_f, g_d))
    result["flash_attention"] = {
        "shape": [B, N, H, D],
        "fwd_max_diff": fwd_diff,
        "bwd_max_diff": bwd_diff,
        "fwd_ms_dense": bench(dense, q, k, v),
        "fwd_ms_flash": bench(flash, q, k, v),
    }
    assert fwd_diff < 2e-5, f"flash fwd mismatch: {fwd_diff}"
    assert bwd_diff < 5e-4, f"flash bwd mismatch: {bwd_diff}"

    # Longer sequence where flash should win (N=2048).
    N2 = 2048
    q2 = jnp.asarray(rng.normal(size=(2, N2, H, D)), jnp.float32)
    k2 = jnp.asarray(rng.normal(size=(2, N2, H, D)), jnp.float32)
    v2 = jnp.asarray(rng.normal(size=(2, N2, H, D)), jnp.float32)
    result["flash_attention_n2048"] = {
        "fwd_ms_dense": bench(dense, q2, k2, v2),
        "fwd_ms_flash": bench(flash, q2, k2, v2),
        "fwd_max_diff": float(jnp.max(jnp.abs(flash(q2, k2, v2)
                                              - dense_hi(q2, k2, v2)))),
    }

    # ---- 2. fused CE at [B, 1000] and the reference 7-class config --------
    for tag, (bb, C, cw) in {
        "imagenet": (256, 1000, None),
        "reference7": (64, 7, jnp.asarray([3, 3, 10, 1, 4, 4, 5],
                                          jnp.float32)),
    }.items():
        logits = jnp.asarray(rng.normal(size=(bb, C)) * 3, jnp.float32)
        labels = jnp.asarray(rng.integers(0, C, size=(bb,)), jnp.int32)
        mask = jnp.asarray((rng.random(bb) > 0.1), jnp.float32)

        ref = jax.jit(lambda lg, lb, m: weighted_cross_entropy(
            lg, lb, class_weights=cw, mask=m))
        fus = jax.jit(lambda lg, lb, m: fused_weighted_cross_entropy(
            lg, lb, class_weights=cw, mask=m))
        l_r, l_f = ref(logits, labels, mask), fus(logits, labels, mask)
        loss_diff = float(jnp.abs(l_r - l_f))
        g_r = jax.jit(jax.grad(lambda lg: weighted_cross_entropy(
            lg, labels, class_weights=cw, mask=mask)))(logits)
        g_f2 = jax.jit(jax.grad(lambda lg: fused_weighted_cross_entropy(
            lg, labels, class_weights=cw, mask=mask)))(logits)
        grad_diff = float(jnp.max(jnp.abs(g_r - g_f2)))
        result[f"fused_ce_{tag}"] = {
            "batch": bb, "classes": C,
            "loss_diff": loss_diff, "grad_max_diff": grad_diff,
            "ms_reference": bench(ref, logits, labels, mask),
            "ms_fused": bench(fus, logits, labels, mask),
        }
        assert loss_diff < 1e-5, f"fused CE {tag} loss mismatch {loss_diff}"
        assert grad_diff < 1e-5, f"fused CE {tag} grad mismatch {grad_diff}"

    # ---- 3. ViT-B/16 train step: dense vs flash, fused loss on/off --------
    from tpuic.config import ModelConfig, OptimConfig
    from tpuic.data.synthetic import synthetic_batch
    from tpuic.models import create_model
    from tpuic.train.optimizer import make_optimizer
    from tpuic.train.state import create_train_state
    from tpuic.train.step import make_train_step

    bsz, size = 64, 224
    batch = synthetic_batch(bsz, size, 1000)
    batch = {kk: jax.device_put(jnp.asarray(vv)) for kk, vv in batch.items()}
    step_ms = {}
    for attn in ("dense", "flash"):
        for fused in ((False, True) if attn == "flash" else (False,)):
            mcfg = ModelConfig(name="vit-b16", num_classes=1000,
                               dtype="bfloat16", attention=attn)
            ocfg = OptimConfig(optimizer="sgd", learning_rate=0.1,
                               class_weights=(), milestones=(),
                               fused_loss=fused)
            model = create_model(mcfg.name, mcfg.num_classes,
                                 dtype=mcfg.dtype, attention=attn)
            state = create_train_state(model, make_optimizer(ocfg),
                                       jax.random.key(0),
                                       (bsz, size, size, 3))
            step = make_train_step(ocfg, mcfg, None, donate=False)
            state, m = step(state, batch)
            float(m["loss"])
            t0 = time.perf_counter()
            n = 10
            for _ in range(n):
                state, m = step(state, batch)
            float(m["loss"])
            key = f"{attn}{'+fusedce' if fused else ''}"
            step_ms[key] = round((time.perf_counter() - t0) / n * 1000, 2)
            step_ms[f"{key}_loss"] = float(m["loss"])
    result["vit_b16_train_step_ms"] = step_ms

    os.makedirs(os.path.join(_REPO, "perf"), exist_ok=True)
    with open(os.path.join(_REPO, "perf", "pallas_smoke.json"), "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    print("PALLAS SMOKE OK")


if __name__ == "__main__":
    main()
