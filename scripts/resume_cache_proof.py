#!/usr/bin/env python
"""Chip proof: preemption resume composes with the device-resident cache.

VERDICT r4 weak #5 / item 6: step-exact resume is CPU-verified
(tests/test_preemption.py), but its interaction with the device-resident
dataset cache — resume mid-epoch => re-upload, stride replay — had never
run on a real chip, and the resident path is the production default on
TPU. This script runs, ON THE CURRENT PLATFORM:

  control      = Trainer.fit(2 epochs), digits ImageFolder, resident cache
  interrupted  = same config, preemption latch tripped mid-epoch-1
                 (the SIGTERM latch, triggered in-process), flush, then a
                 fresh Trainer resumes and finishes

and asserts (a) the resident cache was actually active in every run,
(b) resume re-entered the interrupted epoch at the recorded step, (c) the
final params match the control (bitwise reported, allclose asserted), and
(d) the resumed loop logged steady throughput. Writes
perf/resume_cache_proof.json.

PR-18 extension (compiled-program registry, docs/performance.md): the
resume is run TWICE from byte-identical checkpoints — arm A cold (no
prewarm: the first fit step pays the train-step compile in the training
line) and arm B prewarmed from the manifest the interrupted run wrote
(Trainer.prewarm compiles+executes every manifest-listed program before
the loop; the fit itself must then be compile-flat, checker-asserted).
The registry is reset() between arms to simulate the cold process a real
restart is.  The JSON gains the prewarm-vs-no-prewarm downtime split.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

OUT = os.path.join(_REPO, "perf", "resume_cache_proof.json")


def main() -> None:
    from tpuic.runtime.axon_guard import exit_if_unreachable
    exit_if_unreachable()

    import jax

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, "tests", ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

    import numpy as np

    from scripts.convergence_digits import ensure_dataset, DATA_ROOT
    from tpuic.config import (Config, DataConfig, MeshConfig, ModelConfig,
                              OptimConfig, RunConfig)
    from tpuic.train.loop import Trainer

    ensure_dataset()
    on_cpu = jax.devices()[0].platform == "cpu"
    work = tempfile.mkdtemp(prefix="tpuic_resume_cache_")

    def cfg(ckpt):
        return Config(
            data=DataConfig(data_dir=DATA_ROOT, resize_size=32,
                            batch_size=128, augment=False,
                            device_cache_mb=4096),
            model=ModelConfig(name="resnet18-cifar", num_classes=10,
                              dtype="float32" if on_cpu else "bfloat16"),
            optim=OptimConfig(optimizer="sgd", learning_rate=0.05,
                              warmup_epochs=1, class_weights=(),
                              milestones=()),
            run=RunConfig(epochs=2, ckpt_dir=ckpt, save_period=100,
                          resume=True, log_every_steps=2),
            mesh=MeshConfig(),
        )

    def trip_after(trainer, n_steps):
        orig, calls = trainer.train_step, []

        def counting_step(state, batch):
            out = orig(state, batch)
            calls.append(1)
            if len(calls) == n_steps:
                trainer.preemption.trigger()
            return out

        trainer.train_step = counting_step
        return calls

    def first_step_probe(trainer):
        """Stamp the wall time the first train step COMPLETES — the
        time-to-first-step split between the two resume arms."""
        orig, box = trainer.train_step, {}

        def probing(state, batch):
            out = orig(state, batch)
            box.setdefault("t", time.perf_counter())
            return out

        trainer.train_step = probing
        return box

    t0 = time.perf_counter()
    control = Trainer(cfg(os.path.join(work, "ck_a")),
                      log_dir=os.path.join(work, "log_a"))
    assert control.train_loader.resident, \
        "resident cache did not engage — the proof target is the resident path"
    steps_per_epoch = control.train_loader.steps_per_epoch()
    control.fit()
    control_s = time.perf_counter() - t0

    # The interrupted run writes the prewarm manifest (the registry's
    # _build_steps hook) — exactly what a production gang member leaves
    # behind for its restarted self.
    manifest = os.path.join(work, "programs.manifest.json")
    os.environ["TPUIC_COMPILE_MANIFEST"] = manifest
    try:
        trip_offset = max(1, steps_per_epoch // 2)
        interrupted = Trainer(cfg(os.path.join(work, "ck_b")),
                              log_dir=os.path.join(work, "log_b"))
        assert interrupted.train_loader.resident
        trip_after(interrupted, steps_per_epoch + trip_offset)
        interrupted.fit()
    finally:
        del os.environ["TPUIC_COMPILE_MANIFEST"]
    assert os.path.exists(manifest), "interrupted run left no manifest"

    # Two resume arms from byte-identical interrupted checkpoints.
    import shutil
    shutil.copytree(os.path.join(work, "ck_b"), os.path.join(work, "ck_b2"))

    from tpuic.analysis.runtime import watch_compiles
    from tpuic.compiled import registry

    def resume_arm(ckpt, log, *, prewarm_manifest=None):
        registry.reset()  # a restart is a cold process: no in-proc reuse
        t1 = time.perf_counter()
        trainer = Trainer(cfg(os.path.join(work, ckpt)),
                          log_dir=os.path.join(work, log))
        assert trainer.train_loader.resident
        assert (trainer.start_epoch, trainer.start_step) == \
            (1, trip_offset), (
                f"resume geometry: expected (1, {trip_offset}), got "
                f"{(trainer.start_epoch, trainer.start_step)}")
        pw = (trainer.prewarm(prewarm_manifest)
              if prewarm_manifest else None)
        t_ready = time.perf_counter()
        probe = first_step_probe(trainer)
        with watch_compiles() as w:
            trainer.fit()
        return {"trainer": trainer, "prewarm": pw,
                "fit_compiles": w.compiles,
                "total_s": time.perf_counter() - t1,
                "first_step_s": probe["t"] - t_ready}

    arm_a = resume_arm("ck_b", "log_b")                       # no prewarm
    arm_b = resume_arm("ck_b2", "log_b2", prewarm_manifest=manifest)
    assert arm_b["fit_compiles"] == 0, (
        f"manifest-prewarmed resume was NOT compile-flat: "
        f"{arm_b['fit_compiles']} backend compile(s) inside fit")
    resumed = arm_a["trainer"]
    resume_s = arm_a["total_s"]

    a = jax.device_get(control.state.params)
    b = jax.device_get(resumed.state.params)
    b2 = jax.device_get(arm_b["trainer"].state.params)
    leaves = list(zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)))
    bitwise = all(np.array_equal(np.asarray(x), np.asarray(y))
                  for x, y in leaves)
    # Prewarm executes the step on a copied state against a throwaway
    # batch — it must not perturb the resumed trajectory by one bit.
    prewarm_bitwise = all(
        np.array_equal(np.asarray(x), np.asarray(y))
        for x, y in zip(jax.tree_util.tree_leaves(b),
                        jax.tree_util.tree_leaves(b2)))
    max_diff = max(float(np.max(np.abs(np.asarray(x, np.float32)
                                       - np.asarray(y, np.float32))))
                   for x, y in leaves)

    rates = []
    with open(os.path.join(work, "log_b", "metrics.jsonl")) as f:
        for line in f:
            rec = json.loads(line)
            if "images_per_sec" in rec:
                rates.append(rec["images_per_sec"])

    result = {
        "platform": jax.devices()[0].platform,
        "device": getattr(jax.devices()[0], "device_kind", "unknown"),
        "dataset": "digits ImageFolder (real data, resident cache)",
        "resident_bytes": control.train_loader.resident_bytes,
        "steps_per_epoch": steps_per_epoch,
        "trip": f"epoch 1 step {trip_offset}",
        "resume_geometry_ok": True,
        "params_bitwise_equal": bool(bitwise),
        "params_max_abs_diff": max_diff,
        # metrics.jsonl of ck_b spans both runs: the pre-interrupt epoch's
        # intervals first, then the resumed run's (the steady-rate
        # evidence is the tail).
        "interrupted_plus_resumed_rates": rates,
        "control_fit_s": round(control_s, 1),
        "resume_fit_s": round(resume_s, 1),
        # Prewarm-vs-no-prewarm downtime split (compiled-program
        # registry, docs/performance.md): arm A pays its compiles at the
        # first step of the training line; arm B pays them in
        # Trainer.prewarm before the loop and its fit is compile-flat.
        "resume_prewarm_fit_s": round(arm_b["total_s"], 1),
        "prewarm_s": round(arm_b["prewarm"]["prewarm_s"], 2),
        "prewarm_programs": arm_b["prewarm"]["programs"],
        "prewarm_manifest_listed": arm_b["prewarm"]["manifest_listed"],
        "first_step_s_no_prewarm": round(arm_a["first_step_s"], 2),
        "first_step_s_after_prewarm": round(arm_b["first_step_s"], 2),
        "fit_compiles_no_prewarm": arm_a["fit_compiles"],
        "fit_compiles_after_prewarm": arm_b["fit_compiles"],
        "prewarm_params_bitwise_equal": bool(prewarm_bitwise),
    }
    with open(OUT, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    assert max_diff == 0.0 or max_diff < 1e-6, \
        f"resumed params diverge from control by {max_diff}"
    assert prewarm_bitwise, \
        "prewarmed resume diverged from the cold resume (prewarm leaked " \
        "into trainer state or loader position)"
    print("RESUME CACHE PROOF OK")


if __name__ == "__main__":
    main()
