#!/usr/bin/env python
"""Chip proof: preemption resume composes with the device-resident cache.

VERDICT r4 weak #5 / item 6: step-exact resume is CPU-verified
(tests/test_preemption.py), but its interaction with the device-resident
dataset cache — resume mid-epoch => re-upload, stride replay — had never
run on a real chip, and the resident path is the production default on
TPU. This script runs, ON THE CURRENT PLATFORM:

  control      = Trainer.fit(2 epochs), digits ImageFolder, resident cache
  interrupted  = same config, preemption latch tripped mid-epoch-1
                 (the SIGTERM latch, triggered in-process), flush, then a
                 fresh Trainer resumes and finishes

and asserts (a) the resident cache was actually active in every run,
(b) resume re-entered the interrupted epoch at the recorded step, (c) the
final params match the control (bitwise reported, allclose asserted), and
(d) the resumed loop logged steady throughput. Writes
perf/resume_cache_proof.json.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

OUT = os.path.join(_REPO, "perf", "resume_cache_proof.json")


def main() -> None:
    from tpuic.runtime.axon_guard import exit_if_unreachable
    exit_if_unreachable()

    import jax

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, "tests", ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

    import numpy as np

    from scripts.convergence_digits import ensure_dataset, DATA_ROOT
    from tpuic.config import (Config, DataConfig, MeshConfig, ModelConfig,
                              OptimConfig, RunConfig)
    from tpuic.train.loop import Trainer

    ensure_dataset()
    on_cpu = jax.devices()[0].platform == "cpu"
    work = tempfile.mkdtemp(prefix="tpuic_resume_cache_")

    def cfg(ckpt):
        return Config(
            data=DataConfig(data_dir=DATA_ROOT, resize_size=32,
                            batch_size=128, augment=False,
                            device_cache_mb=4096),
            model=ModelConfig(name="resnet18-cifar", num_classes=10,
                              dtype="float32" if on_cpu else "bfloat16"),
            optim=OptimConfig(optimizer="sgd", learning_rate=0.05,
                              warmup_epochs=1, class_weights=(),
                              milestones=()),
            run=RunConfig(epochs=2, ckpt_dir=ckpt, save_period=100,
                          resume=True, log_every_steps=2),
            mesh=MeshConfig(),
        )

    def trip_after(trainer, n_steps):
        orig, calls = trainer.train_step, []

        def counting_step(state, batch):
            out = orig(state, batch)
            calls.append(1)
            if len(calls) == n_steps:
                trainer.preemption.trigger()
            return out

        trainer.train_step = counting_step
        return calls

    t0 = time.perf_counter()
    control = Trainer(cfg(os.path.join(work, "ck_a")),
                      log_dir=os.path.join(work, "log_a"))
    assert control.train_loader.resident, \
        "resident cache did not engage — the proof target is the resident path"
    steps_per_epoch = control.train_loader.steps_per_epoch()
    control.fit()
    control_s = time.perf_counter() - t0

    trip_offset = max(1, steps_per_epoch // 2)
    interrupted = Trainer(cfg(os.path.join(work, "ck_b")),
                          log_dir=os.path.join(work, "log_b"))
    assert interrupted.train_loader.resident
    trip_after(interrupted, steps_per_epoch + trip_offset)
    interrupted.fit()

    t1 = time.perf_counter()
    resumed = Trainer(cfg(os.path.join(work, "ck_b")),
                      log_dir=os.path.join(work, "log_b"))
    assert resumed.train_loader.resident
    assert (resumed.start_epoch, resumed.start_step) == (1, trip_offset), (
        f"resume geometry: expected (1, {trip_offset}), got "
        f"{(resumed.start_epoch, resumed.start_step)}")
    resumed.fit()
    resume_s = time.perf_counter() - t1

    a = jax.device_get(control.state.params)
    b = jax.device_get(resumed.state.params)
    leaves = list(zip(jax.tree_util.tree_leaves(a),
                      jax.tree_util.tree_leaves(b)))
    bitwise = all(np.array_equal(np.asarray(x), np.asarray(y))
                  for x, y in leaves)
    max_diff = max(float(np.max(np.abs(np.asarray(x, np.float32)
                                       - np.asarray(y, np.float32))))
                   for x, y in leaves)

    rates = []
    with open(os.path.join(work, "log_b", "metrics.jsonl")) as f:
        for line in f:
            rec = json.loads(line)
            if "images_per_sec" in rec:
                rates.append(rec["images_per_sec"])

    result = {
        "platform": jax.devices()[0].platform,
        "device": getattr(jax.devices()[0], "device_kind", "unknown"),
        "dataset": "digits ImageFolder (real data, resident cache)",
        "resident_bytes": control.train_loader.resident_bytes,
        "steps_per_epoch": steps_per_epoch,
        "trip": f"epoch 1 step {trip_offset}",
        "resume_geometry_ok": True,
        "params_bitwise_equal": bool(bitwise),
        "params_max_abs_diff": max_diff,
        # metrics.jsonl of ck_b spans both runs: the pre-interrupt epoch's
        # intervals first, then the resumed run's (the steady-rate
        # evidence is the tail).
        "interrupted_plus_resumed_rates": rates,
        "control_fit_s": round(control_s, 1),
        "resume_fit_s": round(resume_s, 1),
    }
    with open(OUT, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result, indent=2))
    assert max_diff == 0.0 or max_diff < 1e-6, \
        f"resumed params diverge from control by {max_diff}"
    print("RESUME CACHE PROOF OK")


if __name__ == "__main__":
    main()
