#!/usr/bin/env python
"""Router soak: the replica-fleet resilience layer's end-to-end CI gate
(docs/serving.md, "Replica routing and failover").

Two REAL engine replicas (``python -m tpuic.serve --listen`` processes,
synthetic-init so every replica carries identical seeded weights) behind
the stdlib-only router, driven by the SHARED loadgen harness with a
Poisson storm anchored at the committed latency knee
(``perf/bench_serve.json``, floored by fresh local capacity probes —
the overload-soak anchoring discipline).  Mid-storm, one replica is
**SIGKILLed** the instant it holds in-flight requests.  Asserted:

- **zero client timeouts**: every offered request either resolves or
  gets a typed verdict inside the generous result window — the router
  sheds and fails over instead of letting clients hang;
- **in-flight failover**: the victim's in-flight requests requeue to
  the survivor under the retry budget (surfaced through run_stream's
  ``on_retry`` outcome hook), unreplayables resolve ``replica_lost``;
- **breaker cycle**: the victim's circuit breaker trips **open** at the
  kill, goes **half-open** once the respawned replica (the ``_Child``
  ladder; warmed from the shared persistent compile cache) reconnects,
  and **closes** when the probe request succeeds — in that order, read
  from the router ledger;
- **exact ledger**, both waves: ``resolved + typed-rejected ==
  offered``, zero untyped errors, zero duplicate deliveries
  (at-most-once);
- **zero steady-state compiles** on the post-respawn fleet: each
  replica's scraped ``tpuic_serve_compiles_total`` is flat across the
  second wave (warmup is the only compile window), and the soak
  process itself runs the wave under ``assert_compiles_flat``.

Artifacts for CI upload on failure: the router ledger (breaker
transition log included), per-replica logs/heartbeats/stack dumps under
``<workdir>/router/r*/``, and the verdict JSON.

    python scripts/router_soak.py --workdir router-soak-work
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import signal
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

CACHE_DIR = os.path.join(_REPO, "tests", ".jax_cache")


def _force_cpu() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
    from tpuic.runtime.axon_guard import drop_axon_vars
    drop_axon_vars(os.environ)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir", CACHE_DIR)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def _committed_knee() -> float:
    try:
        with open(os.path.join(_REPO, "perf", "bench_serve.json")) as f:
            return float(json.load(f)["open_loop_knee_req_per_sec"] or 0.0)
    except (OSError, ValueError, KeyError, TypeError):
        return 0.0


def _scrape_counter(port, name: str) -> float:
    """One counter from a replica's /metrics (0.0 when unreachable —
    the caller decides whether that is fatal)."""
    import urllib.request
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}/metrics", timeout=2.0) as resp:
            text = resp.read().decode("utf-8", "replace")
    except Exception:
        return float("nan")
    for ln in text.splitlines():
        if ln.startswith(name) and not ln.startswith("#"):
            try:
                return float(ln.rsplit(None, 1)[1])
            except (ValueError, IndexError):
                pass
    return float("nan")


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--workdir", default="router-soak-work")
    p.add_argument("--model", default="resnet18-cifar")
    p.add_argument("--size", type=int, default=24)
    p.add_argument("--buckets", default="1,4,8")
    p.add_argument("--requests", type=int, default=600,
                   help="storm length (wave 1)")
    p.add_argument("--requests-rejoin", type=int, default=200,
                   help="post-respawn wave length (wave 2: the rejoin "
                        "probe + compiles-flat window)")
    p.add_argument("--storm-factor", type=float, default=1.0,
                   help="drive = factor x max(committed knee, local "
                        "capacity anchor) — 'a Poisson storm at the "
                        "committed knee': half the 2-replica fleet's "
                        "headroom, so the kill makes the survivor "
                        "carry the whole knee")
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--spawn-timeout-s", type=float, default=600.0)
    args = p.parse_args(argv)

    _force_cpu()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpuic.analysis.runtime import assert_compiles_flat
    from tpuic.models import create_model
    from tpuic.serve import InferenceEngine, make_forward
    from tpuic.serve.loadgen import probe_unbatched_rps, run_stream
    from tpuic.serve.router import Router

    workdir = os.path.abspath(args.workdir)
    shutil.rmtree(workdir, ignore_errors=True)
    os.makedirs(workdir, exist_ok=True)
    failures = []

    # ---- local capacity anchors (the overload-soak discipline) --------
    # Built FIRST so the shared persistent compile cache is hot before
    # any replica spawns: replica warmup (and the respawn mid-soak)
    # then loads executables from disk instead of recompiling — which
    # is also what makes the compiles-flat assertion meaningful.
    buckets = tuple(int(b) for b in args.buckets.split(","))
    model = create_model(args.model, 10, dtype="float32")
    variables = model.init(jax.random.key(0),
                           jnp.zeros((1, args.size, args.size, 3),
                                     jnp.float32), train=False)
    probe_engine = InferenceEngine(
        forward_fn=make_forward(model, normalize=True),
        variables=variables, image_size=args.size, input_dtype=np.uint8,
        buckets=buckets, max_wait_ms=5.0, queue_size=256)
    probe_engine.warmup()
    rng = np.random.default_rng(args.seed)
    reqs = [rng.integers(0, 256, (1, args.size, args.size, 3), np.uint8)
            for _ in range(max(args.requests, 400))]
    local_rps, service_s, _, _ = probe_unbatched_rps(probe_engine, reqs)
    n_cap = min(400, len(reqs))
    t_cap = time.perf_counter()
    run_stream(probe_engine, reqs[:n_cap])
    batched_rps = n_cap / max(time.perf_counter() - t_cap, 1e-9)
    probe_engine.close()
    knee = _committed_knee()
    # Per-replica capacity anchor: the knee, floored by the local
    # batched probe discounted for socket/JSON transport overhead.
    anchor = max(knee, local_rps, 0.5 * batched_rps)
    drive_rps = args.storm_factor * anchor

    # ---- the fleet ----------------------------------------------------
    replica_cmd = [
        sys.executable, "-m", "tpuic.serve",
        "--synthetic-init", "--model", args.model, "--num-classes", "10",
        "--resize", str(args.size), "--buckets", args.buckets,
        "--max-wait-ms", "5", "--queue-size", "256",
        "--listen", "127.0.0.1:0", "--prom-port", "-1",
        "--compile-cache-dir", CACHE_DIR,
        "--drain-timeout", "10",
    ]
    router = Router(
        replica_cmd=replica_cmd, n_replicas=2,
        state_dir=os.path.join(workdir, "router"),
        knee_rps=anchor,            # spill limit: Little's law at the knee
        retry_ratio=0.1, retry_cap=32.0, max_attempts=3,
        breaker_threshold=3, breaker_cooldown_s=0.5,
        ping_interval_s=0.1, ping_timeout_s=3.0,
        wedge_timeout_s=60.0, spawn_timeout_s=args.spawn_timeout_s,
        respawn_backoff_s=0.2, grace_s=15.0, drain_timeout_s=30.0)
    print(f"[router_soak] anchors: knee={knee:g} unbatched="
          f"{local_rps:.1f} batched={batched_rps:.1f} -> drive "
          f"{drive_rps:.1f} req/s over 2 replicas", file=sys.stderr)
    router.start(timeout_s=args.spawn_timeout_s)

    try:
        victim = router.replicas[0]
        victim_pid = victim.child.pid
        kill_stamp = {"t": None, "inflight": 0}

        # ---- wave 1: Poisson storm + SIGKILL mid-storm ----------------
        import threading

        def killer() -> None:
            deadline = time.monotonic() + 60.0
            while time.monotonic() < deadline:
                if len(victim.inflight) >= 1 and router.stats.snapshot()[
                        "offered"] >= args.requests // 4:
                    break
                time.sleep(0.001)
            kill_stamp["inflight"] = len(victim.inflight)
            kill_stamp["t"] = time.time()
            os.kill(victim_pid, signal.SIGKILL)

        retried, outcomes = [], []
        items = [(r, {"timeout": 0}) for r in reqs[:args.requests]]
        offsets = np.cumsum(rng.exponential(1.0 / drive_rps,
                                            size=args.requests))
        kt = threading.Thread(target=killer, daemon=True)
        kt.start()
        wall1, _, snap1 = run_stream(
            router, items, offsets_s=offsets, result_timeout_s=120.0,
            on_done=lambda i, ok, s: outcomes.append(ok),
            on_retry=lambda i, n: retried.append((i, n)))
        kt.join(timeout=70.0)
        if kill_stamp["t"] is None:
            failures.append("the killer thread never fired — the storm "
                            "kept the victim idle; nothing was proven")

        # zero client timeouts: run_stream returning at all means no
        # future hit the 120 s result window, and the settled ledger
        # must account for every offered request exactly.
        if len(outcomes) != args.requests:
            failures.append(
                f"outcome hook fired {len(outcomes)}/{args.requests} "
                "times — some request neither resolved nor got a "
                "typed verdict (a client would have timed out)")
        if snap1["requests"] + snap1["rejected"] != args.requests \
                or snap1["errors"] != 0:
            failures.append(
                f"wave-1 ledger violation: {snap1['requests']} resolved "
                f"+ {snap1['rejected']} rejected (+{snap1['errors']} "
                f"untyped errors) != {args.requests} offered")
        if snap1["duplicates"] or snap1["wire_errors"]:
            # The kill window is the ONLY place an original response
            # can race a failover replay, and a SIGKILLed replica can
            # send nothing after its EOF — at-most-once must hold with
            # zero duplicate deliveries and zero torn-framing lines.
            failures.append(
                f"at-most-once violated in wave 1: "
                f"{snap1['duplicates']} duplicate response(s), "
                f"{snap1['wire_errors']} wire error(s)")
        bad_causes = set(snap1["rejected_by"]) - {
            "queue_full", "deadline", "replica_lost"}
        if bad_causes:
            failures.append(f"unexpected reject causes: {bad_causes}")
        if kill_stamp["inflight"] >= 1 and snap1["failovers"] < 1:
            failures.append(
                f"victim died holding {kill_stamp['inflight']} "
                "request(s) but no failover was recorded")
        if snap1["retries"] and not retried:
            failures.append("router recorded replays but the loadgen "
                            "on_retry hook never fired — the one-"
                            "harness contract broke")

        # ---- respawn + rejoin ----------------------------------------
        deadline = time.monotonic() + args.spawn_timeout_s
        while time.monotonic() < deadline:
            if victim.state == "up":
                break
            time.sleep(0.1)
        else:
            failures.append("victim never respawned to 'up' within the "
                            "spawn timeout")
        new_pid = victim.child.pid if victim.child else None
        if new_pid == victim_pid:
            failures.append("victim 'respawn' kept the killed pid — no "
                            "new process was started")

        # settle, then pin compiles across wave 2 (warmup is the only
        # compile window; the respawned replica warmed from the cache)
        time.sleep(1.0)
        ports = [r.prom_port for r in router.replicas]
        compiles_before = [_scrape_counter(
            pt, "tpuic_serve_compiles_total") for pt in ports]
        r0_routed_before = victim.routed  # cumulative: delta proves rejoin
        snap2 = None
        if victim.state == "up":
            with assert_compiles_flat(0, what="router soak wave 2 "
                                              "(soak process)"):
                _, _, snap2 = run_stream(
                    router,
                    [(r, {"timeout": 0})
                     for r in reqs[:args.requests_rejoin]],
                    offsets_s=np.cumsum(rng.exponential(
                        1.0 / drive_rps, size=args.requests_rejoin)),
                    result_timeout_s=120.0)
            if snap2["requests"] + snap2["rejected"] \
                    != args.requests_rejoin or snap2["errors"] != 0:
                failures.append(
                    f"wave-2 ledger violation: {snap2['requests']} + "
                    f"{snap2['rejected']} (+{snap2['errors']} errors) "
                    f"!= {args.requests_rejoin}")
            if snap2["duplicates"] or snap2["wire_errors"]:
                failures.append(
                    f"at-most-once violated in wave 2: "
                    f"{snap2['duplicates']} duplicate response(s), "
                    f"{snap2['wire_errors']} wire error(s)")
            if victim.routed <= r0_routed_before:
                # victim.routed is cumulative across waves — only the
                # DELTA proves wave-2 traffic actually reached the
                # respawned replica (a breaker stuck open would leave
                # it flat while the fleet still answers).
                failures.append("wave 2 never routed to the respawned "
                                "replica — rejoin unproven")
        compiles_after = [_scrape_counter(
            pt, "tpuic_serve_compiles_total") for pt in ports]
        for name, before, after in zip(("r0", "r1"), compiles_before,
                                       compiles_after):
            if before != before or after != after:  # NaN: scrape failed
                failures.append(f"{name}: compile counter unscrapable "
                                "(before/after wave 2)")
            elif after != before:
                failures.append(
                    f"{name}: {after - before:g} steady-state "
                    f"compile(s) during wave 2 — the respawn/rejoin "
                    "path recompiled instead of hitting the cache")

        # ---- breaker cycle from the ledger ----------------------------
        events = []
        try:
            with open(router.ledger_path) as f:
                events = [json.loads(ln) for ln in f if ln.strip()]
        except OSError:
            failures.append("router ledger unreadable")
        b = [e for e in events if e.get("event") == "router_breaker"
             and e.get("replica") == "r0"]
        states = [e["new"] for e in b]
        try:
            i_open = states.index("open")
            i_half = states.index("half_open", i_open)
            states.index("closed", i_half)
        except ValueError:
            failures.append(
                f"breaker cycle open->half_open->closed not observed "
                f"for the killed replica (saw: {states})")
        if not any(e.get("event") == "router_failover"
                   and e.get("replica") == "r0" for e in events):
            failures.append("no router_failover event for the victim "
                            "in the ledger")
        dup = (snap1["duplicates"]
               + (snap2["duplicates"] if snap2 else 0))

        verdict = {
            "anchors": {"committed_knee_rps": knee,
                        "local_unbatched_rps": round(local_rps, 2),
                        "local_batched_rps": round(batched_rps, 2),
                        "drive_rps": round(drive_rps, 2),
                        "probe_service_s": round(service_s, 5)},
            "kill": {"pid": victim_pid,
                     "inflight_at_kill": kill_stamp["inflight"],
                     "respawned_pid": new_pid},
            "wave1": {k: snap1[k] for k in
                      ("offered", "requests", "rejected", "rejected_by",
                       "errors", "retries", "failovers",
                       "failover_requeued", "failover_lost",
                       "duplicates", "wire_errors", "latency_ms")},
            "wave1_wall_s": round(wall1, 2),
            "on_retry_hook_fires": len(retried),
            "wave2": ({k: snap2[k] for k in
                       ("offered", "requests", "rejected", "errors",
                        "duplicates", "wire_errors")}
                      if snap2 else None),
            "wave2_routed_to_respawned": (victim.routed
                                          - r0_routed_before),
            "breaker_r0_states": states,
            "compiles_during_wave2": [
                (a - bfr) if (a == a and bfr == bfr) else None
                for bfr, a in zip(compiles_before, compiles_after)],
            "duplicate_responses": dup,
            "replicas": router.replica_health(),
        }
        with open(os.path.join(workdir, "router_soak_verdict.json"),
                  "w") as f:
            json.dump(verdict, f, indent=2, default=str)
        print(json.dumps(verdict, indent=2, default=str))
    finally:
        router.close()

    if failures:
        for msg in failures:
            print(f"[router_soak] FAIL: {msg}", file=sys.stderr)
        return 1
    print(f"[router_soak] OK: SIGKILL mid-storm at {drive_rps:.0f} "
          f"req/s -> {snap1['failover_requeued']} requeued / "
          f"{snap1['failover_lost']} replica_lost, zero client "
          f"timeouts, breaker open->half_open->closed rejoin, both "
          f"ledgers exact, compiles flat on the post-respawn fleet",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
