#!/usr/bin/env python
"""Long-sequence attention bench: dense vs Pallas flash at growing N.

The round-3 smoke (perf/pallas_smoke.json) showed flash LOSES to dense at
ViT-B's N=197 — its value is O(N*D) HBM at long sequence lengths. This
script quantifies the crossover on the real chip: ViT-B/16 train step at
224/384/512px (N = 197/577/1025 tokens) with attention='dense' vs 'flash',
recording step time and peak memory. Writes perf/long_seq.json.

Each (size, attention) config runs in its OWN subprocess:
``peak_bytes_in_use`` is a process-lifetime high-water mark, so measuring
several configs in one process would floor every later number at the
earlier peak and erase exactly the dense-vs-flash memory difference this
bench exists to show.

Usage: python scripts/long_seq_bench.py [--sizes 224,384,512] [--batch 32]
"""

from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def measure(size: int, attention: str, batch: int, n_steps: int = 10,
            remat: bool = False, remat_policy: str = "dots"):
    import jax

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, "tests", ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

    from tpuic.config import ModelConfig, OptimConfig
    from tpuic.data.synthetic import synthetic_batch
    from tpuic.models import create_model_from_config
    from tpuic.train.optimizer import make_optimizer
    from tpuic.train.state import create_train_state
    from tpuic.train.step import make_train_step

    # remat: at N >= 2k the NON-attention activations (qkv/mlp intermediates
    # x depth) alone exceed HBM at useful batch sizes; rematerializing them
    # keeps the measurement about the attention memory term, which is the
    # dense-vs-flash difference this bench exists to isolate.
    mcfg = ModelConfig(name="vit-b16", num_classes=1000, dtype="bfloat16",
                       attention=attention, remat=remat,
                       remat_policy=remat_policy)
    ocfg = OptimConfig(optimizer="sgd", learning_rate=0.1, class_weights=(),
                       milestones=())
    # create_model_from_config, NOT create_model: the model-level remat
    # policies ('attention' -> remat_core, 'blocks' -> remat_blocks) only
    # flow from the CONFIG path; building the model directly would
    # silently measure step-level remat only (XLA's own auto-remat then
    # masks the difference at memory-pressure shapes).
    model = create_model_from_config(mcfg)
    state = create_train_state(model, make_optimizer(ocfg),
                               jax.random.key(0), (batch, size, size, 3))
    data = synthetic_batch(batch, size, mcfg.num_classes)
    data = {k: jax.device_put(v) for k, v in data.items()}
    step = make_train_step(ocfg, mcfg, None, donate=True)
    state, m = step(state, data)
    float(m["loss"])  # force completion (tunnel-safe sync)
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, m = step(state, data)
    float(m["loss"])
    dt = (time.perf_counter() - t0) / n_steps
    mem = None
    try:
        ms = jax.devices()[0].memory_stats()
        mem = round(ms.get("peak_bytes_in_use", 0) / (1 << 20))
    except Exception:
        pass
    n_tokens = (size // 16) ** 2 + 1
    return {"size": size, "tokens": n_tokens, "attention": attention,
            "remat": remat, "remat_policy": remat_policy if remat else None,
            "step_ms": round(1000 * dt, 2), "peak_mem_mb": mem,
            "images_per_sec": round(batch / dt, 1),
            "platform": jax.devices()[0].platform,
            "device": getattr(jax.devices()[0], "device_kind", "?")}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--sizes", default="224,384,512")
    ap.add_argument("--batch", type=int, default=32)
    ap.add_argument("--remat", action="store_true",
                    help="rematerialize encoder activations (needed to "
                         "reach N>=2k at useful batch sizes)")
    ap.add_argument("--remat-policy", default="dots",
                    choices=("dots", "attention", "blocks"),
                    help="what --remat recomputes (ModelConfig.remat_policy;"
                         " 'blocks' = per-encoder-block, the long-context "
                         "memory mode)")
    ap.add_argument("--out", default=os.path.join(_REPO, "perf",
                                                  "long_seq.json"))
    ap.add_argument("--_child", nargs=2, metavar=("SIZE", "ATTENTION"),
                    help=argparse.SUPPRESS)
    args = ap.parse_args()

    if args._child:
        size, attention = int(args._child[0]), args._child[1]
        print(json.dumps(measure(size, attention, args.batch,
                                 remat=args.remat,
                                 remat_policy=args.remat_policy)),
              flush=True)
        return 0

    from tpuic.runtime.axon_guard import exit_if_unreachable
    exit_if_unreachable()

    rows = []
    configs = [(size, attention)
               for size in (int(s) for s in args.sizes.split(","))
               for attention in ("dense", "flash")]
    for size, attention in configs:
        # Popen + terminate-then-kill rather than subprocess.run: run's
        # timeout SIGKILLs immediately, and killing a child mid-TPU-RPC
        # is what wedged the tunnel after the N=1025 hang (the JAX
        # client never unwinds the stream). SIGTERM first gives it a
        # grace window to close the backend.
        proc = subprocess.Popen(
            [sys.executable, os.path.abspath(__file__),
             "--batch", str(args.batch)]
            + (["--remat"] if args.remat else [])
            + ["--remat-policy", args.remat_policy]
            + ["--_child", str(size), attention],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            text=True, cwd=_REPO)
        try:
            stdout, stderr = proc.communicate(timeout=900)
            rc = proc.returncode
        except subprocess.TimeoutExpired:
            proc.terminate()
            try:
                # communicate, not wait: the pipes must keep draining or a
                # child with a full stderr buffer blocks in write() and
                # burns the grace window.
                proc.communicate(timeout=30)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.communicate()
            row = {"size": size, "attention": attention,
                   "error": "timed out after 900s"}
            rows.append(row)
            print(json.dumps(row), flush=True)
            if is_tunneled() and not tpu_reachable(120):
                rows.append({"error": "tunnel dead after timeout; "
                                      "aborting remaining configs"})
                print(json.dumps(rows[-1]), flush=True)
                break
            continue
        row = None
        for line in reversed((stdout or "").strip().splitlines()):
            try:
                row = json.loads(line)
                break
            except (json.JSONDecodeError, ValueError):
                continue
        if row is None:
            # Prefer the XLA OOM line (the reason this bench exists is to
            # find it) over the generic traceback tail.
            import re as _re
            err_lines = [_re.sub(r"\x1b\[[0-9;]*m", "", ln)
                         for ln in (stderr or "").strip().splitlines()]
            oom = [ln for ln in err_lines if "Ran out of memory" in ln]
            tail = (oom[0].split("error.", 1)[-1].strip() if oom
                    else " | ".join(err_lines[-2:]))
            row = {"size": size, "attention": attention, "oom": bool(oom),
                   "error": f"rc={rc}: {tail[:300]}"}
        rows.append(row)
        print(json.dumps(row), flush=True)
    out = {"batch": args.batch, "model": "vit-b16", "remat": args.remat,
           "remat_policy": args.remat_policy if args.remat else None,
           "rows": rows}
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
