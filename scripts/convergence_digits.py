#!/usr/bin/env python
"""Train to convergence on real data and report top-1 (VERDICT r4 item 2).

The accuracy half of the BASELINE.md north star has had no end-to-end
evidence: no model was ever trained to convergence on a real dataset by
this framework. This script closes that. Dataset: sklearn's handwritten
digits — the only real image-classification set reachable in this
zero-egress environment (scripts/make_digits_dataset.py documents why) —
materialized as a reference-layout ImageFolder and fed through the FULL
production path (glob index -> packed uint8 memmap -> device-resident
cache -> Trainer.fit with checkpointing/val/logging).

Recipe (recipes/README.md #1 adapted to the dataset): resnet18-cifar,
32px, global batch 128, SGD momentum 0.9, warmup-cosine, --no-augment
(digits are orientation-sensitive: the reference's always-on rot90/flip
chain aliases 6<->9).

Control: the SAME architecture (torch_ref.build_resnet('resnet18-cifar'),
the replica family used for checkpoint-conversion parity), SAME data
tensors (loaded via the tpuic dataset so normalization is bitwise
identical), SAME schedule (linear warmup -> cosine, mirrored from
tpuic/train/schedule.py), trained with torch SGD on CPU. Writes
perf/convergence_digits.json.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

DATA_ROOT = os.path.join(_REPO, ".data", "digits")
OUT = os.path.join(_REPO, "perf", "convergence_digits.json")

EPOCHS = 40
BATCH = 128
LR = 0.05
WARMUP_EPOCHS = 3
WEIGHT_DECAY = 5e-4


def ensure_dataset() -> None:
    if not os.path.isdir(os.path.join(DATA_ROOT, "train")):
        from scripts.make_digits_dataset import build
        counts = build(DATA_ROOT)
        print(f"built digits ImageFolder: {counts}")


def run_tpuic(epochs: int, model: str = "resnet18-cifar",
              optimizer: str = "sgd", lr: float = LR,
              mixup: float = 0.0, cutmix: float = 0.0) -> dict:
    import jax

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, "tests", ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

    from tpuic.config import (Config, DataConfig, MeshConfig, ModelConfig,
                              OptimConfig, RunConfig)
    from tpuic.train.loop import Trainer

    on_cpu = jax.devices()[0].platform == "cpu"
    ckpt = tempfile.mkdtemp(prefix="tpuic_digits_ckpt_")
    log_dir = os.path.join(_REPO, "perf", "convergence_digits_logs")
    os.makedirs(log_dir, exist_ok=True)
    cfg = Config(
        data=DataConfig(data_dir=DATA_ROOT, resize_size=32, batch_size=BATCH,
                        augment=False),
        model=ModelConfig(name=model, num_classes=10,
                          dtype="float32" if on_cpu else "bfloat16"),
        optim=OptimConfig(optimizer=optimizer, learning_rate=lr,
                          warmup_epochs=WARMUP_EPOCHS,
                          weight_decay=WEIGHT_DECAY,
                          mixup_alpha=mixup, cutmix_alpha=cutmix,
                          class_weights=(), milestones=()),
        run=RunConfig(epochs=epochs, ckpt_dir=ckpt, save_period=20,
                      resume=False, log_every_steps=10),
        mesh=MeshConfig(),
    )
    t0 = time.perf_counter()
    trainer = Trainer(cfg, log_dir=log_dir)
    best = trainer.fit()
    wall = time.perf_counter() - t0
    return {
        "framework": "tpuic",
        "model": model, "resize": 32, "batch": BATCH,
        "optimizer": f"{optimizer}(wd={WEIGHT_DECAY})",
        "schedule": f"warmup_cosine(lr={lr}, warmup={WARMUP_EPOCHS}ep)",
        "epochs": epochs, "augment": False,
        "mixup": mixup, "cutmix": cutmix,
        "n_train": len(trainer.train_ds), "n_val": len(trainer.val_ds),
        "best_val_top1": best,
        "wall_s": round(wall, 1),
        "platform": jax.devices()[0].platform,
        "device": getattr(jax.devices()[0], "device_kind", "unknown"),
        "dtype": cfg.model.dtype,
    }


def _load_fold_arrays(fold: str):
    """Load a fold through the tpuic dataset (clean decode path) so the
    control sees bitwise-identical normalized tensors."""
    import numpy as np

    from tpuic.config import DataConfig
    from tpuic.data.folder import ImageFolderDataset

    ds = ImageFolderDataset(DATA_ROOT, fold, 32, DataConfig(resize_size=32))
    xs, ys = [], []
    for i in range(len(ds)):
        img, label, _ = ds.load(i)  # no rng -> clean (matches augment=False)
        xs.append(img)
        ys.append(label)
    return np.stack(xs), np.asarray(ys, np.int64)


def run_torch_control(epochs: int) -> dict:
    import numpy as np
    import torch
    import torch.nn.functional as F

    from tpuic.checkpoint.torch_ref import build_resnet

    torch.manual_seed(0)
    xtr, ytr = _load_fold_arrays("train")
    xva, yva = _load_fold_arrays("val")
    # NHWC float32 -> NCHW torch tensors.
    xtr_t = torch.from_numpy(np.transpose(xtr, (0, 3, 1, 2))).contiguous()
    ytr_t = torch.from_numpy(ytr)
    xva_t = torch.from_numpy(np.transpose(xva, (0, 3, 1, 2))).contiguous()
    yva_t = torch.from_numpy(yva)

    model = build_resnet("resnet18-cifar", num_classes=10)
    opt = torch.optim.SGD(model.parameters(), lr=LR, momentum=0.9,
                          weight_decay=WEIGHT_DECAY)
    steps_per_epoch = len(xtr_t) // BATCH  # drop_last, as the tpuic loader
    # THE schedule, not a re-implementation: evaluate the same
    # warmup_cosine_schedule object the tpuic optimizer runs (pre-computed
    # per step so torch never touches jax mid-training).
    from tpuic.train.schedule import warmup_cosine_schedule
    sched = warmup_cosine_schedule(LR, WARMUP_EPOCHS, epochs,
                                   steps_per_epoch)
    lr_table = [float(sched(t)) for t in range(epochs * steps_per_epoch)]

    def lr_at(t: int) -> float:
        return lr_table[min(t, len(lr_table) - 1)]

    g = torch.Generator().manual_seed(0)
    t0 = time.perf_counter()
    best = 0.0
    step = 0
    for _epoch in range(epochs):
        model.train()
        order = torch.randperm(len(xtr_t), generator=g)
        for b in range(steps_per_epoch):
            idx = order[b * BATCH:(b + 1) * BATCH]
            for pg in opt.param_groups:
                pg["lr"] = lr_at(step)
            opt.zero_grad()
            loss = F.cross_entropy(model(xtr_t[idx]), ytr_t[idx])
            loss.backward()
            opt.step()
            step += 1
        model.eval()
        with torch.no_grad():
            correct = 0
            for lo in range(0, len(xva_t), 256):
                pred = model(xva_t[lo:lo + 256]).argmax(1)
                correct += int((pred == yva_t[lo:lo + 256]).sum())
        best = max(best, 100.0 * correct / len(xva_t))
    wall = time.perf_counter() - t0
    return {
        "framework": "torch (torch_ref replica, CPU)",
        "model": "resnet18-cifar", "resize": 32, "batch": BATCH,
        "optimizer": f"sgd(momentum=0.9, wd={WEIGHT_DECAY})",
        "schedule": f"warmup_cosine(lr={LR}, warmup={WARMUP_EPOCHS}ep)",
        "epochs": epochs, "augment": False,
        "n_train": int(len(xtr_t)), "n_val": int(len(xva_t)),
        "best_val_top1": round(best, 2),
        "wall_s": round(wall, 1),
        "platform": "cpu", "dtype": "float32",
    }


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--epochs", type=int, default=EPOCHS)
    p.add_argument("--model", default="resnet18-cifar",
                   help="secondary models (e.g. vit-tiny) are recorded "
                        "under 'tpuic_<model>'; the torch control pairs "
                        "with the primary resnet18-cifar entry only")
    p.add_argument("--optimizer", default="sgd")
    p.add_argument("--lr", type=float, default=LR)
    p.add_argument("--mixup", type=float, default=0.0,
                   help="orientation-SAFE augmentation for ViT-family "
                        "runs (rot/flip alias digit classes; mixup/cutmix "
                        "do not)")
    p.add_argument("--cutmix", type=float, default=0.0)
    p.add_argument("--skip-tpuic", action="store_true")
    p.add_argument("--skip-control", action="store_true")
    args = p.parse_args()

    # The torch control path is jax-free; only the tpuic run needs the
    # backend, so only it refuses on a dead tunnel.
    if not args.skip_tpuic:
        from tpuic.runtime.axon_guard import exit_if_unreachable
        exit_if_unreachable()

    ensure_dataset()

    result = {}
    if os.path.exists(OUT):
        with open(OUT) as f:
            try:
                result = json.load(f)
            except ValueError:
                result = {}
    result.setdefault("dataset", {
        "name": "sklearn handwritten digits (UCI)",
        "why": "only real image dataset reachable under zero egress; "
               "CIFAR-10/ImageNet have no local copy "
               "(scripts/make_digits_dataset.py)",
        "n_images": 1797, "classes": 10, "native_size": "8x8",
    })
    if not args.skip_tpuic:
        key = ("tpuic" if args.model == "resnet18-cifar"
               else f"tpuic_{args.model}")
        result[key] = run_tpuic(args.epochs, model=args.model,
                                optimizer=args.optimizer, lr=args.lr,
                                mixup=args.mixup, cutmix=args.cutmix)
        print(json.dumps(result[key], indent=2))
    if not args.skip_control:
        result["torch_control"] = run_torch_control(args.epochs)
        print(json.dumps(result["torch_control"], indent=2))
    if "tpuic" in result and "torch_control" in result:
        result["top1_delta_tpuic_minus_torch"] = round(
            result["tpuic"]["best_val_top1"]
            - result["torch_control"]["best_val_top1"], 2)
    with open(OUT, "w") as f:
        json.dump(result, f, indent=2)
    print(f"wrote {OUT}")


if __name__ == "__main__":
    main()
