#!/usr/bin/env python
"""Prove Trainer.fit() end-to-end on the chip at bench-grade throughput.

VERDICT r2 weak #4 / next-round item 4: the r2 BENCH number was produced by
bench.py's hand-rolled loop; `Trainer.fit()` as shipped logged (and
device-synced) every step and had never run on the TPU. This script builds
a synthetic ImageFolder, runs `train.py`'s Trainer (packed loader + device
augmentation + the default log cadence) for a few epochs on the chip, and
reports the in-loop steady-state images/sec next to bench.py's number.

Writes perf/fit_proof.json. Done criterion: loop throughput within ~10% of
the freshest live bench.py line (perf/bench_last_tpu.json) at the same
(resnet50, b128, bf16, sgd) config.
"""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def steady_rate(rates, logs_per_epoch):
    """True median of the logged rates with each epoch's FIRST interval
    dropped (epoch 0's carries compile; every epoch's carries queue ramp).

    Guards the degenerate cases that would silently zero the round's key
    artifact: logs_per_epoch < 1 (fewer steps than the log cadence) keeps
    everything; an all-dropped list falls back to the raw median."""
    if logs_per_epoch < 1:
        keep = list(rates)
    else:
        keep = [r for i, r in enumerate(rates) if i % logs_per_epoch != 0]
    if not keep:
        keep = list(rates)
    if not keep:
        return 0.0
    import statistics
    return float(statistics.median(keep))


def main():
    from tpuic.runtime.axon_guard import exit_if_unreachable
    exit_if_unreachable()

    import jax

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, "tests", ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

    from tpuic.config import (Config, DataConfig, MeshConfig, ModelConfig,
                              OptimConfig, RunConfig)
    from tpuic.data.synthetic import make_synthetic_imagefolder
    from tpuic.train.loop import Trainer

    n_per_class = int(os.environ.get("TPUIC_FIT_PER_CLASS", "1536"))
    epochs = int(os.environ.get("TPUIC_FIT_EPOCHS", "3"))
    batch = int(os.environ.get("TPUIC_FIT_BATCH", "128"))

    root = tempfile.mkdtemp(prefix="tpuic_fitproof_")
    t0 = time.perf_counter()
    # Val is 1/8 of train: the proof measures the TRAIN loop's throughput;
    # a full-size val fold only adds pack time and resident-cache upload.
    make_synthetic_imagefolder(root, classes=("a", "b", "c", "d"),
                               per_class=n_per_class, size=224,
                               folds=("train",))
    make_synthetic_imagefolder(root, classes=("a", "b", "c", "d"),
                               per_class=max(64, n_per_class // 8), size=224,
                               folds=("val",))
    make_time = time.perf_counter() - t0
    ckpt = os.path.join(root, "ckpt")
    log_dir = os.path.join(_REPO, "perf", "fit_proof_logs")
    os.makedirs(log_dir, exist_ok=True)
    cfg = Config(
        data=DataConfig(data_dir=root, resize_size=224, batch_size=batch),
        model=ModelConfig(name="resnet50", num_classes=4, dtype="bfloat16"),
        # lr 0.01: flat 0.1 on a from-scratch resnet50 diverges to NaN in a
        # few steps on this synthetic set (round-3 run) — the proof should
        # show a loss that MOVES, not just steps that execute.
        optim=OptimConfig(optimizer="sgd", learning_rate=0.01,
                          class_weights=(), milestones=()),
        run=RunConfig(epochs=epochs, ckpt_dir=ckpt, save_period=100,
                      resume=False, log_every_steps=10),
        mesh=MeshConfig(),
    )
    t1 = time.perf_counter()
    trainer = Trainer(cfg, log_dir=log_dir)
    setup_time = time.perf_counter() - t1
    t2 = time.perf_counter()
    best = trainer.fit()
    fit_time = time.perf_counter() - t2

    # Steady-state: the logged images_per_sec samples, dropping each epoch's
    # first interval (contains compile on epoch 0 and queue ramp).
    rates = []
    with open(os.path.join(log_dir, "metrics.jsonl")) as f:
        for line in f:
            rec = json.loads(line)
            if "images_per_sec" in rec:
                rates.append(rec["images_per_sec"])
    steps_per_epoch = trainer.train_loader.steps_per_epoch()
    logs_per_epoch = steps_per_epoch // cfg.run.log_every_steps
    rate = steady_rate(rates, logs_per_epoch)

    # Single source of truth for "bench img/s" (VERDICT r4 weak #2 /
    # item 7): the freshest live bench.py line (bench_last_tpu.json,
    # refreshed by the poller on every tunnel recovery), falling back to
    # the r3 sweep only if this bench has never succeeded on chip.
    bench_src = os.path.join(_REPO, "perf", "bench_last_tpu.json")
    try:
        with open(bench_src) as f:
            bench_rate = float(json.load(f)["result"]["value"])
        bench_src = "perf/bench_last_tpu.json"
    except (OSError, ValueError, KeyError, TypeError):
        bench_rate, bench_src = 2674.0, "perf/sweep.json b128 (fallback)"
    result = {
        "model": "resnet50", "batch": batch, "epochs": epochs,
        "n_train_images": n_per_class * 4,
        "dataset_gen_s": round(make_time, 1),
        "trainer_setup_s": round(setup_time, 1),
        "fit_s": round(fit_time, 1),
        "best_val_acc": best,
        "loop_images_per_sec_median_steady": rate,
        "bench_images_per_sec": bench_rate,
        "bench_source": bench_src,
        "loop_vs_bench": round(rate / bench_rate, 4),
        "all_logged_rates": rates,
        "platform": jax.devices()[0].platform,
    }
    with open(os.path.join(_REPO, "perf", "fit_proof.json"), "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps({k: v for k, v in result.items()
                      if k != "all_logged_rates"}, indent=2))
    assert result["loop_vs_bench"] > 0.85, \
        f"loop at {rate} img/s is >15% below bench {bench_rate}"
    print("FIT PROOF OK")


if __name__ == "__main__":
    main()
