#!/usr/bin/env python
"""Telemetry smoke gate (ISSUE 3 acceptance; runs in tier-1 CI).

Drives the REAL CLI end to end: builds a synthetic ImageFolder, runs
``train.py --steps N --metrics-jsonl out.jsonl`` as a subprocess on CPU,
then asserts the telemetry contract:

- the JSONL parses, with a ``step`` event for every step and the full
  time breakdown (total/data/dispatch/device) in each;
- exactly one final goodput report whose named buckets
  (productive/input/compile/checkpoint/skip/rollback/eval) sum to within
  2% of the measured wall time — the "where did the time go" ledger must
  actually add up.

Exit 0 on success; prints the goodput report either way.

    python scripts/telemetry_smoke.py [--steps 5] [--keep]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
STEP_KEYS = {"total_ms", "data_ms", "dispatch_ms", "device_ms"}
BUCKETS = ("productive", "input", "compile", "checkpoint", "skip",
           "rollback", "eval")


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=5)
    p.add_argument("--tolerance", type=float, default=0.02,
                   help="max |named buckets - wall| / wall")
    p.add_argument("--keep", action="store_true",
                   help="keep the temp workdir for inspection")
    args = p.parse_args()

    work = tempfile.mkdtemp(prefix="tpuic_tm_smoke_")
    try:
        sys.path.insert(0, _REPO)
        from tpuic.data.synthetic import make_synthetic_imagefolder
        data = os.path.join(work, "data")
        # 3 classes x 8 images / batch 2 = 12 steps/epoch: the --steps
        # budget always stops mid-epoch, so the run is train-only.
        make_synthetic_imagefolder(data, classes=("a", "b", "c"),
                                   per_class=8, size=32)
        jsonl = os.path.join(work, "events.jsonl")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   TF_CPP_MIN_LOG_LEVEL="3")
        # Enough epochs to cover the budget at 12 steps/epoch (later
        # epochs include val passes — the eval bucket absorbs them).
        epochs = args.steps // 12 + 1
        cmd = [sys.executable, os.path.join(_REPO, "train.py"),
               "--datadir", data, "--model", "resnet18-cifar",
               "--resize", "32", "--batchsize", "2",
               "--epochs", str(epochs),
               "--optimizer", "adam", "--lr", "1e-3",
               "--no-class-weights", "--log-every-steps", "1",
               "--ckpt-dir", os.path.join(work, "cp"),
               "--steps", str(args.steps), "--metrics-jsonl", jsonl]
        proc = subprocess.run(cmd, cwd=_REPO, env=env, text=True,
                              capture_output=True, timeout=1200)
        if proc.returncode != 0:
            print(proc.stdout[-2000:])
            print(proc.stderr[-2000:], file=sys.stderr)
            print(f"FAIL: train.py exited {proc.returncode}")
            return 1

        recs = [json.loads(ln) for ln in open(jsonl)]  # must parse
        steps = [r for r in recs if r["event"] == "step"]
        assert len(steps) == args.steps, \
            f"expected {args.steps} step events, got {len(steps)}"
        assert [r["step"] for r in steps] == list(range(1, args.steps + 1))
        for r in steps:
            missing = STEP_KEYS - set(r)
            assert not missing, f"step {r['step']} missing {missing}"

        finals = [r for r in recs if r["event"] == "goodput"
                  and r.get("final")]
        assert len(finals) == 1, f"want 1 final goodput, got {len(finals)}"
        rep = finals[0]
        print("goodput:", json.dumps(
            {k: v for k, v in rep.items() if k not in ("event", "t")},
            indent=2))
        named = sum(rep[f"{k}_s"] for k in BUCKETS)
        wall = rep["wall_s"]
        assert wall > 0, "empty goodput window"
        gap = abs(wall - named) / wall
        print(f"wall {wall:.3f}s, named buckets {named:.3f}s, "
              f"gap {100 * gap:.2f}% (tolerance "
              f"{100 * args.tolerance:.0f}%)")
        assert gap <= args.tolerance, \
            f"goodput buckets leave {100 * gap:.2f}% of wall unaccounted"
        print(f"OK: {len(steps)} step events with full breakdown; "
              f"goodput ledger adds up")
        return 0
    finally:
        if args.keep:
            print(f"workdir kept: {work}")
        else:
            shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
