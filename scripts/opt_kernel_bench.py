#!/usr/bin/env python
"""Fused optimizer update vs the optax chain, measured.

The PR-16 claim behind OptimConfig.fused_optimizer: the one-pass
params/grads/moments update (tpuic/kernels/optimizer_update.py — Pallas
on TPU, a single fused jnp expression elsewhere) beats the optax
lars/lamb chains, which materialize an update-sized temporary per chain
link.  This script times both arms on real model-shaped pytrees — jit'd
update + apply_updates, identical inputs — and asserts the steady state
performs ZERO backend compiles (tpuic.analysis.runtime
assert_compiles_flat), so the headline can't be hiding a retrace.

Writes ``perf/fused_optimizer.json``.  The committed artifact carries
the caveat in-band: CPU numbers from this container (the jnp arm; the
Pallas kernel path needs a chip and is trajectory-pinned against the
same references in tests/test_fused_optimizer.py).

    python scripts/opt_kernel_bench.py [--out perf/fused_optimizer.json]
        [--model resnet18] [--reps 40]
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import statistics
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

OPTIMIZERS = ("lars", "lamb")


def _time_arm(tx, params, grads, reps: int):
    """p50/p90 ms of one jit'd update+apply on a warm cache, compile-flat."""
    import jax
    import optax
    from tpuic.analysis.runtime import assert_compiles_flat

    @jax.jit
    def apply(p, s, g):
        updates, s2 = tx.update(g, s, p)
        return optax.apply_updates(p, updates), s2

    state = tx.init(params)
    p, s = params, state
    for _ in range(3):  # warmup: compile + cache effects
        p, s = apply(p, s, grads)
    jax.block_until_ready(p)
    times = []
    with assert_compiles_flat(what="steady-state optimizer update"):
        for _ in range(reps):
            t0 = time.perf_counter()
            p, s = apply(p, s, grads)
            jax.block_until_ready(p)
            times.append((time.perf_counter() - t0) * 1e3)
    qs = statistics.quantiles(times, n=10)
    return {"p50_ms": round(statistics.median(times), 3),
            "p90_ms": round(qs[8], 3)}


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default=os.path.join(_REPO, "perf",
                                                 "fused_optimizer.json"))
    p.add_argument("--model", default="resnet18")
    p.add_argument("--reps", type=int, default=40)
    args = p.parse_args()

    import jax
    from tpuic.config import OptimConfig
    from tpuic.kernels import default_opt_impl
    from tpuic.models import create_model
    from tpuic.train.optimizer import make_optimizer
    from tpuic.utils import tree_bytes

    model = create_model(args.model, 10, dtype="float32")
    variables = model.init(jax.random.key(0),
                           jax.numpy.zeros((2, 64, 64, 3)), train=False)
    params = variables["params"]
    n_params = sum(x.size for x in jax.tree.leaves(params))
    # Grads shaped like a real backward pass, small but nonzero.
    keys = iter(jax.random.split(jax.random.key(1),
                                 len(jax.tree.leaves(params))))
    grads = jax.tree.map(
        lambda x: 0.01 * jax.random.normal(next(keys), x.shape, x.dtype),
        params)

    out = {"schema": "tpuic.fused_optimizer.v1",
           "platform": jax.devices()[0].platform,
           "impl": default_opt_impl(),
           "model": args.model,
           "param_count": int(n_params),
           "param_bytes": tree_bytes(params),
           "reps": args.reps,
           "steady_state_compiles": 0,
           "caveat": ("CPU container measurement: the fused arm runs the "
                      "single-expression jnp path (default_opt_impl() off "
                      "TPU); the Pallas kernel is trajectory-pinned "
                      "against the same numpy references in "
                      "tests/test_fused_optimizer.py and awaits a chip "
                      "for its own timing. Zero steady-state compiles is "
                      "asserted, not assumed."),
           "optimizers": {}}
    for opt in OPTIMIZERS:
        cfg = OptimConfig(optimizer=opt, learning_rate=1e-3,
                          class_weights=(), milestones=())
        rows = {}
        for arm, fused in (("optax", False), ("fused", True)):
            tx = make_optimizer(dataclasses.replace(
                cfg, fused_optimizer=fused))
            rows[arm] = _time_arm(tx, params, grads, args.reps)
        rows["speedup_p50"] = round(
            rows["optax"]["p50_ms"] / rows["fused"]["p50_ms"], 3)
        out["optimizers"][opt] = rows
        print(f"[opt-bench] {opt}: optax {rows['optax']['p50_ms']:.2f} ms "
              f"vs fused {rows['fused']['p50_ms']:.2f} ms p50 "
              f"({rows['speedup_p50']:.2f}x), 0 steady-state compiles")
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
    print(f"[opt-bench] artifact -> {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
