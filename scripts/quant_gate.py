#!/usr/bin/env python
"""Quantization accuracy gate (tier-1 CI; docs/performance.md,
"Quantized serving").

The serve dtype ladder's contract is that a quantized rung moves
predictions by at most the committed epsilon
(``tpuic.quant.DEFAULT_EPSILON``) on the pinned synthetic eval set.
This script proves it BOTH ways, the same bidirectional discipline as
the perf-regression and roofline gates:

- clean: the bf16 and int8 rungs of a pinned seeded model must pass
  (top-1 agreement with fp32 >= 1 - epsilon);
- ``--corrupt --expect-fail``: the same int8 rung built from a seeded
  weight corruption (``quant.corrupt_variables``) must FAIL the gate —
  a gate that cannot fire is decoration.

Everything is seeded (model init, eval images, corruption), so the CI
verdict is reproducible.

    python scripts/quant_gate.py
    python scripts/quant_gate.py --corrupt --expect-fail
"""

from __future__ import annotations

import argparse
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _force_cpu() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
    from tpuic.runtime.axon_guard import drop_axon_vars
    drop_axon_vars(os.environ)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, "tests", ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--model", default="resnet18-cifar")
    p.add_argument("--num-classes", type=int, default=10)
    p.add_argument("--size", type=int, default=24)
    p.add_argument("--eval-n", type=int, default=256)
    p.add_argument("--corrupt", action="store_true",
                   help="build the int8 rung from seeded-corrupted "
                        "weights (the gate-can-fire arm)")
    p.add_argument("--expect-fail", action="store_true",
                   help="exit 0 IFF the gate fails (CI's bidirectional "
                        "proof)")
    args = p.parse_args(argv)

    _force_cpu()
    import jax
    import jax.numpy as jnp

    from tpuic import quant
    from tpuic.models import create_model

    model = create_model(args.model, args.num_classes, dtype="float32")
    variables = model.init(
        jax.random.key(0),
        jnp.zeros((1, args.size, args.size, 3), jnp.float32), train=False)
    imgs = quant.eval_images(args.eval_n, args.size)
    floor = 1.0 - quant.DEFAULT_EPSILON

    variants = quant.serve_variants(model, variables,
                                    ("fp32", "bf16", "int8"),
                                    normalize=True)
    ref_fwd, ref_vars = variants["fp32"]
    ref = jax.jit(ref_fwd)

    failed = []
    for tag in ("bf16", "int8"):
        fwd, qv = variants[tag]
        if args.corrupt and tag == "int8":
            # The must-fail arm: quantize weights that no longer match
            # the fp32 reference — the exact bug class (a broken
            # quantization pass, a stale scale tree) the gate exists
            # to catch.
            qv = quant.quantize_variables(
                quant.corrupt_variables(variables, seed=0))
        agree = quant.top1_agreement(ref, ref_vars, jax.jit(fwd), qv, imgs)
        verdict = "ok" if agree >= floor else "FAILED"
        print(f"[quant-gate] {tag:<5} top-1 agreement {agree:.4f} "
              f"(floor {floor:.4f}, epsilon {quant.DEFAULT_EPSILON}) "
              f"{verdict}")
        if agree < floor:
            failed.append(tag)

    if args.expect_fail:
        if failed:
            print(f"[quant-gate] expected failure observed on "
                  f"{', '.join(failed)} — the gate can fire "
                  "(bidirectional proof OK)")
            return 0
        print("[quant-gate] ERROR: seeded corruption did NOT trip the "
              "gate — the gate is decoration", file=sys.stderr)
        return 2
    if failed:
        print(f"[quant-gate] REGRESSION: rung(s) {', '.join(failed)} "
              f"moved top-1 past the committed epsilon", file=sys.stderr)
        return 2
    print("[quant-gate] clean: every rung within epsilon")
    return 0


if __name__ == "__main__":
    sys.exit(main())
