#!/bin/bash
# Chip work queue for when the axon tunnel recovers. Run items in order,
# checking reachability between each (the tunnel can re-wedge).
# Round-4 ordering = VERDICT r3 "Next round" items 1, 2, 3, 4, 6.
set -x -o pipefail
failures=0
cd /root/repo
probe() { python -c "
from tpuic.runtime.axon_guard import tpu_reachable
import sys; sys.exit(0 if tpu_reachable(150) else 1)"; }

probe || { echo "chip_queue: tunnel down ($failures item failures so far)"; exit $((90 + failures)); }
# 1. THE round-3 carryover: Trainer.fit at bench-grade throughput via the
#    device-resident cache (chunked + bounded-peak upload now).
TPUIC_FIT_EPOCHS=3 python scripts/fit_proof.py 2>&1 | tail -20 || failures=$((failures+1))

probe || { echo "chip_queue: tunnel down ($failures item failures so far)"; exit $((90 + failures)); }
# 2. Compute-bound MFU datapoint: ViT-B/16 bf16 batch sweep (VERDICT r3
#    item 2 — the 0.70 north star lives or dies on a transformer number).
python scripts/perf_sweep.py --batches 32,64,128,256 --model vit-b16 \
  --out perf/vit_sweep.json 2>&1 | tail -6 || failures=$((failures+1))

probe || { echo "chip_queue: tunnel down ($failures item failures so far)"; exit $((90 + failures)); }
# 3. s2d stem sweep at the bench batch size.
python scripts/perf_sweep.py --batches 96,128 --model resnet50-s2d --out perf/sweep_s2d.json 2>&1 | tail -5 || failures=$((failures+1))

probe || { echo "chip_queue: tunnel down ($failures item failures so far)"; exit $((90 + failures)); }
# 3b. Kernel microbench rerun: flash now uses length-adaptive blocks
#     (one k-pass at N=197, 512-blocks at N=2048) — refresh the smoke
#     numbers the r3 "flash never wins" verdict was based on.
python scripts/pallas_smoke.py 2>&1 | tail -4 || failures=$((failures+1))

probe || { echo "chip_queue: tunnel down ($failures item failures so far)"; exit $((90 + failures)); }
# 4. Long-sequence dense-vs-flash crossover (flash must win somewhere or
#    be demoted — VERDICT r3 item 4): standard sizes, then the long-N
#    probe (N=2305/4097 with remat) where dense is expected to OOM.
python scripts/long_seq_bench.py --sizes 224,384,512 --batch 32 2>&1 | tail -8 || failures=$((failures+1))
python scripts/long_seq_bench.py --sizes 768,1024 --batch 16 --remat \
  --out perf/long_seq_4k.json 2>&1 | tail -6 || failures=$((failures+1))

probe || { echo "chip_queue: tunnel down ($failures item failures so far)"; exit $((90 + failures)); }
# 5. bench path reconciliation: the SPMD (1-device mesh) step vs the
#    mesh=None step at the bench config (VERDICT r3 item 6).
python scripts/perf_sweep.py --batches 128 --model resnet50 --spmd \
  --out perf/sweep_spmd.json 2>&1 | tail -3 || failures=$((failures+1))

probe || { echo "chip_queue: tunnel down ($failures item failures so far)"; exit $((90 + failures)); }
# 6. BN-stat bytes: bf16 batch-stat accumulation at the bench config
#    (VERDICT r3 item 7; tolerance pinned in tests/test_models.py).
python scripts/perf_sweep.py --batches 128 --model resnet50 --bn-bf16-stats \
  --out perf/sweep_bnbf16.json 2>&1 | tail -3 || failures=$((failures+1))

probe || { echo "chip_queue: tunnel down ($failures item failures so far)"; exit $((90 + failures)); }
# 7. Fresh bench line (sanity; the driver runs it too at round end).
python bench.py 2>&1 | tail -2 || failures=$((failures+1))
echo "chip_queue: $failures item(s) failed"
exit $failures
