#!/bin/bash
# Chip work queue for when the axon tunnel recovers. Run items in order,
# checking reachability between each (the tunnel can re-wedge).
set -x -o pipefail
failures=0
cd /root/repo
probe() { python -c "
from tpuic.runtime.axon_guard import tpu_reachable
import sys; sys.exit(0 if tpu_reachable(150) else 1)"; }

probe || { echo "chip_queue: tunnel down ($failures item failures so far)"; exit $((90 + failures)); }
# 1. THE round-3 item: Trainer.fit at bench-grade throughput via the
#    device-resident cache (chunked upload now).
TPUIC_FIT_EPOCHS=3 python scripts/fit_proof.py 2>&1 | tail -20 || failures=$((failures+1))

probe || { echo "chip_queue: tunnel down ($failures item failures so far)"; exit $((90 + failures)); }
# 2. s2d stem sweep at the bench batch size.
python scripts/perf_sweep.py --batches 96,128 --model resnet50-s2d --out perf/sweep_s2d.json 2>&1 | tail -5 || failures=$((failures+1))

probe || { echo "chip_queue: tunnel down ($failures item failures so far)"; exit $((90 + failures)); }
# 3. Long-sequence dense-vs-flash crossover.
python scripts/long_seq_bench.py --sizes 224,384,512 --batch 32 2>&1 | tail -8 || failures=$((failures+1))

probe || { echo "chip_queue: tunnel down ($failures item failures so far)"; exit $((90 + failures)); }
# 4. Fresh bench line (sanity; the driver runs it too at round end).
python bench.py 2>&1 | tail -2 || failures=$((failures+1))
echo "chip_queue: $failures item(s) failed"
exit $failures
