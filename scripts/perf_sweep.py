#!/usr/bin/env python
"""Batch-size / remat sweep of the ResNet-50 train step on the local chip.

Round-3 perf work (VERDICT r2 weak #1): the r2 bench pinned per-chip batch
at 64 and recorded MFU 0.2655 with no optimization attempted. This script
measures step time across per-chip batch sizes (and optionally remat) and
writes perf/sweep.json for PERF_ANALYSIS.md.

Usage: python scripts/perf_sweep.py [--batches 64,128,256] [--remat]
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

PEAK_BF16 = 197e12  # TPU v5e


def measure(per_chip_batch: int, remat: bool, n_steps: int = 30,
            model_name: str = "resnet50", size: int = 224,
            attention: str = "dense", fused_loss: bool = False,
            spmd: bool = False, bn_f32_stats: bool = True,
            remat_policy: str = "dots") -> dict:
    """``spmd=True`` builds a mesh even on one chip and runs the sharded
    step executable — the production path — so its dispatch/compile delta
    vs the unannotated single-chip path is a measured row, not a claim
    (VERDICT r3 weak #4 / next-round item 6)."""
    import jax
    import jax.numpy as jnp

    import contextlib

    from tpuic.config import MeshConfig, ModelConfig, OptimConfig
    from tpuic.data.synthetic import synthetic_batch
    from tpuic.models import create_model_from_config
    from tpuic.runtime.mesh import data_sharding, make_mesh
    from tpuic.train.optimizer import make_optimizer
    from tpuic.train.state import create_train_state
    from tpuic.train.step import make_train_step

    n_chips = jax.device_count()
    global_batch = per_chip_batch * n_chips
    mcfg = ModelConfig(name=model_name, num_classes=1000, dtype="bfloat16",
                       remat=remat, remat_policy=remat_policy,
                       attention=attention, bn_f32_stats=bn_f32_stats)
    ocfg = OptimConfig(optimizer="sgd", learning_rate=0.1, class_weights=(),
                      milestones=(), fused_loss=fused_loss)
    mesh = make_mesh(MeshConfig()) if (spmd or n_chips > 1) else None
    # from_config so every model-shaping field (attention, bn stats,
    # remat_core for remat_policy='attention') flows to the module.
    model = create_model_from_config(mcfg, mesh=mesh)
    with (mesh if mesh is not None else contextlib.nullcontext()):
        state = create_train_state(model, make_optimizer(ocfg),
                                   jax.random.key(0),
                                   (global_batch, size, size, 3))
    batch = synthetic_batch(global_batch, size, mcfg.num_classes)
    if mesh is not None:
        sh = data_sharding(mesh)
        batch = {k: jax.device_put(v, sh) for k, v in batch.items()}
    else:
        batch = {k: jax.device_put(jnp.asarray(v)) for k, v in batch.items()}
    step = make_train_step(ocfg, mcfg, mesh, donate=True)

    lowered = step.lower(state, batch)
    compiled = lowered.compile()
    cost = compiled.cost_analysis()
    flops_per_step = float(cost["flops"])
    t_comp = time.perf_counter()
    state, m = step(state, batch)
    float(m["loss"])
    compile_s = time.perf_counter() - t_comp
    t0 = time.perf_counter()
    for _ in range(n_steps):
        state, m = step(state, batch)
    float(m["loss"])
    dt = time.perf_counter() - t0
    step_ms = 1000 * dt / n_steps
    imgs = global_batch * n_steps / dt
    mfu = flops_per_step * (n_steps / dt) / (PEAK_BF16 * n_chips)
    mem = compiled.memory_analysis()
    out = {
        "model": model_name,
        "per_chip_batch": per_chip_batch,
        "remat": remat,
        "remat_policy": remat_policy if remat else None,
        "size": size,
        "attention": attention,
        "fused_loss": fused_loss,
        "spmd": mesh is not None,
        "bn_f32_stats": bn_f32_stats,
        "step_ms": round(step_ms, 2),
        "images_per_sec_per_chip": round(imgs / n_chips, 1),
        "mfu": round(mfu, 4),
        "flops_per_step": flops_per_step,
        "flops_per_image": round(flops_per_step / global_batch / 1e9, 2),
        "compile_s": round(compile_s, 1),
        "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
    }
    if mem is not None:
        out["peak_memory_mb"] = round(
            getattr(mem, "temp_size_in_bytes", 0) / 1e6, 1)
        out["argument_mb"] = round(
            getattr(mem, "argument_size_in_bytes", 0) / 1e6, 1)
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batches", default="64,128,256")
    ap.add_argument("--model", default="resnet50")
    ap.add_argument("--size", type=int, default=224)
    from tpuic.models import ATTENTION_IMPLS
    ap.add_argument("--attention", default="dense",
                    choices=list(ATTENTION_IMPLS),
                    help="vit attention impl")
    ap.add_argument("--fused-loss", action="store_true",
                    help="Pallas fused cross-entropy")
    ap.add_argument("--spmd", action="store_true",
                    help="run the sharded (mesh) step even on one chip — "
                         "the production executable (VERDICT r3 item 6)")
    ap.add_argument("--bn-bf16-stats", action="store_true",
                    help="accumulate BN batch stats in bf16 (HBM-byte "
                         "experiment, VERDICT r3 item 7)")
    ap.add_argument("--remat", action="store_true",
                    help="also measure remat=True at each batch size")
    ap.add_argument("--remat-policy", default="dots",
                    choices=["dots", "attention", "blocks", "gelu"],
                    help="policy for the remat rows: 'attention' recomputes "
                         "only the [B,H,N,N] ViT tensors; 'blocks' = "
                         "per-encoder-block, the long-context memory mode; "
                         "'gelu' drops only the ViT [B,N,4D] MLP "
                         "pre-activations (lightest; see ModelConfig)")
    ap.add_argument("--out", default=os.path.join(_REPO, "perf", "sweep.json"))
    args = ap.parse_args()

    from tpuic.runtime.axon_guard import exit_if_unreachable
    exit_if_unreachable()

    import jax
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, "tests", ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

    results = []
    for b in [int(x) for x in args.batches.split(",")]:
        for remat in ([False, True] if args.remat else [False]):
            try:
                r = measure(b, remat, model_name=args.model, size=args.size,
                            attention=args.attention,
                            fused_loss=args.fused_loss, spmd=args.spmd,
                            bn_f32_stats=not args.bn_bf16_stats,
                            remat_policy=args.remat_policy)
            except Exception as e:  # OOM at large batch is a data point
                r = {"model": args.model, "per_chip_batch": b, "remat": remat,
                     "remat_policy": args.remat_policy if remat else None,
                     "error": f"{type(e).__name__}: {e}"[:300]}
            print(json.dumps(r), flush=True)
            results.append(r)
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump({"device": str(jax.devices()[0]), "model": args.model,
                   "results": results}, f, indent=2)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
