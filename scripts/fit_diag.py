#!/usr/bin/env python
"""Decompose Trainer-loop time on the chip: loader vs H2D+prep vs step vs
log sync. Diagnoses the fit_proof gap (loop 440 img/s vs bench 2674)."""

from __future__ import annotations

import json
import os
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def main():
    import jax
    import jax.numpy as jnp
    import numpy as np

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, "tests", ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

    from tpuic.config import DataConfig, ModelConfig, OptimConfig
    from tpuic.data.folder import ImageFolderDataset
    from tpuic.data.pack import pack_dataset
    from tpuic.data.pipeline import Loader
    from tpuic.data.synthetic import make_synthetic_imagefolder
    from tpuic.models import create_model
    from tpuic.train.optimizer import make_optimizer
    from tpuic.train.state import create_train_state
    from tpuic.train.step import make_train_step

    B, S = 128, 224
    root = tempfile.mkdtemp(prefix="tpuic_diag_")
    make_synthetic_imagefolder(root, classes=("a", "b", "c", "d"),
                               per_class=512, size=S, folds=("train",))
    cfg = DataConfig(data_dir=root, resize_size=S, batch_size=B)
    ds = ImageFolderDataset(root, "train", S, cfg)
    packed = pack_dataset(ds, os.path.join(root, ".p"), verbose=False)
    loader = Loader(packed, B, mesh=None, seed=0, prefetch=2)

    mcfg = ModelConfig(name="resnet50", num_classes=4, dtype="bfloat16")
    ocfg = OptimConfig(optimizer="sgd", learning_rate=0.01, class_weights=(),
                       milestones=())
    model = create_model(mcfg.name, mcfg.num_classes, dtype=mcfg.dtype)
    state = create_train_state(model, make_optimizer(ocfg), jax.random.key(0),
                               (B, S, S, 3))
    step = make_train_step(ocfg, mcfg, None, donate=True)
    out = {}

    # 1. producer-only rate (drain the queue, no device work)
    t0 = time.perf_counter()
    n = 0
    for batch in loader.epoch(0):
        jax.block_until_ready(batch["image"])
        n += B
    out["loader_only_img_s"] = round(n / (time.perf_counter() - t0), 1)

    # 2. fixed-batch step rate (bench.py equivalent, loader out of the loop)
    const = {"image": jnp.zeros((B, S, S, 3), jnp.float32),
             "label": jnp.zeros((B,), jnp.int32),
             "mask": jnp.ones((B,), jnp.float32)}
    const = {k: jax.device_put(v) for k, v in const.items()}
    state, m = step(state, const)
    float(m["loss"])
    t0 = time.perf_counter()
    for _ in range(20):
        state, m = step(state, const)
    float(m["loss"])
    out["const_batch_step_img_s"] = round(20 * B / (time.perf_counter() - t0),
                                          1)

    # 3. loader + step, NO logging sync
    t0 = time.perf_counter()
    n = 0
    for batch in loader.epoch(1):
        state, m = step(state, {k: batch[k]
                                for k in ("image", "label", "mask")})
        n += B
    float(m["loss"])
    out["loop_no_log_img_s"] = round(n / (time.perf_counter() - t0), 1)

    # 4. loader + step + per-10-step sync (fit_proof cadence)
    t0 = time.perf_counter()
    n = 0
    for i, batch in enumerate(loader.epoch(2)):
        state, m = step(state, {k: batch[k]
                                for k in ("image", "label", "mask")})
        n += B
        if (i + 1) % 10 == 0:
            float(m["loss"])
            float(m["accuracy"])
            int(jax.device_get(state.step))
    out["loop_log10_img_s"] = round(n / (time.perf_counter() - t0), 1)

    # 5. single scalar readback latency after idle device
    time.sleep(0.5)
    t0 = time.perf_counter()
    float(m["loss"])
    out["idle_readback_ms"] = round(1000 * (time.perf_counter() - t0), 2)

    out["loss_after_60_steps"] = float(m["loss"])
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
