#!/usr/bin/env python
"""Materialize the sklearn handwritten-digits set as an ImageFolder.

This environment has zero network egress and no local copy of CIFAR-10 /
ImageNet, so the one REAL image-classification dataset available is
scikit-learn's bundled UCI handwritten digits (1,797 samples, 10 classes,
8x8 grayscale — `sklearn.datasets.load_digits`). This script writes it in
the reference's ImageFolder layout (`root/{train,val}/{class}/{id}.png`,
reference dp/loader.py:20-21) with a deterministic stratified 80/20 split,
so the FULL tpuic path — glob index, pack, device cache, Trainer — runs on
real data end to end.

Images are written at native 8x8; the pipeline's resize (DataConfig.
resize_size) upscales exactly like any other small-image dataset.
"""

from __future__ import annotations

import argparse
import os

import numpy as np
from PIL import Image


def build(root: str, val_frac: float = 0.2, seed: int = 0) -> dict:
    from sklearn.datasets import load_digits

    digits = load_digits()
    # 0..16 float -> uint8 0..255 (exact: 16 * 15 = 240 + round-up scale).
    images = np.round(digits.images * (255.0 / 16.0)).astype(np.uint8)
    labels = digits.target
    rng = np.random.default_rng(seed)
    counts = {"train": 0, "val": 0}
    for cls in range(10):
        idx = np.nonzero(labels == cls)[0]
        idx = idx[rng.permutation(len(idx))]
        n_val = max(1, int(round(len(idx) * val_frac)))
        for fold, members in (("val", idx[:n_val]), ("train", idx[n_val:])):
            d = os.path.join(root, fold, str(cls))
            os.makedirs(d, exist_ok=True)
            for i in members:
                Image.fromarray(images[i], mode="L").save(
                    os.path.join(d, f"d{i:04d}.png"))
            counts[fold] += len(members)
    return counts


def main() -> None:
    p = argparse.ArgumentParser()
    p.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        ".data", "digits"))
    p.add_argument("--val-frac", type=float, default=0.2)
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args()
    if os.path.isdir(os.path.join(args.out, "train")):
        print(f"already built: {args.out}")
        return
    counts = build(args.out, args.val_frac, args.seed)
    print(f"wrote {counts['train']} train / {counts['val']} val PNGs "
          f"to {args.out}")


if __name__ == "__main__":
    main()
