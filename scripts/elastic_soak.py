#!/usr/bin/env python
"""Elastic soak (ISSUE 15 acceptance; runs in tier-1 CI).

The end-to-end proof of elastic data parallelism
(`tpuic.runtime.gang.GangSupervisor` in elastic mode driving TWO real
`train.py` ranks, CPU, synthetic data — independent ranks via the
`TPUIC_FLEET_RANK(S)` launcher override, the fleet_smoke caveat: this
container's CPU jax implements no multiprocess collectives, and
independent deterministic ranks are exactly what the bitwise verdict
wants anyway), raced against an UNDISTURBED single-process baseline:

- ``rank_crash@8#1`` SIGKILLs rank 1 mid epoch 1 (``slow_step#0.3``
  drags both ranks so the survivor is provably mid-flight);
- the fleet DEGRADES instead of restarting: the membership file walks
  init -> degrade -> rejoin, the survivor re-forms IN PLACE from the
  fleet-agreed step (one spawn record for rank 0 in the whole ledger —
  zero survivor process restarts; its stream carries a 'reform' event
  with acted=true and NO 'restart' event), and training continues;
- the FIRST replacement is armed with ``rank_rejoin_flap#1`` and dies
  inside its catch-up restore — the flap burns only rank 1's respawn
  budget (ledger 'flap', no extra membership transition); the SECOND
  replacement restores under the fleet cap, rejoins at its first
  post-restore step, and finishes;
- convergence-parity gate: both ranks' final committed optimizer step
  and per-epoch eval accuracies are BITWISE identical to the
  undisturbed baseline;
- the fleet aggregator passes the elastic coverage gate
  (``--membership ledger.jsonl``) over the per-rank streams, while the
  strict ``--require-ranks 3`` still fails (missing rank) — the
  timeline gate is additive, not a loosening;

plus the typed floor on cheap stdlib children: with 3 ranks and
``min_ranks=2``, the first kill produces a DEGRADE event and the second
kill stops the gang with the typed ``EXIT_BELOW_MIN`` verdict (the last
survivor still gets its flush window, exit 43).

Exit 0 on success.   python scripts/elastic_soak.py [--keep] [-v]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import textwrap
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from tpuic.runtime.gang import GangSupervisor  # noqa: E402
from tpuic.runtime.membership import read_membership  # noqa: E402
from tpuic.runtime.supervisor import (EXIT_BELOW_MIN,  # noqa: E402
                                      EXIT_PREEMPTED)

RANKS = 2
CRASH_RANK = 1
# Same workload math as the gang soak: 2 classes x 12 / global batch 4 =
# 6 steps/epoch, 2 epochs -> final optimizer step 12; epoch 0's commit is
# step 6 — the fleet-agreed degrade step (rank 1 dies at step 8, past the
# commit, so the survivor restores BACK to 6 and replays 7..12).
PER_CLASS = 12
BATCH = 4
EPOCHS = 2
STEPS_PER_EPOCH = (2 * PER_CLASS) // BATCH
FINAL_STEP = EPOCHS * STEPS_PER_EPOCH
# Per-RESPAWN chaos (elastic indexing): the original spawns get the kill,
# the first replacement flaps inside its catch-up restore, the second
# replacement runs clean and rejoins.
CHAOS = [f"rank_crash@8#{CRASH_RANK},slow_step#0.3",
         f"rank_rejoin_flap#{CRASH_RANK}", ""]


def _train_cmd(data: str, ckpt: str, cache: str, jsonl: str) -> list:
    return [sys.executable, os.path.join(_REPO, "train.py"),
            "--datadir", data, "--model", "resnet18-cifar",
            "--resize", "24", "--batchsize", str(BATCH),
            "--epochs", str(EPOCHS), "--optimizer", "sgd", "--lr", "0.01",
            "--no-class-weights", "--log-every-steps", "1",
            "--save-period", "1", "--workers", "2",
            "--ckpt-dir", ckpt, "--cache-dir", cache,
            "--metrics-jsonl", jsonl]


def _events(path: str) -> list:
    from tpuic.telemetry.events import read_jsonl
    return read_jsonl(path, on_torn=lambda ln: print(
        f"  [soak] skipping torn jsonl line in {path}: {ln[:80]!r}"))


def _evals(recs: list) -> dict:
    out = {}
    for r in recs:
        if r["event"] == "eval":
            out[int(r["epoch"])] = r["accuracy"]
    return out


def _final_meta_step(ckpt_model_dir: str):
    try:
        man = json.load(open(os.path.join(ckpt_model_dir,
                                          "latest.manifest.json")))
        return int(man["step"])
    except (OSError, ValueError, KeyError, TypeError):
        return None


def _floor_phase(work: str, check) -> None:
    """Typed floor on stdlib children (~2 s): first kill degrades,
    second kill below min_ranks stops with EXIT_BELOW_MIN."""
    child = os.path.join(work, "floor_child.py")
    with open(child, "w") as f:
        f.write(textwrap.dedent("""\
            import os, signal, sys, time
            from tpuic.runtime.supervisor import (EXIT_PREEMPTED,
                                                  HeartbeatWriter)
            hb = HeartbeatWriter(os.environ["TPUIC_HEARTBEAT_FILE"],
                                 min_interval_s=0.0)
            rank = int(os.environ["TPUIC_FLEET_RANK"])
            signal.signal(signal.SIGTERM,
                          lambda s, f: sys.exit(EXIT_PREEMPTED))
            hb.last_step = 1; hb.beat()
            if rank == 1:
                time.sleep(0.4); os.kill(os.getpid(), signal.SIGKILL)
            if rank == 2:
                time.sleep(1.4); os.kill(os.getpid(), signal.SIGKILL)
            while True:
                hb.beat(); time.sleep(0.05)
        """))
    sup = GangSupervisor(
        [sys.executable, child], os.path.join(work, "floor_state"),
        ranks=3, elastic=True, min_ranks=2, max_respawns=0,
        watchdog_s=30.0, startup_grace_s=30.0, poll_s=0.05, grace_s=10.0,
        backoff_s=0.05, backoff_max_s=0.1, env={"PYTHONPATH": _REPO})
    rc = sup.run()
    check(rc == EXIT_BELOW_MIN,
          f"second kill below min_ranks stopped the gang with the typed "
          f"verdict {EXIT_BELOW_MIN} (got {rc})")
    check(sup.degrades == 1,
          f"the FIRST kill produced exactly one degrade event "
          f"({sup.degrades})")
    evs = [json.loads(ln) for ln in open(sup.ledger_file)]
    give = [e for e in evs if e["event"] == "giveup"]
    check(bool(give) and "below min replicas" in give[0]["reason"],
          f"giveup names the typed cause ({give and give[0]['reason']})")
    exits0 = [e for e in evs if e["event"] == "exit" and e["rank"] == 0]
    check(bool(exits0) and exits0[-1]["returncode"] == EXIT_PREEMPTED,
          f"last survivor got its flush window — exit {EXIT_PREEMPTED} "
          f"(exits {[e['returncode'] for e in exits0]})")


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--watchdog-s", type=float, default=30.0)
    p.add_argument("--workdir", default="",
                   help="run here instead of a temp dir (CI passes a "
                        "fixed path so the gang ledger / membership "
                        "file / per-rank dumps can be uploaded on "
                        "failure)")
    p.add_argument("--keep", action="store_true")
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args()

    t_start = time.monotonic()
    work = args.workdir or tempfile.mkdtemp(prefix="tpuic_elastic_")
    os.makedirs(work, exist_ok=True)
    failures: list = []
    passed = False
    baseline = None

    def check(ok: bool, msg: str) -> None:
        print(("  ok  " if ok else "  FAIL") + f" {msg}")
        if not ok:
            failures.append(msg)

    try:
        print("[soak] typed floor: degrade on the first kill, "
              f"EXIT_BELOW_MIN {EXIT_BELOW_MIN} on the second")
        _floor_phase(work, check)
        if failures:
            return 1

        # -- dataset + parallel baseline --------------------------------
        from tpuic.data.synthetic import make_synthetic_imagefolder
        data = os.path.join(work, "data")
        make_synthetic_imagefolder(data, classes=("a", "b"),
                                   per_class=PER_CLASS, size=24)
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   TF_CPP_MIN_LOG_LEVEL="3", XLA_FLAGS="",
                   JAX_COMPILATION_CACHE_DIR=os.path.join(work,
                                                          "jax_cache"))
        sink = None if args.verbose else subprocess.DEVNULL
        base_jsonl = os.path.join(work, "baseline.jsonl")
        base_ckpt = os.path.join(work, "ckpt_base")
        print("[soak] baseline (undisturbed, single process) started in "
              "parallel")
        baseline = subprocess.Popen(
            _train_cmd(data, base_ckpt, os.path.join(work, "cache_base"),
                       base_jsonl),
            cwd=_REPO, env=env, stdout=sink, stderr=sink)

        # -- the elastic 2-rank fleet -----------------------------------
        streams = os.path.join(work, "streams")
        os.makedirs(streams, exist_ok=True)
        state_dir = os.path.join(work, "supervise")
        gang_cmd = _train_cmd(data, os.path.join(work, "cp{rank}"),
                              os.path.join(work, "cache{rank}"),
                              os.path.join(streams, "events.jsonl"))
        print(f"[soak] elastic fleet of {RANKS} ranks under chaos "
              f"({'; '.join(s or 'fault-free' for s in CHAOS)})")
        sup = GangSupervisor(
            gang_cmd, state_dir, ranks=RANKS, elastic=True, min_ranks=1,
            watchdog_s=args.watchdog_s, startup_grace_s=600.0,
            quit_wait_s=2.0, grace_s=15.0, poll_s=0.25, max_restarts=4,
            max_respawns=4, backoff_s=0.25, backoff_max_s=2.0,
            heartbeat_interval_s=0.2, chaos=CHAOS,
            ckpt_dirs=os.path.join(work, "cp{rank}", "resnet18-cifar"),
            env=dict(env, PYTHONPATH=_REPO))
        rc = sup.run()
        base_rc = baseline.wait(timeout=900)

        # -- the verdict -------------------------------------------------
        print(f"[soak] fleet finished (exit {rc}, {sup.degrades} "
              f"degrade(s), {sup.rejoins} rejoin(s), respawns "
              f"{sup.respawns}); baseline exit {base_rc}")
        check(rc == 0, "elastic fleet completed cleanly (exit 0)")
        check(base_rc == 0, "baseline completed cleanly (exit 0)")
        check(sup.degrades == 1 and sup.rejoins == 1,
              f"exactly one degrade and one rejoin "
              f"({sup.degrades}/{sup.rejoins})")
        check(sup.respawns == {0: 0, CRASH_RANK: 2},
              f"the survivor was NEVER respawned and the flapping "
              f"replacement cost rank {CRASH_RANK} a second respawn "
              f"({sup.respawns})")
        check(sup.violations == 0,
              "zero per-rank step-accounting violations")

        ledger = [json.loads(ln) for ln in open(sup.ledger_file)]
        spawns0 = [e for e in ledger
                   if e["event"] == "spawn" and e["rank"] == 0]
        check(len(spawns0) == 1,
              f"ZERO survivor process restarts — one spawn record for "
              f"rank 0 in the whole ledger ({len(spawns0)})")
        degrade = [e for e in ledger if e["event"] == "degrade"]
        check(len(degrade) == 1
              and degrade[0]["resume_step"] == STEPS_PER_EPOCH,
              f"degrade re-formed from the fleet-agreed step "
              f"{STEPS_PER_EPOCH} — epoch 0's commit, not anything the "
              f"survivor ran ahead to "
              f"({[e.get('resume_step') for e in degrade]})")
        check(any(e["event"] == "flap" and e["rank"] == CRASH_RANK
                  for e in ledger),
              "the first replacement's death INSIDE its catch-up "
              "restore was booked as a flap")
        mem = [e["reason"] for e in ledger if e["event"] == "membership"]
        check(mem == ["init", "degrade", "rejoin"],
              f"membership timeline is exactly init->degrade->rejoin "
              f"(the flap added no transition): {mem}")
        final_view = read_membership(sup.membership_file)
        check(final_view is not None
              and final_view.active == list(range(RANKS)),
              f"final membership back to full strength "
              f"({final_view and final_view.active})")

        from tpuic.telemetry.fleet import rank_stream_path
        b_recs = _events(base_jsonl)
        b_eval = _evals(b_recs)
        b_meta = _final_meta_step(os.path.join(base_ckpt,
                                               "resnet18-cifar"))
        check(b_meta == FINAL_STEP,
              f"baseline committed final step {FINAL_STEP} (got {b_meta})")
        for rank in range(RANKS):
            recs = _events(rank_stream_path(
                os.path.join(streams, "events.jsonl"), rank))
            reforms = [r for r in recs
                       if r["event"] == "reform" and r.get("acted")]
            restarts = [r for r in recs if r["event"] == "restart"]
            if rank == 0:
                check(len(reforms) == 1
                      and reforms[0]["resume_step"] == STEPS_PER_EPOCH,
                      f"survivor re-formed IN PLACE from step "
                      f"{STEPS_PER_EPOCH} ({reforms})")
                check(not restarts,
                      f"survivor stream carries NO restart event — its "
                      f"process never died ({restarts})")
            else:
                check(bool(restarts),
                      f"replacement announced its respawned life "
                      f"({restarts})")
            meta = _final_meta_step(os.path.join(work, f"cp{rank}",
                                                 "resnet18-cifar"))
            check(meta == b_meta,
                  f"rank {rank} final checkpointed step matches baseline "
                  f"({meta} == {b_meta})")
            ev = _evals(recs)
            check(ev == b_eval and set(ev) == set(range(EPOCHS)),
                  f"rank {rank} per-epoch eval accuracy bitwise-equal to "
                  f"baseline ({ev} == {b_eval})")
            per_epoch: dict = {}
            for r in recs:
                if r["event"] == "eval":
                    per_epoch.setdefault(int(r["epoch"]),
                                         set()).add(r["accuracy"])
            check(all(len(v) == 1 for v in per_epoch.values()),
                  f"rank {rank} replayed evals bitwise identical "
                  f"({per_epoch})")

        # The aggregator over the per-rank streams: the elastic
        # membership-timeline gate passes; the strict gate still fires
        # on genuinely missing coverage.
        report_path = os.path.join(work, "fleet_report.json")
        cli = subprocess.run(
            [sys.executable, "-m", "tpuic.telemetry.fleet", streams,
             "--membership", sup.ledger_file, "--json", report_path],
            cwd=_REPO, env=env, text=True, capture_output=True,
            timeout=120)
        print(cli.stdout, end="")
        check(cli.returncode == 0,
              f"aggregator passed the elastic --membership gate "
              f"(exit {cli.returncode}; stderr "
              f"{cli.stderr.strip()[-200:]})")
        rep = (json.load(open(report_path))
               if os.path.exists(report_path) else {})
        tl = rep.get("membership", {})
        check(tl.get("ever_ranks") == list(range(RANKS))
              and [t["reason"] for t in tl.get("transitions", [])]
              == ["init", "degrade", "rejoin"],
              f"report carries the membership timeline ({tl.get('ever_ranks')}, "
              f"{[t.get('reason') for t in tl.get('transitions', [])]})")
        gate = subprocess.run(
            [sys.executable, "-m", "tpuic.telemetry.fleet", streams,
             "--require-ranks", str(RANKS + 1)],
            cwd=_REPO, env=env, text=True, capture_output=True,
            timeout=120)
        check(gate.returncode == 1,
              f"strict --require-ranks {RANKS + 1} still fails on the "
              f"missing rank (exit {gate.returncode})")

        took = time.monotonic() - t_start
        if failures:
            print(f"\nFAIL: {len(failures)} assertion(s) in {took:.1f}s")
            for f in failures:
                print(f"  - {f}")
            return 1
        print(f"\nOK: elastic soak green in {took:.1f}s — rank killed "
              f"mid-epoch degraded the fleet (zero survivor restarts), "
              f"the flapping replacement burned only its own budget, "
              f"the second replacement rejoined, and the final metrics "
              f"are bitwise-equal to the undisturbed baseline")
        passed = True
        return 0
    finally:
        if baseline is not None and baseline.poll() is None:
            baseline.kill()
            baseline.wait()
        if args.keep or not passed:
            print(f"workdir kept: {work}")
        else:
            shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
