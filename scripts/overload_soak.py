#!/usr/bin/env python
"""Overload soak: the admission layer's end-to-end CI gate
(docs/serving.md, "Admission control and overload").

Drives the REAL InferenceEngine (real flax model, real AOT bucket
executables — the bench_serve workload) at **3x the committed latency
knee** (``perf/bench_serve.json``, floored by fresh local capacity
probes — unbatched AND full-batching — so a faster CI machine is still
genuinely overloaded; see the probe comments in ``main``) with a 90/10
low/high priority mix, and proves the ISSUE-7 contract in BOTH
directions:

- **admission on** (priority classes + eviction + low-class deadlines):
  the high-priority class keeps its p99 SLO while the low class is shed
  — the flood pays for the overload, not the traffic with a promise;
- **admission off** (same offered drive, classless FIFO): the
  high-tagged requests' p99 demonstrably violates the same SLO — a gate
  that cannot fire is decoration (the PR-6 regress-gate discipline).

Also asserted: the shed ledger is EXACT (every offered request either
resolved or was rejected under exactly one cause —
``accepted + shed == offered``, no silent drops, no double counting),
admission adds zero steady-state compiles and zero device syncs
(tpuic.analysis runtime checkers), and RSS stays bounded across the
overload (a shedding server must not hoard what it sheds).

The SLO threshold is machine-relative (a multiple of a light-load
probe's p99), so the verdict survives CI machines of any speed.

    python scripts/overload_soak.py
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time


_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

MIX_LOW = 0.9  # 90/10 low/high priority mix


def _force_cpu() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    from tpuic.runtime.axon_guard import drop_axon_vars
    drop_axon_vars(os.environ)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, "tests", ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def _rss_mb() -> float:
    """Current RSS in MB via the SHARED helper (tpuic.metrics.meters.
    process_rss_bytes — the same read behind the prom gauge and the
    memory sampler; this script used to carry its own /proc parser)."""
    from tpuic.metrics.meters import process_rss_bytes
    rss = process_rss_bytes()
    return (rss or 0.0) / (1 << 20)


def _committed_knee() -> float:
    """The latency knee the serve bench committed (req/s); 0 when the
    artifact is absent (fresh checkout) — the local probe then rules."""
    try:
        with open(os.path.join(_REPO, "perf", "bench_serve.json")) as f:
            return float(json.load(f)["open_loop_knee_req_per_sec"] or 0.0)
    except (OSError, ValueError, KeyError, TypeError):
        return 0.0


def _drive(engine, items, offsets, quantile):
    """Per-class latency/ledger accounting over the SHARED loadgen
    harness — the same ``run_stream`` pacing and settling the bench and
    the perf-regression gate use, so the CI overload gate cannot
    silently measure differently.

    ``items``: (array, submit_kwargs, cls) triples.  Per-class external
    walls come from ``run_stream``'s ``on_done`` hook: completion
    stamps land the instant each future settles (batcher thread), not
    when the driver's result-wait loop reaches it — waiting on future
    i must not inflate request j's measured latency.  Rejections
    (typed, or the bare ``queue.Full`` of the classless FIFO arm) are
    that request's outcome, counted not crashed.  Returns per-class
    {offered, ok, rejected, p99_ms} plus the settled engine snapshot."""
    from tpuic.serve.loadgen import run_stream

    classes = [cls for _, _, cls in items]
    lock = threading.Lock()
    done = []  # (cls, ok, latency_s)

    def on_done(i, ok, latency_s):
        with lock:
            done.append((classes[i], ok, latency_s))

    _, _, snap = run_stream(engine, [(arr, kw) for arr, kw, _ in items],
                            offsets_s=offsets, on_done=on_done)
    out = {}
    for cls in ("high", "low"):
        lats = [s for c, ok, s in done if c == cls and ok and s is not None]
        offered = sum(1 for c in classes if c == cls)
        out[cls] = {
            "offered": offered,
            "ok": len(lats),
            "rejected": offered - len(lats),
            "p99_ms": (round(1000.0 * quantile(lats, 99), 3)
                       if lats else None),
        }
    return out, snap


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet18-cifar")
    p.add_argument("--size", type=int, default=24)
    p.add_argument("--buckets", default="1,4,8",
                   help="bucket ladder. The max bucket bounds the "
                        "head-of-line block a high-priority arrival "
                        "can suffer (one in-flight batch + its own) — "
                        "exactly the admission-tier tuning lever "
                        "docs/serving.md derives from the knee")
    p.add_argument("--requests", type=int, default=1200)
    p.add_argument("--queue-size", type=int, default=512,
                   help="burst-sized queue: deep enough that blind "
                        "FIFO queueing (the admission-off arm) costs "
                        "seconds under sustained overload — the "
                        "failure mode admission exists to prevent")
    p.add_argument("--overload-factor", type=float, default=3.0)
    p.add_argument("--slo-factor", type=float, default=8.0,
                   help="high-priority p99 SLO = this x the light-load "
                        "probe's p99 (machine-relative, CI-speed-proof; "
                        "the headroom covers one full max-bucket "
                        "in-flight batch of flood ahead of a high "
                        "arrival)")
    p.add_argument("--seed", type=int, default=0)
    args = p.parse_args(argv)

    _force_cpu()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpuic.analysis.runtime import (assert_compiles_flat,
                                        count_device_gets)
    from tpuic.metrics.meters import quantile
    from tpuic.models import create_model
    from tpuic.serve import InferenceEngine, make_forward

    buckets = tuple(int(b) for b in args.buckets.split(","))
    model = create_model(args.model, 10, dtype="float32")
    variables = model.init(jax.random.key(0),
                           jnp.zeros((1, args.size, args.size, 3),
                                     jnp.float32), train=False)
    engine = InferenceEngine(
        forward_fn=make_forward(model, normalize=True), variables=variables,
        image_size=args.size, input_dtype=np.uint8, buckets=buckets,
        max_wait_ms=5.0, queue_size=args.queue_size)
    engine.warmup()
    warmup_compiles = engine.stats.compiles

    rng = np.random.default_rng(args.seed)
    reqs = [rng.integers(0, 256, (1, args.size, args.size, 3), np.uint8)
            for _ in range(args.requests)]

    # Local capacity probes — the committed knee is floored by TWO fresh
    # local anchors so the drive saturates ON THIS MACHINE regardless of
    # how fast it is relative to the machine that committed the knee:
    #
    # 1. the shared stall-stripped UNBATCHED probe
    #    (loadgen.probe_unbatched_rps, same one bench_serve's sweep
    #    uses) — the light-load SLO anchor below also needs it;
    # 2. a BATCHED capacity probe: a burst offered as fast as possible
    #    through the shared run_stream harness, achieved rate = the
    #    engine's true service capacity with full batching. This is the
    #    fix for the machine-speed sensitivity PR 8 flagged: batching
    #    multiplies throughput (up to the max bucket, ~8x here), so on a
    #    fast container 3x the UNBATCHED rate can sit BELOW batched
    #    capacity — the "overload" arms then never saturate (0% shed,
    #    off-arm meets its SLO) and the soak proves nothing in either
    #    direction. Anchoring to max(knee, unbatched, batched) keeps the
    #    off-arm provably saturated at any machine speed.
    from tpuic.serve.loadgen import probe_batched_rps, probe_unbatched_rps
    local_rps, _, _, _ = probe_unbatched_rps(engine, reqs)
    batched_rps = probe_batched_rps(engine, reqs,
                                    probe_n=min(400, args.requests))
    knee = _committed_knee()
    drive_rps = args.overload_factor * max(knee, local_rps, batched_rps)

    # Light-load probe: the machine-relative SLO anchor (all high class,
    # far below the knee — what latency SHOULD look like).
    n_light = min(120, args.requests)
    light_offsets = np.cumsum(rng.exponential(
        1.0 / max(1.0, 0.4 * local_rps), size=n_light))
    light_items = [(r, {"priority": "high"}, "high")
                   for r in reqs[:n_light]]
    light, _ = _drive(engine, light_items, light_offsets, quantile)
    slo_ms = max(args.slo_factor * (light["high"]["p99_ms"] or 0.0), 60.0)

    # The 90/10 mixed overload drive, offered identically to both arms.
    classes = rng.permutation(
        ["low"] * int(round(args.requests * MIX_LOW))
        + ["high"] * (args.requests
                      - int(round(args.requests * MIX_LOW))))
    offsets = np.cumsum(rng.exponential(1.0 / drive_rps,
                                        size=args.requests))

    # Arm 1 — admission ON: priority classes, non-blocking typed
    # rejects, eviction, and a deadline (= the SLO budget) on the low
    # class so stale flood sheds at pop time instead of wasting slots.
    on_items = [
        (r, ({"priority": "low", "deadline_ms": slo_ms, "timeout": 0}
             if c == "low" else {"priority": "high", "timeout": 0}), c)
        for r, c in zip(reqs, classes)]
    rss_before = _rss_mb()
    with assert_compiles_flat(0, what="overload soak (admission on)"):
        with count_device_gets() as gets_on:
            on, snap_on = _drive(engine, on_items, offsets, quantile)

    # Arm 2 — admission OFF: same offered traffic, classless FIFO,
    # blind queue-full drops only.
    off_items = [(r, {"timeout": 0}, c) for r, c in zip(reqs, classes)]
    with count_device_gets() as gets_off:
        off, snap_off = _drive(engine, off_items, offsets, quantile)
    rss_after = _rss_mb()

    verdict = {
        "committed_knee_rps": knee, "local_unbatched_rps": round(
            local_rps, 2),
        "local_batched_rps": round(batched_rps, 2),
        "drive_rps": round(drive_rps, 2),
        "slo_ms": round(slo_ms, 3),
        "light_p99_ms": light["high"]["p99_ms"],
        "admission_on": {**on, "rejected_by": snap_on["rejected_by"],
                         "ledger": [snap_on["requests"],
                                    snap_on["rejected"]],
                         "span_ms": snap_on.get("span_ms"),
                         "batch_hist": snap_on.get("batch_hist")},
        "admission_off": {**off,
                          "rejected_by": snap_off["rejected_by"],
                          "span_ms": snap_off.get("span_ms")},
        "device_gets": [gets_on.count, gets_off.count],
        "steady_compiles": [snap_on["compiles"], snap_off["compiles"]],
        "warmup_compiles": warmup_compiles,
        "rss_mb": [round(rss_before, 1), round(rss_after, 1)],
    }
    print(json.dumps(verdict, indent=2))
    engine.close()

    failures = []
    # 1. The contract: high-priority p99 holds its SLO under 3x overload
    #    WITH admission...
    p99_on = on["high"]["p99_ms"]
    if p99_on is None or p99_on > slo_ms:
        failures.append(
            f"high-priority p99 {p99_on} ms blew the {slo_ms:.1f} ms SLO "
            "WITH admission on — the layer failed to protect its class")
    # ... and high-priority traffic is actually served, not shed.
    if on["high"]["ok"] < 0.98 * on["high"]["offered"]:
        failures.append(
            f"admission shed high-priority traffic: "
            f"{on['high']['ok']}/{on['high']['offered']} served")
    # 2. Low-priority traffic is genuinely shed (this IS overload).
    low_shed = on["low"]["rejected"] / max(1, on["low"]["offered"])
    if low_shed < 0.05:
        failures.append(
            f"low-priority shed rate {low_shed:.3f} — the drive did not "
            "overload the engine; the soak proved nothing")
    # 3. Bidirectional: the SAME drive without admission violates.
    p99_off = off["high"]["p99_ms"]
    if p99_off is not None and p99_off <= slo_ms:
        failures.append(
            f"high-tagged p99 {p99_off} ms met the {slo_ms:.1f} ms SLO "
            "WITHOUT admission — the gate cannot distinguish on from off")
    # 4. The shed ledger is exact: accepted + shed == offered.
    if snap_on["requests"] + snap_on["rejected"] != args.requests:
        failures.append(
            f"ledger violation: {snap_on['requests']} resolved + "
            f"{snap_on['rejected']} rejected != {args.requests} offered")
    per_cls = {}
    for by_prio in snap_on["rejected_by"].values():
        for prio, n in by_prio.items():
            per_cls[prio] = per_cls.get(prio, 0) + n
    if per_cls != {c: r["rejected"] for c, r in on.items()
                   if r["rejected"]}:
        failures.append(
            f"per-class reject split {per_cls} disagrees with the "
            f"futures' own outcomes "
            f"{ {c: r['rejected'] for c, r in on.items()} }")
    # 5. Admission adds zero steady-state compiles and zero device syncs
    #    (each arm's snapshot counts only ITS run: stats reset per arm;
    #    the XLA layer is separately pinned by assert_compiles_flat).
    if snap_on["compiles"] != 0 or snap_off["compiles"] != 0:
        failures.append(
            f"steady-state compiles during the arms: "
            f"{[snap_on['compiles'], snap_off['compiles']]} != [0, 0]")
    if gets_on.count != gets_off.count:
        failures.append(
            f"admission changed the device_get count: "
            f"{gets_on.count} vs {gets_off.count}")
    # 6. RSS bounded: a shedding server must not hoard what it sheds.
    if rss_after - rss_before > 400.0:
        failures.append(
            f"RSS grew {rss_after - rss_before:.0f} MB across the "
            "overload arms")

    if failures:
        for f in failures:
            print(f"[overload_soak] FAIL: {f}", file=sys.stderr)
        return 1
    print(f"[overload_soak] OK: at {drive_rps:.0f} req/s "
          f"(3x max(knee {knee:g}, unbatched {local_rps:.0f}, "
          f"batched {batched_rps:.0f})), high p99 "
          f"{p99_on} ms <= SLO {slo_ms:.1f} ms with {100 * low_shed:.0f}% "
          f"of low shed; without admission p99 {p99_off} ms (violation "
          "proven); ledger exact; 0 new compiles; RSS bounded",
          file=sys.stderr)
    return 0


if __name__ == "__main__":
    sys.exit(main())
