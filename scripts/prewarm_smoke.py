#!/usr/bin/env python
"""Prewarm smoke (ISSUE 18 acceptance; runs in tier-1 CI).

End-to-end proof of the compiled-program registry's restart path
(tpuic/compiled/, docs/performance.md "Compiled-program registry"): a
REAL supervised training run (`tpuic.runtime.supervisor.Supervisor`
driving the real `train.py` CLI as a child, CPU, synthetic data) is
SIGTERMed mid-epoch-1 (clean preemption flush, exit 43) and restarted.
The first life exported ``TPUIC_COMPILE_MANIFEST``, so its
``Trainer._build_steps`` left a prewarm manifest behind; the restarted
life finds it pre-existing and prewarms every listed program BEFORE its
first step, against the shared persistent XLA cache.

The verdict asserts, from the metrics JSONL both lives appended to:

- >= 1 automatic restart; the sigterm attempt exited with code 43,
- the manifest exists on disk and passes its CRC (tpuic.compiled
  refuses torn manifests — a load here is the integrity check),
- the restarted life emitted ``compile_cache action=prewarm_done``
  BETWEEN its 'restart' event and its first 'step' event,
- ZERO 'compile' events after prewarm_done, the first post-restart
  step included — every backend compile the resumed run will ever need
  (both the restored-state and the steady-state call signatures of each
  program) was paid up front by the prewarm,
- bitwise-equal gang resume: final checkpointed optimizer step and
  per-epoch eval accuracies identical to an UNDISTURBED parallel
  baseline (same config, no chaos, no manifest).

Exit 0 on success.   python scripts/prewarm_smoke.py [--keep] [-v]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from tpuic.runtime.supervisor import EXIT_PREEMPTED, Supervisor  # noqa: E402

# 24 train images / global batch 4 = 6 host-tracked steps per epoch;
# fault keys are global step numbers (0-based), so key 8 is mid-epoch-1
# — the restart resumes INSIDE an epoch, the harder geometry.
PER_CLASS = 12
BATCH = 4
EPOCHS = 2
CHAOS = ["sigterm@8",  # clean flush, exit 43, restart prewarms
         ""]           # fault-free final attempt completes


def _train_cmd(data: str, ckpt: str, cache: str, jsonl: str) -> list:
    return [sys.executable, os.path.join(_REPO, "train.py"),
            "--datadir", data, "--model", "resnet18-cifar",
            "--resize", "24", "--batchsize", str(BATCH),
            "--epochs", str(EPOCHS), "--optimizer", "sgd", "--lr", "0.01",
            "--no-class-weights", "--log-every-steps", "1",
            "--save-period", "1", "--workers", "2",
            "--ckpt-dir", ckpt, "--cache-dir", cache,
            "--metrics-jsonl", jsonl]


def _events(path: str) -> list:
    from tpuic.telemetry.events import read_jsonl
    return read_jsonl(path, on_torn=lambda ln: print(
        f"  [smoke] skipping torn jsonl line in {path}: {ln[:80]!r}"))


def _evals(recs: list) -> dict:
    out = {}
    for r in recs:
        if r["event"] == "eval":
            out[int(r["epoch"])] = r["accuracy"]
    return out


def _final_meta_step(ckpt: str):
    try:
        man = json.load(open(os.path.join(ckpt, "resnet18-cifar",
                                          "latest.manifest.json")))
        return int(man["step"])
    except (OSError, ValueError, KeyError, TypeError):
        return None


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    # 60s (vs chaos_soak's 20): the longest legitimately silent span is
    # one cold backend-compile phase, which can exceed 20s on a loaded
    # CI box — hang detection is chaos_soak's contract, not this one's.
    p.add_argument("--watchdog-s", type=float, default=60.0)
    p.add_argument("--keep", action="store_true",
                   help="keep the temp workdir for inspection")
    p.add_argument("-v", "--verbose", action="store_true",
                   help="stream child stdout/stderr instead of hiding it")
    args = p.parse_args()

    t_start = time.monotonic()
    work = tempfile.mkdtemp(prefix="tpuic_prewarm_")
    failures: list = []

    def check(ok: bool, msg: str) -> None:
        print(("  ok  " if ok else "  FAIL") + f" {msg}")
        if not ok:
            failures.append(msg)

    try:
        from tpuic.data.synthetic import make_synthetic_imagefolder
        data = os.path.join(work, "data")
        make_synthetic_imagefolder(data, classes=("a", "b"),
                                   per_class=PER_CLASS, size=24)
        # Shared persistent XLA cache across both lives AND the baseline
        # (identical env => identical trajectories; the restart's prewarm
        # compiles become disk reads). XLA_FLAGS overridden, not popped —
        # see chaos_soak.py for why.
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   TF_CPP_MIN_LOG_LEVEL="3", XLA_FLAGS="",
                   JAX_COMPILATION_CACHE_DIR=os.path.join(work,
                                                          "jax_cache"))

        base_jsonl = os.path.join(work, "baseline.jsonl")
        base_ckpt = os.path.join(work, "ckpt_base")
        sink = None if args.verbose else subprocess.DEVNULL
        print("[smoke] baseline (undisturbed, no manifest) started "
              "in parallel")
        baseline = subprocess.Popen(
            _train_cmd(data, base_ckpt, os.path.join(work, "cache_base"),
                       base_jsonl),
            cwd=_REPO, env=env, stdout=sink, stderr=sink)

        manifest = os.path.join(work, "programs.manifest.json")
        print(f"[smoke] supervised run: {len(CHAOS)} attempts "
              f"({', '.join(s or 'fault-free' for s in CHAOS)}), "
              f"prewarm manifest {manifest}")
        sup_jsonl = os.path.join(work, "supervised.jsonl")
        sup_ckpt = os.path.join(work, "ckpt_sup")
        sup = Supervisor(
            _train_cmd(data, sup_ckpt, os.path.join(work, "cache_sup"),
                       sup_jsonl),
            os.path.join(work, "supervise"), watchdog_s=args.watchdog_s,
            startup_grace_s=600.0, quit_wait_s=2.0, grace_s=5.0,
            poll_s=0.25, max_restarts=4, backoff_s=0.25, backoff_max_s=2.0,
            crash_loop_k=3, heartbeat_interval_s=0.2, chaos=CHAOS,
            env=dict(env, PYTHONPATH=_REPO,
                     TPUIC_COMPILE_MANIFEST=manifest))
        rc = sup.run()
        base_rc = baseline.wait(timeout=900)

        print(f"[smoke] supervised run finished (exit {rc}, "
              f"{len(sup.attempts)} attempts, {sup.restarts} restarts); "
              f"baseline exit {base_rc}")
        check(rc == 0, "supervised run completed cleanly (exit 0)")
        check(base_rc == 0, "baseline completed cleanly (exit 0)")
        check(sup.restarts >= 1,
              f"{sup.restarts} automatic restart(s) observed (>= 1)")
        codes = [a.returncode for a in sup.attempts]
        check(EXIT_PREEMPTED in codes,
              f"sigterm attempt exited {EXIT_PREEMPTED} per the contract "
              f"(attempt codes: {codes})")

        # -- manifest integrity (the reader IS the CRC check) -----------
        try:
            from tpuic.compiled import ProgramKey, load_manifest
            entries = load_manifest(manifest)
            models = sorted(ProgramKey.from_dict(e["key"]).model
                            for e in entries)
            check(len(entries) >= 2 and
                  any(m.endswith(":step") for m in models) and
                  any(m.endswith(":eval") for m in models),
                  f"manifest lists the train+eval step programs "
                  f"({models})")
        except Exception as e:
            check(False, f"prewarm manifest unreadable: {e}")

        # -- the steady-state contract, from the event stream -----------
        recs = _events(sup_jsonl)
        kinds = [r.get("event") for r in recs]
        check("restart" in kinds, "restarted life announced itself "
              "with a 'restart' event")
        last_restart = (len(kinds) - 1 - kinds[::-1].index("restart")
                        if "restart" in kinds else len(kinds))
        after = recs[last_restart:]
        after_kinds = [r.get("event") for r in after]
        first_step = (after_kinds.index("step")
                      if "step" in after_kinds else len(after))
        prewarms = [r for r in after[:first_step]
                    if r.get("event") == "compile_cache"
                    and r.get("action") == "prewarm_done"]
        pw_summary = [{k: r.get(k) for k in ("programs", "manifest_listed",
                                             "duration_s")}
                      for r in prewarms]
        check(len(prewarms) == 1,
              f"restarted life prewarmed before its first step "
              f"({pw_summary})")
        check(bool(prewarms) and prewarms[0].get("manifest_listed")
              == prewarms[0].get("programs") == 2,
              "prewarm covered both step programs, all manifest-listed")
        # Stronger than "after the first step": the prewarm executes
        # BOTH call signatures of each program (restored-state and
        # steady-state — see Trainer.prewarm), so even the first
        # post-restart step must dispatch without a single compile.
        pw_idx = (after.index(prewarms[0]) + 1 if prewarms else len(after))
        late_compiles = [r for r in after[pw_idx:]
                         if r.get("event") == "compile"]
        check(first_step < len(after) and not late_compiles,
              f"ZERO compiles after prewarm_done, first post-restart "
              f"step included ({len(late_compiles)} observed)")

        # -- bitwise-equal gang resume vs the undisturbed baseline ------
        b_recs = _events(base_jsonl)
        b_meta = _final_meta_step(base_ckpt)
        s_meta = _final_meta_step(sup_ckpt)
        check(b_meta is not None and s_meta == b_meta,
              f"final checkpointed optimizer step matches baseline "
              f"({s_meta} == {b_meta})")
        b_eval, s_eval = _evals(b_recs), _evals(recs)
        check(set(b_eval) == set(s_eval) == set(range(EPOCHS)),
              f"both runs evaluated every epoch (baseline {sorted(b_eval)}, "
              f"supervised {sorted(s_eval)})")
        check(b_eval == s_eval,
              f"per-epoch eval accuracy identical to baseline "
              f"({s_eval} == {b_eval})")

        took = time.monotonic() - t_start
        if failures:
            print(f"\nFAIL: {len(failures)} assertion(s) in {took:.1f}s")
            for f in failures:
                print(f"  - {f}")
            return 1
        print(f"\nOK: prewarm smoke green in {took:.1f}s — restart "
              f"prewarmed {prewarms[0].get('programs')} programs in "
              f"{prewarms[0].get('duration_s')}s, fit compile-flat, "
              f"resume bitwise-equal to baseline")
        return 0
    finally:
        if args.keep:
            print(f"workdir kept: {work}")
        else:
            shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
