#!/usr/bin/env python
"""Chip smoke for the packed flash kernels' dynamic-valid SMEM path.

The ring composition is the only caller of ``valid=`` (a device scalar in
SMEM) + ``masked_sentinel=-inf`` — and ring needs a seq-axis >= 2, which
the single tunneled chip cannot provide. This drives that exact kernel
configuration directly on one chip (no mesh): packed fwd/bwd with a
rotating device-scalar validity count, checked against the folded kernels
and a masked dense reference. Writes perf/packed_valid_smoke.json.

The 4D grid + SMEM scalar + leading-dim-2 lse blocks are the Mosaic-only
risk interpret mode cannot vouch for (PERF_ANALYSIS.md §10f, r3 lesson).
"""

from __future__ import annotations

import importlib
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def main():
    from tpuic.runtime.axon_guard import exit_if_unreachable
    exit_if_unreachable()

    import jax
    import jax.numpy as jnp
    import numpy as np

    fa = importlib.import_module("tpuic.kernels.flash_attention")
    b, n, h, d = 2, 64, 4, 64
    assert fa._use_packed(h, d)
    key = jax.random.key(0)
    q, k, v = (jax.random.normal(jax.random.fold_in(key, i), (b, n, h, d),
                                 jnp.float32) for i in range(3))
    bq, bk = fa._resolve_blocks(n, None, None)
    interp = jax.devices()[0].platform != "tpu"
    rows = []
    for vl in (n, 40, 0):  # full, partial, FULLY-masked (sentinel path)
        valid = jnp.asarray([vl], jnp.int32)
        o_p, lse_p = fa._flash_fwd_packed(
            q, k, v, bq, bk, interp, with_lse=True, valid=valid,
            masked_sentinel=fa._NEG_INF)
        o_f, lse_f = fa._flash_fwd(
            q, k, v, bq, bk, interp, with_lse=True, valid=valid,
            masked_sentinel=fa._NEG_INF)
        g = jnp.ones_like(q)
        g_p = fa._flash_bwd_packed(q, k, v, o_p, lse_p, g, bq, bk, interp,
                                   valid=valid)
        g_f = fa._flash_bwd(q, k, v, o_f, lse_f, g, bq, bk, interp,
                            valid=valid)
        diffs = {
            "o": float(jnp.abs(o_p - o_f).max()),
            "lse": float(jnp.abs(lse_p - lse_f).max()),
            **{name: float(jnp.abs(a - c).max())
               for name, a, c in zip(("dq", "dk", "dv"), g_p, g_f)},
        }
        if vl > 0:  # dense cross-check on the valid slice
            s = jnp.einsum("bqhd,bkhd->bhqk", q, k[:, :vl]) / np.sqrt(d)
            ref = jnp.einsum("bhqk,bkhd->bqhd",
                             jax.nn.softmax(s, -1), v[:, :vl])
            diffs["o_vs_dense"] = float(jnp.abs(o_p - ref).max())
        ok = all(x < 1e-4 for x in diffs.values())
        rows.append({"valid": vl, "ok": ok, "max_diffs": diffs})
        print(json.dumps(rows[-1]), flush=True)

    out = {"device": str(jax.devices()[0].device_kind),
           "platform": jax.devices()[0].platform,
           "blocks": [bq, bk], "shape": [b, n, h, d],
           "ok": all(r["ok"] for r in rows), "rows": rows}
    path = os.path.join(_REPO, "perf", "packed_valid_smoke.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(f"wrote {path}; ok={out['ok']}")
    return 0 if out["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
