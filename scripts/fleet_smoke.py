#!/usr/bin/env python
"""Fleet observability smoke (ISSUE 9 acceptance; runs in tier-1 CI).

End-to-end proof of the per-rank fleet view (docs/observability.md,
"Fleet view"): TWO real ``train.py`` processes run the same pinned CPU
workload as a rank-identified fleet, rank 1 seeded slow via the
existing ``slow_step#`` fault point (runtime/faults.py), and the
offline aggregator (``python -m tpuic.telemetry.fleet``) must attribute
the straggler to the correct rank:

- every event in each rank's JSONL stream carries ``rank``/``ranks``
  fields, and the streams land side by side as ``events.jsonl`` /
  ``events.rank1.jsonl`` (the per-rank naming convention);
- the aggregator's skew ledger sees the seeded slowdown: per-step
  cross-rank spread at least half the injected stall, rank 1 slowest in
  (nearly) every step, and the straggler verdict — asserted through the
  real CLI (``--expect-straggler 1``), the same invocation an operator
  would run against a pod's shared metrics directory.

Rank identity rides the ``TPUIC_FLEET_RANK(S)`` launcher override: this
container's CPU jax implements no multiprocess collectives (the
tests/test_multiprocess caveat), so the two ranks train independently —
which is exactly what the skew math wants anyway (host walls free of
cross-rank equalization; see the fleet module docstring's measurement
caveat).  On a real pod the tag comes from runtime/distributed.py and
the same aggregator runs unchanged.

Exit 0 on success.   python scripts/fleet_smoke.py [--keep] [-v]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

RANKS = 2
SLOW_RANK = 1
STEPS = 8
WARMUP = 2  # compile/cache warmup steps excluded from the skew math


def _train_cmd(data: str, work: str, rank: int) -> list:
    return [sys.executable, os.path.join(_REPO, "train.py"),
            "--datadir", data, "--model", "resnet18-cifar",
            "--resize", "24", "--batchsize", "2",
            "--epochs", "1", "--optimizer", "sgd", "--lr", "0.01",
            "--no-class-weights", "--no-pack",
            # Free-running hosts: per-step drains (log_every 1) would
            # equalize host step walls across a synchronized fleet; the
            # production cadence keeps the skew visible per step.
            "--log-every-steps", "999",
            "--workers", "2", "--save-period", "99",
            "--steps", str(STEPS),
            "--ckpt-dir", os.path.join(work, f"cp{rank}"),
            "--metrics-jsonl", os.path.join(work, "events.jsonl")]


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--slow-s", type=float, default=0.5,
                   help="seeded per-step stall on the straggler rank")
    p.add_argument("--keep", action="store_true")
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args()

    t0 = time.monotonic()
    work = tempfile.mkdtemp(prefix="tpuic_fleet_")
    failures: list = []

    def check(ok: bool, msg: str) -> None:
        print(("  ok  " if ok else "  FAIL") + f" {msg}")
        if not ok:
            failures.append(msg)

    try:
        from tpuic.data.synthetic import make_synthetic_imagefolder
        data = os.path.join(work, "data")
        # 2 classes x 16 / batch 2 = 16 steps/epoch; --steps 8 stops
        # mid-epoch (train-only — no val, no checkpoint churn).
        make_synthetic_imagefolder(data, classes=("a", "b"), per_class=16,
                                   size=24)
        base_env = dict(os.environ, JAX_PLATFORMS="cpu",
                        TF_CPP_MIN_LOG_LEVEL="3", XLA_FLAGS="",
                        TPUIC_FLEET_RANKS=str(RANKS),
                        # Both ranks compile the same program: share the
                        # persistent cache so the second compile is a hit.
                        JAX_COMPILATION_CACHE_DIR=os.path.join(
                            work, "jax_cache"))
        sink = None if args.verbose else subprocess.DEVNULL
        print(f"[fleet_smoke] launching {RANKS} ranks "
              f"(rank {SLOW_RANK} seeded slow_step#{args.slow_s:g})")
        procs = []
        for rank in range(RANKS):
            env = dict(base_env, TPUIC_FLEET_RANK=str(rank))
            if rank == SLOW_RANK:
                env["TPUIC_FAULTS"] = f"slow_step#{args.slow_s}"
            procs.append(subprocess.Popen(
                _train_cmd(data, work, rank), cwd=_REPO, env=env,
                stdout=sink, stderr=sink))
        for rank, proc in enumerate(procs):
            rc = proc.wait(timeout=900)
            check(rc == 0, f"rank {rank} train.py exited cleanly (got {rc})")
        if failures:
            return 1

        # Per-rank streams, rank-tagged events.
        from tpuic.telemetry.events import read_jsonl
        from tpuic.telemetry.fleet import rank_stream_path
        streams = {}
        for rank in range(RANKS):
            path = rank_stream_path(os.path.join(work, "events.jsonl"), rank)
            recs = read_jsonl(path)
            streams[rank] = recs
            steps = [r for r in recs if r.get("event") == "step"]
            check(len(steps) == STEPS,
                  f"rank {rank} stream has {len(steps)} step events "
                  f"(want {STEPS}) in {os.path.basename(path)}")
            check(all(r.get("rank") == rank and r.get("ranks") == RANKS
                      for r in recs),
                  f"every rank-{rank} event carries rank={rank}/"
                  f"ranks={RANKS}")
            mems = [r for r in recs if r.get("event") == "memory"]
            check(len(mems) >= STEPS and all(
                      m.get("bytes_in_use", 0) > 0 for m in mems),
                  f"rank {rank} sampled device memory at step boundaries "
                  f"({len(mems)} samples)")

        # The aggregator verdict, through the REAL CLI — the operator
        # invocation, not a private API.
        report_path = os.path.join(work, "fleet_report.json")
        cli = subprocess.run(
            [sys.executable, "-m", "tpuic.telemetry.fleet", work,
             "--warmup", str(WARMUP), "--json", report_path,
             "--expect-straggler", str(SLOW_RANK)],
            cwd=_REPO, env=base_env, text=True, capture_output=True,
            timeout=120)
        print(cli.stdout, end="")
        check(cli.returncode == 0,
              f"aggregator CLI attributed the straggler to rank "
              f"{SLOW_RANK} (exit {cli.returncode}; stderr: "
              f"{cli.stderr.strip()[-200:]})")
        rep = json.load(open(report_path)) if os.path.exists(report_path) \
            else {}
        common = rep.get("steps_common", 0)
        check(common == STEPS - WARMUP,
              f"{common} common steps entered the skew math "
              f"(want {STEPS - WARMUP})")
        spread = (rep.get("spread_ms") or {}).get("p50", 0.0)
        check(spread >= 1000.0 * args.slow_s * 0.5,
              f"p50 cross-rank spread {spread:g} ms reflects the seeded "
              f"{1000 * args.slow_s:g} ms stall")
        strag = rep.get("straggler") or {}
        check(strag.get("slowest_step_frac", 0.0) >= 0.8,
              f"straggler rank was slowest in "
              f"{100 * strag.get('slowest_step_frac', 0):g}% of steps")
        wait_ms = (rep.get("per_rank", {}).get(str(SLOW_RANK), {})
                   .get("est_collective_wait_ms", 0.0))
        check(wait_ms >= (STEPS - WARMUP) * 1000 * args.slow_s * 0.5,
              f"rank {SLOW_RANK} est collective wait {wait_ms:g} ms "
              f"covers the injected stall")

        took = time.monotonic() - t0
        if failures:
            print(f"\nFAIL: {len(failures)} assertion(s) in {took:.1f}s")
            for f in failures:
                print(f"  - {f}")
            return 1
        print(f"\nOK: fleet smoke green in {took:.1f}s — rank "
              f"{SLOW_RANK} attributed as straggler "
              f"({strag.get('excess_share', 0):.0%} of fleet excess, "
              f"spread p50 {spread:g} ms)")
        return 0
    finally:
        if args.keep:
            print(f"workdir kept: {work}")
        else:
            shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
