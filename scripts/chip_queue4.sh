#!/bin/bash
# Round-4 queue #4: work stranded by the third tunnel flap (~11:45 UTC).
#   1. True blocks-remat N=4097 rows: the code-review found long_seq_bench
#      built its model via create_model(), so the earlier
#      long_seq_4k_blocks.json rows measured NO model-level remat (XLA
#      auto-remat carried flash; artifact preserved as
#      perf/long_seq_4k_autoremat.json). Re-measure with the fixed bench.
#   2. Fresh live-TPU bench line (refreshes perf/bench_last_tpu.json).
# Run via: nohup bash scripts/chip_poller.sh scripts/chip_queue4.sh &
set -x -o pipefail
failures=0
cd /root/repo

# Don't contend with a driver-run bench/dryrun on the single chip (the
# pattern lives in chip_wait.sh; these measurements are the round's
# record and must not be skewed by queue traffic).
. scripts/chip_wait.sh
chip_wait "$MEASURE_PAT" "chip_queue4"

python scripts/long_seq_bench.py --sizes 1024 --batch 16 --remat \
  --remat-policy blocks \
  --out perf/long_seq_4k_blocks.json 2>&1 | tail -4 || failures=$((failures+1))

python bench.py 2>&1 | tail -2 || failures=$((failures+1))

# 3. ViT-L/16 MFU: wider matmuls (1024 hidden / 4096 mlp) should sit
#    closer to the MXU roof than ViT-B's 0.537 — the scaling datapoint
#    for the 0.70-north-star frontier (PERF_ANALYSIS.md §10f).
python scripts/perf_sweep.py --batches 16,32,64 --model vit-l16 \
  --out perf/vitl_sweep.json 2>&1 | tail -4 || failures=$((failures+1))

# 4. Lane-packed flash layout: first Mosaic execution (interpret-mode is
#    bitwise vs the folded kernel; the 4D grid + leading-dim-2 lse blocks
#    are the chip risk). Smoke first, then the A/B at the ViT-B b64 train
#    step and the long-N row where the 2x layout saving matters most.
#    TPUIC_FLASH_PACKED=0 is the escape hatch if Mosaic rejects it.
python scripts/pallas_smoke.py 2>&1 | tail -3 || failures=$((failures+1))
python scripts/packed_valid_smoke.py 2>&1 | tail -2 || failures=$((failures+1))
TPUIC_FLASH_PACKED=0 python scripts/perf_sweep.py --batches 64 \
  --model vit-b16 --attention flash \
  --out perf/vit_flash_folded.json 2>&1 | tail -3 || failures=$((failures+1))
python scripts/perf_sweep.py --batches 64 --model vit-b16 \
  --attention flash \
  --out perf/vit_flash_packed.json 2>&1 | tail -3 || failures=$((failures+1))
python scripts/long_seq_bench.py --sizes 768 --batch 16 --remat \
  --remat-policy blocks \
  --out perf/long_seq_2305_packed.json 2>&1 | tail -4 || failures=$((failures+1))

echo "chip_queue4: $failures item(s) failed"
exit $failures
