#!/bin/bash
# Round-5 recovery poller (VERDICT r4 item 5b): loop FOREVER; on every
# tunnel recovery, refresh the live bench line FIRST (bench.py persists
# perf/bench_last_tpu.json on every TPU success, so the scoreboard always
# has the freshest possible live number), then run each queue script that
# has not completed yet (stamp files in perf/). Unlike chip_poller.sh this
# never exits: later flap/recovery cycles keep re-benching.
# Usage: nohup bash scripts/chip_poller5.sh > perf/chip_poller5.log 2>&1 &
set -o pipefail
cd /root/repo
. scripts/chip_wait.sh
log() { echo "$(date -u +%FT%TZ) $*"; }
while true; do
  if python -c "
from tpuic.runtime.axon_guard import tpu_reachable
import sys; sys.exit(0 if tpu_reachable(150) else 1)"; then
    # 1-core host, 1 chip: never contend with pytest, an already-running
    # queue, or any driver-run measurement (two concurrent benches would
    # skew both). Pattern shared with the queue scripts (chip_wait.sh).
    chip_wait "chip_queue|$MEASURE_PAT" "tunnel up"
    log "tunnel up; refreshing bench line"
    timeout 900 python bench.py 2>&1 | tail -1
    for q in scripts/chip_queue4.sh scripts/chip_queue5.sh scripts/chip_queue6.sh; do
      stamp="perf/.$(basename "$q" .sh)_done"
      if [ ! -e "$stamp" ]; then
        log "running $q"
        bash "$q"
        rc=$?
        log "$q exited rc=$rc"
        # Stamp regardless of rc: each item inside the queue logs its own
        # failure; re-running a whole 30-min queue on every recovery would
        # burn the very windows this poller exists to exploit. A failed
        # item is requeued explicitly (new queue script) after triage.
        echo "rc=$rc $(date -u +%FT%TZ)" > "$stamp"
      fi
    done
  else
    log "tunnel down; sleeping"
  fi
  sleep 420
done
