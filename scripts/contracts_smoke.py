#!/usr/bin/env python
"""Runtime-contract smoke gate (ISSUE 4 acceptance; runs in tier-1 CI).

The shared ``tpuic.analysis.runtime`` checkers (docs/analysis.md)
applied to the REAL hot paths, in-process:

- **train**: ``Trainer.train_epoch`` — epoch 0 warms up (compiles the
  step), epoch 1 runs under ``assert_compiles_flat(0)`` +
  ``bounded_device_gets`` with the deferred-drain budget (one batched
  get per log interval plus the per-epoch step-counter read).  The
  warmup epoch's device_get count is measured bare first, and the
  checked epoch must MATCH it exactly: the checkers themselves add
  zero host syncs (the PR-2/3 on-vs-off discipline).
- **serve**: ``InferenceEngine`` AOT warmup over a real model, then a
  mixed-size request stream covering every padding bucket under
  ``assert_compiles_flat(0)``, cross-checked against the engine's own
  executable-cache counters.

Exit 0 on success; prints one summary line per contract.

    python scripts/contracts_smoke.py [--keep]
"""

from __future__ import annotations

import argparse
import os
import shutil
import sys
import tempfile

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def train_contract(work: str) -> None:
    from tpuic.analysis import runtime as contracts
    from tpuic.config import (Config, DataConfig, MeshConfig, ModelConfig,
                              OptimConfig, RunConfig)
    from tpuic.data.synthetic import make_synthetic_imagefolder
    from tpuic.train.loop import Trainer

    data = os.path.join(work, "data")
    # 3 classes x 8 images / batch 4 = 6 steps/epoch, every batch full:
    # fixed shapes, so epoch 1 must be compile-flat.
    make_synthetic_imagefolder(data, classes=("a", "b", "c"),
                               per_class=8, size=32)
    cfg = Config(
        data=DataConfig(data_dir=data, resize_size=32, batch_size=4,
                        num_workers=2, shuffle_seed=0),
        model=ModelConfig(name="resnet18-cifar", num_classes=0,
                          dtype="float32"),
        optim=OptimConfig(optimizer="adam", learning_rate=1e-3,
                          class_weights=(), milestones=()),
        run=RunConfig(epochs=2, ckpt_dir=os.path.join(work, "cp"),
                      save_period=0, resume=False, log_every_steps=1),
        mesh=MeshConfig(),
    )
    trainer = Trainer(cfg)
    steps = trainer.train_loader.steps_per_epoch()

    # Warmup epoch, bare: compiles the step, measures the drain budget.
    with contracts.watch_compiles() as warm, \
            contracts.count_device_gets() as bare:
        trainer.train_epoch(0)
    assert warm.compiles >= 1, "warmup epoch compiled nothing?"
    # The deferred-drain discipline: one batched get per log interval
    # (log_every_steps=1 -> one per step) + the per-epoch step-counter
    # read.  A per-step readback regression would blow well past this.
    budget = steps + 3
    assert bare.count <= budget, \
        f"warmup epoch used {bare.count} device_gets (budget {budget})"

    # Steady-state epoch under the full checker stack.
    with contracts.count_device_gets() as checked:
        with contracts.assert_compiles_flat(what="train steady state"):
            with contracts.bounded_device_gets(budget,
                                               what="train steady state"):
                trainer.train_epoch(1)
    # Zero added host syncs from the checkers themselves.
    assert checked.count == bare.count, \
        f"checkers changed the sync count: {bare.count} bare vs " \
        f"{checked.count} checked"
    print(f"[contracts] train: {steps}-step epoch compile-flat, "
          f"{checked.count} device_gets (budget {budget}), "
          f"checkers added 0 syncs")


def serve_contract() -> None:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpuic.analysis import runtime as contracts
    from tpuic.models import create_model
    from tpuic.serve import InferenceEngine

    model = create_model("resnet18-cifar", num_classes=3, dtype="float32")
    variables = model.init(jax.random.PRNGKey(0),
                           jnp.zeros((1, 32, 32, 3), jnp.float32),
                           train=False)
    buckets = (1, 2, 4)
    eng = InferenceEngine(model, variables, image_size=32,
                          buckets=buckets, max_wait_ms=0.0)
    eng.warmup()
    assert eng.stats.compiles == len(buckets)

    rng = np.random.default_rng(0)
    sizes = [1, 2, 3, 4] * 3  # covers every bucket, incl. padded dispatch
    with contracts.assert_compiles_flat(what="serve steady state"):
        futs = [eng.submit(rng.standard_normal(
            (n, 32, 32, 3)).astype(np.float32)) for n in sizes]
        for f in futs:
            f.result(timeout=120)
        eng.close()
    s = eng.stats.snapshot()
    assert s["compiles"] == len(buckets), "steady-state recompile"
    assert s["executable_cache_hits"] == s["device_calls"]
    print(f"[contracts] serve: {len(sizes)} requests over buckets "
          f"{buckets} compile-flat, {s['device_calls']} device calls "
          "all cache hits")


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--keep", action="store_true",
                   help="keep the temp workdir for inspection")
    args = p.parse_args()
    work = tempfile.mkdtemp(prefix="tpuic_contracts_")
    try:
        train_contract(work)
        serve_contract()
        print("[contracts] OK")
        return 0
    finally:
        if args.keep:
            print(f"workdir kept: {work}")
        else:
            shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
