#!/usr/bin/env python
"""bf16 mixed-precision convergence-parity gate + train-speed evidence.

The PR-16 contract behind ``--compute-dtype bf16``: forward/backward in
bfloat16, f32 master weights and optimizer moments, f32 loss — so a
pinned short recipe must converge the same as the f32 arm.  This script
runs both arms (LARS and LAMB) on the pinned synthetic recipe and gates
on the trajectory-mean loss staying within ``--tol`` (default 5%;
measured clean drift is ~0.4%, the seeded master-weight bug drifts
~20%+).

Bidirectional: ``--inject bf16_master_truncate --expect-fail`` arms the
registered fault (tpuic/runtime/faults.py) that rounds the f32 master
weights through bf16 inside the compiled step — the no-f32-master
mistake this gate exists to catch — and the script then exits 0 IFF the
parity gate fails.

Unless ``--no-async-evidence``, it also runs the pinned train.py
workload twice (async checkpoint commits on/off) and records the final
goodput ledger from each: with ``RunConfig.async_checkpoint`` (the
default) the blocking ``checkpoint`` bucket must be ~0 while
``checkpoint_async_s`` absorbs the commit work — saves overlapped with
compute, the PR-16 goodput claim.

Writes ``perf/bf16_train.json``.  Step times for both arms are recorded
honestly: XLA *CPU* emulates bf16, so the bf16 arm is SLOWER here (the
same caveat the serve dtype ladder carries in its committed baseline);
the speed claim is for the MXU, the parity claim is what CI gates.

    python scripts/bf16_parity.py [--out perf/bf16_train.json]
    python scripts/bf16_parity.py --inject bf16_master_truncate --expect-fail
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import statistics
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")
os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")

# The pinned recipe: resnet18-cifar @ 32px, batch 8, 16 steps over a
# 4-batch synthetic stream; LRs chosen so the trajectory is past warmup
# noise but nowhere near the zero-loss regime (relative diffs of
# near-zero losses are noise, not signal).
_STEPS = 16
_BATCH = 8
_LRS = {"lars": 0.2, "lamb": 1e-3}


def _run_arm(opt: str, tag: str, inject: str = ""):
    """One training arm: (trajectory-mean loss, steady-state p50 ms)."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from tpuic.config import ModelConfig, OptimConfig
    from tpuic.data.synthetic import synthetic_batch
    from tpuic.models import create_model
    from tpuic.runtime import faults
    from tpuic.train.optimizer import make_optimizer
    from tpuic.train.state import create_train_state
    from tpuic.train.step import make_train_step

    ocfg = OptimConfig(optimizer=opt, learning_rate=_LRS[opt],
                       class_weights=(), milestones=())
    mcfg = ModelConfig(name="resnet18-cifar", num_classes=3,
                       dtype=("bfloat16" if tag == "bf16" else "float32"),
                       compute_dtype=tag)
    model = create_model(mcfg.name, mcfg.num_classes, dtype=mcfg.dtype)
    state = create_train_state(model, make_optimizer(ocfg),
                               jax.random.key(0), (_BATCH, 32, 32, 3))
    if inject:
        faults.arm(inject)
    try:
        # The inject is trace-time, so it must stay armed through the
        # first call below (jit traces lazily); seed=2 forces a fresh
        # trace instead of reusing the clean arm's cached executable.
        step = make_train_step(ocfg, mcfg, mesh=None, donate=False,
                               seed=2 if inject else 0)
        losses, times = [], []
        for i in range(_STEPS):
            batch = {k: jnp.asarray(v) for k, v in
                     synthetic_batch(_BATCH, 32, 3, seed=i % 4).items()}
            t0 = time.perf_counter()
            state, m = step(state, batch)
            loss = float(m["loss"])  # device sync: honest step timing
            times.append((time.perf_counter() - t0) * 1e3)
            losses.append(loss)
    finally:
        faults.reset()
    return (float(np.mean(losses[3:])),
            round(statistics.median(times[2:]), 1))


def _goodput_final(workdir: str, extra_args):
    """Final goodput ledger of one pinned train.py run (saves enabled)."""
    from tpuic.data.synthetic import make_synthetic_imagefolder
    from tpuic.telemetry.events import read_jsonl
    data = os.path.join(workdir, "data")
    if not os.path.isdir(data):
        make_synthetic_imagefolder(data, classes=("a", "b", "c"),
                                   per_class=8, size=32)
    jsonl = os.path.join(workdir, "events.jsonl")
    if os.path.exists(jsonl):
        os.unlink(jsonl)
    ckpt = os.path.join(workdir, "cp")
    shutil.rmtree(ckpt, ignore_errors=True)
    env = dict(os.environ, JAX_PLATFORMS="cpu", TF_CPP_MIN_LOG_LEVEL="3")
    env.pop("TPUIC_FAULTS", None)
    cmd = [sys.executable, os.path.join(_REPO, "train.py"),
           "--datadir", data, "--model", "resnet18-cifar",
           "--resize", "32", "--batchsize", "2", "--epochs", "2",
           "--optimizer", "adam", "--lr", "1e-3", "--no-class-weights",
           "--log-every-steps", "1", "--ckpt-dir", ckpt,
           "--metrics-jsonl", jsonl] + list(extra_args)
    proc = subprocess.run(cmd, cwd=_REPO, env=env, text=True,
                          capture_output=True, timeout=1200)
    if proc.returncode != 0:
        raise RuntimeError(f"train.py exited {proc.returncode}:\n"
                           f"{proc.stdout[-1500:]}\n{proc.stderr[-1500:]}")
    finals = [r for r in read_jsonl(jsonl)
              if r["event"] == "goodput" and r.get("final")]
    if len(finals) != 1:
        raise RuntimeError(f"expected 1 final goodput report, "
                           f"got {len(finals)}")
    rep = finals[0]
    keep = ("wall_s", "checkpoint_s", "checkpoint_async_s",
            "frac_checkpoint", "accounted_frac", "compute_dtype")
    return {k: rep[k] for k in keep if k in rep}


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--out", default=os.path.join(_REPO, "perf",
                                                 "bf16_train.json"))
    p.add_argument("--tol", type=float, default=0.05)
    p.add_argument("--inject", default="",
                   help="fault point to arm (e.g. bf16_master_truncate)")
    p.add_argument("--expect-fail", action="store_true",
                   help="exit 0 IFF the parity gate fails (seeded-fault CI "
                        "arm); the artifact is not rewritten")
    p.add_argument("--no-async-evidence", action="store_true",
                   help="skip the train.py async-checkpoint goodput runs")
    args = p.parse_args()

    import jax

    out = {"schema": "tpuic.bf16_train.v1",
           "platform": jax.devices()[0].platform,
           "recipe": {"model": "resnet18-cifar", "batch": _BATCH,
                      "steps": _STEPS, "lrs": _LRS},
           "tol": args.tol,
           "caveat": ("CPU container: XLA emulates bf16, so the bf16 arm's "
                      "step times are SLOWER than f32 here — recorded "
                      "honestly, same caveat as the serve dtype ladder. "
                      "The MXU speedup claim needs a chip; the "
                      "convergence-parity numbers are platform-honest and "
                      "are what CI gates."),
           "optimizers": {}}
    failures = []
    for opt in ("lars", "lamb"):
        f32_loss, f32_ms = _run_arm(opt, "f32")
        bf16_loss, bf16_ms = _run_arm(opt, "bf16", inject=args.inject)
        rel = abs(bf16_loss - f32_loss) / f32_loss
        ok = rel <= args.tol
        if not ok:
            failures.append(f"{opt}: rel diff {rel:.4f} > tol {args.tol}")
        out["optimizers"][opt] = {
            "f32": {"mean_loss": round(f32_loss, 5), "step_p50_ms": f32_ms},
            "bf16": {"mean_loss": round(bf16_loss, 5),
                     "step_p50_ms": bf16_ms},
            "rel_diff": round(rel, 4), "parity_ok": ok,
        }
        print(f"[bf16-parity] {opt}: f32 {f32_loss:.5f} ({f32_ms:.0f} ms) "
              f"vs bf16 {bf16_loss:.5f} ({bf16_ms:.0f} ms) — rel "
              f"{rel:.4f} {'OK' if ok else 'FAIL'}"
              + (f" [inject={args.inject}]" if args.inject else ""))

    if args.expect_fail:
        if failures:
            print("[bf16-parity] parity broke under the seeded fault, "
                  "as it must — the gate can see the bug")
            return 0
        print("[bf16-parity] ERROR: gate passed despite the seeded fault "
              "— the parity check is blind", file=sys.stderr)
        return 1

    if not args.no_async_evidence:
        work = tempfile.mkdtemp(prefix="tpuic_bf16_async_")
        try:
            async_rep = _goodput_final(work, [])
            sync_rep = _goodput_final(work, ["--no-async-checkpoint"])
        finally:
            shutil.rmtree(work, ignore_errors=True)
        out["async_checkpoint"] = {"async": async_rep, "sync": sync_rep}
        print(f"[bf16-parity] goodput checkpoint bucket: async "
              f"{async_rep.get('checkpoint_s')}s blocking + "
              f"{async_rep.get('checkpoint_async_s')}s overlapped vs sync "
              f"{sync_rep.get('checkpoint_s')}s blocking")
        # The PR-16 goodput claim, gated: commits overlapped with compute
        # (async bucket non-trivial) and the blocking bucket ~0.
        if not (async_rep.get("checkpoint_async_s", 0.0) > 0.0
                and async_rep["checkpoint_s"]
                < max(0.05, 0.25 * max(sync_rep["checkpoint_s"], 1e-9))):
            failures.append(
                f"async commit did not empty the blocking checkpoint "
                f"bucket: {async_rep} vs sync {sync_rep}")

    if failures:
        for f in failures:
            print(f"[bf16-parity] FAIL: {f}", file=sys.stderr)
        return 1
    os.makedirs(os.path.dirname(args.out), exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
    print(f"[bf16-parity] artifact -> {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
