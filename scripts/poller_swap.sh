#!/bin/bash
# One-shot watchdog: the poller running since before chip_queue6.sh was
# written parsed its queue list at startup and will never run queue6.
# Wait until that poller's current pass is fully stamped out (queue5 done,
# no queue script active, NO live measurement), then replace it with a
# fresh chip_poller5.sh that picks up the full queue4/5/6 list.
# Usage: nohup bash scripts/poller_swap.sh >> perf/chip_poller5.log 2>&1 &
set -o pipefail
cd /root/repo
. scripts/chip_wait.sh
log() { echo "$(date -u +%FT%TZ) poller_swap: $*"; }

# Non-blocking MEASURE_PAT probe (ADVICE r5): the old gate only checked
# queue scripts, so a poller mid-bench (e.g. a driver-initiated bench.py
# between queue items) could be swapped out UNDER a running measurement.
# chip_busy is chip_wait.sh's single-source predicate (same pattern, same
# self/driver exclusions).
measure_busy() {
  if chip_busy "$MEASURE_PAT"; then
    log "measurement live ($CHIP_BUSY_PROC) — holding the swap"
    return 0
  fi
  return 1
}

while true; do
  if [ -e perf/.chip_queue5_done ] \
      && ! pgrep -f 'scripts/chip_queue[0-9]' > /dev/null \
      && ! measure_busy; then
    old=$(pgrep -f 'bash scripts/chip_poller5.sh' | head -1)
    if [ -n "$old" ] && [ "$old" != "$$" ]; then
      log "queues stamped; replacing poller pid $old"
      kill "$old"
      sleep 2
    fi
    nohup bash scripts/chip_poller5.sh >> perf/chip_poller5.log 2>&1 &
    log "new poller started pid $!"
    exit 0
  fi
  sleep 120
done
