#!/usr/bin/env python
"""Profile smoke gate (ISSUE 11 acceptance; runs in tier-1 CI).

Drives the REAL device-time-attribution wiring end to end, then proves
the roofline gate is bidirectional:

1. A short real ``train.py`` run with tracing forced (``TPUIC_TRACE``)
   and ``--trace-analyze``: the trace trigger must capture a window, the
   analyzer must auto-run (trace started/stopped events + at least one
   ``profile`` event), and the final waterfall's per-op-class device
   times must sum to within ``--tolerance`` of the measured telemetry
   ``device_ms`` bucket, each class carrying a roofline verdict and the
   per-layer rollup naming real model layers.
2. ``python -m tpuic.telemetry.profile --check`` against the committed
   ``perf/roofline_baseline.json`` must pass clean, and the same check
   under a seeded partial stall (``--inject slow_step``) must FAIL
   naming the shifted metric — a gate that cannot fire is decoration.

The analysis JSONs land in --workdir (uploaded as CI artifacts on
failure).  Exit 0 on success.

    python scripts/profile_smoke.py [--steps 12] [--keep]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
VERDICTS = {"compute-bound", "hbm-bound", "overhead"}


def fail(msg: str) -> int:
    print(f"[profile-smoke] FAIL: {msg}", file=sys.stderr)
    return 1


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=12)
    p.add_argument("--tolerance", type=float, default=0.05,
                   help="max |sum(class ms) - device bucket| / bucket")
    p.add_argument("--workdir", default="",
                   help="where the analysis JSONs land (default: temp)")
    p.add_argument("--keep", action="store_true")
    args = p.parse_args()

    work = args.workdir or tempfile.mkdtemp(prefix="tpuic_profile_smoke_")
    os.makedirs(work, exist_ok=True)
    try:
        sys.path.insert(0, _REPO)
        from tpuic.telemetry.events import read_jsonl
        from tpuic.telemetry.profile import profile_workload

        # -- 1. real wiring: train.py + TPUIC_TRACE + --trace-analyze --
        run_dir = os.path.join(work, "run")
        _, wf = profile_workload(args.steps, keep_dir=run_dir)
        with open(os.path.join(work, "waterfall.json"), "w") as f:
            json.dump(wf, f, indent=2)
        recs = read_jsonl(os.path.join(run_dir, "events.jsonl"))
        trace_actions = [r.get("action") for r in recs
                         if r["event"] == "trace"]
        if "started" not in trace_actions or "stopped" not in trace_actions:
            return fail(f"forced trace window did not capture cleanly "
                        f"(trace actions: {trace_actions})")
        profiles = [r for r in recs if r["event"] == "profile"
                    and not r.get("error")]
        if not profiles:
            return fail("no successful profile event published")
        classes = wf.get("classes") or {}
        if not classes:
            return fail("final waterfall has no op classes")
        total = sum(c["ms"] for c in classes.values())
        bucket = float(wf.get("device_ms_per_step") or 0.0)
        if bucket <= 0:
            return fail(f"no measured device bucket in the waterfall: {wf}")
        gap = abs(total - bucket) / bucket
        if gap > args.tolerance:
            return fail(
                f"op-class times sum to {total:.3f} ms but the telemetry "
                f"device bucket is {bucket:.3f} ms/step "
                f"({100 * gap:.1f}% > {100 * args.tolerance:.0f}%)")
        missing = [k for k, c in classes.items()
                   if c.get("verdict") not in VERDICTS]
        if missing:
            return fail(f"classes without a roofline verdict: {missing}")
        if not any("layer" in k for k in (wf.get("layers") or {})):
            return fail(f"per-layer rollup names no model layers: "
                        f"{list((wf.get('layers') or {}))[:5]}")
        # The analytic-FLOPs cross-check (goodput.check_flops_drift)
        # must stay inside the 10% warning threshold: the table feeds
        # every in-band MFU number, and PR 10's 43% resnet18-cifar
        # finding (a MAC count pasted as FLOPs) is exactly the rot this
        # assertion keeps fixed.
        drift = wf.get("analytic_flops_drift")
        if drift is None:
            return fail("waterfall carries no analytic_flops_drift "
                        "cross-check (table or cost analysis missing "
                        "for the pinned workload model)")
        if drift >= 0.10:
            return fail(f"analytic FLOPs table drifts {100 * drift:.1f}% "
                        f">= 10% from the compiler's count — fix "
                        f"FWD_FLOPS_PER_IMAGE (goodput.py) and re-derive "
                        f"the regression baseline via --write-baseline")
        print(f"[profile-smoke] waterfall OK: {len(classes)} classes sum "
              f"{total:.2f} ms vs device bucket {bucket:.2f} ms/step "
              f"({100 * gap:.2f}%), analytic-FLOPs drift "
              f"{100 * drift:.1f}% (<10%), "
              f"{len(wf.get('layers') or {})} layers, "
              f"{wf.get('tainted_steps_excluded', 0)} tainted steps "
              f"excluded")

        # -- 2. the roofline gate, both directions ---------------------
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   TF_CPP_MIN_LOG_LEVEL="3")
        base = [sys.executable, "-m", "tpuic.telemetry.profile",
                "--check", "--steps", str(args.steps)]
        clean = subprocess.run(
            base + ["--report", os.path.join(work, "gate_clean.json")],
            cwd=_REPO, env=env, text=True, capture_output=True,
            timeout=1200)
        if clean.returncode != 0:
            return fail(f"clean roofline check failed "
                        f"(rc={clean.returncode}):\n{clean.stdout[-1500:]}"
                        f"\n{clean.stderr[-800:]}")
        print("[profile-smoke] clean roofline check passed")
        faulted = subprocess.run(
            base + ["--inject", "slow_step", "--expect-fail",
                    "--report", os.path.join(work, "gate_faulted.json")],
            cwd=_REPO, env=env, text=True, capture_output=True,
            timeout=1200)
        if faulted.returncode != 0:
            return fail(
                f"seeded stall did NOT trip the roofline gate "
                f"(rc={faulted.returncode}):\n{faulted.stdout[-1500:]}"
                f"\n{faulted.stderr[-800:]}")
        with open(os.path.join(work, "gate_faulted.json")) as f:
            rep = json.load(f)
        print(f"[profile-smoke] seeded stall tripped the gate on: "
              f"{', '.join(rep.get('regressed_metrics', []))}")
        print("[profile-smoke] OK")
        return 0
    finally:
        if not args.keep and not args.workdir:
            shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
