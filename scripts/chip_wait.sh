# Shared busy-wait for the 1-core / 1-chip host: block until no
# measurement-skewing process is running. Source this and call chip_wait.
#
# MEASURE_PAT matches the SCRIPT NAMES (not the invocation prefix — a
# 'python bench.py' prefix pattern misses '/usr/bin/python3
# /root/repo/bench.py', exactly how bench_cache_timing.py spawns its
# children): every perf/measurement entry point plus pytest. Queue
# scripts wait on MEASURE_PAT; the poller adds 'chip_queue' on top (a
# queue must NOT wait on its own name).
MEASURE_PAT='bench\.py|perf_sweep\.py|long_seq_bench\.py|pallas_smoke\.py|packed_valid_smoke\.py|fit_proof\.py|resume_cache_proof\.py|convergence_digits\.py|bench_data\.py|__graft_entry__|pytest'

# Non-blocking probe: is any real measurement process matching $1 alive?
# Returns 0 and sets CHIP_BUSY_PROC="pid:argv" when one is; returns 1 when
# clear. The driver filter lives HERE and only here:
#
# pgrep -f matches the FULL argv, and the session driver (`claude -p
# --append-system-prompt ...`) embeds the literal strings "bench.py" and
# "pytest" in its prompt argv — so a raw `pgrep -f "$MEASURE_PAT"` matches
# the always-running driver and deadlocks the wait (this exact hang ate the
# 08:29Z recovery window). Filter matches down to real measurement
# processes: skip ourselves, and skip anything whose cmdline is the driver
# or its sh/bash wrappers (identified by the claude/append-system-prompt
# argv, which no measurement process has).
chip_busy() {
  local p cmd
  CHIP_BUSY_PROC=""
  for p in $(pgrep -f "$1" 2>/dev/null); do
    [ "$p" = "$$" ] && continue
    cmd=$(tr '\0' ' ' 2>/dev/null < "/proc/$p/cmdline") || continue
    case "$cmd" in
      *claude*|*append-system-prompt*) continue ;;
    esac
    CHIP_BUSY_PROC="$p:${cmd:0:80}"
    return 0
  done
  return 1
}

chip_wait() {
  # $1: pgrep -f pattern; $2: log tag. Blocks until chip_busy clears.
  while chip_busy "$1"; do
    echo "$(date -u +%FT%TZ) $2: waiting for running measurement/tests ($CHIP_BUSY_PROC)"
    sleep 60
  done
  return 0
}
