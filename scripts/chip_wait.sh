# Shared busy-wait for the 1-core / 1-chip host: block until no
# measurement-skewing process is running. Source this and call chip_wait.
#
# MEASURE_PAT matches the SCRIPT NAMES (not the invocation prefix — a
# 'python bench.py' prefix pattern misses '/usr/bin/python3
# /root/repo/bench.py', exactly how bench_cache_timing.py spawns its
# children): every perf/measurement entry point plus pytest. Queue
# scripts wait on MEASURE_PAT; the poller adds 'chip_queue' on top (a
# queue must NOT wait on its own name).
MEASURE_PAT='bench\.py|perf_sweep\.py|long_seq_bench\.py|pallas_smoke\.py|packed_valid_smoke\.py|fit_proof\.py|resume_cache_proof\.py|convergence_digits\.py|bench_data\.py|__graft_entry__|pytest'

chip_wait() {
  # $1: pgrep -f pattern; $2: log tag
  while pgrep -f "$1" > /dev/null; do
    echo "$(date -u +%FT%TZ) $2: waiting for running measurement/tests"
    sleep 60
  done
}
