#!/usr/bin/env python
"""Before/after artifact for the Pallas fused conv+BN+ReLU kernel
(tpuic/kernels/conv_bn_relu.py) -> perf/fused_conv_bn.json.

Three views, each labeled with exactly what it is:

- **parity** (measured): max-abs difference of the fused vs unfused
  inference forward per ResNet variant — the numerics contract
  tests/test_kernels.py pins (atol 1e-4 documented; measured ~1e-7 in
  float32, the fused kernel's f32 tap accumulation is *tighter* than a
  bf16 unfused graph).
- **hlo_waterfall_unfused / hlo_waterfall_fused_interpret** (modeled,
  v5e roofline constants): the op-class waterfalls of the two CPU
  lowerings.  CAVEAT, stated in-artifact: the interpret-mode lowering
  materializes every tap slice as a real copy, which Mosaic never does
  (taps are VMEM reads) — the fused CPU waterfall is an artifact of the
  interpreter, not a picture of the TPU program.
- **finding** (the honest one): on this backend XLA ALREADY
  epilogue-fuses the inference BN affine + ReLU into each convolution
  fusion, so the *unfused* forward's elementwise+copy boundary traffic
  is ~0 to begin with (measured and recorded).  The committed
  perf/roofline_baseline.json's elementwise+copy fraction lives in the
  TRAIN step (backward transposes, optimizer), which an inference
  kernel cannot touch.  What the Pallas kernel buys on TPU — explicit
  taps-as-GEMMs MXU layout (the space-to-depth argument applied to
  every block), f32 VMEM accumulation, and one guaranteed output write
  per block independent of XLA's fusion heuristics — is recorded here
  as the per-block **mosaic_boundary** accounting (bytes the kernel's
  contract admits at its boundary vs the activation roundtrips a
  *non*-epilogue-fusing compiler would pay), pending a chip measurement
  (the perf/pallas_smoke.json pattern).

    python scripts/fused_conv_bench.py --out perf/fused_conv_bn.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _force_cpu() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.setdefault("TF_CPP_MIN_LOG_LEVEL", "3")
    from tpuic.runtime.axon_guard import drop_axon_vars
    drop_axon_vars(os.environ)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, "tests", ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def np_prod(shape) -> int:
    out = 1
    for d in shape:
        out *= int(d)
    return out


def _waterfall(exe, peak, bw):
    from tpuic.telemetry.goodput import cost_analysis_dict
    from tpuic.telemetry.profile import hlo_waterfall
    try:
        cost = cost_analysis_dict(exe)
    except Exception:
        cost = {}
    wf = hlo_waterfall(exe.as_text(),
                       total_flops=float(cost.get("flops", 0.0)),
                       peak=peak, hbm_bytes_per_s=bw)
    wf.pop("layers", None)
    return wf


def _ew_copy_frac(wf) -> dict:
    cls = wf["classes"]
    ms = sum(c["ms"] for c in cls.values()) or 1.0
    by = sum(c["bytes"] for c in cls.values()) or 1.0
    ew = sum(cls.get(k, {"ms": 0, "bytes": 0})["ms"]
             for k in ("elementwise", "copy"))
    ewb = sum(cls.get(k, {"ms": 0, "bytes": 0})["bytes"]
              for k in ("elementwise", "copy"))
    return {"ms_frac": round(ew / ms, 4), "bytes_frac": round(ewb / by, 4)}


def _mosaic_boundary(variables) -> dict:
    """Structural boundary accounting from the model's real parameter
    shapes: the kernel admits in + weights + affine + ONE output write
    per fused call (the epilogue is VMEM-interior by construction)."""
    import jax

    shapes = []

    def record(path, leaf):
        name = "/".join(str(getattr(k, "key", k)) for k in path)
        if name.endswith("kernel") and getattr(leaf, "ndim", 0) == 4:
            shapes.append((name, tuple(leaf.shape)))
    jax.tree_util.tree_map_with_path(record, variables["params"])
    w_bytes = sum(4 * int(np_prod(s)) for _, s in shapes)
    return {"fused_calls": len(shapes),
            "weight_bytes_f32": w_bytes,
            "note": ("each fused call bounds its HBM traffic to "
                     "in + weights + affine + ONE output write by "
                     "construction; a non-epilogue-fusing compiler "
                     "pays +2 activation roundtrips (BN, ReLU) per "
                     "call — XLA CPU/TPU inference usually fuses "
                     "these already (see finding), Mosaic makes the "
                     "bound structural rather than heuristic")}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--models", default="resnet18-cifar,resnet50")
    p.add_argument("--batch", type=int, default=32)
    p.add_argument("--out", default=os.path.join("perf",
                                                 "fused_conv_bn.json"))
    args = p.parse_args(argv)

    _force_cpu()
    import jax
    import jax.numpy as jnp
    import numpy as np

    from tpuic.models import create_model
    from tpuic.telemetry.goodput import HBM_GBPS, PEAK_FLOPS

    # Model the part the kernel targets: v5e roofline constants, where
    # bandwidth-bound elementwise traffic actually costs (the CPU
    # constants drown it under a slow nominal matmul peak).
    peak, bw = PEAK_FLOPS["TPU v5e"], HBM_GBPS["TPU v5e"] * 1e9

    out = {"metric": "fused_conv_bn_relu_parity_and_waterfalls",
           "batch": args.batch, "roofline_constants": "TPU v5e (modeled)",
           "models": {}}
    for name in args.models.split(","):
        name = name.strip()
        size = 32 if "cifar" in name else 64
        x = jnp.asarray(np.random.default_rng(0).standard_normal(
            (args.batch, size, size, 3)), jnp.float32)
        base = create_model(name, 10, dtype="float32")
        fused = create_model(name, 10, dtype="float32",
                             fused_conv_bn=True)
        v = base.init(jax.random.key(0), x[:1], train=False)
        a = base.apply(v, x, train=False)
        b = fused.apply(v, x, train=False)
        parity = float(jnp.abs(a - b).max())

        exe_u = jax.jit(lambda v, x: base.apply(
            v, x, train=False)).lower(v, x).compile()
        exe_f = jax.jit(lambda v, x: fused.apply(
            v, x, train=False)).lower(v, x).compile()
        wf_u, wf_f = _waterfall(exe_u, peak, bw), _waterfall(exe_f, peak,
                                                             bw)
        out["models"][name] = {
            "image_size": size,
            "parity_max_abs_diff_f32": parity,
            "unfused_ew_copy": _ew_copy_frac(wf_u),
            "fused_interpret_ew_copy": _ew_copy_frac(wf_f),
            "hlo_waterfall_unfused": wf_u,
            "hlo_waterfall_fused_interpret": wf_f,
            "mosaic_boundary": _mosaic_boundary(v),
        }
    out["finding"] = (
        "XLA already epilogue-fuses the inference BN affine + ReLU into "
        "each conv fusion on this backend: the UNFUSED forward's "
        "elementwise+copy boundary fraction is ~0 (see "
        "unfused_ew_copy; resnet50's nonzero number is a single "
        "zero-cost `bitcast` layout reinterpretation around the stem "
        "maxpool that the cost model charges boundary bytes for, not "
        "real traffic), so the waterfall cannot show an "
        "elementwise->matmul shift for the inference graph here. The "
        "committed perf/roofline_baseline.json's elementwise+copy "
        "fraction belongs to the TRAIN step (backward transposes, "
        "optimizer update), out of an inference kernel's reach. The "
        "fused kernel's parity is pinned and its Mosaic boundary bound "
        "is structural (mosaic_boundary.note); the "
        "fused_interpret waterfall is the INTERPRETER's lowering "
        "(materialized tap slices) and does not represent the TPU "
        "program — chip measurement pending, the perf/pallas_smoke.json "
        "pattern.")
    os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
    with open(args.out, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps({k: v for k, v in out.items() if k != "models"}))
    for name, m in out["models"].items():
        print(f"[fused-conv] {name}: parity {m['parity_max_abs_diff_f32']:.2e}, "
              f"unfused ew+copy {m['unfused_ew_copy']}, "
              f"fused(interpret) ew+copy {m['fused_interpret_ew_copy']}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
