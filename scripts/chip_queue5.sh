#!/bin/bash
# Round-5 queue: the measurements this round owes the chip.
#   1. Convergence on real data (VERDICT r4 item 2): digits ImageFolder
#      through the full production path; overwrites the "tpuic" entry of
#      perf/convergence_digits.json with a live-TPU training run (the
#      torch control is CPU-side and kept).
#   2. Resident-cache preemption resume (item 6): SIGTERM latch mid-epoch
#      with the device-resident dataset active, resume, compare to an
#      uninterrupted control.
#   3. Warm-compile-cache bench timing (item 5a): two back-to-back
#      bench.py runs; run 2's wall clock is the flap-window evidence.
# Run via: nohup bash scripts/chip_poller5.sh &   (runs queue4 first)
set -x -o pipefail
failures=0
cd /root/repo
. scripts/chip_wait.sh

chip_wait "$MEASURE_PAT" "chip_queue5"

python scripts/convergence_digits.py --skip-control 2>&1 | tail -6 \
  || failures=$((failures+1))

python scripts/resume_cache_proof.py 2>&1 | tail -6 \
  || failures=$((failures+1))

python scripts/bench_cache_timing.py 2>&1 | tail -2 \
  || failures=$((failures+1))

# 4. remat_policy='gelu' A/B (VERDICT r4 item 3's suggested experiment):
#    MlpUpGelu under nn.remat drops the [B,N,4D] mlp_up pre-activation —
#    the dual-output fusion writes the ViT-B b64 profile fingered as the
#    largest single op class in the 0.537-vs-0.70 gap. --remat sweeps
#    plain AND remat rows at each batch, so this one invocation is the
#    A/B; b128 also probes whether the freed residuals move the
#    allocator cliff (§10b).
python scripts/perf_sweep.py --batches 64,128 --model vit-b16 \
  --remat --remat-policy gelu \
  --out perf/vit_gelu_remat.json 2>&1 | tail -4 || failures=$((failures+1))

echo "chip_queue5: $failures item(s) failed"
exit $failures
