#!/usr/bin/env python
"""Gang soak (ISSUE 10 acceptance; runs in tier-1 CI).

The end-to-end proof of coordinated multi-rank supervision
(`tpuic.runtime.gang.GangSupervisor` driving TWO real `train.py` ranks
as one unit, CPU, synthetic data — independent ranks via the
`TPUIC_FLEET_RANK(S)` launcher override, the `fleet_smoke.py` caveat:
this container's CPU jax implements no multiprocess collectives, and
independent deterministic ranks are exactly what the bitwise verdict
wants anyway), raced against an UNDISTURBED single-process baseline:

- attempt 0 seeds ``rank_crash@8#1`` — rank 1 is SIGKILLed mid epoch 1
  while rank 0 keeps training (``slow_step#`` drags both ranks so the
  survivor is provably mid-flight when the crash lands);
- the gang must tear down as a unit: the SURVIVOR gets its SIGTERM
  flush window and exits 43 (observed in the attempt's per-rank codes)
  with a step-exact checkpoint;
- the coordinated restart resumes on the FLEET-AGREED step: the gang
  ledger's ``gang_resume`` records the newest step every rank's
  committed manifest covers (epoch 0's commit — NOT the survivor's
  newer teardown flush), and each rank's ``restart`` event proves it
  landed there (epoch 1, step 0 — no rank resumed ahead of the fleet);
- exactly ONE coordinated restart happens, zero ledger violations, and
  both ranks' final optimizer step and per-epoch eval accuracies are
  BITWISE identical to the undisturbed baseline;
- the fleet aggregator (`python -m tpuic.telemetry.fleet
  --require-ranks 2`) passes over the per-rank streams and its
  ``duplicate_steps`` surfaces the replay; ``--require-ranks 3`` fails,
  proving the coverage gate is bidirectional;

plus the poison contract on cheap stdlib children: exit 44 from ONE
rank stops the whole gang without restart (the survivor still gets its
flush window).

The zero-added-syncs/zero-compiles half of the acceptance (the gang env
wiring — per-rank heartbeat, fleet tag, resume cap — adds no device
work) is checker-asserted in tier-1
(tests/test_gang.py::test_gang_env_wiring_zero_syncs_zero_compiles).

Exit 0 on success.   python scripts/gang_soak.py [--keep] [-v]
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile
import textwrap
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from tpuic.runtime.gang import GangSupervisor  # noqa: E402
from tpuic.runtime.supervisor import (EXIT_POISON,  # noqa: E402
                                      EXIT_PREEMPTED)

RANKS = 2
CRASH_RANK = 1
# 2 classes x 12 / global batch 4 = 6 steps/epoch; 2 epochs, no skipped
# steps -> the final optimizer step is 12. rank_crash@8 SIGKILLs rank 1
# at host step key 8 (epoch 1, loop index 2); slow_step#0.3 drags BOTH
# ranks so rank 0 is provably mid-epoch when the teardown TERM lands
# (sleeps never change the math — the baseline runs full speed).
PER_CLASS = 12
BATCH = 4
EPOCHS = 2
STEPS_PER_EPOCH = (2 * PER_CLASS) // BATCH
FINAL_STEP = EPOCHS * STEPS_PER_EPOCH
CHAOS = [f"rank_crash@8#{CRASH_RANK},slow_step#0.3", ""]


def _train_cmd(data: str, ckpt: str, cache: str, jsonl: str) -> list:
    return [sys.executable, os.path.join(_REPO, "train.py"),
            "--datadir", data, "--model", "resnet18-cifar",
            "--resize", "24", "--batchsize", str(BATCH),
            "--epochs", str(EPOCHS), "--optimizer", "sgd", "--lr", "0.01",
            "--no-class-weights", "--log-every-steps", "1",
            "--save-period", "1", "--workers", "2",
            "--ckpt-dir", ckpt, "--cache-dir", cache,
            "--metrics-jsonl", jsonl]


def _events(path: str) -> list:
    from tpuic.telemetry.events import read_jsonl
    return read_jsonl(path, on_torn=lambda ln: print(
        f"  [soak] skipping torn jsonl line in {path}: {ln[:80]!r}"))


def _evals(recs: list) -> dict:
    out = {}
    for r in recs:
        if r["event"] == "eval":
            out[int(r["epoch"])] = r["accuracy"]
    return out


def _final_meta_step(ckpt_model_dir: str):
    try:
        man = json.load(open(os.path.join(ckpt_model_dir,
                                          "latest.manifest.json")))
        return int(man["step"])
    except (OSError, ValueError, KeyError, TypeError):
        return None


def _poison_phase(work: str, check) -> None:
    """Poison contract on stdlib children (~1 s): exit 44 from one rank
    stops the gang without restart; the survivor flushes 43."""
    child = os.path.join(work, "poison_child.py")
    with open(child, "w") as f:
        f.write(textwrap.dedent("""\
            import os, signal, sys, time
            from tpuic.runtime.supervisor import (EXIT_POISON,
                                                  EXIT_PREEMPTED,
                                                  HeartbeatWriter)
            hb = HeartbeatWriter(os.environ["TPUIC_HEARTBEAT_FILE"],
                                 min_interval_s=0.0)
            signal.signal(signal.SIGTERM,
                          lambda s, f: sys.exit(EXIT_PREEMPTED))
            if os.environ["TPUIC_FLEET_RANK"] == "1":
                hb.last_step = 1; hb.beat()
                # Wait for rank 0's first beat (its TERM handler is
                # registered before it beats) so the teardown's flush
                # window finds an armed survivor, not a mid-import one.
                peer = os.environ["TPUIC_HEARTBEAT_FILE"].replace(
                    ".rank1", "")
                t0 = time.monotonic()
                while (not os.path.exists(peer)
                       and time.monotonic() - t0 < 30):
                    time.sleep(0.02)
                sys.exit(EXIT_POISON)
            while True:
                hb.last_step = 1; hb.beat()
                time.sleep(0.02)
        """))
    sup = GangSupervisor(
        [sys.executable, child], os.path.join(work, "poison_state"),
        ranks=RANKS, watchdog_s=30.0, startup_grace_s=30.0, poll_s=0.05,
        grace_s=10.0, max_restarts=4, backoff_s=0.05, backoff_max_s=0.1,
        env={"PYTHONPATH": _REPO})
    rc = sup.run()
    check(rc == EXIT_POISON,
          f"poison from one rank stopped the gang with exit "
          f"{EXIT_POISON} (got {rc})")
    check(sup.restarts == 0 and len(sup.attempts) == 1,
          f"no restart after poison ({sup.restarts} restarts, "
          f"{len(sup.attempts)} attempts)")
    codes = sup.attempts[0].codes if sup.attempts else []
    check(codes and codes[1] == EXIT_POISON
          and codes[0] == EXIT_PREEMPTED,
          f"survivor got its flush window during the poison teardown "
          f"(codes {codes})")


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--watchdog-s", type=float, default=30.0)
    p.add_argument("--workdir", default="",
                   help="run here instead of a temp dir (CI passes a "
                        "fixed path so per-rank stackdump/flightdump "
                        "artifacts can be uploaded on failure)")
    p.add_argument("--keep", action="store_true")
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args()

    t_start = time.monotonic()
    work = args.workdir or tempfile.mkdtemp(prefix="tpuic_gang_")
    os.makedirs(work, exist_ok=True)
    failures: list = []
    passed = False       # set only on the fully-green path: an unhandled
    baseline = None      # exception must also keep the artifacts


    def check(ok: bool, msg: str) -> None:
        print(("  ok  " if ok else "  FAIL") + f" {msg}")
        if not ok:
            failures.append(msg)

    try:
        print("[soak] poison contract: exit 44 from one rank stops the "
              "gang without restart")
        _poison_phase(work, check)
        if failures:
            return 1

        # -- dataset + parallel baseline --------------------------------
        from tpuic.data.synthetic import make_synthetic_imagefolder
        data = os.path.join(work, "data")
        make_synthetic_imagefolder(data, classes=("a", "b"),
                                   per_class=PER_CLASS, size=24)
        # Identical env on every side (the chaos_soak discipline): the
        # shared persistent compile cache pays each XLA compile once,
        # and cpu + cache + skip-guard disables donation on ALL of
        # baseline and both ranks, so the bitwise comparison holds.
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   TF_CPP_MIN_LOG_LEVEL="3", XLA_FLAGS="",
                   JAX_COMPILATION_CACHE_DIR=os.path.join(work,
                                                          "jax_cache"))
        sink = None if args.verbose else subprocess.DEVNULL
        base_jsonl = os.path.join(work, "baseline.jsonl")
        base_ckpt = os.path.join(work, "ckpt_base")
        print("[soak] baseline (undisturbed, single process) started in "
              "parallel")
        baseline = subprocess.Popen(
            _train_cmd(data, base_ckpt, os.path.join(work, "cache_base"),
                       base_jsonl),
            cwd=_REPO, env=env, stdout=sink, stderr=sink)

        # -- the supervised 2-rank gang ---------------------------------
        streams = os.path.join(work, "streams")
        os.makedirs(streams, exist_ok=True)
        state_dir = os.path.join(work, "supervise")
        gang_cmd = _train_cmd(data, os.path.join(work, "cp{rank}"),
                              os.path.join(work, "cache{rank}"),
                              os.path.join(streams, "events.jsonl"))
        print(f"[soak] gang of {RANKS} ranks under chaos "
              f"({'; '.join(s or 'fault-free' for s in CHAOS)})")
        sup = GangSupervisor(
            gang_cmd, state_dir, ranks=RANKS,
            watchdog_s=args.watchdog_s, startup_grace_s=600.0,
            quit_wait_s=2.0, grace_s=15.0, poll_s=0.25, max_restarts=4,
            backoff_s=0.25, backoff_max_s=2.0, crash_loop_k=3,
            heartbeat_interval_s=0.2, chaos=CHAOS,
            ckpt_dirs=os.path.join(work, "cp{rank}", "resnet18-cifar"),
            env=dict(env, PYTHONPATH=_REPO))
        rc = sup.run()
        base_rc = baseline.wait(timeout=900)

        # -- the verdict -------------------------------------------------
        print(f"[soak] gang finished (exit {rc}, {len(sup.attempts)} "
              f"attempts, {sup.restarts} restarts, best fleet step "
              f"{sup.best_fleet_step}); baseline exit {base_rc}")
        check(rc == 0, "gang completed cleanly (exit 0)")
        check(base_rc == 0, "baseline completed cleanly (exit 0)")
        check(sup.restarts == 1 and sup.crash_restarts == 1,
              f"exactly ONE coordinated gang restart "
              f"({sup.restarts} restarts, {sup.crash_restarts} crash)")
        check(sup.violations == 0,
              "zero per-rank step-accounting violations")
        first = sup.attempts[0] if sup.attempts else None
        check(first is not None and first.codes[CRASH_RANK] < 0,
              f"rank {CRASH_RANK} died by signal in attempt 0 "
              f"(codes {first and first.codes})")
        check(first is not None
              and first.codes[1 - CRASH_RANK] == EXIT_PREEMPTED,
              f"the SURVIVING rank got its flush window — exit "
              f"{EXIT_PREEMPTED} observed (codes {first and first.codes})")

        ledger = [json.loads(ln) for ln in open(sup.ledger_file)]
        resume = [r for r in ledger if r["event"] == "gang_resume"]
        check(len(resume) == 1
              and resume[0]["step"] == STEPS_PER_EPOCH,
              f"coordinated restart resumed on the fleet-agreed step "
              f"{STEPS_PER_EPOCH} — epoch 0's commit, not the "
              f"survivor's newer teardown flush "
              f"(ledger: {[r.get('step') for r in resume]})")

        from tpuic.telemetry.fleet import rank_stream_path
        b_recs = _events(base_jsonl)
        b_eval = _evals(b_recs)
        b_meta = _final_meta_step(os.path.join(base_ckpt,
                                               "resnet18-cifar"))
        check(b_meta == FINAL_STEP,
              f"baseline committed final step {FINAL_STEP} (got {b_meta})")
        for rank in range(RANKS):
            recs = _events(rank_stream_path(
                os.path.join(streams, "events.jsonl"), rank))
            restarts = [r for r in recs if r["event"] == "restart"]
            check(len(restarts) == 1
                  and restarts[0]["epoch"] == 1
                  and restarts[0]["step_in_epoch"] == 0,
                  f"rank {rank} resumed at epoch 1 step 0 — the fleet "
                  f"step, never ahead of it ({restarts})")
            meta = _final_meta_step(os.path.join(work, f"cp{rank}",
                                                 "resnet18-cifar"))
            check(meta == b_meta,
                  f"rank {rank} final checkpointed step matches baseline "
                  f"({meta} == {b_meta})")
            ev = _evals(recs)
            check(ev == b_eval and set(ev) == set(range(EPOCHS)),
                  f"rank {rank} per-epoch eval accuracy bitwise-equal to "
                  f"baseline ({ev} == {b_eval})")
            per_epoch: dict = {}
            for r in recs:
                if r["event"] == "eval":
                    per_epoch.setdefault(int(r["epoch"]),
                                         set()).add(r["accuracy"])
            check(all(len(v) == 1 for v in per_epoch.values()),
                  f"rank {rank} replayed evals bitwise identical "
                  f"({per_epoch})")

        # The aggregator over the per-rank streams: full coverage
        # required, and the replay must surface as duplicate_steps.
        report_path = os.path.join(work, "fleet_report.json")
        cli = subprocess.run(
            [sys.executable, "-m", "tpuic.telemetry.fleet", streams,
             "--require-ranks", str(RANKS), "--json", report_path],
            cwd=_REPO, env=env, text=True, capture_output=True,
            timeout=120)
        print(cli.stdout, end="")
        check(cli.returncode == 0,
              f"aggregator passed with --require-ranks {RANKS} "
              f"(exit {cli.returncode}; stderr "
              f"{cli.stderr.strip()[-200:]})")
        rep = (json.load(open(report_path))
               if os.path.exists(report_path) else {})
        dup = rep.get("duplicate_steps") or {}
        check(bool(dup),
              f"duplicate_steps surfaces the coordinated replay ({dup})")
        gate = subprocess.run(
            [sys.executable, "-m", "tpuic.telemetry.fleet", streams,
             "--require-ranks", str(RANKS + 1)],
            cwd=_REPO, env=env, text=True, capture_output=True,
            timeout=120)
        check(gate.returncode == 1,
              f"--require-ranks {RANKS + 1} fails on the missing rank "
              f"(exit {gate.returncode}) — the coverage gate is "
              "bidirectional")

        took = time.monotonic() - t_start
        if failures:
            print(f"\nFAIL: {len(failures)} assertion(s) in {took:.1f}s")
            for f in failures:
                print(f"  - {f}")
            return 1
        print(f"\nOK: gang soak green in {took:.1f}s — one coordinated "
              f"restart, survivor flushed 43, fleet-agreed resume at "
              f"step {STEPS_PER_EPOCH}, final metrics bitwise-equal to "
              "baseline, poison stops the gang")
        passed = True
        return 0
    finally:
        if baseline is not None and baseline.poll() is None:
            # An exception above (a timeout, a torn ledger) must not
            # leak a still-training baseline into the CI job.
            baseline.kill()
            baseline.wait()
        if args.keep or not passed:
            # Check failures AND unhandled exceptions both keep the
            # artifacts — the tier1.yml failure-upload step needs the
            # gang ledger and per-rank dumps to diagnose anything.
            print(f"workdir kept: {work}")
        else:
            shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
