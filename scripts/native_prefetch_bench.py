#!/usr/bin/env python
"""Native-decode prefetch before/after -> perf/native_prefetch.json.

The zero-cost-input claim, measured on the pinned CPU telemetry
workload (the regress gate's train.py invocation, forced onto the
**decode path** with --no-pack so every sample decodes in the Loader's
prefetch workers each epoch):

- **off**: --no-native — PIL decode + NumPy resize/augment/normalize
  per sample (the parity reference).
- **on**: the native core — ``decode_resize`` (libjpeg DCT-scaled /
  libpng + the shared nearest-resize index math) + the fused
  ``prep_image`` pass, still in the same prefetch workers, now cheap
  enough that decode keeps ahead of the (tiny, CPU) train step.

The artifact records the per-step telemetry ``input`` (data-wait)
bucket and the goodput ``frac_input`` both ways, plus a **parity**
block: one batch loaded through both paths must match exactly (PNG
fixtures — the native decode is bitwise the NumPy path there, pinned
by tests/test_native.py).

    python scripts/native_prefetch_bench.py --out perf/native_prefetch.json
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import subprocess
import sys
import tempfile

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def _run_workload(work: str, steps: int, native: bool) -> dict:
    from tpuic.data.synthetic import make_synthetic_imagefolder
    from tpuic.telemetry.events import read_jsonl

    data = os.path.join(work, "data")
    if not os.path.isdir(data):
        make_synthetic_imagefolder(data, classes=("a", "b", "c"),
                                   per_class=8, size=32)
    tag = "native" if native else "numpy"
    jsonl = os.path.join(work, f"events_{tag}.jsonl")
    env = dict(os.environ, JAX_PLATFORMS="cpu", TF_CPP_MIN_LOG_LEVEL="3")
    env.pop("TPUIC_TRACE", None)
    env.pop("TPUIC_FAULTS", None)
    cmd = [sys.executable, os.path.join(_REPO, "train.py"),
           "--datadir", data, "--model", "resnet18-cifar",
           "--resize", "32", "--batchsize", "2",
           "--epochs", str(steps // 12 + 1), "--optimizer", "adam",
           "--lr", "1e-3", "--no-class-weights", "--log-every-steps", "1",
           "--ckpt-dir", os.path.join(work, f"cp_{tag}"),
           "--steps", str(steps), "--metrics-jsonl", jsonl,
           "--no-pack"] + ([] if native else ["--no-native"])
    proc = subprocess.run(cmd, cwd=_REPO, env=env, text=True,
                          capture_output=True, timeout=1200)
    if proc.returncode != 0:
        raise RuntimeError(f"workload ({tag}) exited {proc.returncode}:\n"
                           f"{proc.stdout[-1200:]}\n{proc.stderr[-1200:]}")
    recs = read_jsonl(jsonl)
    steps_ev = [r for r in recs if r["event"] == "step"]
    final = [r for r in recs if r["event"] == "goodput"
             and r.get("final")][0]
    data_ms = [float(r.get("data_ms", 0.0)) for r in steps_ev[1:]]
    return {
        "steps": len(steps_ev),
        "input_ms_mean": round(sum(data_ms) / max(1, len(data_ms)), 3),
        "input_ms_max": round(max(data_ms or [0.0]), 3),
        "frac_input": final.get("frac_input"),
        "input_s_total": final.get("input_s"),
    }


def _parity(work: str) -> dict:
    """One sample loaded through both paths must be identical (PNG)."""
    import dataclasses

    import numpy as np

    from tpuic.config import DataConfig
    from tpuic.data.folder import ImageFolderDataset

    data = os.path.join(work, "data")
    cfg = DataConfig(data_dir=data, resize_size=32, native=True)
    ds_nat = ImageFolderDataset(data, "train", 32, cfg)
    ds_np = ImageFolderDataset(data, "train", 32,
                               dataclasses.replace(cfg, native=False))
    worst = 0.0
    for idx in range(0, len(ds_nat), 3):
        rng1 = np.random.default_rng([0, 0, idx])
        rng2 = np.random.default_rng([0, 0, idx])
        a, la, ia = ds_nat.load(idx, rng1)
        b, lb, ib = ds_np.load(idx, rng2)
        assert (la, ia) == (lb, ib)
        worst = max(worst, float(np.abs(a - b).max()))
    if worst > 2e-5:  # color-op float rounding; geometry is bitwise
        raise AssertionError(f"native/NumPy parity broken: {worst}")
    return {"samples_checked": len(range(0, len(ds_nat), 3)),
            "max_abs_diff": worst}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--steps", type=int, default=12)
    p.add_argument("--out", default=os.path.join("perf",
                                                 "native_prefetch.json"))
    p.add_argument("--workdir", default="")
    args = p.parse_args(argv)

    from tpuic import native
    work = args.workdir or tempfile.mkdtemp(prefix="tpuic_native_bench_")
    os.makedirs(work, exist_ok=True)
    try:
        off = _run_workload(work, args.steps, native=False)
        on = _run_workload(work, args.steps, native=True)
        parity = _parity(work)
        out = {
            "metric": "input_bucket_ms_native_prefetch",
            "workload": {"train_steps": args.steps, "batch": 2,
                         "size": 32, "path": "decode (--no-pack)"},
            "native_core": {"prep": native.available(),
                            "decode": native.decode_available()},
            "numpy_path": off,
            "native_path": on,
            "input_ms_mean_reduction": round(
                off["input_ms_mean"] - on["input_ms_mean"], 3),
            "parity": parity,
            "note": ("pinned CPU telemetry workload forced onto the "
                     "per-epoch decode path; the production packed path "
                     "already measures ~0 input by serving memmap rows "
                     "(docs/performance.md). The native decode+prep in "
                     "the prefetch workers is the same win for the "
                     "unpacked/first-epoch case."),
        }
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(out, f, indent=2)
        print(json.dumps({k: out[k] for k in
                          ("numpy_path", "native_path",
                           "input_ms_mean_reduction", "parity")},
                         indent=None))
        return 0
    finally:
        if not args.workdir:
            shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
