#!/usr/bin/env python
"""Bulk-score soak (ISSUE 17 acceptance; runs in tier-1 CI).

The end-to-end proof of elastic bulk scoring (``python -m tpuic.score``
— TWO real worker processes on CPU sharing a results directory via the
file lease queue), raced against an UNDISTURBED single-worker baseline
over the same corpus and the same trained checkpoint:

- rank 1 is armed with ``scorer_crash@1#1``: it is SIGKILLed at its
  FIRST shard commit, in the nastiest window — result file linked into
  place, CRC manifest and ledger record not yet written;
- this soak is the launcher: it books the death into the PR-15
  membership file (init -> degrade -> rejoin) and launches a
  replacement rank 1, which picks up fresh leases mid-corpus;
- the survivors adopt the dead rank's published-but-unmanifested shard
  and RECOVER its missing ledger record (``recovered: true``) — a
  committed shard is never rescored, an uncommitted one never dropped;
- the fleet audit (``python -m tpuic.telemetry.fleet --score-ledger``)
  exits 0 on both jobs: scored + quarantined == corpus per shard and in
  total, ZERO duplicate commit records, zero drops;
- every per-shard result file is BITWISE equal between the disturbed
  elastic run and the undisturbed baseline (canonical result bytes);
- every worker's ``score_done`` reports ZERO steady-state compiles
  (the int8 ladder is warmed before the counter is zeroed);

plus both bidirectional arms: a seeded ``shard_corrupt@2#1`` lands
exactly one row in the ledger's quarantined column with the accounting
still exact (audit exit 0), and a tampered ledger copy — one commit
record duplicated, then one dropped — fails the audit loudly (exit 1).

Exit 0 on success.   python scripts/score_soak.py [--keep] [-v]
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)

from tpuic.runtime.membership import (ENV_MEMBERSHIP_FILE,  # noqa: E402
                                      Membership, write_membership)
from tpuic.telemetry.fleet import (ENV_FLEET_RANK,  # noqa: E402
                                   ENV_FLEET_RANKS)

RANKS = 2
CRASH_RANK = 1
PER_CLASS = 16          # 2 classes x 16 -> 32-row val corpus
SHARD_SIZE = 4          # -> 8 shards: both ranks provably mid-corpus
BATCH = 4
DTYPE = "int8"          # the quant ladder rung the scorer defaults to
MODEL = "resnet18-cifar"
RESIZE = 24


def _score_cmd(data: str, out: str, ckpt: str) -> list:
    return [sys.executable, "-m", "tpuic.score",
            "--datadir", data, "--out", out, "--ckpt-dir", ckpt,
            "--model", "auto", "--dtype", DTYPE,
            "--shard-size", str(SHARD_SIZE), "--batchsize", str(BATCH),
            "--ttl", "10", "--poll", "0.1"]


def _events(paths: list) -> list:
    from tpuic.telemetry.events import read_jsonl
    recs: list = []
    for p in paths:
        recs.extend(read_jsonl(p, on_torn=lambda ln: print(
            f"  [soak] skipping torn jsonl line: {ln[:80]!r}")))
    return recs


def _audit(out: str, env: dict, report_path: str, prom: str = "") -> int:
    cmd = [sys.executable, "-m", "tpuic.telemetry.fleet", out,
           "--score-ledger", "--json", report_path]
    if prom:
        cmd += ["--prom-dump", prom]
    cli = subprocess.run(cmd, cwd=_REPO, env=env, text=True,
                         capture_output=True, timeout=120)
    print(cli.stdout, end="")
    if cli.returncode != 0:
        print(cli.stderr, end="", file=sys.stderr)
    return cli.returncode


def main() -> int:
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--workdir", default="",
                   help="run here instead of a temp dir (CI passes a "
                        "fixed path so the ledgers / membership file / "
                        "per-rank streams can be uploaded on failure)")
    p.add_argument("--keep", action="store_true")
    p.add_argument("-v", "--verbose", action="store_true")
    args = p.parse_args()

    t_start = time.monotonic()
    work = args.workdir or tempfile.mkdtemp(prefix="tpuic_score_")
    os.makedirs(work, exist_ok=True)
    failures: list = []
    passed = False

    def check(ok: bool, msg: str) -> None:
        print(("  ok  " if ok else "  FAIL") + f" {msg}")
        if not ok:
            failures.append(msg)

    try:
        # -- corpus + a real trained checkpoint --------------------------
        from tpuic.data.synthetic import make_synthetic_imagefolder
        data = os.path.join(work, "data")
        make_synthetic_imagefolder(data, classes=("a", "b"),
                                   per_class=PER_CLASS, size=RESIZE)
        n_corpus = 2 * PER_CLASS
        n_shards = n_corpus // SHARD_SIZE
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   TF_CPP_MIN_LOG_LEVEL="3", XLA_FLAGS="",
                   PYTHONPATH=_REPO,
                   JAX_COMPILATION_CACHE_DIR=os.path.join(work,
                                                          "jax_cache"))
        env.pop("TPUIC_FAULTS", None)
        sink = None if args.verbose else subprocess.DEVNULL
        ckpt = os.path.join(work, "ckpt")
        print(f"[soak] training the tiny {MODEL} checkpoint the corpus "
              "is scored against")
        train = subprocess.run(
            [sys.executable, os.path.join(_REPO, "train.py"),
             "--datadir", data, "--model", MODEL, "--resize", str(RESIZE),
             "--batchsize", "8", "--epochs", "1", "--optimizer", "sgd",
             "--lr", "0.01", "--no-class-weights", "--workers", "2",
             "--save-period", "1", "--ckpt-dir", ckpt, "--cache-dir",
             os.path.join(work, "cache")],
            cwd=_REPO, env=env, stdout=sink, stderr=sink, timeout=600)
        check(train.returncode == 0,
              f"trainer produced the checkpoint (exit {train.returncode})")
        if failures:
            return 1

        # -- undisturbed single-worker baseline --------------------------
        out_base = os.path.join(work, "score_base")
        print("[soak] baseline: one undisturbed worker over the corpus")
        base = subprocess.run(_score_cmd(data, out_base, ckpt), cwd=_REPO,
                              env=env, stdout=sink, stderr=sink,
                              timeout=600)
        check(base.returncode == 0,
              f"baseline scorer exit 0 (got {base.returncode})")
        check(_audit(out_base, env,
                     os.path.join(work, "audit_base.json")) == 0,
              "baseline ledger audit exact (exit 0)")

        # -- the elastic 2-worker run under scorer_crash -----------------
        out_el = os.path.join(work, "score_elastic")
        member = os.path.join(work, "membership.json")
        write_membership(member, Membership(
            version=1, world=RANKS, active=list(range(RANKS)),
            resume_step=None, reason="init", t=time.time()))
        renv = [dict(env, **{ENV_FLEET_RANK: str(r),
                             ENV_FLEET_RANKS: str(RANKS),
                             ENV_MEMBERSHIP_FILE: member})
                for r in range(RANKS)]
        # Rank 1 dies at its FIRST commit, after the link, before the
        # manifest — the crash window the adopt/recover path exists for.
        renv[CRASH_RANK]["TPUIC_FAULTS"] = f"scorer_crash@1#{CRASH_RANK}"
        print(f"[soak] elastic fleet of {RANKS} workers; rank "
              f"{CRASH_RANK} armed scorer_crash@1#{CRASH_RANK}")
        # The armed rank launches first: it dies at its FIRST commit, so
        # a head start guarantees the kill fires even if the peer turns
        # out much faster — the peer is then provably mid-corpus when
        # the replacement picks up the pieces.
        w1 = subprocess.Popen(_score_cmd(data, out_el, ckpt), cwd=_REPO,
                              env=renv[CRASH_RANK], stdout=sink,
                              stderr=sink)
        w0 = subprocess.Popen(_score_cmd(data, out_el, ckpt), cwd=_REPO,
                              env=renv[0], stdout=sink, stderr=sink)
        rc1 = w1.wait(timeout=600)
        check(rc1 == -9, f"rank {CRASH_RANK} was SIGKILLed mid-corpus "
                         f"by scorer_crash (exit {rc1})")
        write_membership(member, Membership(
            version=2, world=RANKS, active=[0], resume_step=None,
            reason="degrade", rank=CRASH_RANK, t=time.time()))
        print(f"[soak] degrade booked; launching replacement rank "
              f"{CRASH_RANK}")
        renv[CRASH_RANK].pop("TPUIC_FAULTS")
        w1b = subprocess.Popen(_score_cmd(data, out_el, ckpt), cwd=_REPO,
                               env=renv[CRASH_RANK], stdout=sink,
                               stderr=sink)
        write_membership(member, Membership(
            version=3, world=RANKS, active=list(range(RANKS)),
            resume_step=None, reason="rejoin", rank=CRASH_RANK,
            t=time.time()))
        rc0 = w0.wait(timeout=600)
        rc1b = w1b.wait(timeout=600)
        check(rc0 == 0, f"survivor rank 0 finished the job (exit {rc0})")
        check(rc1b == 0, f"replacement rank {CRASH_RANK} finished "
                         f"cleanly (exit {rc1b})")

        # -- the verdict -------------------------------------------------
        report_path = os.path.join(work, "audit_elastic.json")
        prom_path = os.path.join(work, "score_elastic.prom")
        check(_audit(out_el, env, report_path, prom=prom_path) == 0,
              "elastic ledger audit exact (exit 0) despite the SIGKILL")
        rep = (json.load(open(report_path))
               if os.path.exists(report_path) else {})
        check(rep.get("n") == n_corpus
              and rep.get("shards_committed") == n_shards,
              f"all {n_shards} shards of the {n_corpus}-row corpus "
              f"committed ({rep.get('shards_committed')}/{rep.get('n')})")
        check(rep.get("rows_scored", -1) + rep.get("rows_quarantined", -1)
              == n_corpus and rep.get("rows_quarantined") == 0,
              f"scored + quarantined == corpus with nothing quarantined "
              f"({rep.get('rows_scored')} + {rep.get('rows_quarantined')})")
        check(rep.get("shards_duplicated") == 0,
              "ZERO duplicate commit records fleet-wide")
        check(rep.get("recovered_records", 0) >= 1,
              f"the dead rank's missing ledger record was RECOVERED by "
              f"a survivor ({rep.get('recovered_records')})")
        prom = open(prom_path).read() if os.path.exists(prom_path) else ""
        check("tpuic_score_ledger_exact 1" in prom,
              "prom exposition carries the exactness gauge")

        base_shards = sorted(glob.glob(os.path.join(out_base, "results",
                                                    "shard-*.jsonl")))
        el_shards = sorted(glob.glob(os.path.join(out_el, "results",
                                                  "shard-*.jsonl")))
        check(len(base_shards) == len(el_shards) == n_shards,
              f"both runs published all {n_shards} shard files")
        diff = [os.path.basename(b) for b, e in zip(base_shards, el_shards)
                if open(b, "rb").read() != open(e, "rb").read()]
        check(not diff,
              "every per-shard result file BITWISE equal to the "
              f"undisturbed baseline (diffs: {diff})")

        dones = [r for r in _events(sorted(
            glob.glob(os.path.join(out_el, "*.jsonl"))
            + glob.glob(os.path.join(out_base, "*.jsonl"))))
            if r.get("event") == "score_done"]
        check(len(dones) == 3,  # baseline + survivor + replacement
              f"every completed worker published score_done "
              f"({len(dones)}; the SIGKILLed life publishes none)")
        compiles = {(r.get("rank"), r.get("steady_compiles"))
                    for r in dones}
        check(all(c == 0 for _, c in compiles),
              f"ZERO steady-state compiles on every worker ({compiles})")

        # -- bidirectional arm: seeded shard_corrupt quarantines ---------
        out_q = os.path.join(work, "score_corrupt")
        print("[soak] bidirectional: shard_corrupt@2#1 must quarantine "
              "exactly one row, accounting still exact")
        q = subprocess.run(_score_cmd(data, out_q, ckpt), cwd=_REPO,
                           env=dict(env, TPUIC_FAULTS="shard_corrupt@2#1"),
                           stdout=sink, stderr=sink, timeout=600)
        check(q.returncode == 0,
              f"seeded-corruption scorer exit 0 (got {q.returncode})")
        qrep_path = os.path.join(work, "audit_corrupt.json")
        check(_audit(out_q, env, qrep_path) == 0,
              "quarantine kept the audit exact (exit 0)")
        qrep = (json.load(open(qrep_path))
                if os.path.exists(qrep_path) else {})
        check(qrep.get("rows_quarantined") == 1
              and qrep.get("rows_scored") == n_corpus - 1,
              f"exactly one row in the quarantined column "
              f"({qrep.get('rows_scored')} + {qrep.get('rows_quarantined')})")
        qcommits = [r for r in _events(sorted(glob.glob(
            os.path.join(out_q, "*.jsonl"))))
            if r.get("event") == "score_commit" and r.get("shard") == 2]
        check(len(qcommits) == 1 and qcommits[0]["quarantined"] == 1,
              f"shard 2's commit record carries the quarantined count "
              f"({[c.get('quarantined') for c in qcommits]})")

        # -- bidirectional arm: a tampered ledger fails loudly -----------
        print("[soak] bidirectional: tampered ledger copies must FAIL "
              "the audit")
        streams = sorted(glob.glob(os.path.join(out_el, "*.jsonl")))
        lines = [ln for s in streams
                 for ln in open(s).read().splitlines(keepends=True)]
        commit_ln = next(ln for ln in lines if '"score_commit"' in ln)
        tam_dup = os.path.join(work, "tampered_dup")
        os.makedirs(tam_dup, exist_ok=True)
        with open(os.path.join(tam_dup, "ledger.jsonl"), "w") as f:
            f.writelines(lines + [commit_ln])
        check(_audit(tam_dup, env,
                     os.path.join(work, "audit_dup.json")) == 1,
              "a DUPLICATED commit record fails the audit (exit 1)")
        tam_drop = os.path.join(work, "tampered_drop")
        os.makedirs(tam_drop, exist_ok=True)
        with open(os.path.join(tam_drop, "ledger.jsonl"), "w") as f:
            f.writelines(ln for ln in lines if ln != commit_ln)
        check(_audit(tam_drop, env,
                     os.path.join(work, "audit_drop.json")) == 1,
              "a DROPPED commit record fails the audit (exit 1)")

        took = time.monotonic() - t_start
        if failures:
            print(f"\nFAIL: {len(failures)} assertion(s) in {took:.1f}s")
            for f in failures:
                print(f"  - {f}")
            return 1
        print(f"\nOK: bulk-score soak green in {took:.1f}s — a worker "
              f"SIGKILLed inside the commit window lost nothing: the "
              f"fleet adopted its shard, recovered its ledger record, "
              f"the audit is exact, and every result byte matches the "
              f"undisturbed baseline")
        passed = True
        return 0
    finally:
        for proc in ("w0", "w1", "w1b"):
            h = locals().get(proc)
            if h is not None and h.poll() is None:
                h.kill()
                h.wait()
        if args.keep or not passed:
            print(f"workdir kept: {work}")
        else:
            shutil.rmtree(work, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
