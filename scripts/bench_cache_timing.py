#!/usr/bin/env python
"""Measure bench.py wall-clock with a cold vs warm persistent compile cache.

VERDICT r4 item 5a: the round-end BENCH capture has lost to tunnel flaps
twice; the mitigation is the persistent compile cache (bench.py sets
jax_compilation_cache_dir) shrinking a live bench from ~30s+ of compile to
seconds, widening the window any flap leaves. This script produces the
before/after evidence. Deleting cache entries would be unsafe (the cache
dir is shared with the test suite), so instead it runs bench.py twice
back-to-back and reports each run's wall clock and the child-reported
compile_s — run 2 demonstrates the warm-cache bench cost. Writes
perf/bench_cache_timing.json.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import time

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_once(tag: str) -> dict:
    t0 = time.perf_counter()
    # NO_WAIT: this script's artifact IS the children's wall clock; the
    # pre-bench contention wait (bench.py:_wait_for_measurements) would
    # silently inflate it by up to 180 s per run.
    env = dict(os.environ, TPUIC_BENCH_NO_WAIT="1")
    proc = subprocess.run([sys.executable, os.path.join(_REPO, "bench.py")],
                          capture_output=True, text=True, timeout=900,
                          env=env)
    wall = time.perf_counter() - t0
    line = {}
    for ln in reversed((proc.stdout or "").strip().splitlines()):
        try:
            line = json.loads(ln)
            break
        except ValueError:
            continue
    return {
        "tag": tag,
        "wall_s": round(wall, 1),
        "compile_s": line.get("detail", {}).get("compile_s"),
        "platform": line.get("detail", {}).get("platform"),
        "value": line.get("value"),
        "error": line.get("error"),
    }


def main() -> None:
    runs = [run_once("run1"), run_once("run2_warm_cache")]
    result = {"runs": runs,
              "note": "run2's wall_s/compile_s is the warm-persistent-cache "
                      "bench cost — the window a tunnel flap must leave for "
                      "a live round-end BENCH line"}
    with open(os.path.join(_REPO, "perf", "bench_cache_timing.json"),
              "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
