#!/usr/bin/env python
"""Profile the ResNet-50 train step on the chip and break down device time.

Captures a jax.profiler trace of a few steady-state steps, parses the
XPlane with jax.profiler.ProfileData, and aggregates TPU op time by HLO
category (convolution / fusion kinds / all-reduce / copy...). Output feeds
PERF_ANALYSIS.md (VERDICT r2 weak #1: "no profile trace" was the gap).

Usage: python scripts/perf_profile.py [--batch 128] [--steps 10]
"""

from __future__ import annotations

import argparse
import collections
import glob
import json
import os
import re
import sys

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, _REPO)


def capture(per_chip_batch: int, n_steps: int, trace_dir: str,
            model: str = "resnet50") -> dict:
    import jax
    import jax.numpy as jnp

    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, "tests", ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)

    from tpuic.config import ModelConfig, OptimConfig
    from tpuic.data.synthetic import synthetic_batch
    from tpuic.models import create_model
    from tpuic.train.optimizer import make_optimizer
    from tpuic.train.state import create_train_state
    from tpuic.train.step import make_train_step

    n_chips = jax.device_count()
    global_batch = per_chip_batch * n_chips
    size = 224
    mcfg = ModelConfig(name=model, num_classes=1000, dtype="bfloat16")
    ocfg = OptimConfig(optimizer="sgd", learning_rate=0.1, class_weights=(),
                       milestones=())
    m = create_model(mcfg.name, mcfg.num_classes, dtype=mcfg.dtype)
    state = create_train_state(m, make_optimizer(ocfg), jax.random.key(0),
                               (global_batch, size, size, 3))
    batch = synthetic_batch(global_batch, size, mcfg.num_classes)
    batch = {k: jax.device_put(jnp.asarray(v)) for k, v in batch.items()}
    step = make_train_step(ocfg, mcfg, None, donate=True)
    state, mtr = step(state, batch)  # compile
    float(mtr["loss"])
    jax.profiler.start_trace(trace_dir)
    for _ in range(n_steps):
        state, mtr = step(state, batch)
    float(mtr["loss"])
    jax.profiler.stop_trace()
    return {"global_batch": global_batch, "n_steps": n_steps}


def analyze(trace_dir: str, n_steps: int, top: int = 30) -> dict:
    from jax.profiler import ProfileData
    paths = sorted(glob.glob(os.path.join(
        trace_dir, "**", "*.xplane.pb"), recursive=True), key=os.path.getmtime)
    if not paths:
        raise FileNotFoundError(f"no xplane under {trace_dir}")
    data = ProfileData.from_file(paths[-1])
    by_name = collections.Counter()
    by_cat = collections.Counter()
    total_ns = 0
    for plane in data.planes:
        if not plane.name.startswith("/device:TPU"):
            continue
        for line in plane.lines:
            # 'XLA Ops' carries per-op exclusive device time. 'Async XLA
            # Ops' are overlapped copies (their duration includes waiting —
            # counting them double-books the step); 'Steps'/'XLA Modules'
            # span whole steps.
            if line.name != "XLA Ops":
                continue
            for ev in line.events:
                dur = ev.duration_ns
                name = ev.name
                if dur <= 0:
                    continue
                total_ns += dur
                by_name[name] += dur
                cat = _categorize(name)
                by_cat[cat] += dur
    result = {
        "trace": paths[-1],
        "total_device_ms": round(total_ns / 1e6, 2),
        "per_step_ms": round(total_ns / 1e6 / max(n_steps, 1), 3),
        "by_category_ms": {k: round(v / 1e6, 2)
                           for k, v in by_cat.most_common()},
        "top_ops_ms": {k: round(v / 1e6, 2)
                       for k, v in by_name.most_common(top)},
    }
    return result


def _categorize(name: str) -> str:
    n = name.lower()
    if "conv" in n and "fusion" not in n:
        return "convolution"
    if n.startswith(("all-reduce", "all-gather", "reduce-scatter",
                     "collective")):
        return "collective"
    if n.startswith("copy") or "transpose" in n:
        return "copy/transpose"
    if "fusion" in n:
        m = re.match(r"(loop_|input_|output_|scatter_)?fusion", n)
        return (m.group(1) or "") + "fusion" if m else "fusion"
    if n.startswith(("dynamic-update-slice", "dynamic-slice")):
        return "slice"
    if n.startswith(("reduce", "scatter")):
        return "reduce/scatter"
    if "dot" in n or "einsum" in n:
        return "matmul"
    if n.startswith("infeed") or n.startswith("outfeed"):
        return "infeed/outfeed"
    return "other"


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=128)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--model", default="resnet50")
    ap.add_argument("--trace-dir", default=os.path.join(_REPO, "perf",
                                                        "trace"))
    ap.add_argument("--out", default=os.path.join(_REPO, "perf",
                                                  "profile.json"))
    ap.add_argument("--analyze-only", action="store_true")
    args = ap.parse_args()
    os.makedirs(args.trace_dir, exist_ok=True)
    if not args.analyze_only:
        meta = capture(args.batch, args.steps, args.trace_dir,
                       model=args.model)
    else:
        meta = {"n_steps": args.steps}
    result = {**meta, **analyze(args.trace_dir, args.steps)}
    with open(args.out, "w") as f:
        json.dump(result, f, indent=2)
    print(json.dumps({k: v for k, v in result.items()
                      if k != "top_ops_ms"}, indent=2))
    print("top ops:")
    for k, v in list(result["top_ops_ms"].items())[:20]:
        print(f"  {v:9.2f} ms  {k}")
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
