#!/usr/bin/env python
"""Input-pipeline throughput benchmark, per host.

SURVEY.md §7 names the input pipeline the #1 hard part (the reference's
analogue is ``DataLoader(num_workers=6, pin_memory=True)``, train.py:114).
Round-3 context: this host has ONE core (nproc=1), so the per-epoch-decode
path tops out around ~220 img/s no matter the worker count — the production
path is the packed uint8 cache (tpuic/data/pack.py): decode once, serve
epochs from a memmap with augmentation/normalization on the accelerator
(tpuic/data/device_prep.py).

Measures, over a synthetic ImageFolder tree:
  - decode-per-epoch Loader grid (native C++ prep on/off x workers) — the
    legacy path, kept for comparison;
  - one-time pack build rate (native libjpeg/libpng decode);
  - the packed Loader's steady-state images/sec/host (headline value).

Prints one JSON line:
  {"metric": "loader_images_per_sec_per_host", "value": N, "unit": ...,
   "detail": {...}}
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import time

# The decode-path grid needs no accelerator, but the packed path's
# augment/normalize runs on the default platform (TPU when present) —
# matching production. This image's sitecustomize force-registers a remote
# TPU backend whose init HANGS when the tunnel is down (round-1/2 failure
# mode), so TPU reachability is probed in a killable child process first;
# unreachable (or TPUIC_DATA_BENCH_CPU=1) falls back to CPU.
from tpuic.runtime.axon_guard import ensure_reachable_or_cpu, force_cpu  # noqa: E402

if os.environ.get("TPUIC_DATA_BENCH_CPU") \
        or os.environ.get("JAX_PLATFORMS") == "cpu":
    force_cpu()  # also pins jax.config — env alone loses to sitecustomize
else:
    # always_probe: a benchmark must emit a number on ANY backend failure
    # (a held chip raises rather than hangs), tunneled or not.
    ensure_reachable_or_cpu(
        timeout=float(os.environ.get("TPUIC_DATA_BENCH_PROBE_S", "120")),
        always_probe=True)
import jax  # noqa: E402


def _measure(loader, epochs=2, start=1) -> float:
    n = 0
    # epoch 0 warms file cache, thread pools, and jit caches; then timed.
    for batch in loader.epoch(0):
        last = batch["image"]
    jax.block_until_ready(last) if hasattr(last, "devices") else None
    t0 = time.perf_counter()
    for e in range(start, start + epochs):
        for batch in loader.epoch(e):
            n += int(batch["image"].shape[0])
            last = batch["image"]
        if hasattr(last, "devices"):
            jax.block_until_ready(last)
    return n / (time.perf_counter() - t0)


def main() -> None:
    from tpuic.config import DataConfig
    from tpuic.data.folder import ImageFolderDataset
    from tpuic.data.pack import pack_dataset
    from tpuic.data.pipeline import Loader
    from tpuic.data.synthetic import make_synthetic_imagefolder
    from tpuic.native import available as native_available

    size = int(os.environ.get("TPUIC_DATA_BENCH_SIZE", "224"))
    per_class = int(os.environ.get("TPUIC_DATA_BENCH_PER_CLASS", "64"))
    batch = int(os.environ.get("TPUIC_DATA_BENCH_BATCH", "32"))
    packed_epochs = int(os.environ.get("TPUIC_DATA_BENCH_EPOCHS", "8"))

    root = tempfile.mkdtemp(prefix="tpuic_databench_")
    try:
        make_synthetic_imagefolder(root, classes=("a", "b", "c", "d"),
                                   per_class=per_class, size=size)
        results = {}
        for native in ([True, False] if native_available() else [False]):
            cfg = DataConfig(data_dir=root, resize_size=size, native=native,
                             pack=False)
            ds = ImageFolderDataset(root, "train", size, cfg)
            for workers in (1, 6):
                loader = Loader(ds, batch, mesh=None, shuffle=True,
                                num_workers=workers, prefetch=4)
                key = f"decode,native={native},workers={workers}"
                results[key] = round(_measure(loader), 1)

        # Production path: pack once (decode cost paid once per dataset),
        # then serve from the memmap with device-side augmentation.
        cfg = DataConfig(data_dir=root, resize_size=size)
        ds = ImageFolderDataset(root, "train", size, cfg)
        t0 = time.perf_counter()
        packed = pack_dataset(ds, os.path.join(root, ".tpuic_pack"),
                              verbose=False)
        results["pack_build"] = round(len(ds) / (time.perf_counter() - t0), 1)
        loader = Loader(packed, batch, mesh=None, shuffle=True, prefetch=4)
        packed_rate = round(_measure(loader, epochs=packed_epochs), 1)
        results["packed"] = packed_rate

        print(json.dumps({
            "metric": "loader_images_per_sec_per_host",
            "value": packed_rate,
            "unit": "images/sec/host",
            "detail": {"image_size": size, "batch": batch,
                       "n_images": per_class * 4,
                       "platform": jax.devices()[0].platform,
                       "grid": results},
        }))
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
