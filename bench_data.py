#!/usr/bin/env python
"""Input-pipeline throughput benchmark: real PNG decode -> augment ->
normalize -> batched host arrays, per host.

SURVEY.md §7 names the input pipeline the #1 hard part (the reference's
analogue is ``DataLoader(num_workers=6, pin_memory=True)``, train.py:114).
This measures images/sec/host through ``tpuic.data.Loader`` over a synthetic
ImageFolder tree (so it runs anywhere), comparing worker-thread counts and
the fused C++ prep core vs the pure-NumPy path.

Prints one JSON line:
  {"metric": "loader_images_per_sec_per_host", "value": N, "unit": ...,
   "detail": {...grid of configs...}}
"""

from __future__ import annotations

import json
import os
import shutil
import tempfile
import time

# Loader bench needs no accelerator; force CPU *before* any jax import and
# again via jax.config (this image's sitecustomize force-registers a remote
# TPU backend whose init can hang — see tests/conftest.py).
os.environ["JAX_PLATFORMS"] = "cpu"
import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def _measure(loader, epochs=2) -> float:
    n = 0
    # epoch 0 warms file cache + thread pools; epoch 1+ timed
    for _ in loader.epoch(0):
        pass
    t0 = time.perf_counter()
    for e in range(1, 1 + epochs):
        for batch in loader.epoch(e):
            n += int(batch["image"].shape[0])
    return n / (time.perf_counter() - t0)


def main() -> None:
    from tpuic.config import DataConfig
    from tpuic.data.folder import ImageFolderDataset
    from tpuic.data.pipeline import Loader
    from tpuic.data.synthetic import make_synthetic_imagefolder
    from tpuic.native import available as native_available

    size = int(os.environ.get("TPUIC_DATA_BENCH_SIZE", "224"))
    per_class = int(os.environ.get("TPUIC_DATA_BENCH_PER_CLASS", "64"))
    batch = int(os.environ.get("TPUIC_DATA_BENCH_BATCH", "32"))

    root = tempfile.mkdtemp(prefix="tpuic_databench_")
    try:
        make_synthetic_imagefolder(root, classes=("a", "b", "c", "d"),
                                   per_class=per_class, size=size)
        results = {}
        for native in ([True, False] if native_available() else [False]):
            cfg = DataConfig(data_dir=root, resize_size=size, native=native)
            ds = ImageFolderDataset(root, "train", size, cfg)
            for workers in (1, 6, max(1, (os.cpu_count() or 8) - 2)):
                loader = Loader(ds, batch, mesh=None, shuffle=True,
                                num_workers=workers, prefetch=4)
                key = f"native={native},workers={workers}"
                results[key] = round(_measure(loader), 1)
        best = max(results.values())
        print(json.dumps({
            "metric": "loader_images_per_sec_per_host",
            "value": best,
            "unit": "images/sec/host",
            "detail": {"image_size": size, "batch": batch,
                       "n_images": per_class * 4, "grid": results},
        }))
    finally:
        shutil.rmtree(root, ignore_errors=True)


if __name__ == "__main__":
    main()
