#!/usr/bin/env python
"""Benchmark: dynamic-batching serve engine vs sequential per-request path.

Drives `tpuic.serve.InferenceEngine` with a synthetic mixed-size request
stream (sizes 1..max_bucket, seeded) at several offered loads and records
the throughput/latency curve, plus the two numbers the tentpole claims:

- **steady_state_compiles = 0**: after warmup, the whole stream performs
  no new lowerings (the executable-cache contract, also pinned by
  tests/test_serve.py::test_compile_counter_flat_after_warmup);
- **vs_sequential >= 2**: batched-engine throughput over the sequential
  baseline that calls a per-shape ``jax.jit`` forward once per request —
  exactly what a caller looping over `tpuic.predict`'s old forward did.
  The baseline is measured STEADY (every shape pre-compiled); the cold
  number (first-pass, compiles on the clock) is recorded alongside as
  ``sequential_cold`` — that is what a fresh process actually pays.

CPU synthetic by design (the artifact is comparative, not a chip
number): JAX_PLATFORMS=cpu is forced, and the persistent compilation
cache (shared with the test suite) keeps reruns cheap.

    python bench_serve.py --out perf/bench_serve.json

Prints one JSON line (bench.py convention) and writes the artifact.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import threading
import time

_REPO = os.path.dirname(os.path.abspath(__file__))


def _force_cpu() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    from tpuic.runtime.axon_guard import drop_axon_vars
    drop_axon_vars(os.environ)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, "tests", ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def _request_stream(n_requests: int, max_size: int, size: int, seed: int):
    """Seeded mixed-size uint8 request list — identical for every path."""
    import numpy as np
    rng = np.random.default_rng(seed)
    reqs = []
    for _ in range(n_requests):
        rows = int(rng.integers(1, max_size + 1))
        reqs.append(rng.integers(0, 256, (rows, size, size, 3), np.uint8))
    return reqs


def _sequential(forward, variables, reqs) -> dict:
    """The old path: one jitted call per request at its natural shape.
    First pass pays one trace+compile per DISTINCT size (cold), second
    pass is steady-state."""
    import jax
    jfwd = jax.jit(forward)

    def one_pass():
        t0 = time.perf_counter()
        for r in reqs:
            probs, order = jfwd(variables, r)
        jax.block_until_ready((probs, order))
        return time.perf_counter() - t0

    cold_s = one_pass()
    steady_s = one_pass()
    images = sum(r.shape[0] for r in reqs)
    return {
        "requests": len(reqs),
        "images": images,
        "distinct_shapes": len({r.shape[0] for r in reqs}),
        "cold_s": round(cold_s, 3),
        "cold_images_per_sec": round(images / cold_s, 2),
        "steady_s": round(steady_s, 3),
        "steady_images_per_sec": round(images / steady_s, 2),
    }


def _engine_run(engine, reqs, rate: float) -> dict:
    """Offer the stream at ``rate`` requests/sec (0 = as fast as possible)
    from a feeder thread; wall clock spans first submit -> last result."""
    engine.stats.reset()
    compiles_before = engine.stats.compiles
    futs = [None] * len(reqs)
    t0 = time.perf_counter()

    def feed():
        for i, r in enumerate(reqs):
            if rate > 0:
                target = t0 + i / rate
                delay = target - time.perf_counter()
                if delay > 0:
                    time.sleep(delay)
            futs[i] = engine.submit(r)

    feeder = threading.Thread(target=feed)
    feeder.start()
    feeder.join()
    for f in futs:
        f.result(timeout=600)
    wall = time.perf_counter() - t0
    # Futures resolve BEFORE the batcher's record_done runs — give the
    # final batch's counters a bounded moment to land so the recorded
    # curve isn't short a batch; images comes from the stream itself.
    deadline = time.perf_counter() + 2.0
    while (engine.stats.snapshot()["requests"] < len(reqs)
           and time.perf_counter() < deadline):
        time.sleep(0.01)
    snap = engine.stats.snapshot()
    images = sum(r.shape[0] for r in reqs)
    return {
        "offered_rate_req_per_sec": rate if rate > 0 else "max",
        "wall_s": round(wall, 3),
        "images_per_sec": round(images / wall, 2),
        "requests_per_sec": round(len(reqs) / wall, 2),
        "latency_ms": snap["latency_ms"],
        "queue_wait_ms": snap["queue_wait_ms"],
        "batch_hist": snap["batch_hist"],
        "pad_efficiency": snap["pad_efficiency"],
        "device_calls": snap["device_calls"],
        "compiles_during_run": snap["compiles"] - compiles_before,
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet18-cifar")
    p.add_argument("--num-classes", type=int, default=10)
    p.add_argument("--size", type=int, default=24)
    p.add_argument("--buckets", default="1,4,16,32")
    p.add_argument("--requests", type=int, default=64)
    p.add_argument("--max-req-size", type=int, default=1,
                   help="request sizes drawn uniformly from 1..this. "
                        "Default 1 = the canonical online case (one image "
                        "per request); larger caller-side batches hand the "
                        "sequential baseline free batching and narrow the "
                        "engine's ratio (recorded in detail.note)")
    p.add_argument("--rates", default="10,25,0",
                   help="offered loads in req/s; 0 = max")
    p.add_argument("--max-wait-ms", type=float, default=5.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--out", default=os.path.join("perf", "bench_serve.json"))
    args = p.parse_args(argv)

    _force_cpu()
    import jax
    import jax.numpy as jnp

    from tpuic.models import create_model
    from tpuic.serve import InferenceEngine, make_forward

    buckets = tuple(int(b) for b in args.buckets.split(","))
    model = create_model(args.model, args.num_classes, dtype="float32")
    variables = model.init(jax.random.key(0),
                           jnp.zeros((1, args.size, args.size, 3),
                                     jnp.float32), train=False)
    # Serving-style forward: raw uint8 in, normalize fused into the
    # compiled program (both paths use the SAME forward — the comparison
    # isolates batching + AOT, not numerics).
    forward = make_forward(model, normalize=True)
    if args.max_req_size > buckets[-1]:
        # Validate up front: engine.submit would raise this inside the
        # feeder thread, where it surfaces as a useless NoneType crash.
        raise SystemExit(f"--max-req-size {args.max_req_size} exceeds the "
                         f"largest bucket {buckets[-1]}")
    reqs = _request_stream(args.requests, args.max_req_size,
                           args.size, args.seed)
    images = sum(r.shape[0] for r in reqs)

    seq = _sequential(forward, variables, reqs)

    import numpy as np
    engine = InferenceEngine(
        forward_fn=forward, variables=variables, image_size=args.size,
        input_dtype=np.uint8, buckets=buckets,
        max_wait_ms=args.max_wait_ms, queue_size=max(64, args.requests))
    warmup_s = engine.warmup()
    curves = []
    for rate_s in args.rates.split(","):
        curves.append(_engine_run(engine, reqs, float(rate_s)))
    engine.close()

    best = max(curves, key=lambda c: c["images_per_sec"])
    steady_compiles = sum(c["compiles_during_run"] for c in curves)
    result = {
        "metric": "serve_images_per_sec_cpu_synthetic",
        "value": best["images_per_sec"],
        "unit": "images/sec",
        "vs_sequential": round(best["images_per_sec"]
                               / seq["steady_images_per_sec"], 3),
        "steady_state_compiles": steady_compiles,
        "detail": {
            "platform": jax.devices()[0].platform,
            "device": getattr(jax.devices()[0], "device_kind", "unknown"),
            "model": args.model,
            "image_size": args.size,
            "buckets": list(buckets),
            "max_wait_ms": args.max_wait_ms,
            "requests": args.requests,
            "images": images,
            "warmup_compile_s": warmup_s,
            "offered_load_curve": curves,
            "sequential_baseline": seq,
            "vs_sequential_cold": round(best["images_per_sec"]
                                        / seq["cold_images_per_sec"], 3),
            "note": ("comparative CPU artifact: same forward, same request "
                     "stream; engine adds micro-batching + bucket-padded "
                     "AOT executables. vs_sequential is a strong function "
                     "of request size — callers that pre-batch hand the "
                     "sequential baseline free batching; sweep "
                     "--max-req-size to measure that curve yourself"),
        },
    }
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
    print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
