#!/usr/bin/env python
"""Benchmark: dynamic-batching serve engine vs sequential per-request path.

Drives `tpuic.serve.InferenceEngine` with a synthetic mixed-size request
stream (sizes 1..max_bucket, seeded) at several offered loads and records
the throughput/latency curve, plus the two numbers the tentpole claims:

- **steady_state_compiles = 0**: after warmup, the whole stream performs
  no new lowerings (the executable-cache contract, also pinned by
  tests/test_serve.py::test_compile_counter_flat_after_warmup);
- **vs_sequential >= 2**: batched-engine throughput over the sequential
  baseline that calls a per-shape ``jax.jit`` forward once per request —
  exactly what a caller looping over `tpuic.predict`'s old forward did.
  The baseline is measured STEADY (every shape pre-compiled); the cold
  number (first-pass, compiles on the clock) is recorded alongside as
  ``sequential_cold`` — that is what a fresh process actually pays.

Plus an **open-loop (Poisson-arrival) saturation sweep**: submissions
follow a seeded Poisson process at a ladder of offered loads derived
from a max-rate probe, never waiting on results, and the artifact
records the **latency knee** — the highest offered load that stays
unsaturated with p99 within ``--knee-factor``x the lightest rung's p99
(``open_loop_knee_req_per_sec``). That curve is what the ROADMAP's
admission-control serve tier will defend; ``--no-open-loop`` skips it.

CPU synthetic by design (the artifact is comparative, not a chip
number): JAX_PLATFORMS=cpu is forced, and the persistent compilation
cache (shared with the test suite) keeps reruns cheap.

    python bench_serve.py --out perf/bench_serve.json

Prints one JSON line (bench.py convention) and writes the artifact.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

_REPO = os.path.dirname(os.path.abspath(__file__))


def _force_cpu() -> None:
    os.environ["JAX_PLATFORMS"] = "cpu"
    from tpuic.runtime.axon_guard import drop_axon_vars
    drop_axon_vars(os.environ)
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_compilation_cache_dir",
                      os.path.join(_REPO, "tests", ".jax_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
    jax.config.update("jax_persistent_cache_min_entry_size_bytes", 0)


def _request_stream(n_requests: int, max_size: int, size: int, seed: int):
    """Seeded mixed-size uint8 request list — identical for every path."""
    import numpy as np
    rng = np.random.default_rng(seed)
    reqs = []
    for _ in range(n_requests):
        rows = int(rng.integers(1, max_size + 1))
        reqs.append(rng.integers(0, 256, (rows, size, size, 3), np.uint8))
    return reqs


def _sequential(forward, variables, reqs) -> dict:
    """The old path: one jitted call per request at its natural shape.
    First pass pays one trace+compile per DISTINCT size (cold), second
    pass is steady-state."""
    import jax
    jfwd = jax.jit(forward)

    def one_pass():
        t0 = time.perf_counter()
        for r in reqs:
            probs, order = jfwd(variables, r)
        jax.block_until_ready((probs, order))
        return time.perf_counter() - t0

    cold_s = one_pass()
    steady_s = one_pass()
    images = sum(r.shape[0] for r in reqs)
    return {
        "requests": len(reqs),
        "images": images,
        "distinct_shapes": len({r.shape[0] for r in reqs}),
        "cold_s": round(cold_s, 3),
        "cold_images_per_sec": round(images / cold_s, 2),
        "steady_s": round(steady_s, 3),
        "steady_images_per_sec": round(images / steady_s, 2),
    }


def _engine_run(engine, reqs, rate: float) -> dict:
    """Offer the stream at ``rate`` requests/sec (0 = as fast as
    possible); wall clock spans first submit -> last result.  Driver is
    the shared ``tpuic.serve.loadgen`` harness (same one the
    perf-regression gate uses)."""
    from tpuic.serve import loadgen
    offsets = [i / rate for i in range(len(reqs))] if rate > 0 else None
    wall, _, snap = loadgen.run_stream(engine, reqs, offsets_s=offsets)
    images = sum(r.shape[0] for r in reqs)
    return {
        "offered_rate_req_per_sec": rate if rate > 0 else "max",
        "wall_s": round(wall, 3),
        "images_per_sec": round(images / wall, 2),
        "requests_per_sec": round(len(reqs) / wall, 2),
        "latency_ms": snap["latency_ms"],
        "queue_wait_ms": snap["queue_wait_ms"],
        "batch_hist": snap["batch_hist"],
        "pad_efficiency": snap["pad_efficiency"],
        "device_calls": snap["device_calls"],
        "compiles_during_run": snap["compiles"],
    }


def _poisson_run(engine, reqs, rate: float, seed: int,
                 grace_s: float, deadline_ms=None, dtype=None) -> dict:
    """Open-loop offered load: submissions follow a seeded Poisson
    process at ``rate`` req/s and never wait for results — the arrival
    process is independent of service, so queueing delay is *measured*,
    not hidden by a closed feedback loop.  (At deep saturation the
    bounded queue's backpressure blocks submit(), which shows up
    honestly as achieved < offered.)

    Saturation verdict: the backlog the run ends with.  After the last
    arrival, an engine that kept up drains within ~one service latency
    (``grace_s``); a backlog materially longer than that means requests
    were queueing faster than they were served.

    ``deadline_ms`` attaches that latency budget to every request
    (docs/serving.md, "Admission control and overload"): a request the
    engine cannot serve inside it is shed at pop time instead of
    queueing unboundedly, and the rung records the resulting
    ``shed_rate`` — the overload-defense curve next to the latency
    knee."""
    import numpy as np

    from tpuic.serve import loadgen
    rng = np.random.default_rng(seed)
    kw = {}
    if deadline_ms is not None:
        kw["deadline_ms"] = deadline_ms
    if dtype is not None:
        kw["dtype"] = dtype  # ladder rung (docs/performance.md)
    items = reqs if not kw else [(r, dict(kw)) for r in reqs]
    # Cumulative exponential gaps = a Poisson arrival process; handing
    # the shared driver precomputed offsets keeps arrivals independent
    # of service by construction.
    offsets = np.cumsum(rng.exponential(1.0 / rate, size=len(reqs)))
    wall, arrival_s, snap = loadgen.run_stream(engine, items,
                                               offsets_s=offsets)
    backlog_s = wall - arrival_s
    return {
        "offered_req_per_sec": round(rate, 2),
        "achieved_req_per_sec": round(snap["requests"] / wall, 2),
        "arrival_s": round(arrival_s, 3),
        "drain_backlog_s": round(backlog_s, 3),
        "saturated": bool(backlog_s > max(2.0 * grace_s,
                                          0.15 * arrival_s)),
        "latency_ms": snap["latency_ms"],
        "queue_wait_ms": snap["queue_wait_ms"],
        "span_ms": snap["span_ms"],
        "pad_efficiency": snap["pad_efficiency"],
        "device_calls": snap["device_calls"],
        "compiles_during_run": snap["compiles"],
        "shed": snap["rejected"],
        "shed_rate": round(snap["rejected"] / max(1, len(reqs)), 4),
    }


def _dtype_ladder_sweep(engine, size: int, n_req: int, seed: int,
                        knee_factor: float, tags, anchor: dict) -> dict:
    """Per-dtype open-loop knee: the SAME Poisson rate ladder (anchored
    once, to the shared dual probe) offered to each configured rung via
    run_stream's submit kwargs, so the rungs' knees are directly
    comparable.  Zero steady-state compiles asserted per rung from the
    run's own compile counters — the AOT contract holds for every
    (dtype, bucket) executable, not just fp32's."""
    reqs = _request_stream(n_req, 1, size, seed)
    unbatched_rps = anchor["unbatched_req_per_sec"]
    service_s = anchor["unbatched_service_ms"] / 1000.0
    ladder = {}
    for t_i, tag in enumerate(tags):
        curve, knee = [], None
        for i, frac in enumerate((0.5, 1.0, 1.5, 2.0, 3.0)):
            pt = _poisson_run(engine, reqs,
                              max(1.0, frac * unbatched_rps),
                              seed + 1000 * t_i + i, grace_s=service_s,
                              dtype=tag)
            pt["fraction_of_unbatched"] = frac
            curve.append(pt)
        base_p99 = curve[0]["latency_ms"].get("p99") or 0.0
        for pt in curve:
            p99 = pt["latency_ms"].get("p99") or 0.0
            if pt["saturated"] or p99 > knee_factor * max(base_p99, 1e-9):
                break
            knee = pt
        compiles = sum(pt["compiles_during_run"] for pt in curve)
        ladder[tag] = {
            "knee_req_per_sec": (knee["offered_req_per_sec"]
                                 if knee is not None else None),
            "knee_p50_ms": (knee["latency_ms"].get("p50")
                            if knee is not None else None),
            "knee_p99_ms": (knee["latency_ms"].get("p99")
                            if knee is not None else None),
            "steady_compiles": compiles,
            "curve": curve,
        }
    return ladder


def _open_loop_sweep(engine, size: int, n_req: int, seed: int,
                     knee_factor: float,
                     fractions=(0.5, 1.0, 1.5, 2.0, 3.0)) -> dict:
    """Drive the engine to saturation with Poisson arrivals and record
    the latency knee.

    The rate ladder is anchored to a *sequential single-request* probe
    (submit one, wait, repeat) with the probe's own queue/batch-formation
    spans stripped out — the service rate with no batching to hide
    behind and no coalescing stall inflating it.  Micro-batching lets
    the engine hold offered loads past 1x that rate, which is exactly
    the region the sweep maps: the knee
    is the highest offered load that is neither saturated (end-of-run
    backlog, see ``_poisson_run``) nor past ``knee_factor``x the
    lightest rung's p99 — the operating point admission control will
    defend."""
    from tpuic.serve import loadgen
    reqs = _request_stream(n_req, 1, size, seed)  # 1 img/req: online case
    # The shared stall-stripped capacity probe (loadgen.py): with the
    # default 5 ms max_wait and a ~2 ms forward, a raw sequential probe
    # would understate capacity ~3x and the sweep would never reach the
    # saturation region it exists to map.  Shared with the CI overload
    # soak, so the gate and this benchmark anchor identically.
    unbatched_rps, service_s, probe_raw_s, stall_s = \
        loadgen.probe_unbatched_rps(engine, reqs)
    # The OTHER half of the dual anchor (PR-9's overload-soak fix,
    # shared via loadgen): full-batching burst capacity.  Recording
    # BOTH probes in the artifact makes container-speed noise in the
    # committed knee (39.27 vs 68.8 req/s across runs of the same
    # machine class) diagnosable — a knee wobble with stable probes is
    # scheduler jitter; a knee wobble tracking the probes is the
    # machine — instead of silently absorbed.
    batched_rps = loadgen.probe_batched_rps(engine, reqs)
    curve, knee = [], None
    for i, frac in enumerate(fractions):
        pt = _poisson_run(engine, reqs, max(1.0, frac * unbatched_rps),
                          seed + i, grace_s=service_s)
        pt["fraction_of_unbatched"] = frac
        curve.append(pt)
    base_p99 = curve[0]["latency_ms"].get("p99") or 0.0
    for pt in curve:
        p99 = pt["latency_ms"].get("p99") or 0.0
        if pt["saturated"] or p99 > knee_factor * max(base_p99, 1e-9):
            # Stop at the FIRST bad rung: a later rung whose backlog
            # verdict wobbles back under the noise floor must not
            # report a knee beyond a load this same run measured as
            # saturated ("highest load that STAYS unsaturated").
            break
        knee = pt
    # Shed-rate curve (the admission layer's artifact, docs/serving.md):
    # the SAME rate ladder with every request carrying the knee-derived
    # latency budget (knee_factor x the lightest rung's p99 — the
    # boundary the knee itself is defined by).  Below the knee sheds
    # stay ~0; past it the engine sheds the unservable fraction at pop
    # time instead of letting every request's latency grow without
    # bound — overload becomes a shed percentage, not a collapse.
    shed_deadline_ms = round(knee_factor * max(base_p99, 1.0), 3)
    shed_curve = []
    for i, frac in enumerate(fractions):
        pt = _poisson_run(engine, reqs, max(1.0, frac * unbatched_rps),
                          seed + 100 + i, grace_s=service_s,
                          deadline_ms=shed_deadline_ms)
        shed_curve.append({
            "fraction_of_unbatched": frac,
            "offered_req_per_sec": pt["offered_req_per_sec"],
            "achieved_req_per_sec": pt["achieved_req_per_sec"],
            "shed": pt["shed"],
            "shed_rate": pt["shed_rate"],
            "served_p99_ms": pt["latency_ms"].get("p99"),
            "compiles_during_run": pt["compiles_during_run"],
        })
    return {
        "mode": "poisson_open_loop",
        "requests_per_rate": n_req,
        "probe_raw_ms": round(1000.0 * probe_raw_s, 3),
        "probe_coalesce_stall_ms": round(1000.0 * stall_s, 3),
        "unbatched_service_ms": round(1000.0 * service_s, 3),
        "unbatched_req_per_sec": round(unbatched_rps, 2),
        "batched_burst_req_per_sec": round(batched_rps, 2),
        "knee_factor": knee_factor,
        "curve": curve,
        "knee": ({"offered_req_per_sec": knee["offered_req_per_sec"],
                  "p99_ms": knee["latency_ms"].get("p99"),
                  "p50_ms": knee["latency_ms"].get("p50")}
                 if knee is not None else None),
        "shed_deadline_ms": shed_deadline_ms,
        "shed_curve": shed_curve,
        "note": ("knee = highest Poisson-offered load that stays "
                 "unsaturated (bounded end-of-run backlog) with p99 "
                 "within knee_factor x the lightest rung's p99; beyond "
                 "it latency is queueing, not service. shed_curve = the "
                 "same ladder with per-request deadline_ms = "
                 "shed_deadline_ms: past the knee the admission layer "
                 "sheds the unservable fraction at pop time instead of "
                 "letting latency grow without bound"),
    }


def main(argv=None) -> int:
    p = argparse.ArgumentParser()
    p.add_argument("--model", default="resnet18-cifar")
    p.add_argument("--num-classes", type=int, default=10)
    p.add_argument("--size", type=int, default=24)
    p.add_argument("--buckets", default="1,4,16,32")
    p.add_argument("--requests", type=int, default=64)
    p.add_argument("--max-req-size", type=int, default=1,
                   help="request sizes drawn uniformly from 1..this. "
                        "Default 1 = the canonical online case (one image "
                        "per request); larger caller-side batches hand the "
                        "sequential baseline free batching and narrow the "
                        "engine's ratio (recorded in detail.note)")
    p.add_argument("--rates", default="10,25,0",
                   help="offered loads in req/s; 0 = max")
    p.add_argument("--max-wait-ms", type=float, default=5.0)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--no-open-loop", action="store_true",
                   help="skip the Poisson open-loop saturation sweep "
                        "(latency-knee measurement)")
    p.add_argument("--open-requests", type=int, default=120,
                   help="requests per open-loop rate rung (1 image each)")
    p.add_argument("--knee-factor", type=float, default=3.0,
                   help="p99 multiple over the lightest rung that "
                        "defines the latency knee")
    p.add_argument("--dtypes", default="fp32,bf16,int8",
                   help="serve dtype ladder (comma list of "
                        "fp32,bf16,int8): per-dtype open-loop knees "
                        "land in detail.dtype_ladder, each rung "
                        "accuracy-gated and compile-counter-asserted")
    p.add_argument("--out", default=os.path.join("perf", "bench_serve.json"))
    args = p.parse_args(argv)

    _force_cpu()
    import jax
    import jax.numpy as jnp

    from tpuic.models import create_model
    from tpuic.serve import InferenceEngine, make_forward

    buckets = tuple(int(b) for b in args.buckets.split(","))
    model = create_model(args.model, args.num_classes, dtype="float32")
    variables = model.init(jax.random.key(0),
                           jnp.zeros((1, args.size, args.size, 3),
                                     jnp.float32), train=False)
    # Serving-style forward: raw uint8 in, normalize fused into the
    # compiled program (both paths use the SAME forward — the comparison
    # isolates batching + AOT, not numerics).
    forward = make_forward(model, normalize=True)
    if args.max_req_size > buckets[-1]:
        # Validate up front: engine.submit would raise this inside the
        # feeder thread, where it surfaces as a useless NoneType crash.
        raise SystemExit(f"--max-req-size {args.max_req_size} exceeds the "
                         f"largest bucket {buckets[-1]}")
    reqs = _request_stream(args.requests, args.max_req_size,
                           args.size, args.seed)
    images = sum(r.shape[0] for r in reqs)

    seq = _sequential(forward, variables, reqs)

    import numpy as np

    from tpuic import quant
    tags = tuple(dict.fromkeys(
        ["fp32"] + [t.strip() for t in args.dtypes.split(",") if t.strip()]))
    variants = quant.serve_variants(model, variables, tags, normalize=True)
    engine = InferenceEngine(
        forward_fn=forward, variables=variables, image_size=args.size,
        input_dtype=np.uint8, buckets=buckets,
        max_wait_ms=args.max_wait_ms, queue_size=max(64, args.requests),
        variants={k: v for k, v in variants.items() if k != "fp32"})
    # Shared warmup helper (tpuic/compiled/): every (variant, bucket)
    # rung AOT-compiles through the process-wide registry; regress.py
    # dedups onto the same call.
    from tpuic.compiled import warm_engine
    warmup_s = warm_engine(engine)
    curves = []
    for rate_s in args.rates.split(","):
        curves.append(_engine_run(engine, reqs, float(rate_s)))
    open_loop = dtype_ladder = accuracy = None
    if not args.no_open_loop:
        open_loop = _open_loop_sweep(engine, args.size, args.open_requests,
                                     args.seed, args.knee_factor)
        if len(tags) > 1:
            # Per-rung knees off the SAME anchor + the accuracy gate
            # result the ladder ships under (docs/performance.md,
            # "Quantized serving").
            dtype_ladder = _dtype_ladder_sweep(
                engine, args.size, args.open_requests, args.seed,
                args.knee_factor, tags, open_loop)
            eval_imgs = quant.eval_images(256, args.size)
            ref = jax.jit(variants["fp32"][0])
            accuracy = {"epsilon": quant.DEFAULT_EPSILON}
            for tag in tags:
                if tag == "fp32":
                    continue
                fwd, qv = variants[tag]
                agree = quant.top1_agreement(ref, variants["fp32"][1],
                                             jax.jit(fwd), qv, eval_imgs)
                accuracy[tag] = {
                    "top1_agreement": round(agree, 4),
                    "gate": "ok" if agree >= 1.0 - quant.DEFAULT_EPSILON
                            else "FAILED"}
    engine.close()

    best = max(curves, key=lambda c: c["images_per_sec"])
    steady_compiles = sum(c["compiles_during_run"] for c in curves)
    if open_loop is not None:
        steady_compiles += sum(pt["compiles_during_run"]
                               for pt in open_loop["curve"])
        steady_compiles += sum(pt["compiles_during_run"]
                               for pt in open_loop["shed_curve"])
    if dtype_ladder is not None:
        steady_compiles += sum(r["steady_compiles"]
                               for r in dtype_ladder.values())
    result = {
        "metric": "serve_images_per_sec_cpu_synthetic",
        "value": best["images_per_sec"],
        "unit": "images/sec",
        "vs_sequential": round(best["images_per_sec"]
                               / seq["steady_images_per_sec"], 3),
        "steady_state_compiles": steady_compiles,
        "open_loop_knee_req_per_sec": (
            open_loop["knee"]["offered_req_per_sec"]
            if open_loop and open_loop.get("knee") else None),
        "detail": {
            "platform": jax.devices()[0].platform,
            "device": getattr(jax.devices()[0], "device_kind", "unknown"),
            "model": args.model,
            "image_size": args.size,
            "buckets": list(buckets),
            "max_wait_ms": args.max_wait_ms,
            "requests": args.requests,
            "images": images,
            "warmup_compile_s": warmup_s,
            "offered_load_curve": curves,
            "open_loop": open_loop,
            "dtype_ladder": dtype_ladder,
            "quant_accuracy": accuracy,
            "sequential_baseline": seq,
            "vs_sequential_cold": round(best["images_per_sec"]
                                        / seq["cold_images_per_sec"], 3),
            "note": ("comparative CPU artifact: same forward, same request "
                     "stream; engine adds micro-batching + bucket-padded "
                     "AOT executables. vs_sequential is a strong function "
                     "of request size — callers that pre-batch hand the "
                     "sequential baseline free batching; sweep "
                     "--max-req-size to measure that curve yourself"),
        },
    }
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w") as f:
            json.dump(result, f, indent=2)
    print(json.dumps(result), flush=True)
    return 0


if __name__ == "__main__":
    sys.exit(main())
