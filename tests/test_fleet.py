"""Fleet observability (ISSUE 9 acceptance): device-memory sampling with
the zero-sync/zero-compile contract, the crash flight recorder's ring
bounds and dump-on-signal, rank tagging + per-rank streams, the fleet
aggregator's skew math on synthetic rank streams, the shared tolerant
JSONL reader's torn-tail policy, and the Prometheus memory/RSS rows."""

import json
import os
import signal
import subprocess
import sys
import time
from types import SimpleNamespace

import jax
import jax.numpy as jnp
import pytest

from tpuic.telemetry import events as tme
from tpuic.telemetry.events import (EVENT_KINDS, EventBus, JsonlSink,
                                    MemorySink, read_jsonl)
from tpuic.telemetry.flight import FlightRecorder
from tpuic.telemetry.fleet import (aggregate, load_streams,
                                   rank_stream_path, tag_bus_with_rank)
from tpuic.telemetry.memory import MemorySampler

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- event-bus plumbing ------------------------------------------------------
def test_new_event_kinds_registered():
    assert "memory" in EVENT_KINDS
    assert "flight_dump" in EVENT_KINDS


def test_rank_tag_merged_into_every_event():
    bus = EventBus()
    ms = MemorySink()
    bus.subscribe(ms)
    bus.publish("step", step=1, total_ms=5.0)
    assert "rank" not in ms.events[-1].data  # untagged: schema unchanged
    bus.rank_tag = {"rank": 3, "ranks": 8}
    bus.publish("step", step=2, total_ms=5.0)
    assert ms.events[-1].data["rank"] == 3
    assert ms.events[-1].data["ranks"] == 8
    # Emitter-provided keys win on collision (the tag is a default).
    bus.publish("step", step=3, rank=7)
    assert ms.events[-1].data["rank"] == 7
    # reset() clears the tag (test isolation, like subscribers).
    bus.reset()
    assert bus.rank_tag is None


def test_tag_bus_with_rank_sources(monkeypatch):
    bus = EventBus()
    # Single process (the live runtime here): no tag — the common path
    # stays untouched.
    assert tag_bus_with_rank(bus) == (0, 1)
    assert bus.rank_tag is None
    # Launcher env override (the CI fleet smoke's source).
    monkeypatch.setenv("TPUIC_FLEET_RANK", "2")
    monkeypatch.setenv("TPUIC_FLEET_RANKS", "4")
    assert tag_bus_with_rank(bus) == (2, 4)
    assert bus.rank_tag == {"rank": 2, "ranks": 4}
    # Explicit arguments beat everything.
    assert tag_bus_with_rank(bus, rank=1, ranks=3) == (1, 3)
    assert bus.rank_tag == {"rank": 1, "ranks": 3}
    # A half-set override fails loudly: silently collapsing every
    # worker to rank 0/1 would interleave k processes into ONE stream.
    monkeypatch.delenv("TPUIC_FLEET_RANKS")
    with pytest.raises(ValueError, match="half-set"):
        tag_bus_with_rank(bus)
    # Same rule for half-set EXPLICIT arguments.
    with pytest.raises(ValueError, match="both rank and ranks"):
        tag_bus_with_rank(bus, rank=2)


def test_rank_stream_path_convention():
    assert rank_stream_path("a/events.jsonl", 0) == "a/events.jsonl"
    assert rank_stream_path("a/events.jsonl", 3) == "a/events.rank3.jsonl"
    assert rank_stream_path("noext", 2) == "noext.rank2.jsonl"


def test_read_jsonl_tolerates_torn_lines(tmp_path):
    path = str(tmp_path / "ev.jsonl")
    with open(path, "w") as f:
        f.write(json.dumps({"event": "step", "step": 1}) + "\n")
        f.write('{"event": "step", "st')          # torn mid-write
        f.write(json.dumps({"event": "step", "step": 2}) + "\n")
        f.write("\n")                              # blank line
        f.write('{"event": "epoch", "epoch": 0}')  # unterminated tail: ok
    torn = []
    recs = read_jsonl(path, on_torn=torn.append)
    # The torn fragment swallowed the following line (no newline between
    # them) — exactly the chaos-soak failure mode; everything that
    # parses survives, the fragment is reported, nothing raises.
    assert [r["event"] for r in recs] == ["step", "epoch"]
    assert len(torn) == 1 and torn[0].startswith('{"event": "step", "st')
    assert read_jsonl(str(tmp_path / "missing.jsonl")) == []


# -- flight recorder ---------------------------------------------------------
def test_flight_recorder_ring_bound_and_trailer(tmp_path):
    bus = EventBus()
    path = str(tmp_path / "flight.jsonl")
    rec = FlightRecorder(path, capacity=8)
    rec.subscribe(bus)
    for i in range(50):
        bus.publish("step", step=i, total_ms=1.0)
        # Per-request firehose kinds are excluded at record time: a
        # busy serve tier must not evict the coarse timeline the dump
        # exists for (aggregate span stats live in the snapshot).
        bus.publish("serve_span", trace=i, total_ms=2.0)
    assert len(rec) == 8  # bounded: the ring keeps only the last N
    t_before_dump = time.time()
    assert rec.dump(reason="test") == path
    recs = read_jsonl(path)
    body, trailer = recs[:-1], recs[-1]
    assert [r["step"] for r in body] == list(range(42, 50))
    assert all(r["event"] == "step" for r in body)  # no spans recorded
    assert trailer["event"] == "flight_dump"
    assert trailer["reason"] == "test" and trailer["events"] == 8
    # Every recorded event precedes the dump (the chaos-soak assertion).
    assert all(r["t"] <= trailer["t"] for r in body)
    assert trailer["t"] >= t_before_dump - 1.0
    assert rec.dumps == 1


def test_flight_recorder_dump_on_sigquit_in_process(tmp_path):
    """The Python-level SIGQUIT handler dumps the ring and restores
    cleanly; chaining to a previous Python handler is preserved."""
    if not hasattr(signal, "SIGQUIT"):
        pytest.skip("no SIGQUIT on this platform")
    bus = EventBus()
    path = str(tmp_path / "flight.jsonl")
    rec = FlightRecorder(path, capacity=16)
    rec.subscribe(bus)
    bus.publish("step", step=1, total_ms=2.0)
    chained = []
    prev_handler = signal.signal(signal.SIGQUIT,
                                 lambda s, f: chained.append(s))
    try:
        assert rec.install_signal_handler()
        os.kill(os.getpid(), signal.SIGQUIT)
        time.sleep(0.05)  # handler runs at the next bytecode boundary
        recs = read_jsonl(path)
        assert recs and recs[-1]["reason"] == "sigquit"
        assert recs[0]["event"] == "step"
        assert chained == [signal.SIGQUIT]  # previous handler chained
    finally:
        signal.signal(signal.SIGQUIT, prev_handler)


def test_flight_recorder_sigquit_chain_with_stack_dump(tmp_path):
    """The full supervised protocol in a bare subprocess: the flight
    recorder registers first, install_stack_dump_handler(chain=True)
    rides the same SIGQUIT — one signal yields the faulthandler stack
    dump AND the event-timeline dump (the train.py/serve wiring)."""
    if not hasattr(signal, "SIGQUIT"):
        pytest.skip("no SIGQUIT on this platform")
    stack = str(tmp_path / "stack.txt")
    flight = str(tmp_path / "flight.jsonl")
    child = f"""
import os, signal, sys, time
sys.path.insert(0, {_REPO!r})
from tpuic.telemetry.events import bus
from tpuic.telemetry.flight import install_flight_recorder
from tpuic.runtime.supervisor import install_stack_dump_handler
rec = install_flight_recorder()
assert rec is not None
install_stack_dump_handler(chain=True)
bus.publish("step", step=1, total_ms=3.0)
bus.publish("quarantine", path="x.png", count=1)
print("READY", flush=True)
while True:
    time.sleep(0.2)
"""
    env = dict(os.environ, TPUIC_STACK_DUMP=stack, TPUIC_FLIGHT_DUMP=flight)
    proc = subprocess.Popen([sys.executable, "-c", child], env=env,
                            stdout=subprocess.PIPE, text=True)
    try:
        assert proc.stdout.readline().strip() == "READY"
        proc.send_signal(signal.SIGQUIT)
        deadline = time.monotonic() + 10.0
        while time.monotonic() < deadline and not os.path.exists(flight):
            time.sleep(0.05)
    finally:
        proc.kill()
        proc.wait()
    body = open(stack).read() if os.path.exists(stack) else ""
    assert "File" in body  # faulthandler wrote real stacks
    recs = read_jsonl(flight)
    assert [r["event"] for r in recs] == ["step", "quarantine",
                                          "flight_dump"]
    assert recs[-1]["reason"] == "sigquit"
    assert all(r["t"] <= recs[-1]["t"] for r in recs[:-1])


def test_install_flight_recorder_noop_unsupervised(monkeypatch):
    from tpuic.telemetry.flight import install_flight_recorder
    monkeypatch.delenv("TPUIC_FLIGHT_DUMP", raising=False)
    assert install_flight_recorder() is None


# -- device-memory sampler ---------------------------------------------------
def test_memory_sampler_cpu_fallback_fields():
    bus = EventBus()
    ms = MemorySink()
    bus.subscribe(ms)
    keep = jnp.ones((256, 256), jnp.float32)  # something live to count
    samp = MemorySampler(publish=bus.publish)
    out = samp.sample(step=5)
    assert out is not None and out["source"] == "live_arrays"
    assert out["step"] == 5
    assert out["bytes_in_use"] >= keep.nbytes
    assert out["process_rss_bytes"] > 0
    assert len(out["devices"]) == len(jax.local_devices())
    for dev in out["devices"]:
        assert {"device", "kind", "bytes_in_use"} <= set(dev)
    # CPU knows no limit: no fabricated headroom.
    assert "headroom_frac" not in out
    ev = ms.of("memory")[-1]
    assert ev.data["bytes_in_use"] == out["bytes_in_use"]
    assert samp.snapshot() is out


def test_memory_sampler_stats_headroom_and_oneshot_warning():
    class FakeDev:
        id = 0
        device_kind = "TPU v5e"

        def __init__(self):
            self.in_use = 15 << 30

        def memory_stats(self):
            return {"bytes_in_use": self.in_use,
                    "peak_bytes_in_use": self.in_use,
                    "bytes_limit": 16 << 30}

    bus = EventBus()
    ms = MemorySink()
    bus.subscribe(ms)
    logs = []
    dev = FakeDev()
    samp = MemorySampler(publish=bus.publish, devices=[dev],
                         warn_headroom_frac=0.05, log=logs.append)
    out = samp.sample(step=1)
    assert out["source"] == "memory_stats"
    assert out["bytes_limit"] == 16 << 30
    assert out["headroom_frac"] == pytest.approx(1 / 16, abs=1e-3)
    assert "warning" not in out and not logs  # 6% headroom: fine
    dev.in_use = int(15.8 * 2**30)  # < 5% headroom now
    out2 = samp.sample(step=2)
    assert out2["warning"] == "low_headroom"
    assert len(logs) == 1 and "LOW HEADROOM" in logs[0]
    out3 = samp.sample(step=3)  # one-shot: still low, no re-warn
    assert "warning" not in out3 and len(logs) == 1
    kinds = [e.data.get("warning") for e in ms.of("memory")]
    assert kinds == [None, "low_headroom", None]


def test_memory_sampler_every_n_steps():
    bus = EventBus()
    ms = MemorySink()
    bus.subscribe(ms)
    samp = MemorySampler(publish=bus.publish, every=3)
    unsub = bus.subscribe(samp.on_event, kinds=("step",))
    for i in range(7):
        bus.publish("step", step=i + 1, total_ms=1.0)
    unsub()
    steps = [e.data.get("step") for e in ms.of("memory")]
    assert steps == [1, 4, 7]


def test_memory_sampler_fallback_auto_throttles():
    """On the live_arrays fallback, a liveness registry past the
    throttle threshold widens the step-boundary cadence (direct
    sample() calls stay unthrottled)."""
    keep = jnp.ones((8, 8))  # at least one live array
    bus = EventBus()
    ms = MemorySink()
    bus.subscribe(ms)
    samp = MemorySampler(publish=bus.publish,
                         fallback_throttle_arrays=0, fallback_stride=4)
    bus.subscribe(samp.on_event, kinds=("step",))
    for i in range(9):
        bus.publish("step", step=i + 1, total_ms=1.0)
    # Step 1 sampled (walk sees > 0 arrays -> stride 4 engages), then
    # only every 4th boundary.
    assert [e.data.get("step") for e in ms.of("memory")] == [1, 5, 9]
    assert samp.sample(step=100) is not None  # direct calls unthrottled
    del keep


def test_memory_sampler_and_rank_tag_zero_syncs_zero_compiles(tmp_path):
    """The acceptance contract (same shape as the PR-3 StepTimer proof):
    after warmup, the loop performs ZERO backend compiles and the
    device_get count is IDENTICAL with memory sampling + rank tagging
    on vs. off — both are host-side metadata/dict plumbing, nothing
    else."""
    from tpuic.analysis import runtime as contracts

    @jax.jit
    def step(s, x):
        s = s + x.sum()
        return s, {"loss": s}

    # Warm every executable the loop touches (the jitted step AND the
    # eager ones/zeros/mul helpers), so the measured loops run under the
    # strict zero-compile contract.
    state = jnp.zeros(())
    state, m = step(state, jnp.ones((4,)) * 0)
    jax.device_get({"loss": m["loss"]})

    def loop(sampling: bool):
        bus = EventBus()
        sink = JsonlSink(str(tmp_path / f"ev_{sampling}.jsonl"))
        bus.subscribe(sink)
        samp = None
        if sampling:
            tag_bus_with_rank(bus, rank=1, ranks=4)
            samp = MemorySampler(publish=bus.publish)
            bus.subscribe(samp.on_event, kinds=("step",))
        with contracts.assert_compiles_flat(
                0, what=f"memory sampler loop (sampling={sampling})"):
            with contracts.count_device_gets() as gets:
                state = jnp.zeros(())
                for i in range(6):
                    state, m = step(state, jnp.ones((4,)) * i)
                    jax.device_get({"loss": m["loss"]})  # the deferred drain
                    bus.publish("step", step=i + 1, total_ms=1.0,
                                data_ms=0.1, dispatch_ms=0.1)
        sink.close()
        if sampling:
            assert samp.samples == 6  # it really ran, every step
            recs = read_jsonl(str(tmp_path / "ev_True.jsonl"))
            assert recs and all(r["rank"] == 1 for r in recs)  # tagged
        return gets.count

    gets_off = loop(False)
    gets_on = loop(True)
    assert gets_on == gets_off == 6
    assert contracts.jit_cache_size(step) == 1


def test_train_telemetry_wires_memory_and_rank(tmp_path, monkeypatch):
    """TrainTelemetry samples memory at step boundaries and tags events
    with the launcher-declared rank; the JSONL stream lands at the
    per-rank derived path."""
    import tpuic.telemetry as tm
    monkeypatch.setenv("TPUIC_FLEET_RANK", "1")
    monkeypatch.setenv("TPUIC_FLEET_RANKS", "2")
    tme.bus.reset()
    jsonl = str(tmp_path / "events.jsonl")
    tt = tm.TrainTelemetry(SimpleNamespace(metrics_jsonl=jsonl),
                           model_name="resnet18-cifar", image_size=32,
                           global_batch=4)
    keep = jnp.ones((64, 64), jnp.float32)  # live bytes for the sampler
    try:
        tme.bus.publish("step", step=1, total_ms=10.0, data_ms=1.0,
                        dispatch_ms=0.5, device_ms=8.5)
    finally:
        tt.close()
        tme.bus.reset()
    derived = rank_stream_path(jsonl, 1)
    assert not os.path.exists(jsonl)
    recs = read_jsonl(derived)
    kinds = [r["event"] for r in recs]
    assert "step" in kinds and "memory" in kinds
    for r in recs:
        assert r["rank"] == 1 and r["ranks"] == 2
    mem = next(r for r in recs if r["event"] == "memory")
    assert mem["step"] == 1 and mem["bytes_in_use"] >= keep.nbytes


# -- fleet aggregator --------------------------------------------------------
def _stream(rank, totals, start_step=1):
    return [{"event": "step", "step": start_step + i, "rank": rank,
             "total_ms": t, "data_ms": 1.0, "dispatch_ms": 0.5,
             "device_ms": t - 1.5}
            for i, t in enumerate(totals)]


def test_aggregate_skew_math_exact():
    streams = {0: _stream(0, [100.0] * 10),
               1: _stream(1, [150.0] * 10),
               2: _stream(2, [110.0] * 10)}
    rep = aggregate(streams)
    assert rep["ranks"] == [0, 1, 2] and rep["steps_common"] == 10
    # Per-step spread: max - min = 50 ms, every step.
    assert rep["spread_ms"] == {"p50": 50.0, "p99": 50.0, "max": 50.0}
    # Slowest-rank histogram: rank 1 wins every step.
    assert rep["per_rank"]["1"]["slowest_steps"] == 10
    assert rep["per_rank"]["0"]["slowest_steps"] == 0
    # Estimated collective wait = rank total minus fleet min, summed.
    assert rep["per_rank"]["0"]["est_collective_wait_ms"] == 0.0
    assert rep["per_rank"]["1"]["est_collective_wait_ms"] == 500.0
    assert rep["per_rank"]["2"]["est_collective_wait_ms"] == 100.0
    s = rep["straggler"]
    assert s["rank"] == 1 and s["slowest_step_frac"] == 1.0
    assert s["excess_share"] == pytest.approx(500.0 / 600.0, abs=1e-4)
    assert rep["per_rank"]["1"]["p50_ms"] == 150.0
    assert rep["per_rank"]["1"]["mean_device_ms"] == pytest.approx(148.5)


def test_aggregate_warmup_and_partial_steps():
    # Rank 1 reported two extra steps no one else saw (died later /
    # started earlier): only fleet-common steps enter the math.
    streams = {0: _stream(0, [100.0] * 6),
               1: _stream(1, [2000.0, 130.0, 130.0, 130.0, 130.0, 130.0]
                          + [130.0, 130.0])}
    rep = aggregate(streams, warmup=1)  # drop the compile-warmup step
    assert rep["steps_common"] == 5
    assert rep["per_rank"]["1"]["est_collective_wait_ms"] == \
        pytest.approx(5 * 30.0)
    assert rep["straggler"]["rank"] == 1
    # Without warmup the 2000 ms compile step would dominate the ledger.
    rep_all = aggregate(streams)
    assert rep_all["steps_common"] == 6
    assert rep_all["per_rank"]["1"]["est_collective_wait_ms"] == \
        pytest.approx(1900.0 + 5 * 30.0)


def test_aggregate_single_rank_has_no_straggler():
    rep = aggregate({0: _stream(0, [100.0] * 4)})
    assert rep["straggler"] is None
    assert rep["steps_common"] == 4
    assert "duplicate_steps" not in rep


def test_aggregate_surfaces_restart_duplicates():
    """A supervised restart replays step numbers into the appended
    stream; the collapse is last-wins but COUNTED — mixed-attempt walls
    must not pose as exact skew."""
    from tpuic.telemetry.fleet import summary_lines
    replayed = _stream(0, [100.0] * 6) + _stream(0, [90.0] * 3,
                                                 start_step=4)
    rep = aggregate({0: replayed, 1: _stream(1, [150.0] * 6)})
    assert rep["duplicate_steps"] == {"0": 3}
    # last occurrence won: steps 4-6 use the replayed 90 ms walls
    assert rep["per_rank"]["0"]["p50_ms"] in (90.0, 100.0)
    assert rep["per_rank"]["1"]["est_collective_wait_ms"] == \
        pytest.approx(3 * 50.0 + 3 * 60.0)
    assert any("duplicate step records" in ln for ln in summary_lines(rep))


def test_load_streams_rank_sources_and_cli(tmp_path):
    """Stream grouping: the record's own rank field wins, the filename
    convention covers untagged streams; the CLI renders the verdict and
    --expect-straggler gates on it."""
    d = tmp_path / "fleet"
    d.mkdir()
    with open(d / "events.jsonl", "w") as f:      # tagged rank 0
        for r in _stream(0, [100.0] * 6):
            f.write(json.dumps(r) + "\n")
    with open(d / "events.rank1.jsonl", "w") as f:  # untagged: filename
        for r in _stream(1, [180.0] * 6):
            r.pop("rank")
            f.write(json.dumps(r) + "\n")
        f.write('{"torn')  # tolerant reader on the aggregation path too
    streams = load_streams([str(d)])
    assert sorted(streams) == [0, 1]
    assert all(r.get("rank", 1) == 1 for r in streams[1])

    from tpuic.telemetry import fleet
    out = str(tmp_path / "report.json")
    rc = fleet.main([str(d), "--json", out, "--expect-straggler", "1"])
    assert rc == 0
    rep = json.load(open(out))
    assert rep["straggler"]["rank"] == 1
    assert rep["per_rank"]["1"]["est_collective_wait_ms"] == \
        pytest.approx(6 * 80.0)
    # The gate really gates: a wrong expectation fails.
    assert fleet.main([str(d), "--expect-straggler", "0"]) == 1
    # And an empty directory is a loud error, not a silent pass.
    empty = tmp_path / "empty"
    empty.mkdir()
    assert fleet.main([str(empty)]) == 2


def test_cli_require_ranks_gates_missing_streams(tmp_path, capsys):
    """--require-ranks N (the gang soak's fleet-coverage gate): a rank
    whose stream is missing entirely must fail the aggregation loudly,
    not have the skew silently computed over the ranks that showed up."""
    from tpuic.telemetry import fleet
    d = tmp_path / "fleet"
    d.mkdir()
    for rank in (0, 1):
        name = "events.jsonl" if rank == 0 else f"events.rank{rank}.jsonl"
        with open(d / name, "w") as f:
            for r in _stream(rank, [100.0] * 4):
                f.write(json.dumps(r) + "\n")
    assert fleet.main([str(d), "--require-ranks", "2"]) == 0
    # Rank 2's stream never arrived: exit 1 naming the missing rank.
    assert fleet.main([str(d), "--require-ranks", "3"]) == 1
    assert "missing rank stream(s) [2]" in capsys.readouterr().err
    # More ranks than expected is just as loud (misconfigured N).
    assert fleet.main([str(d), "--require-ranks", "1"]) == 1
    assert "unexpected rank(s) [1]" in capsys.readouterr().err


# -- prometheus rows ---------------------------------------------------------
def test_prom_memory_and_rss_rows():
    from tpuic.telemetry.goodput import GoodputTracker
    from tpuic.telemetry.prom import (memory_rows, serve_exposition,
                                      train_exposition)
    mem = {"source": "memory_stats",
           "devices": [{"device": "0", "kind": "TPU v5e",
                        "bytes_in_use": 100, "peak_bytes_in_use": 120,
                        "bytes_limit": 200, "headroom_frac": 0.5},
                       {"device": "1", "kind": "TPU v5e",
                        "bytes_in_use": 90}]}
    rows = memory_rows(mem)
    assert rows[0][:3] == ("device_memory_bytes", 100, "gauge")
    assert rows[0][4] == {"device": "0", "kind": "in_use"}
    assert memory_rows(None) == []
    gt = GoodputTracker(flops_per_step=1e9, peak_flops=1e12)
    gt.start()
    text = train_exposition(gt.report(), memory=mem)
    assert 'tpuic_train_device_memory_bytes{device="0",kind="in_use"} 100' \
        in text
    assert 'tpuic_train_device_memory_bytes{device="0",kind="peak"} 120' \
        in text
    assert 'tpuic_train_device_memory_bytes{device="0",kind="limit"} 200' \
        in text
    assert 'tpuic_train_device_memory_headroom_frac{device="0"} 0.5' in text
    assert 'tpuic_train_device_memory_bytes{device="1",kind="in_use"} 90' \
        in text
    # device 1 reported no limit: no fabricated headroom/limit rows
    assert 'device="1",kind="limit"' not in text
    assert "tpuic_train_process_rss_bytes " in text

    from tpuic.serve.metrics import ServeStats
    stext = serve_exposition(ServeStats().snapshot(), memory=mem)
    assert 'tpuic_serve_device_memory_bytes{device="0",kind="in_use"}' \
        in stext
    assert "tpuic_serve_process_rss_bytes " in stext
    # No snapshot: no memory series at all (absent, not 0).
    assert "device_memory_bytes" not in serve_exposition(
        ServeStats().snapshot())


def test_process_rss_bytes_shared_helper():
    from tpuic.metrics.meters import process_rss_bytes
    rss = process_rss_bytes()
    assert rss is not None and rss > 1 << 20  # a live interpreter > 1 MB


# -- tensorboard sink --------------------------------------------------------
def test_tensorboard_sink_memory_scalars():
    from tpuic.telemetry.events import Event, TensorBoardSink

    class StubTB:
        def __init__(self):
            self.calls = []

        def scalars(self, step, **kw):
            self.calls.append((step, kw))

    tb = StubTB()
    sink = TensorBoardSink(tb)
    sink(Event("memory", time.time(),
               {"step": 7, "bytes_in_use": 1000, "peak_bytes_in_use": 1200,
                "process_rss_bytes": 5000, "headroom_frac": 0.25,
                "devices": []}))
    assert tb.calls == [(7, {"memory_bytes_in_use": 1000.0,
                             "memory_peak_bytes_in_use": 1200.0,
                             "memory_process_rss_bytes": 5000.0,
                             "memory_headroom_frac": 0.25})]
